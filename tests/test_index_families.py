"""IVF_FLAT / IVF_PQ / HNSW functional + recall tests.

Mirrors reference suites test/unit_test/vector/test_vector_index_ivf_flat.cc,
test_vector_index_ivf_pq.cc (hybrid contract), test_vector_index_hnsw.cc."""

import numpy as np
import pytest

from dingo_tpu.index import (
    FilterSpec,
    IndexParameter,
    IndexType,
    NotSupported,
    new_index,
)
from dingo_tpu.index.base import NotTrained
from dingo_tpu.ops.distance import Metric


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((5000, 32)).astype(np.float32)
    ids = np.arange(5000, dtype=np.int64)
    q = x[:16] + 0.01 * rng.standard_normal((16, 32)).astype(np.float32)
    d = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    want = np.argsort(d, 1)[:, :10]
    return ids, x, q, want


def recall(res, want):
    return np.mean([len(set(r.ids) & set(w)) / 10 for r, w in zip(res, want)])


# ---------------- IVF_FLAT ----------------


def ivf_param(**kw):
    defaults = dict(
        index_type=IndexType.IVF_FLAT, dimension=32, ncentroids=32,
        default_nprobe=8,
    )
    defaults.update(kw)
    return IndexParameter(**defaults)


def test_ivf_untrained_raises(corpus):
    ids, x, q, want = corpus
    idx = new_index(1, ivf_param())
    idx.add(ids[:100], x[:100])
    with pytest.raises(NotTrained):
        idx.search(q, 10)
    assert idx.need_train() and not idx.is_trained()


def test_ivf_train_too_small_raises(corpus):
    ids, x, q, want = corpus
    idx = new_index(1, ivf_param())
    idx.add(ids[:10], x[:10])
    with pytest.raises(NotTrained):
        idx.train()


def test_ivf_full_probe_is_exact(corpus):
    ids, x, q, want = corpus
    idx = new_index(1, ivf_param())
    idx.add(ids, x)
    idx.train()
    res = idx.search(q, 10, nprobe=32)
    assert recall(res, want) == 1.0


def test_ivf_partial_probe_recall(corpus):
    ids, x, q, want = corpus
    idx = new_index(1, ivf_param())
    idx.add(ids, x)
    idx.train()
    res = idx.search(q, 10, nprobe=8)
    assert recall(res, want) >= 0.7


def test_ivf_add_after_train(corpus):
    """Vectors added post-train get assigned to lists immediately."""
    ids, x, q, want = corpus
    idx = new_index(1, ivf_param())
    idx.add(ids[:4000], x[:4000])
    idx.train()
    idx.add(ids[4000:], x[4000:])
    res = idx.search(q, 10, nprobe=32)
    assert recall(res, want) == 1.0


def test_ivf_filter_and_delete(corpus):
    ids, x, q, want = corpus
    idx = new_index(1, ivf_param())
    idx.add(ids, x)
    idx.train()
    idx.delete(ids[:500])
    res = idx.search(q, 20, filter_spec=FilterSpec(ranges=[(1000, 2000)]),
                     nprobe=32)
    for r in res:
        assert ((r.ids >= 1000) & (r.ids < 2000)).all()


def test_ivf_save_load(tmp_path, corpus):
    ids, x, q, want = corpus
    idx = new_index(1, ivf_param())
    idx.add(ids, x)
    idx.train()
    idx.save(str(tmp_path))
    idx2 = new_index(1, ivf_param())
    idx2.load(str(tmp_path))
    assert idx2.is_trained()
    r1 = idx.search(q[:4], 5, nprobe=8)
    r2 = idx2.search(q[:4], 5, nprobe=8)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a.ids, b.ids)


# ---------------- IVF_PQ ----------------


def pq_param(**kw):
    defaults = dict(
        index_type=IndexType.IVF_PQ, dimension=32, ncentroids=16,
        nsubvector=8, default_nprobe=8,
    )
    defaults.update(kw)
    return IndexParameter(**defaults)


def test_ivfpq_hybrid_untrained_exact(corpus):
    """The hybrid contract: untrained IVF_PQ serves EXACT flat search
    (vector_index_ivf_pq.h:113-115), unlike IVF_FLAT which errors."""
    ids, x, q, want = corpus
    idx = new_index(2, pq_param())
    idx.add(ids, x)
    res = idx.search(q, 10)
    assert recall(res, want) == 1.0


def test_ivfpq_trained_recall(corpus):
    ids, x, q, want = corpus
    idx = new_index(2, pq_param())
    idx.add(ids, x)
    idx.train()
    assert idx.is_trained()
    res = idx.search(q, 10, nprobe=16)
    # residual PQ8 over 32d: coarse codes; self-neighbors should survive
    assert recall(res, want) >= 0.5


def test_ivfpq_add_after_train_and_delete(corpus):
    ids, x, q, want = corpus
    idx = new_index(2, pq_param())
    idx.add(ids[:4000], x[:4000])
    idx.train()
    idx.add(ids[4000:], x[4000:])
    assert idx.get_count() == 5000
    idx.delete(ids[:100])
    res = idx.search(q, 10, nprobe=16)
    for r in res:
        assert (r.ids >= 100).all()


def test_ivfpq_save_load(tmp_path, corpus):
    ids, x, q, want = corpus
    idx = new_index(2, pq_param())
    idx.add(ids, x)
    idx.train()
    idx.save(str(tmp_path))
    idx2 = new_index(2, pq_param())
    idx2.load(str(tmp_path))
    r1 = idx.search(q[:4], 5, nprobe=8)
    r2 = idx2.search(q[:4], 5, nprobe=8)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a.ids, b.ids)


def test_ivfpq_dimension_not_divisible():
    from dingo_tpu.index.base import InvalidParameter

    with pytest.raises(InvalidParameter):
        new_index(2, pq_param(dimension=30))


# ---------------- HNSW ----------------


def hnsw_param(**kw):
    defaults = dict(
        index_type=IndexType.HNSW, dimension=32, nlinks=16, efconstruction=80,
    )
    defaults.update(kw)
    return IndexParameter(**defaults)


def test_hnsw_recall(corpus):
    ids, x, q, want = corpus
    idx = new_index(3, hnsw_param())
    idx.add(ids, x)
    res = idx.search(q, 10, ef=80)
    assert recall(res, want) >= 0.9


def test_hnsw_delete_and_rebuild_trigger(corpus):
    ids, x, q, want = corpus
    idx = new_index(3, hnsw_param())
    idx.add(ids[:1000], x[:1000])
    assert not idx.need_to_rebuild()
    idx.delete(ids[:600])
    # deleted (600) * 2 > total (1000): reference trigger
    assert idx.need_to_rebuild()
    res = idx.search(q, 5, ef=80)
    for r in res:
        assert (r.ids >= 600).all()


def test_hnsw_filter(corpus):
    ids, x, q, want = corpus
    idx = new_index(3, hnsw_param())
    idx.add(ids, x)
    res = idx.search(q, 5, filter_spec=FilterSpec(ranges=[(2000, 3000)]),
                     ef=200)
    for r in res:
        if len(r.ids):
            assert ((r.ids >= 2000) & (r.ids < 3000)).all()


def test_hnsw_upsert_moves_vector(corpus):
    ids, x, q, want = corpus
    idx = new_index(3, hnsw_param())
    idx.add(ids[:100], x[:100])
    idx.upsert(ids[:1], x[4999][None, :])
    res = idx.search(x[4999][None, :], 1, ef=50)
    assert res[0].ids[0] == 0
    assert res[0].distances[0] == pytest.approx(0.0, abs=1e-3)


def test_hnsw_save_load(tmp_path, corpus):
    ids, x, q, want = corpus
    idx = new_index(3, hnsw_param())
    idx.add(ids[:2000], x[:2000])
    idx.save(str(tmp_path))
    idx2 = new_index(3, hnsw_param())
    idx2.load(str(tmp_path))
    assert idx2.get_count() == 2000
    r1 = idx.search(q[:4], 5)
    r2 = idx2.search(q[:4], 5)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a.ids, b.ids)


def test_hnsw_empty_search():
    idx = new_index(3, hnsw_param())
    res = idx.search(np.zeros((2, 32), np.float32), 5)
    assert all(len(r.ids) == 0 for r in res)


# ---------------- factory ----------------


def test_factory_diskann_requires_server_addr():
    """Every index type is now creatable; DISKANN without a configured
    --role=diskann endpoint fails with a clear error, not NotSupported."""
    from dingo_tpu.index.base import VectorIndexError

    with pytest.raises(VectorIndexError, match="diskann_server_addr"):
        new_index(1, IndexParameter(index_type=IndexType.DISKANN,
                                    dimension=8))


def test_ivfpq_host_vectors_mode(corpus):
    """host_vectors=True: full vectors stay in host memory (HostSlotStore);
    trained search serves from device codes, untrained fallback scans host
    chunks — the 10M x 768 config-3 memory model at test scale."""
    import numpy as _np

    from dingo_tpu.index.slot_store import HostSlotStore

    ids, x, q, want = corpus
    idx = new_index(9, pq_param(host_vectors=True))
    assert isinstance(idx.store, HostSlotStore)
    idx.add(ids, x)
    assert isinstance(idx.store.vecs, _np.ndarray)  # never on device
    # untrained: exact chunked host scan
    res = idx.search(q, 10)
    assert recall(res, want) == 1.0
    # trained: ADC prune + exact host rerank beats pure ADC
    idx.train()
    res = idx.search(q, 10, nprobe=16)
    rerank_recall = recall(res, want)
    assert rerank_recall >= 0.5
    dev = new_index(9, pq_param())
    dev.add(ids, x)
    dev.train()
    dev_recall = recall(dev.search(q, 10, nprobe=16), want)
    assert rerank_recall >= dev_recall
    # with rerank disabled the two stores produce identical results
    from dingo_tpu.common.config import FLAGS

    prev = FLAGS.get("ivfpq_rerank_factor")
    FLAGS.set("ivfpq_rerank_factor", 1)
    try:
        a = idx.search(q[:4], 5, nprobe=16)
        b = dev.search(q[:4], 5, nprobe=16)
    finally:
        FLAGS.set("ivfpq_rerank_factor", prev)
    for ra, rb in zip(a, b):
        _np.testing.assert_array_equal(ra.ids, rb.ids)


def test_ivfpq_host_vectors_chunk_boundary():
    """Host scan must merge correctly across chunk boundaries."""
    import numpy as _np

    import dingo_tpu.index.ivf_pq as mod

    old = mod.HOST_SCAN_CHUNK
    mod.HOST_SCAN_CHUNK = 256
    try:
        rng = _np.random.default_rng(4)
        x = rng.standard_normal((1000, 32)).astype(_np.float32)
        ids = _np.arange(1000, dtype=_np.int64)
        idx = new_index(10, pq_param(host_vectors=True))
        idx.add(ids, x)
        q = x[[5, 300, 999]]
        res = idx.search(q, 3)
        assert [r.ids[0] for r in res] == [5, 300, 999]
    finally:
        mod.HOST_SCAN_CHUNK = old


def test_ivfpq_host_vectors_save_load_keeps_mode(tmp_path):
    """Round-1 review regression: load() must honor host_vectors, not
    silently convert back to a device store."""
    import numpy as _np

    from dingo_tpu.index.slot_store import HostSlotStore

    rng = _np.random.default_rng(6)
    x = rng.standard_normal((2000, 32)).astype(_np.float32)
    ids = _np.arange(2000, dtype=_np.int64)
    idx = new_index(11, pq_param(host_vectors=True))
    idx.add(ids, x)
    idx.train()
    idx.save(str(tmp_path))
    idx2 = new_index(11, pq_param(host_vectors=True))
    idx2.load(str(tmp_path))
    assert isinstance(idx2.store, HostSlotStore)
    assert isinstance(idx2.store.vecs, _np.ndarray)
    a = idx.search(x[:3], 5, nprobe=16)
    b = idx2.search(x[:3], 5, nprobe=16)
    for ra, rb in zip(a, b):
        _np.testing.assert_array_equal(ra.ids, rb.ids)


def test_ivfpq_chunked_train_encode():
    """Training encodes in bounded device chunks; results must match the
    single-shot path (exercised with a tiny chunk size)."""
    import numpy as _np

    import dingo_tpu.index.ivf_pq as mod

    old = mod.ENCODE_CHUNK
    mod.ENCODE_CHUNK = 512
    try:
        rng = _np.random.default_rng(8)
        x = rng.standard_normal((3000, 32)).astype(_np.float32)
        ids = _np.arange(3000, dtype=_np.int64)
        idx = new_index(12, pq_param(host_vectors=True))
        idx.add(ids, x)
        idx.train()
        res = idx.search(x[:8] + 0.001, 5, nprobe=16)
        hits = sum(1 for i, r in enumerate(res) if i in set(r.ids))
        assert hits >= 6  # chunked encode produces a working index
    finally:
        mod.ENCODE_CHUNK = old


def test_host_vectors_survives_pb_roundtrip():
    """host_vectors must survive the RPC decode boundary, or region
    creation silently reverts to a device store and OOMs at scale."""
    from dingo_tpu.server import convert

    p = pq_param(host_vectors=True)
    back = convert.index_parameter_from_pb(convert.index_parameter_to_pb(p))
    assert back.host_vectors is True
    p2 = pq_param()
    back2 = convert.index_parameter_from_pb(convert.index_parameter_to_pb(p2))
    assert back2.host_vectors is False
