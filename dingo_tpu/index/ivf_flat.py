"""TpuIvfFlat: inverted-file index with TPU k-means training and
bucketed list-scan search.

Reference: VectorIndexIvfFlat (src/vector/vector_index_ivf_flat.{h,cc} —
faiss::IndexIVFFlat with a separately-held quantizer, vector_index_ivf_flat.h:
137; train-data bookkeeping :144-145; untrained search returns
EVECTOR_NOT_SUPPORT so VectorReader falls back to brute force,
vector_reader.cc:1814-1833).

TPU-first design:
  train  — on-device Lloyd k-means (ops/kmeans.py) over a sampled subset
           (max_points_per_centroid * nlist, faiss ClusteringParameters
           convention), deterministic farthest-first init.
  layout — ground truth lives in a flat SlotStore (same arrays as TpuFlat);
           a *bucketed view* [B, cap_list, d] of fixed-width spill buckets
           (ivf_layout.py) is (re)built lazily after mutations. cap_list
           tracks the MEAN list size; long lists spill into extra buckets,
           so HBM is bounded by ~n*d + nlist*cap_list*d regardless of
           assignment skew.
  search — [b, nlist] centroid scores -> top-nprobe coarse lists ->
           on-device expansion to virtual bucket probes -> lax.scan over
           probe ranks: gather one bucket per query per rank
           ([b, cap_list, d] dynamic gather), distance einsum, running
           top-k merge. HBM traffic per query ~ nprobe/nlist of the index
           (vs full scan) — the win IVF exists for. (A Pallas kernel that
           DMAs list tiles and skips unprobed lists is the planned upgrade.)

Semantics parity: untrained index raises NotTrained (reader brute-force
fallback contract); deletes tombstone; adds are accepted before training
(vectors buffer in the SlotStore; assignment happens at train time —
the reference buffers train data similarly).
"""

from __future__ import annotations

import functools
import json
import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    InvalidParameter,
    NotTrained,
    SearchResult,
    VectorIndex,
    strip_invalid,
)
from dingo_tpu.index.flat import BinaryPm1Mixin, _SlotStoreIndex, _pad_batch
from dingo_tpu.index.ivf_layout import BucketLayout, build_layout, expand_probes
from dingo_tpu.index.slot_store import SlotStore, _next_pow2
from dingo_tpu.ops.distance import (
    Metric,
    normalize,
    score_matrix,
    scores_to_distances,
    squared_norms,
)
from dingo_tpu.ops.kmeans import (
    MAX_POINTS_PER_CENTROID,
    kmeans_assign,
    train_kmeans,
)
from dingo_tpu.ops.topk import merge_topk, topk_scores


def coarse_probes(queries, centroids, c_sqnorm, nprobe):
    """Top-nprobe coarse lists per query: [b, nprobe] int32. Plain function
    (shard_map-safe); `_probe_lists` is the jitted wrapper."""
    # Coarse quantizer is always L2 (faiss uses the metric's quantizer, but
    # L2 on normalized data == cosine ordering; IP uses L2 quantizer too in
    # the reference's faiss config).
    d = (
        squared_norms(queries)[:, None]
        - 2.0
        * jnp.einsum(
            "bd,nd->bn",
            queries,
            centroids,
            precision=jax.lax.Precision.HIGHEST,
        )
        + c_sqnorm[None, :]
    )
    _, idx = jax.lax.top_k(-d, nprobe)
    return idx.astype(jnp.int32)


_probe_lists = jax.jit(coarse_probes, static_argnames=("nprobe",))


def ivf_scan_scores(
    buckets, bucket_sqnorm, bucket_valid, bucket_slot, probes, queries, k, metric
):
    """Scan nprobe bucket ranks per query with a running top-k.

    buckets:     [nlist, cap_list, d]
    bucket_*:    [nlist, cap_list] (sqnorm f32 / valid bool / slot int32)
    probes:      [b, nprobe] int32
    queries:     [b, d]
    Returns raw SCORES (descending-better) + slots — shard_map-safe (no
    jit, no distance conversion) so the mesh-sharded IVF can merge scores
    across shards before converting; `_ivf_scan_kernel` is the single-
    device jitted wrapper.
    """
    b = queries.shape[0]
    nprobe = probes.shape[1]
    neg_inf = jnp.float32(-jnp.inf)

    def body(carry, r):
        best_vals, best_slots = carry
        lists_r = jnp.take(probes, r, axis=1)        # [b] (-1 = padded rank)
        rank_ok = lists_r >= 0
        lists_c = jnp.where(rank_ok, lists_r, 0)
        data = jnp.take(buckets, lists_c, axis=0)
        if not jnp.issubdtype(data.dtype, jnp.floating):
            # int8 stores (binary ivf): promote after the gather; float
            # stores (incl. bf16) keep their dtype — the einsum accumulates
            # in f32 via preferred_element_type either way
            data = data.astype(jnp.float32)
        sq = jnp.take(bucket_sqnorm, lists_c, axis=0)
        val = jnp.take(bucket_valid, lists_c, axis=0) & rank_ok[:, None]
        slot = jnp.take(bucket_slot, lists_c, axis=0)
        # per-query distance to its own bucket: einsum over d
        if metric is Metric.L2:
            dots = jnp.einsum(
                "bd,bcd->bc", queries, data,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            scores = -(squared_norms(queries)[:, None] - 2.0 * dots + sq)
        else:  # IP / cosine (queries pre-normalized for cosine)
            scores = jnp.einsum(
                "bd,bcd->bc", queries, data,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
        scores = jnp.where(val, scores, neg_inf)
        vals_r, idx_r = jax.lax.top_k(scores, min(k, scores.shape[1]))
        slots_r = jnp.take_along_axis(slot, idx_r, axis=1)
        slots_r = jnp.where(jnp.isneginf(vals_r), -1, slots_r)
        best_vals, best_slots = merge_topk(
            best_vals, best_slots, vals_r, slots_r, k
        )
        return (best_vals, best_slots), None

    init = (
        jnp.full((b, k), neg_inf, jnp.float32),
        jnp.full((b, k), -1, jnp.int32),
    )
    (vals, slots), _ = jax.lax.scan(body, init, jnp.arange(nprobe))
    return vals, slots


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _ivf_scan_kernel(
    buckets, bucket_sqnorm, bucket_valid, bucket_slot, probes, queries, k, metric
):
    vals, slots = ivf_scan_scores(
        buckets, bucket_sqnorm, bucket_valid, bucket_slot, probes, queries,
        k, metric,
    )
    return scores_to_distances(vals, metric), slots


class TpuIvfFlat(_SlotStoreIndex):
    #: metric the bucketed scan kernel runs with (the binary subclass scans
    #: with INNER_PRODUCT over ±1 vectors and converts to hamming after)
    _scan_metric: Metric

    def __init__(self, index_id: int, parameter: IndexParameter):
        VectorIndex.__init__(self, index_id, parameter)
        if parameter.dimension <= 0:
            raise InvalidParameter(f"dimension {parameter.dimension}")
        if parameter.ncentroids <= 0:
            raise InvalidParameter(f"ncentroids {parameter.ncentroids}")
        if parameter.metric is Metric.HAMMING and type(self) is TpuIvfFlat:
            raise InvalidParameter("use BINARY_IVF_FLAT for hamming")
        self._scan_metric = parameter.metric
        self.store = SlotStore(parameter.dimension, jnp.dtype(parameter.dtype))
        self.nlist = parameter.ncentroids
        self.centroids: Optional[jax.Array] = None       # [nlist, d]
        self._c_sqnorm: Optional[jax.Array] = None
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)
        self._layout: Optional[BucketLayout] = None
        self._buckets = None          # [B, cap_list, d]
        self._bucket_sqnorm = None
        self._view_dirty = True

    def _prep_vectors(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, np.float32)
        if vectors.ndim != 2 or vectors.shape[1] != self.dimension:
            raise InvalidParameter(
                f"vector dim {vectors.shape} != {self.dimension}"
            )
        if self.metric is Metric.COSINE:
            vectors = np.asarray(normalize(jnp.asarray(vectors)))
        return vectors

    def _prep_queries(self, queries: np.ndarray) -> np.ndarray:
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.shape[1] != self.dimension:
            raise InvalidParameter(
                f"query dim {queries.shape[1]} != {self.dimension}"
            )
        if self.metric is Metric.COSINE:
            queries = np.asarray(normalize(jnp.asarray(queries)))
        return queries

    # -- mutation: track assignments ---------------------------------------
    def upsert(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        vectors = self._prep_vectors(vectors)
        if len(ids) != len(vectors):
            raise InvalidParameter("ids/vectors length mismatch")
        slots = self.store.put(np.asarray(ids, np.int64), vectors)
        if self._assign_h.shape[0] < self.store.capacity:
            grown = np.full((self.store.capacity,), -1, np.int32)
            grown[: self._assign_h.shape[0]] = self._assign_h
            self._assign_h = grown
        if self.is_trained():
            assign = np.asarray(kmeans_assign(jnp.asarray(vectors), self.centroids))
            self._assign_h[slots] = assign
        self._view_dirty = True
        self.write_count_since_save += len(ids)

    def delete(self, ids: np.ndarray) -> None:
        removed = self.store.remove(np.asarray(ids, np.int64))
        self._view_dirty = True
        self.write_count_since_save += removed

    # -- training ----------------------------------------------------------
    def need_train(self) -> bool:
        return True

    def is_trained(self) -> bool:
        return self.centroids is not None

    def train(self, vectors: Optional[np.ndarray] = None) -> None:
        """Train the coarse quantizer. With no explicit train set, samples
        the stored vectors (VectorIndexManager::TrainForBuild samples the
        region, vector_index_manager.cc:1365)."""
        if vectors is None:
            snap = self.store.to_host()
            vectors = snap["vectors"]
        vectors = np.asarray(vectors, np.float32)
        if len(vectors) < self.nlist:
            raise NotTrained(
                f"need >= {self.nlist} train vectors, have {len(vectors)}"
            )
        if self.metric is Metric.COSINE:
            vectors = np.asarray(normalize(jnp.asarray(vectors)))
        cap = MAX_POINTS_PER_CENTROID * self.nlist
        if len(vectors) > cap:
            sel = np.random.default_rng(self.id).choice(
                len(vectors), cap, replace=False
            )
            vectors = vectors[sel]
        self.centroids, _ = train_kmeans(
            jnp.asarray(vectors), k=self.nlist, iters=10, seed=self.id
        )
        self._c_sqnorm = squared_norms(self.centroids)
        # (re)assign everything currently stored
        live = np.flatnonzero(self.store.ids_by_slot >= 0)
        if len(live):
            _, vecs = self.store.gather(self.store.ids_by_slot[live])
            assign = np.asarray(kmeans_assign(jnp.asarray(vecs), self.centroids))
            self._assign_h[live] = assign
        self._view_dirty = True

    # -- bucketed view ------------------------------------------------------
    def _rebuild_view(self) -> None:
        """Group live slots into fixed-width spill buckets (ivf_layout.py)."""
        lay = build_layout(self._assign_h, self.store.valid_h, self.nlist)
        self._layout = lay
        with self.store.device_lock:   # gather reads store.vecs (donatable)
            self._buckets = lay.gather_rows(self.store.vecs)
            self._bucket_sqnorm = jnp.take(
                self.store.sqnorm, lay.gather_idx
            ).reshape(lay.nbuckets, lay.cap_list)
        self._view_dirty = False

    def _bucket_valid_for_filter(self, filter_spec: Optional[FilterSpec]):
        if filter_spec is None or filter_spec.is_empty():
            return self._layout.bucket_valid
        mask = filter_spec.slot_mask(self.store.ids_by_slot)
        bucket_slot = self._layout.bucket_slot_h
        safe = np.where(bucket_slot >= 0, bucket_slot, 0)
        bmask = mask[safe] & (bucket_slot >= 0)
        return jnp.asarray(bmask)

    # -- search -------------------------------------------------------------
    def search(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
        nprobe: Optional[int] = None,
    ) -> List[SearchResult]:
        return self.search_async(queries, topk, filter_spec, nprobe)()

    def search_async(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
        nprobe: Optional[int] = None,
    ):
        if not self.is_trained():
            raise NotTrained("IVF_FLAT not trained")  # reader falls back
        queries = self._prep_queries(queries)
        if self._view_dirty:
            self._rebuild_view()
        b = queries.shape[0]
        nprobe = min(nprobe or self.parameter.default_nprobe, self.nlist)
        qpad = jnp.asarray(_pad_batch(queries))
        lay = self._layout
        # lease BEFORE dispatch: kernel slots must stay limbo-parked until
        # resolve translates them (delete+reinsert would misattribute)
        lease = self.store.begin_search()
        try:
            probes = _probe_lists(qpad, self.centroids, self._c_sqnorm, nprobe)
            vprobes = expand_probes(
                probes, lay.probe_table, nprobe, lay.max_spill
            )
            valid = self._bucket_valid_for_filter(filter_spec)
            from dingo_tpu.common.config import pallas_ivf_enabled

            if (
                pallas_ivf_enabled(self.dimension)
                and self.metric in (
                    Metric.L2, Metric.INNER_PRODUCT, Metric.COSINE
                )
                and self.store.vecs.dtype in (jnp.float32, jnp.bfloat16)
                # kernel keeps top-k in a 128-lane output block; larger k
                # (and its unrolled select rounds) stays on the XLA path
                and int(topk) <= 64
            ):
                from dingo_tpu.ops.distance import metric_ascending
                from dingo_tpu.ops.pallas_ivf import ivf_list_search

                vals, slots = ivf_list_search(
                    vprobes, qpad, self._buckets, self._bucket_sqnorm,
                    valid, lay.bucket_slot, k=int(topk),
                    ascending=metric_ascending(self._scan_metric),
                )
                dists = scores_to_distances(vals, self._scan_metric)
            else:
                dists, slots = _ivf_scan_kernel(
                    self._buckets,
                    self._bucket_sqnorm,
                    valid,
                    lay.bucket_slot,
                    vprobes,
                    qpad,
                    k=int(topk),
                    metric=self._scan_metric,
                )
        except Exception:
            lease.release()
            raise
        store = self.store
        dists.copy_to_host_async()
        slots.copy_to_host_async()
        def resolve() -> List[SearchResult]:
            try:
                dists_h, slots_h = jax.device_get((dists, slots))
                ids = store.ids_of_slots(slots_h[:b])
                dists_h = self._convert_distances(dists_h)
                return [strip_invalid(i, d) for i, d in zip(ids, dists_h[:b])]
            finally:
                lease.release()

        return resolve

    # -- lifecycle -----------------------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        snap = self.store.to_host()
        extras = {}
        if self.is_trained():
            extras["centroids"] = np.asarray(self.centroids)
            live = self.store.ids_by_slot >= 0
            extras["assign"] = self._assign_h[np.flatnonzero(live)]
        np.savez(os.path.join(path, "ivf_flat.npz"), **snap, **extras)
        meta = self._save_meta()
        meta["nlist"] = self.nlist
        meta["trained"] = self.is_trained()
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self._check_meta(meta)
        if meta["nlist"] != self.nlist:
            raise InvalidParameter(
                f"snapshot nlist {meta['nlist']} != {self.nlist}"
            )
        data = np.load(os.path.join(path, "ivf_flat.npz"))
        self.store = SlotStore(self.dimension, jnp.dtype(self.parameter.dtype),
                               max(len(data["ids"]), 1))
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)
        self.centroids = None
        self._c_sqnorm = None
        if len(data["ids"]):
            # bypass upsert's assignment (we restore it directly)
            vecs = data["vectors"]
            if self.metric is Metric.COSINE:
                vecs = np.asarray(normalize(jnp.asarray(vecs)))
            slots = self.store.put(np.asarray(data["ids"], np.int64), vecs)
        else:
            slots = np.empty(0, np.int64)
        if self._assign_h.shape[0] < self.store.capacity:
            grown = np.full((self.store.capacity,), -1, np.int32)
            grown[: self._assign_h.shape[0]] = self._assign_h
            self._assign_h = grown
        if meta.get("trained"):
            self.centroids = jnp.asarray(data["centroids"])
            self._c_sqnorm = squared_norms(self.centroids)
            self._assign_h[slots] = data["assign"]
        self.apply_log_id = meta["apply_log_id"]
        self._view_dirty = True
        self.write_count_since_save = 0


class TpuBinaryIvfFlat(BinaryPm1Mixin, TpuIvfFlat):
    """Binary (bit-packed) IVF with hamming list scan.

    Reference: faiss::IndexBinaryIVF behind the NewBinaryIVFFlat factory arm
    (vector_index_factory.h:37-68; vector_index_ivf_flat.cc:60-62).
    dimension is in BITS; the wire format is [n, dimension//8] uint8 rows.

    TPU-first: vectors unpack once at write time into a ±1 int8 store (same
    trick as TpuBinaryFlat), so the coarse quantizer is plain float k-means
    over ±1 space and the list scan is an int8 MXU matmul —
    hamming(a, b) = (nbits - <pm(a), pm(b)>) / 2. Centroids stay float
    (fractional centroids order candidate lists strictly better than
    re-binarized ones; faiss quantizes them because CPU hamming is its only
    fast kernel, a constraint the MXU does not have).
    """

    def __init__(self, index_id: int, parameter: IndexParameter):
        if parameter.dimension <= 0 or parameter.dimension % 8:
            raise InvalidParameter("binary dimension must be multiple of 8")
        super().__init__(index_id, parameter)
        self.nbytes = parameter.dimension // 8
        self.store = SlotStore(parameter.dimension, jnp.int8)
        self._scan_metric = Metric.INNER_PRODUCT
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)

    # packed <-> ±1 codec + distance conversion come from BinaryPm1Mixin

    def train(self, vectors: Optional[np.ndarray] = None) -> None:
        """Float k-means over ±1 space. An explicit train set arrives
        bit-packed (the wire format); the implicit path samples the already-
        unpacked store."""
        if vectors is not None:
            vectors = self._prep_vectors(vectors)
        super().train(vectors)

    # -- lifecycle (packed on disk) -----------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        snap = self.store.to_host()
        extras = {}
        if self.is_trained():
            extras["centroids"] = np.asarray(self.centroids)
            live = self.store.ids_by_slot >= 0
            extras["assign"] = self._assign_h[np.flatnonzero(live)]
        np.savez(
            os.path.join(path, "binary_ivf_flat.npz"),
            ids=snap["ids"],
            vectors=self._repack(snap["vectors"]),
            **extras,
        )
        meta = self._save_meta()
        meta["nlist"] = self.nlist
        meta["trained"] = self.is_trained()
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self._check_meta(meta)
        if meta["nlist"] != self.nlist:
            raise InvalidParameter(
                f"snapshot nlist {meta['nlist']} != {self.nlist}"
            )
        data = np.load(os.path.join(path, "binary_ivf_flat.npz"))
        self.store = SlotStore(self.dimension, jnp.int8,
                               max(len(data["ids"]), 1))
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)
        self.centroids = None
        self._c_sqnorm = None
        if len(data["ids"]):
            slots = self.store.put(
                np.asarray(data["ids"], np.int64),
                self._unpack_pm1(np.asarray(data["vectors"], np.uint8)),
            )
        else:
            slots = np.empty(0, np.int64)
        if self._assign_h.shape[0] < self.store.capacity:
            grown = np.full((self.store.capacity,), -1, np.int32)
            grown[: self._assign_h.shape[0]] = self._assign_h
            self._assign_h = grown
        if meta.get("trained"):
            self.centroids = jnp.asarray(data["centroids"])
            self._c_sqnorm = squared_norms(self.centroids)
            self._assign_h[slots] = data["assign"]
        self.apply_log_id = meta["apply_log_id"]
        self._view_dirty = True
        self.write_count_since_save = 0
