"""Device-failure recovery: the graduated HBM OOM ladder + degraded mode.

A device allocation failure (real XlaRuntimeError RESOURCE_EXHAUSTED, or
the chaos shim's indistinguishable InjectedDeviceFault — ops/devfault.py)
during an index write or search used to propagate raw: a raft apply would
fail, a search would 500. The ladder turns it into graceful degradation:

  rung 1  drop_rerank   — free the region's DeviceRerankCache (bf16/sq8
                          tiers; recall-advisory, rebuilt by future offers)
  rung 2  evict_mirrors — free the dimension-blocked scan mirror and the
                          HNSW adjacency mirror (both are DERIVED copies;
                          the pruned/beam kernels fall back to the dense
                          paths that gate on `vecs_blk is not None` /
                          re-export lazily)
  rung 3  retry         — re-run the failed op once against the slimmer
                          footprint (index mutations are upserts/deletes:
                          idempotent, safe to re-apply)

If the retry still OOMs the region goes **device-degraded**: writes stop
materializing into the device index (the engine — raft/WAL — remains the
source of truth and keeps every write; apply_log_id does NOT advance, so
replica digest comparisons at equal applied indices stay sound), searches
are served exact from the engine via the host path
(vector_reader._host_exact_search), the heartbeat carries a
device_degraded flag (`cluster top` shows DEV-DEGRADED), and a background
re-materialization rebuilds the index from the engine at an
advisory-lower precision tier (device_recovery.remat_precision) — the
region DEFINITION keeps its declared precision, only the resident build
narrows. On success the region exits degraded mode with full parity.

The same plane owns the scrub-corruption response: a region whose
integrity scrub confirmed a device-state mismatch (PR 11) is rebuilt
from the engine — rebuild-from-truth, same mechanism, no precision drop.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from dingo_tpu.common.log import get_logger, region_log
from dingo_tpu.common.metrics import METRICS

_log = get_logger("index.recovery")

#: ladder rung names (metric label values for fault.oom_recoveries)
RUNG_DROP_RERANK = "drop_rerank"
RUNG_EVICT_MIRRORS = "evict_mirrors"
RUNG_RETRY = "retry"
RUNG_DEGRADE = "degrade"


class DeviceDegraded(RuntimeError):
    """The ladder was exhausted: the region is now device-degraded and the
    op must be absorbed by the degraded path (host search / engine-only
    write), not retried against the device."""

    def __init__(self, region_id: int, cause: str = ""):
        super().__init__(
            f"region {region_id} device-degraded"
            + (f" ({cause})" if cause else "")
        )
        self.region_id = region_id


def _looks_like_oom(exc: BaseException) -> bool:
    from dingo_tpu.obs.hbm import looks_like_oom

    return looks_like_oom(exc)


class DeviceRecoveryPlane:
    """Process-global degraded-region registry + the OOM ladder."""

    def __init__(self, registry=METRICS):
        self._lock = threading.Lock()
        #: region_id -> {"reason", "since", "remat_pending"}
        self._degraded: Dict[int, Dict[str, Any]] = {}
        self._reg = registry
        self.ladder_runs = 0

    @staticmethod
    def enabled() -> bool:
        from dingo_tpu.common.config import FLAGS

        return bool(FLAGS.get("device_recovery_enabled"))

    # -- degraded registry ---------------------------------------------------
    def is_degraded(self, region_id: int) -> bool:
        if not self._degraded:      # serving fast path: one attribute read
            return False
        with self._lock:
            return region_id in self._degraded

    def degraded_regions(self) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            return {rid: dict(info) for rid, info in self._degraded.items()}

    def mark_degraded(self, region_id: int, reason: str) -> None:
        with self._lock:
            fresh = region_id not in self._degraded
            self._degraded[region_id] = {
                "reason": reason,
                "since": time.time(),
                "remat_pending": True,
            }
            n = len(self._degraded)
        if fresh:
            self._reg.counter("fault.oom_recoveries",
                              labels={"rung": RUNG_DEGRADE}).add(1)
            from dingo_tpu.obs.events import EVENTS

            EVENTS.emit(
                "recovery", region_id, "device_degraded", 0, 1,
                trigger="oom",
                evidence={"rung": RUNG_DEGRADE, "reason": reason},
            )
            region_log(_log, region_id).error(
                "region device-degraded (%s): serving host-exact, "
                "device writes deferred to re-materialization", reason)
        self._reg.gauge("fault.degraded_regions").set(float(n))
        # published (digest, applied) pairs can be torn by the partial
        # device write that stranded us here — withhold this region's
        # verdict until the re-materialized index re-primes the ledger
        from dingo_tpu.obs.integrity import INTEGRITY

        INTEGRITY.forget_region(region_id)

    def clear_degraded(self, region_id: int) -> None:
        with self._lock:
            self._degraded.pop(region_id, None)
            n = len(self._degraded)
        self._reg.gauge("fault.degraded_regions").set(float(n))

    # -- the ladder ----------------------------------------------------------
    def attempt(self, wrapper, region_id: int, op: Callable[[], Any],
                kind: str = "op", cause: Optional[BaseException] = None):
        """Run `op()` with OOM recovery: on an OOM-classified failure walk
        the ladder (drop rerank -> evict mirrors) and retry once; a second
        OOM marks the region degraded and raises DeviceDegraded. Non-OOM
        exceptions propagate untouched. Pass `cause` when the caller
        already caught the first OOM itself — the initial run is skipped
        and the ladder starts immediately."""
        first = cause
        if first is None:
            try:
                return op()
            except Exception as e:  # noqa: BLE001 — classified below
                if not _looks_like_oom(e) or not self.enabled():
                    raise
                first = e
        t0 = time.perf_counter()
        self.ladder_runs += 1
        region_log(_log, region_id).warning(
            "device OOM during %s (%s: %s) — running recovery ladder",
            kind, type(first).__name__, first)
        self._run_ladder(wrapper, region_id)
        try:
            out = op()
        except Exception as e2:  # noqa: BLE001
            if not _looks_like_oom(e2):
                raise
            self.mark_degraded(region_id, f"oom during {kind}")
            self._reg.latency("fault.recovery_ms").observe_us(
                (time.perf_counter() - t0) * 1e6)
            raise DeviceDegraded(region_id, f"oom during {kind}") from e2
        self._reg.counter("fault.oom_recoveries",
                          labels={"rung": RUNG_RETRY}).add(1)
        self._reg.latency("fault.recovery_ms").observe_us(
            (time.perf_counter() - t0) * 1e6)
        region_log(_log, region_id).info(
            "device OOM recovered by ladder retry (%s)", kind)
        return out

    def _run_ladder(self, wrapper, region_id: int) -> None:
        from dingo_tpu.obs.events import EVENTS

        idx = getattr(wrapper, "own_index", None) if wrapper else None
        if idx is None:
            return
        if self._drop_rerank(idx):
            self._reg.counter("fault.oom_recoveries",
                              labels={"rung": RUNG_DROP_RERANK}).add(1)
            EVENTS.emit("recovery", region_id, "recovery_rung", "",
                        RUNG_DROP_RERANK, trigger="oom",
                        evidence={"rung": RUNG_DROP_RERANK})
        if self._evict_mirrors(idx):
            self._reg.counter("fault.oom_recoveries",
                              labels={"rung": RUNG_EVICT_MIRRORS}).add(1)
            EVENTS.emit("recovery", region_id, "recovery_rung", "",
                        RUNG_EVICT_MIRRORS, trigger="oom",
                        evidence={"rung": RUNG_EVICT_MIRRORS})

    @staticmethod
    def _drop_rerank(idx) -> bool:
        if getattr(idx, "_rerank_cache", None) is None:
            return False
        idx._rerank_cache = None
        return True

    @staticmethod
    def _evict_mirrors(idx) -> bool:
        store = getattr(idx, "store", None)
        if store is None:
            return False
        freed = False
        lock = getattr(store, "device_lock", None)
        import contextlib

        with (lock if lock is not None else contextlib.nullcontext()):
            if getattr(store, "vecs_blk", None) is not None:
                # the pruned streaming kernel gates on `vecs_blk is not
                # None` (index/flat.py) and the write path skips the
                # mirror when absent — dropping it is a clean fallback
                # to the dense scan, not a correctness change
                store.vecs_blk = None
                store.bsq_blk = None
                freed = True
            if getattr(store, "adj", None) is not None:
                # HNSW re-exports adjacency lazily on the next device
                # search; until then the host beam fallback serves
                store.adj = None
                store.graph_deg = 0
                if hasattr(idx, "_graph_key"):
                    idx._graph_key = None
                freed = True
        return freed

    # -- re-materialization --------------------------------------------------
    @staticmethod
    def remat_parameter(param):
        """The advisory-lower-precision build parameter for a degraded
        region's re-materialization. The region definition is untouched —
        this narrows only the resident rebuild. Thin shim over the ONE
        shared precision-override helper (index/manager.py
        precision_override, also the tier ladder's arm)."""
        from dingo_tpu.common.config import FLAGS
        from dingo_tpu.index.manager import precision_override

        target = str(FLAGS.get("device_recovery_remat_precision"))
        return precision_override(param, target)

    def rematerialize(self, manager, region, raft_log=None) -> bool:
        """Rebuild a degraded region's index from the engine (source of
        truth) at the advisory-lower precision, then exit degraded mode.
        Returns False when a rebuild is already in flight (retried by the
        next maintenance tick). Rides manager.rebuild_at_precision — the
        same arm the deliberate tier ladder uses — so the emergency path
        has no private rebuild copy."""
        from dingo_tpu.common.config import FLAGS

        rid = region.id
        target = str(FLAGS.get("device_recovery_remat_precision"))
        try:
            ok = manager.rebuild_at_precision(region, raft_log=raft_log,
                                              precision=target)
        except Exception:
            region_log(_log, rid).exception("re-materialization failed")
            return False
        if not ok:
            return False
        self._reg.counter("fault.rematerializations").add(1)
        # remat rides the streaming bulk-build arm (ISSUE 18c): repair
        # time IS degraded-serving time, so the build plane counts remats
        # next to its rows/batches series
        self._reg.counter("build.remat_rebuilds", region_id=rid).add(1)
        from dingo_tpu.obs.events import EVENTS

        EVENTS.emit(
            "recovery", rid, "device_degraded", 1, 0, trigger="remat",
            evidence={"precision": target or "default"},
        )
        self.clear_degraded(rid)
        region_log(_log, rid).info(
            "re-materialized from engine at precision=%s — degraded "
            "mode cleared", target or "default")
        return True

    def run_rematerializations(self, node) -> int:
        """Maintenance-tick body (rides the integrity scrub crontab):
        re-materialize every degraded region of `node`, and rebuild-from-
        engine every region whose scrub confirmed device-state corruption
        (the PR 11 poisoned-array response)."""
        n = 0
        pending = self.degraded_regions()
        for rid, info in pending.items():
            if not info.get("remat_pending"):
                continue
            region = node.meta.get_region(rid)
            if region is None:                 # region gone: just clear
                self.clear_degraded(rid)
                continue
            raft_node = node.engine.get_node(rid)
            raft_log = raft_node.log if raft_node is not None else None
            if self.rematerialize(node.index_manager, region,
                                  raft_log=raft_log):
                n += 1
        n += self._rebuild_corrupted(node)
        return n

    def _rebuild_corrupted(self, node) -> int:
        """Scrub-confirmed mismatches: rebuild the poisoned index from the
        engine. The scrub status holds ``mismatch=True`` until a clean
        decisive pass over the REBUILT index clears it."""
        from dingo_tpu.obs.integrity import INTEGRITY

        n = 0
        for region in node.meta.get_all_regions():
            _a, _d, mismatch = INTEGRITY.region_report(None, region.id)
            if not mismatch:
                continue
            wrapper = region.vector_index_wrapper
            if wrapper is None or wrapper.own_index is None:
                continue
            raft_node = node.engine.get_node(region.id)
            raft_log = raft_node.log if raft_node is not None else None
            try:
                if node.index_manager.rebuild(region, raft_log=raft_log):
                    self._reg.counter("fault.rebuilds").add(1)
                    # fresh index, fresh ledger; the stale CORRUPT verdict
                    # belongs to the poisoned index that no longer serves
                    INTEGRITY.forget_region(region.id)
                    INTEGRITY.rebuild_from_index(wrapper.own_index)
                    region_log(_log, region.id).warning(
                        "corrupted device state rebuilt from engine")
                    n += 1
            except Exception:
                region_log(_log, region.id).exception(
                    "corruption rebuild failed")
        return n

    def clear(self) -> None:
        with self._lock:
            self._degraded.clear()
        self._reg.gauge("fault.degraded_regions").set(0.0)


#: process-global plane (one device; regions share the HBM failure domain)
RECOVERY = DeviceRecoveryPlane()
