"""Typed memcomparable value/row serialization.

Reference: the dingo-serial submodule (src/serial/) provides schema-typed
memcomparable row/key encoding so table keys sort correctly in the KV space;
SURVEY.md §2.4 requires the *behavior* (order-preserving typed encoding).
Original implementation: tagged, order-preserving encodings for null / bool /
int64 / float64 / string, composable into multi-column keys.

Ordering rules:
  null < bool < int < float < string   (type tag orders first)
  int64:  offset-binary (x ^ sign bit) big-endian
  float64: IEEE754 with sign-dependent bit flip (standard memcomparable trick)
  string: memcomparable byte groups (mvcc codec scheme)
"""

from __future__ import annotations

import struct
from typing import Any, List, Sequence, Tuple

from dingo_tpu.mvcc.codec import Codec

_TAG_NULL = 0x01
_TAG_BOOL = 0x02
_TAG_INT = 0x03
_TAG_FLOAT = 0x04
_TAG_STR = 0x05


def encode_value(v: Any) -> bytes:
    if v is None:
        return bytes([_TAG_NULL])
    if isinstance(v, bool):
        return bytes([_TAG_BOOL, 1 if v else 0])
    if isinstance(v, int):
        return bytes([_TAG_INT]) + struct.pack(">Q", (v + (1 << 63)) & ((1 << 64) - 1))
    if isinstance(v, float):
        bits = struct.unpack(">Q", struct.pack(">d", v))[0]
        if bits & (1 << 63):
            bits ^= (1 << 64) - 1          # negative: flip all
        else:
            bits ^= 1 << 63                # positive: flip sign
        return bytes([_TAG_FLOAT]) + struct.pack(">Q", bits)
    if isinstance(v, str):
        return bytes([_TAG_STR]) + Codec.encode_bytes(v.encode("utf-8"))
    if isinstance(v, bytes):
        return bytes([_TAG_STR]) + Codec.encode_bytes(v)
    raise TypeError(f"unencodable type {type(v)}")


def decode_value(data: bytes, offset: int = 0) -> Tuple[Any, int]:
    """Returns (value, next_offset)."""
    tag = data[offset]
    offset += 1
    if tag == _TAG_NULL:
        return None, offset
    if tag == _TAG_BOOL:
        return bool(data[offset]), offset + 1
    if tag == _TAG_INT:
        (raw,) = struct.unpack(">Q", data[offset:offset + 8])
        return raw - (1 << 63), offset + 8
    if tag == _TAG_FLOAT:
        (bits,) = struct.unpack(">Q", data[offset:offset + 8])
        if bits & (1 << 63):
            bits ^= 1 << 63
        else:
            bits ^= (1 << 64) - 1
        return struct.unpack(">d", struct.pack(">Q", bits))[0], offset + 8
    if tag == _TAG_STR:
        raw, consumed = Codec.decode_bytes(data[offset:])
        try:
            return raw.decode("utf-8"), offset + consumed
        except UnicodeDecodeError:
            return raw, offset + consumed
    raise ValueError(f"bad tag {tag:#x}")


def encode_row_key(values: Sequence[Any]) -> bytes:
    """Multi-column memcomparable key: tuple ordering == byte ordering."""
    return b"".join(encode_value(v) for v in values)


def decode_row_key(data: bytes) -> List[Any]:
    out: List[Any] = []
    offset = 0
    while offset < len(data):
        v, offset = decode_value(data, offset)
        out.append(v)
    return out
