"""Percolator transaction tests (mirrors reference test/unit_test/txn/:
prewrite/commit, conflicts, pessimistic locks, resolve, GC — directly against
the txn engine + a raw engine, no RPC)."""

import time

import numpy as np
import pytest

from dingo_tpu.engine.concurrency import ConcurrencyManager
from dingo_tpu.engine.mono_engine import MonoStoreEngine
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.engine.txn import (
    KeyIsLocked,
    Mutation,
    Op,
    TxnEngine,
    TxnNotFound,
    WriteConflict,
)
from dingo_tpu.store.region import Region, RegionDefinition


def make_txn():
    region = Region(RegionDefinition(
        region_id=1, start_key=b"", end_key=b"\xff" * 8
    ))
    engine = MonoStoreEngine(MemEngine())
    return TxnEngine(engine, region)


def test_prewrite_commit_get():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"a", b"1"), Mutation(Op.PUT, b"b", b"2")],
               primary=b"a", start_ts=10)
    # uncommitted: reads at ts>=10 see the lock
    with pytest.raises(KeyIsLocked):
        t.get(b"a", 15)
    assert t.get(b"a", 5) is None  # before the txn: no lock conflict
    t.commit([b"a", b"b"], start_ts=10, commit_ts=20)
    assert t.get(b"a", 25) == b"1"
    assert t.get(b"a", 15) is None  # snapshot before commit
    assert t.get(b"b", 25) == b"2"


def test_delete_and_overwrite_versions():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"v1")], b"k", 10)
    t.commit([b"k"], 10, 11)
    t.prewrite([Mutation(Op.PUT, b"k", b"v2")], b"k", 20)
    t.commit([b"k"], 20, 21)
    t.prewrite([Mutation(Op.DELETE, b"k")], b"k", 30)
    t.commit([b"k"], 30, 31)
    assert t.get(b"k", 15) == b"v1"
    assert t.get(b"k", 25) == b"v2"
    assert t.get(b"k", 35) is None


def test_write_conflict():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"x")], b"k", 10)
    t.commit([b"k"], 10, 15)
    # txn that started before the commit must conflict
    with pytest.raises(WriteConflict):
        t.prewrite([Mutation(Op.PUT, b"k", b"y")], b"k", 12)
    # txn starting after is fine
    t.prewrite([Mutation(Op.PUT, b"k", b"z")], b"k", 20)


def test_lock_blocks_other_txn():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"x")], b"k", 10)
    with pytest.raises(KeyIsLocked):
        t.prewrite([Mutation(Op.PUT, b"k", b"y")], b"k", 11)
    # same txn retries prewrite idempotently
    t.prewrite([Mutation(Op.PUT, b"k", b"x")], b"k", 10)


def test_rollback_then_late_prewrite_fails():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"x")], b"k", 10)
    t.batch_rollback([b"k"], 10)
    assert t.get(b"k", 20) is None
    # the rollback tombstone blocks a late prewrite of the SAME txn
    with pytest.raises(WriteConflict):
        t.prewrite([Mutation(Op.PUT, b"k", b"x")], b"k", 10)


def test_commit_idempotent_and_missing():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"x")], b"k", 10)
    t.commit([b"k"], 10, 20)
    t.commit([b"k"], 10, 20)  # idempotent
    with pytest.raises(TxnNotFound):
        t.commit([b"q"], 99, 100)


def test_pessimistic_flow():
    t = make_txn()
    t.pessimistic_lock([b"k"], b"k", start_ts=10, for_update_ts=10)
    # other txn blocked
    with pytest.raises(KeyIsLocked):
        t.pessimistic_lock([b"k"], b"k", start_ts=11, for_update_ts=11)
    # reads are NOT blocked by a pessimistic lock
    assert t.get(b"k", 15) is None
    # convert to real write
    t.prewrite([Mutation(Op.PUT, b"k", b"v")], b"k", 10)
    t.commit([b"k"], 10, 20)
    assert t.get(b"k", 25) == b"v"


def test_pessimistic_rollback():
    t = make_txn()
    t.pessimistic_lock([b"k"], b"k", 10, 10)
    t.pessimistic_rollback([b"k"], 10)
    t.pessimistic_lock([b"k"], b"k", 11, 11)  # now free


def test_check_txn_status_expired_lock():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"v")], b"k", 10, lock_ttl_ms=1)
    time.sleep(0.01)
    st = t.check_txn_status(b"k", 10, caller_start_ts=50)
    assert st["action"] == "rolled_back"
    # secondary resolution: txn rolled back everywhere
    with pytest.raises(WriteConflict):
        t.prewrite([Mutation(Op.PUT, b"k", b"v")], b"k", 10)


def test_check_txn_status_committed():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"v")], b"k", 10)
    t.commit([b"k"], 10, 20)
    st = t.check_txn_status(b"k", 10, 50)
    assert st == {"action": "committed", "commit_ts": 20}


def test_resolve_lock_commits_secondaries():
    t = make_txn()
    t.prewrite(
        [Mutation(Op.PUT, b"a", b"1"), Mutation(Op.PUT, b"b", b"2"),
         Mutation(Op.PUT, b"c", b"3")],
        b"a", 10,
    )
    t.commit([b"a"], 10, 20)       # primary committed, secondaries stranded
    n = t.resolve_lock(10, 20)     # scans for leftover locks
    assert n == 2
    assert t.get(b"b", 25) == b"2" and t.get(b"c", 25) == b"3"


def test_resolve_lock_rollback():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"a", b"1")], b"a", 10)
    t.resolve_lock(10, 0)
    assert t.get(b"a", 20) is None


def test_heart_beat_extends_ttl():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"v")], b"k", 10, lock_ttl_ms=100)
    ttl = t.heart_beat(b"k", 10, 60_000)
    assert ttl == 60_000
    st = t.check_txn_status(b"k", 10, 50)
    assert st["action"] == "locked"


def test_scan_snapshot():
    t = make_txn()
    for i, key in enumerate([b"a", b"b", b"c", b"d"]):
        t.prewrite([Mutation(Op.PUT, key, b"v%d" % i)], key, 10 + i)
        t.commit([key], 10 + i, 20 + i)
    t.prewrite([Mutation(Op.DELETE, b"b")], b"b", 40)
    t.commit([b"b"], 40, 41)
    got = t.scan(b"a", b"z", read_ts=50)
    assert [k for k, _ in got] == [b"a", b"c", b"d"]
    got25 = t.scan(b"a", b"z", read_ts=22)
    assert [k for k, _ in got25] == [b"a", b"b", b"c"]
    got_lim = t.scan(b"a", b"z", read_ts=50, limit=2)
    assert len(got_lim) == 2


def test_scan_hits_lock():
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"a", b"1")], b"a", 10)
    t.commit([b"a"], 10, 20)
    t.prewrite([Mutation(Op.PUT, b"b", b"2")], b"b", 30)
    with pytest.raises(KeyIsLocked):
        t.scan(b"a", b"z", read_ts=35)
    # read below the lock ts is fine
    assert [k for k, _ in t.scan(b"a", b"z", read_ts=25)] == [b"a"]


def test_gc_drops_old_versions():
    t = make_txn()
    for ts in (10, 20, 30):
        t.prewrite([Mutation(Op.PUT, b"k", b"v%d" % ts)], b"k", ts)
        t.commit([b"k"], ts, ts + 1)
    t.prewrite([Mutation(Op.PUT, b"dead", b"x")], b"dead", 40)
    t.commit([b"dead"], 40, 41)
    t.prewrite([Mutation(Op.DELETE, b"dead")], b"dead", 50)
    t.commit([b"dead"], 50, 51)
    removed = t.gc(safe_ts=60)
    assert removed > 0
    # newest version of k survives; old ones gone
    assert t.get(b"k", 100) == b"v30"
    assert t.get(b"k", 25) is None  # history below safe point dropped
    # fully-deleted key wiped
    assert t.get(b"dead", 100) is None


def test_latches_serialize():
    cm = ConcurrencyManager()
    order = []
    import threading

    def worker(tag):
        with cm.with_keys([b"x", b"y"]):
            order.append(f"{tag}-in")
            time.sleep(0.02)
            order.append(f"{tag}-out")

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    # no interleaving inside the critical section
    for i in range(0, 6, 2):
        assert order[i].endswith("-in") and order[i + 1].endswith("-out")
        assert order[i].split("-")[0] == order[i + 1].split("-")[0]


def test_commit_bare_pessimistic_lock_rejected():
    """Regression: a pessimistic lock with no prewrite has no data row —
    committing it must not fabricate a phantom PUT."""
    from dingo_tpu.engine.txn import LockTypeMismatch

    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"old")], b"k", 5)
    t.commit([b"k"], 5, 6)
    t.pessimistic_lock([b"k"], b"k", 10, 10)
    with pytest.raises(LockTypeMismatch):
        t.commit([b"k"], 10, 20)
    # resolve_lock rolls the bare pessimistic lock back, old value survives
    t.resolve_lock(10, 20)
    assert t.get(b"k", 30) == b"old"


def test_pessimistic_conflict_behind_rollback_record():
    """Regression: a newest ROLLBACK record must not hide a real committed
    write from the for_update_ts conflict check."""
    t = make_txn()
    t.prewrite([Mutation(Op.PUT, b"k", b"v")], b"k", 80)
    t.commit([b"k"], 80, 90)
    t.batch_rollback([b"k"], 100)  # rollback tombstone at ts 100
    with pytest.raises(WriteConflict):
        t.pessimistic_lock([b"k"], b"k", start_ts=55, for_update_ts=50)


def test_concurrent_prewrite_same_key_excluded():
    import threading

    t = make_txn()
    errors = []

    def worker(ts):
        try:
            t.prewrite([Mutation(Op.PUT, b"k", b"v%d" % ts)], b"k", ts)
        except KeyIsLocked as e:
            errors.append(e)

    ths = [threading.Thread(target=worker, args=(ts,)) for ts in (10, 11)]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    assert len(errors) == 1  # exactly one lost the race
