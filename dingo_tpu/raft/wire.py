"""Typed wire codec for raft RPC payloads.

The reference transports typed protobuf messages over braft/brpc; round 1
used pickle here, which turns the raft port into arbitrary code execution
for anyone who can reach it. Raft messages are plain trees of
None/bool/int/float/str/bytes/list/tuple/dict, so a tag-length-value codec
covers them exactly — decoding allocates only those types and can never
execute code. Tuples decode as lists (callers only iterate/unpack).
"""

from __future__ import annotations

import struct
from typing import Any

_NONE, _TRUE, _FALSE, _INT, _FLOAT, _STR, _BYTES, _LIST, _DICT = range(9)

_MAX_DEPTH = 32


class WireError(ValueError):
    pass


def _enc(obj: Any, out: list, depth: int) -> None:
    if depth > _MAX_DEPTH:
        raise WireError("encode: nesting too deep")
    if obj is None:
        out.append(bytes([_NONE]))
    elif obj is True:
        out.append(bytes([_TRUE]))
    elif obj is False:
        out.append(bytes([_FALSE]))
    elif isinstance(obj, int):
        if not -(2**63) <= obj < 2**63:
            raise WireError(f"int out of signed-64 range: {obj}")
        out.append(struct.pack(">Bq", _INT, obj))
    elif isinstance(obj, float):
        out.append(struct.pack(">Bd", _FLOAT, obj))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(struct.pack(">BQ", _STR, len(raw)))
        out.append(raw)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out.append(struct.pack(">BQ", _BYTES, len(raw)))
        out.append(raw)
    elif isinstance(obj, (list, tuple)):
        out.append(struct.pack(">BQ", _LIST, len(obj)))
        for item in obj:
            _enc(item, out, depth + 1)
    elif isinstance(obj, dict):
        out.append(struct.pack(">BQ", _DICT, len(obj)))
        for key, val in obj.items():
            if not isinstance(key, str):
                raise WireError(f"dict key must be str, got {type(key)}")
            _enc(key, out, depth + 1)
            _enc(val, out, depth + 1)
    else:
        raise WireError(f"unsupported wire type: {type(obj)}")


def encode(obj: Any) -> bytes:
    out: list = []
    _enc(obj, out, 0)
    return b"".join(out)


def _dec(buf: bytes, pos: int, depth: int):
    if depth > _MAX_DEPTH:
        raise WireError("decode: nesting too deep")
    if pos >= len(buf):
        raise WireError("decode: truncated")
    tag = buf[pos]
    pos += 1
    if tag == _NONE:
        return None, pos
    if tag == _TRUE:
        return True, pos
    if tag == _FALSE:
        return False, pos
    if tag == _INT:
        if pos + 8 > len(buf):
            raise WireError("decode: truncated int")
        return struct.unpack_from(">q", buf, pos)[0], pos + 8
    if tag == _FLOAT:
        if pos + 8 > len(buf):
            raise WireError("decode: truncated float")
        return struct.unpack_from(">d", buf, pos)[0], pos + 8
    if tag in (_STR, _BYTES):
        if pos + 8 > len(buf):
            raise WireError("decode: truncated length")
        (n,) = struct.unpack_from(">Q", buf, pos)
        pos += 8
        if pos + n > len(buf):
            raise WireError("decode: truncated payload")
        raw = buf[pos : pos + n]
        pos += n
        if tag == _STR:
            try:
                return raw.decode("utf-8"), pos
            except UnicodeDecodeError as e:
                raise WireError(f"decode: invalid utf-8 in str: {e}") from e
        return raw, pos
    if tag == _LIST:
        if pos + 8 > len(buf):
            raise WireError("decode: truncated count")
        (n,) = struct.unpack_from(">Q", buf, pos)
        pos += 8
        if n > len(buf):  # each element costs >= 1 byte
            raise WireError("decode: list count exceeds buffer")
        items = []
        for _ in range(n):
            item, pos = _dec(buf, pos, depth + 1)
            items.append(item)
        return items, pos
    if tag == _DICT:
        if pos + 8 > len(buf):
            raise WireError("decode: truncated count")
        (n,) = struct.unpack_from(">Q", buf, pos)
        pos += 8
        if n > len(buf):
            raise WireError("decode: dict count exceeds buffer")
        d = {}
        for _ in range(n):
            key, pos = _dec(buf, pos, depth + 1)
            if not isinstance(key, str):
                raise WireError("decode: dict key must be str")
            val, pos = _dec(buf, pos, depth + 1)
            d[key] = val
        return d, pos
    raise WireError(f"decode: unknown tag {tag}")


def decode(buf: bytes) -> Any:
    obj, pos = _dec(buf, 0, 0)
    if pos != len(buf):
        raise WireError(f"decode: {len(buf) - pos} trailing bytes")
    return obj


# -- object layer: plain trees + numpy arrays --------------------------------
# ndarray envelope key set; a user dict can only collide by carrying exactly
# these four keys, and the decoder then validates every field strictly
_ND_KEYS = frozenset(("__nd__", "dtype", "shape", "data"))


def to_plain(v: Any) -> Any:
    """Normalize a value tree for encode(): ndarrays become tagged dicts."""
    import numpy as np

    if isinstance(v, np.ndarray):
        return {
            "__nd__": True,
            "dtype": str(v.dtype),
            "shape": [int(s) for s in v.shape],
            "data": v.tobytes(),
        }
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if isinstance(v, (list, tuple)):
        return [to_plain(i) for i in v]
    if isinstance(v, dict):
        return {k: to_plain(x) for k, x in v.items()}
    return v


def from_plain(v: Any) -> Any:
    """Inverse of to_plain. Raises WireError on a malformed nd envelope
    (bad dtype, negative shape, size mismatch) — never ValueError."""
    import numpy as np

    if isinstance(v, dict):
        if v.get("__nd__") is True and set(v) == _ND_KEYS:
            try:
                dtype = np.dtype(v["dtype"])
                shape = [int(s) for s in v["shape"]]
                data = v["data"]
                if not isinstance(data, bytes):
                    raise WireError("nd envelope: data must be bytes")
                if any(s < 0 for s in shape):
                    raise WireError("nd envelope: negative shape")
                count = int(np.prod(shape)) if shape else 1
                if count * dtype.itemsize != len(data):
                    raise WireError(
                        f"nd envelope: {len(data)} bytes != "
                        f"shape {shape} x {dtype}"
                    )
                return np.frombuffer(data, dtype=dtype).reshape(shape)
            except WireError:
                raise
            except (TypeError, ValueError) as e:
                raise WireError(f"nd envelope: {e}") from e
        return {k: from_plain(x) for k, x in v.items()}
    if isinstance(v, list):
        return [from_plain(i) for i in v]
    return v


def encode_obj(obj: Any) -> bytes:
    """encode() over to_plain-normalized input: accepts numpy arrays and
    numpy scalar types anywhere in the tree."""
    return encode(to_plain(obj))


def decode_obj(buf: bytes) -> Any:
    return from_plain(decode(buf))


def blob_checksum(blob: bytes) -> int:
    """Integrity checksum for transfer blobs (BR region export/import).
    One definition shared by client and server — the two sides silently
    disagreeing would fail every transfer. crc32: C-speed on multi-MB
    blobs."""
    import zlib

    return zlib.crc32(blob) & 0xFFFFFFFF
