"""Workload-heat & capacity plane (ISSUE 17): access-heat sketches,
working-set estimation, the per-shape kernel cost model, and the
coordinator's advisory-only capacity rollups.

Acceptance: the decay sketch loses mass at the configured e-folding
rate and stays bounded at heat.max_entries; skewed vs uniform traffic
separates cleanly in hot_fraction/gini; the working-set estimator
matches an exact replay of the access stream; the per-shape cost model
beats the scalar-EWMA wait estimate by >70% under mixed kernel shapes;
observing a live IVF region adds zero steady-state recompiles and is
inert with the flag off; the heat_* rollups round-trip through the
heartbeat pb; plan_store fires demote/split advisories exactly at
their thresholds; and `cluster capacity` / `cluster top` render the
evidence (with '-' when there is none).
"""

import math
import time

import numpy as np
import pytest

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.metrics import METRICS, MetricsRegistry
from dingo_tpu.obs.heat import (
    HEAT,
    SLOT_BLOCK,
    TIER_BYTES,
    HeatPlane,
    _RegionHeat,
    gini,
    hot_fraction,
    working_set_rows,
)
from dingo_tpu.obs.cost import COST, CostModel, kernel_id, kernel_region


@pytest.fixture()
def heat_env():
    """Clean heat/cost state + restored flags."""
    saved = {k: FLAGS.get(k) for k in (
        "heat_enabled", "heat_decay_s", "heat_max_entries",
        "cost_enabled", "cost_prior_row_ms",
        "capacity_advise", "capacity_headroom_target",
    )}
    HEAT.reset()
    COST.reset()
    try:
        yield
    finally:
        for k, v in saved.items():
            FLAGS.set(k, v)
        HEAT.reset()
        COST.reset()


# ---------------------------------------------------------------------------
# sketch math
# ---------------------------------------------------------------------------

def test_sketch_decays_at_the_configured_rate():
    """A unit untouched for n decay constants keeps e^-n of its mass
    relative to a fresh touch (the time-warp basis must be invisible)."""
    tau = 10.0
    rh = _RegionHeat(0.0)
    rh.fold("ivf", np.array([1]), 1.0, 0.0, tau, 4096)
    rh.fold("ivf", np.array([2]), 1.0, 3.0 * tau, tau, 4096)
    scale = math.exp((rh.t0 - 3.0 * tau) / tau)   # warp -> true mass
    m1 = rh.mass[("ivf", 1)] * scale
    m2 = rh.mass[("ivf", 2)] * scale
    assert m2 == pytest.approx(1.0)
    assert m1 / m2 == pytest.approx(math.exp(-3.0), rel=1e-6)


def test_sketch_rebases_without_changing_relative_mass():
    """Past _REBASE_WARP the warped floats are renormalized; the
    relative masses (all any consumer reads) must not move."""
    tau = 1.0
    rh = _RegionHeat(0.0)
    rh.fold("ivf", np.array([1, 1, 1]), 1.0, 0.0, tau, 4096)
    rh.fold("ivf", np.array([2]), 1.0, 5.0, tau, 4096)
    before = rh.mass[("ivf", 1)] / rh.mass[("ivf", 2)]
    rh.fold("ivf", np.array([3]), 1.0, 40.0, tau, 4096)  # forces rebase
    assert rh.t0 == 40.0
    after = rh.mass[("ivf", 1)] / rh.mass[("ivf", 2)]
    assert after == pytest.approx(before, rel=1e-9)
    for v in rh.mass.values():
        assert np.isfinite(v)


def test_sketch_memory_is_bounded_and_keeps_the_hottest():
    """Folding more distinct units than the cap evicts the coldest;
    a repeatedly-touched unit must survive."""
    rh = _RegionHeat(0.0)
    cap = 64
    rh.fold("slot", np.full(50, 7), 1.0, 0.0, 10.0, cap)   # hot unit 7
    for start in range(0, 500, 100):
        rh.fold("slot", np.arange(start + 100, start + 200), 1.0,
                0.0, 10.0, cap)
    assert len(rh.mass) <= cap
    assert ("slot", 7) in rh.mass


def test_hot_fraction_separates_skewed_from_uniform():
    uniform = np.ones(100)
    zipf = 1.0 / np.arange(1, 101) ** 1.5
    assert hot_fraction(uniform) == pytest.approx(0.1)
    assert hot_fraction(zipf) > 0.7
    assert gini(uniform) == pytest.approx(0.0, abs=1e-9)
    assert gini(zipf) > 0.6
    assert gini(np.array([])) == 0.0 and hot_fraction(np.array([])) == 0.0


def test_working_set_matches_exact_replay():
    """The estimator's rows-to-serve-p% must equal an exact replay of
    the access stream (same counts, no decay -> identical math)."""
    rng = np.random.default_rng(5)
    units = rng.zipf(1.3, 20_000) % 200           # skewed unit stream
    counts = np.bincount(units, minlength=200).astype(np.float64)
    rows = np.full(200, 32.0)
    est = working_set_rows(counts, rows, (50, 90, 99))
    # exact replay: hottest-first cumulative coverage of the raw stream
    order = np.argsort(counts)[::-1]
    cum = np.cumsum(counts[order]) / counts.sum()
    for p in (50, 90, 99):
        exact_units = int(np.searchsorted(cum, p / 100.0)) + 1
        assert est[p] == exact_units * 32


# ---------------------------------------------------------------------------
# the async plane
# ---------------------------------------------------------------------------

def test_plane_folds_off_thread_and_derives_stats(heat_env):
    FLAGS.set("heat_enabled", True)
    plane = HeatPlane(MetricsRegistry())
    rng = np.random.default_rng(11)
    # region 1: skewed; region 2: uniform over the same unit count
    for _ in range(20):
        plane.observe(1, "ivf", rng.zipf(1.5, 256) % 64)
        plane.observe(2, "ivf", rng.integers(0, 64, 256))
    assert plane.flush(timeout=30.0)
    s1, s2 = plane.region_stats(1), plane.region_stats(2)
    assert s1 is not None and s2 is not None
    assert s1["touches"] == s2["touches"] == 20 * 256
    assert s1["hot_fraction"] > s2["hot_fraction"] + 0.2
    assert s1["gini"] > s2["gini"] + 0.2
    plane.forget_region(1)
    assert plane.region_stats(1) is None


def test_slot_kind_maps_to_blocks_and_filters_padding(heat_env):
    """FLAT/HNSW feed raw result slots: -1 padding must be dropped and
    slots collapse to SLOT_BLOCK-sized units on the worker."""
    FLAGS.set("heat_enabled", True)
    plane = HeatPlane(MetricsRegistry())
    slots = np.array([0, 5, SLOT_BLOCK + 1, -1, -1, 3 * SLOT_BLOCK])
    plane.observe(9, "slot", slots)
    assert plane.flush()
    masses = plane.unit_masses(9, "slot")
    assert set(masses) == {("slot", 0), ("slot", 1), ("slot", 3)}
    st = plane.region_stats(9)
    assert st["touches"] == 4                      # -1s never counted


def test_working_set_prices_the_layout_tier(heat_env):
    FLAGS.set("heat_enabled", True)
    plane = HeatPlane(MetricsRegistry())
    rows = np.full(8, 100.0)

    def layout():
        return {"unit_rows": rows, "row_bytes": 64 * TIER_BYTES["sq8"],
                "tier": "sq8", "dim": 64}

    plane.register_layout(3, "ivf", layout)
    plane.observe(3, "ivf", np.repeat(np.arange(8), [80, 5, 5, 2, 2, 2,
                                                     2, 2]))
    assert plane.flush()
    st = plane.region_stats(3)
    assert st["tier"] == "sq8"
    # p50 of the traffic sits on one 100-row unit at 64 B/row
    assert st["ws_bytes"][50] == 100 * 64
    # the fp32 what-if prices the same rows at 4 bytes/coordinate
    assert st["ws_bytes_tier"]["fp32"][50] == 100 * 64 * 4


def test_flag_off_is_inert(heat_env):
    """heat_enabled off: call sites never reach observe(); even direct
    enqueue on a fresh plane is the only state — the global HEAT stays
    empty after an index search (wired-path check in the e2e test)."""
    FLAGS.set("heat_enabled", False)
    from dingo_tpu.obs.heat import heat_enabled

    assert not heat_enabled()
    assert HEAT.unit_masses(123) == {}
    assert HEAT.region_stats(123) is None


def test_overflow_drops_and_counts(heat_env):
    reg = MetricsRegistry()
    plane = HeatPlane(reg)
    # stall the worker by not starting it: enqueue past QUEUE_MAX
    from dingo_tpu.obs import heat as heat_mod

    with plane._cond:                  # hold the lock so nothing drains
        pass
    for _ in range(heat_mod.QUEUE_MAX + 10):
        with plane._cond:
            if len(plane._queue) >= heat_mod.QUEUE_MAX:
                break
            plane._queue.append((1, "ivf", np.array([1]), 1.0,
                                 time.time()))
    plane.observe(1, "ivf", np.array([2]))        # queue is full -> drop
    assert reg.counter("heat.dropped", region_id=1).get() >= 1


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

def test_kernel_id_shapes():
    key = (7, 10, (("nprobe", 8),))
    kid = kernel_id(key)
    assert kid.startswith("r7:k10:") and len(kid) == len("r7:k10:") + 8
    assert kernel_id((7, 10)) == "r7:k10"
    assert kernel_region(key) == 7
    assert kernel_region("opaque") is None


def test_cost_model_beats_scalar_ewma_under_mixed_shapes(heat_env):
    """Two kernel families with 50x different per-row costs, mixed
    batch shapes: the per-(kernel, ladder-point) model's wait estimates
    must cut the scalar-EWMA baseline's error by >70% (<30% of it)."""
    FLAGS.set("cost_enabled", True)
    model = CostModel(MetricsRegistry())
    alpha = 0.3
    scalar_row = 0.0
    seen = 0

    def true_ms(kind, rows):
        pad = 1
        while pad < rows:
            pad *= 2
        return (0.05 + 0.01 * pad) if kind == "cheap" else (2.0 + 0.5 * pad)

    rng = np.random.default_rng(3)
    for _ in range(200):
        kind = "cheap" if rng.random() < 0.5 else "wide"
        rows = int(rng.choice([4, 8, 32, 64]))
        ms = true_ms(kind, rows)
        model.note(kind, rows, ms)
        # the old coalescer discipline: ONE per-row EWMA over everything
        per_row = ms / rows
        scalar_row = per_row if seen == 0 else (
            (1.0 - alpha) * scalar_row + alpha * per_row)
        seen += 1
    probes = [("cheap", 4), ("cheap", 64), ("wide", 8), ("wide", 64)]
    model_err = sum(abs(model.estimate_run_ms(k, r) - true_ms(k, r))
                    for k, r in probes)
    scalar_err = sum(abs(scalar_row * r - true_ms(k, r))
                     for k, r in probes)
    assert model_err < 0.3 * scalar_err, (model_err, scalar_err)


def test_cost_model_interpolates_and_clamps(heat_env):
    FLAGS.set("cost_enabled", True)
    model = CostModel(MetricsRegistry())
    for _ in range(5):
        model.note("k", 32, 3.2)                   # one measured point
    assert model.estimate_run_ms("k", 32) == pytest.approx(3.2)
    # larger than support: scaled up, never below the measured point
    assert model.estimate_run_ms("k", 64) >= 3.2
    # smaller than support: never above the measured larger dispatch
    assert model.estimate_run_ms("k", 8) <= 3.2
    # unmeasured kernel: the conservative prior
    FLAGS.set("cost_prior_row_ms", 0.5)
    assert model.estimate_run_ms("other", 10) == pytest.approx(5.0)


def test_cost_forget_region_drops_prefixed_kernels(heat_env):
    model = CostModel(MetricsRegistry())
    model.note(kernel_id((7, 10)), 8, 1.0, region_id=7)
    model.note(kernel_id((8, 10)), 8, 1.0, region_id=8)
    assert model.region_row_us(7) > 0.0
    model.forget_region(7)
    assert model.region_row_us(7) == 0.0
    assert not model.has_model("r7:k10")
    assert model.has_model("r8:k10")


def test_coalescer_cold_start_sheds_on_the_prior(heat_env):
    """Satellite fix: before ANY sample lands, estimated_wait_ms must
    answer the conservative prior, not 0 — and the legacy 0.0 only
    survives with the cost model explicitly off."""
    from dingo_tpu.common.coalescer import SearchCoalescer

    co = SearchCoalescer(lambda key, q: [[] for _ in q], window_ms=1.0)
    try:
        FLAGS.set("cost_enabled", True)
        FLAGS.set("cost_prior_row_ms", 0.5)
        assert co.estimated_wait_ms(8) == pytest.approx(8 * 0.5)
        FLAGS.set("cost_enabled", False)
        assert co.estimated_wait_ms(8) == 0.0     # old behavior, opt-out
    finally:
        co.stop()


def test_coalescer_feeds_the_cost_model(heat_env):
    """A dispatched batch's completion must land in COST under the
    kernel id derived from the coalescer key, and estimated_wait_ms
    must then answer from the model for that key."""
    from dingo_tpu.common.coalescer import SearchCoalescer

    FLAGS.set("cost_enabled", True)
    key = (41, 10, (("nprobe", 4),))
    co = SearchCoalescer(
        lambda k, q: (time.sleep(0.01), [[] for _ in q])[1],
        window_ms=1.0)
    try:
        co.submit(key, np.zeros((4, 8), np.float32)).result(timeout=30)
        kid = kernel_id(key)
        deadline = time.monotonic() + 10.0
        while not COST.has_model(kid) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert COST.has_model(kid)
        assert COST.estimate_run_ms(kid, 4) >= 5.0   # the 10ms sleep
        assert COST.region_row_us(41) > 0.0
    finally:
        co.stop()


# ---------------------------------------------------------------------------
# end-to-end through a live index
# ---------------------------------------------------------------------------

def test_ivf_heat_end_to_end_zero_recompiles(heat_env):
    """Heat on a live IVF region: probed buckets land in the sketch
    with NO extra kernel shapes (zero steady-state recompiles across
    heat off -> on) and the flag-off arm leaves the plane untouched."""
    from dingo_tpu.index import IndexParameter, IndexType, new_index

    n, d, nlist, nprobe, k = 2000, 32, 8, 4, 5
    rng = np.random.default_rng(7)
    x = rng.standard_normal((n, d)).astype(np.float32)
    idx = new_index(71, IndexParameter(
        index_type=IndexType.IVF_FLAT, dimension=d, ncentroids=nlist,
        default_nprobe=nprobe,
    ))
    idx.store.reserve(n)
    idx.upsert(np.arange(n, dtype=np.int64), x)
    idx.train()
    q = x[:16] + 0.01
    FLAGS.set("heat_enabled", False)
    idx.search(q, k, nprobe=nprobe)               # warm the shape
    assert HEAT.region_stats(71) is None          # off = inert
    recomp = METRICS.counter("xla.recompiles")
    before = recomp.get()
    FLAGS.set("heat_enabled", True)
    for _ in range(5):
        idx.search(q, k, nprobe=nprobe)
    assert HEAT.flush()
    assert recomp.get() == before                 # same programs only
    st = HEAT.region_stats(71)
    assert st is not None and st["touches"] >= 5 * 16 * nprobe
    masses = HEAT.unit_masses(71, "ivf")
    assert masses and all(0 <= u < nlist for (_, u) in masses)
    assert st["ws_bytes"][99] > 0                 # layout provider wired


# ---------------------------------------------------------------------------
# heartbeats, capacity plane, CLI
# ---------------------------------------------------------------------------

def test_heat_rollups_round_trip_heartbeat_pb():
    from dingo_tpu.metrics.snapshot import RegionMetricsSnapshot
    from dingo_tpu.server import convert
    from dingo_tpu.server import dingo_pb2 as pb

    rm = RegionMetricsSnapshot(region_id=5)
    rm.heat_hot_fraction = 0.875
    rm.heat_gini = 0.62
    rm.heat_working_set_p50 = 1 << 20
    rm.heat_working_set_p90 = 5 << 20
    rm.heat_working_set_p99 = 9 << 20
    rm.heat_touches = 12345
    rm.cost_row_us = 17.25
    wire = convert.region_metrics_to_pb(rm).SerializeToString()
    parsed = pb.RegionMetrics()
    parsed.ParseFromString(wire)
    back = convert.region_metrics_from_pb(parsed)
    assert back.heat_hot_fraction == pytest.approx(0.875)
    assert back.heat_gini == pytest.approx(0.62)
    assert (back.heat_working_set_p50, back.heat_working_set_p90,
            back.heat_working_set_p99) == (1 << 20, 5 << 20, 9 << 20)
    assert back.heat_touches == 12345
    assert back.cost_row_us == pytest.approx(17.25)


def _region(rid, resident, ws99, touches, hot):
    from dingo_tpu.metrics.snapshot import RegionMetricsSnapshot

    rm = RegionMetricsSnapshot(region_id=rid)
    rm.device_memory_bytes = resident
    rm.heat_working_set_p99 = ws99
    rm.heat_touches = touches
    rm.heat_hot_fraction = hot
    return rm


def _store(store_id, limit, in_use, regions):
    from dingo_tpu.metrics.snapshot import StoreMetricsSnapshot

    snap = StoreMetricsSnapshot(store_id=store_id)
    snap.device_bytes_limit = limit
    snap.device_bytes_in_use = in_use
    snap.regions = regions
    return snap


def test_plan_store_demote_threshold():
    from dingo_tpu.coordinator import capacity as cap

    cold = _region(1, 100 << 20, 10 << 20, 5000, 0.2)
    # under the headroom target with a touch-qualified cold region
    plan = cap.plan_store(
        _store("s1", 256 << 20, 246 << 20, [cold]), target=0.2)
    kinds = [a.kind for a in plan["advice"]]
    assert kinds == ["demote"]
    a = plan["advice"][0]
    assert a.region_id == 1 and a.bytes_at_stake == 90 << 20
    # comfortable headroom: no demote
    plan = cap.plan_store(
        _store("s1", 256 << 20, 100 << 20, [cold]), target=0.2)
    assert plan["advice"] == []
    # under target but the sketch has no evidence: no demote
    fresh = _region(1, 100 << 20, 10 << 20, cap.MIN_TOUCHES - 1, 0.2)
    plan = cap.plan_store(
        _store("s1", 256 << 20, 246 << 20, [fresh]), target=0.2)
    assert plan["advice"] == []


def test_plan_store_split_threshold():
    from dingo_tpu.coordinator import capacity as cap

    hot = _region(1, 10 << 20, 8 << 20, 9000, 0.7)
    warm = _region(2, 10 << 20, 8 << 20, 1000, 0.7)
    plan = cap.plan_store(
        _store("s1", 256 << 20, 20 << 20, [hot, warm]), target=0.2)
    assert [a.kind for a in plan["advice"]] == ["split"]
    assert plan["advice"][0].region_id == 1
    # below the hot-core bar: concentration alone is not enough
    mild = _region(1, 10 << 20, 8 << 20, 9000,
                   cap.SPLIT_HOT_FRACTION - 0.01)
    plan = cap.plan_store(
        _store("s1", 256 << 20, 20 << 20, [mild, warm]), target=0.2)
    assert plan["advice"] == []
    # below the traffic-share bar: hot but not dominant
    a = _region(1, 10 << 20, 8 << 20, 4000, 0.9)
    b = _region(2, 10 << 20, 8 << 20, 6000, 0.2)
    plan = cap.plan_store(
        _store("s1", 256 << 20, 20 << 20, [a, b]), target=0.2)
    assert plan["advice"] == []


def test_coordinator_capacity_hook_and_advisory_dedupe(heat_env):
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.engine.raw_engine import MemEngine

    FLAGS.set("capacity_advise", True)
    FLAGS.set("capacity_headroom_target", 0.2)
    coord = CoordinatorControl(MemEngine(), replication=1)
    coord.register_store("s1")
    snap = _store("s1", 256 << 20, 250 << 20,
                  [_region(3, 200 << 20, 4 << 20, 8000, 0.9)])
    c = METRICS.counter("capacity.advisories", region_id=3,
                        labels={"kind": "demote"})
    before = c.get()
    coord.store_heartbeat("s1", region_ids=[3], metrics=snap)
    coord.store_heartbeat("s1", region_ids=[3], metrics=snap)
    plans = coord.capacity_report()
    assert len(plans) == 1 and plans[0]["store_id"] == "s1"
    assert {a.kind for a in plans[0]["advice"]} == {"demote", "split"}
    # repeated beats with the same advice tick the counter ONCE
    assert c.get() == before + 1
    # advisory plane off: plans retract, nothing breaks
    FLAGS.set("capacity_advise", False)
    coord.store_heartbeat("s1", region_ids=[3], metrics=snap)
    assert coord.capacity_report() == []


def test_cluster_capacity_render():
    from dingo_tpu.client.cli import format_cluster_capacity
    from dingo_tpu.server import dingo_pb2 as pb

    resp = pb.GetStoreMetricsResponse()
    e = resp.stores.add()
    e.store_id = "s1"
    e.metrics.store_id = "s1"
    e.metrics.device_bytes_limit = 256 << 20
    e.metrics.device_bytes_in_use = 250 << 20
    r = e.metrics.regions.add()
    r.region_id = 3
    r.device_memory_bytes = 200 << 20
    r.heat_working_set_p99 = 4 << 20
    r.heat_touches = 8000
    r.heat_hot_fraction = 0.9
    out = format_cluster_capacity(resp)
    assert "HEADROOM" in out and "DEMAND-P99" in out
    assert "demote" in out and "split" in out
    assert "s1" in out and "4.0MB" in out
    # a store with no heat evidence renders '-' demand, no advisories
    resp2 = pb.GetStoreMetricsResponse()
    e2 = resp2.stores.add()
    e2.store_id = "s2"
    e2.metrics.store_id = "s2"
    e2.metrics.device_bytes_limit = 256 << 20
    e2.metrics.device_bytes_in_use = 10 << 20
    out2 = format_cluster_capacity(resp2)
    assert "no capacity advisories" in out2


def test_cluster_top_heat_columns():
    from dingo_tpu.client.cli import format_cluster_top
    from dingo_tpu.server import dingo_pb2 as pb

    resp = pb.GetStoreMetricsResponse()
    e = resp.stores.add()
    e.store_id = "s1"
    r = e.metrics.regions.add()
    r.region_id = 4
    r.heat_hot_fraction = 0.91
    r.heat_working_set_p99 = 10 << 20
    r.heat_touches = 500
    cold = e.metrics.regions.add()
    cold.region_id = 5                    # no sketch evidence
    out = format_cluster_top(resp)
    assert "HEAT" in out and "WSET" in out
    assert "0.91" in out and "10.0MB" in out
    row5 = next(ln for ln in out.splitlines()
                if ln.startswith("5 "))
    assert "-" in row5                    # no evidence renders '-'
