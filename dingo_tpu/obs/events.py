"""Control-plane flight recorder: the decision event ledger.

After PRs 9-19 seven autonomous controllers navigate the speed/recall/
memory operating-point space live — the SLO tuner, the QoS shed ladder,
the tier manager, the device-recovery plane, the replica planner, the
capacity advisor, and the cache stale rung — all writing into
``index.tuning``, precision advisories, and tier rungs. This module is
the missing record of *which* controller moved *which* knob on *what*
evidence: every actuation emits one :class:`Event` whose ``evidence``
field snapshots the exact metric values the controller read when it
decided (tuner: CI bounds vs the SLO; shed: estimated wait vs
``qos.max_queue_ms``; tier manager: headroom + windowed QPS; recovery:
the OOM rung; planner/capacity: heartbeat QPS / working-set inputs;
cache: the degrade level).

Plane shape (the HEAT/QUALITY/PRESSURE discipline):

- module singleton ``EVENTS``; ``events.enabled`` off means ``emit`` is
  ONE flag read and allocates nothing;
- a bounded per-node ring (``events.max_entries``) with overflow counted
  in ``event.dropped`` — the ledger may forget, it may never grow;
- per-actor monotone sequence numbers that survive restart (the epoch-ms
  base makes a restarted store's seq continue above its predecessor's),
  so the coordinator can dedupe re-sent events exactly;
- ``harvest()`` hands each event to the heartbeat exactly once (the
  metrics collector batches ``events.heartbeat_batch`` per beat and the
  coordinator merges them into the cluster timeline).

Emission is synchronous and host-only: an emit is a dict -> JSON dump +
a deque append under one lock, no device touch, no worker thread —
controller decisions are rare (crontab ticks), so unlike the heat/quality
planes there is nothing to take off the serving path.

Coordinator-side, :class:`ClusterTimeline` merges heartbeat batches into
a causally-ordered cluster view: each store's wall clock is normalized by
the heartbeat receive offset (the METRICS_STALE_MS receive-clock
discipline — ``recv_ms - collected_at_ms`` absorbs skew), events order by
(adjusted ts, node, actor_seq), and per-(node, actor) max-seq dedupe makes
re-delivered heartbeats idempotent. ``explain_region`` reconstructs every
currently live override/rung/advisory on a region as the chain of events
that explains it, flagging live knobs with no surviving explanation
("orphan knobs" — the ring or timeline forgot, or a writer bypassed the
ledger; the dingolint knob-audit checker exists to make the latter
impossible).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from dingo_tpu.common import persist
from dingo_tpu.common.metrics import METRICS

#: the seven controller actors (and the knobs they own) — documentation
#: and render-order, not an emit-time allowlist: a new controller may
#: emit under a new actor name without touching this table, the
#: ARCHITECTURE.md controller table is generated from the same data
ACTORS = (
    # actor       knobs it moves             evidence fields it snapshots
    ("tuner",     "nprobe/ef/rerank_factor/precision(advisory)",
     "ci_low, ci_high, slo, p99_ms, budget_ms, queries"),
    ("shed",      "degrade_level (+saved tuning writes)",
     "pressure_ms, max_queue_ms, level"),
    ("tier",      "tier rung",
     "from, to, headroom, qps, advisory, ms"),
    ("recovery",  "device_degraded, recovery rung",
     "rung, reason, precision"),
    ("planner",   "replica count",
     "qps, target_qps, floor, peers, add/drop store"),
    ("capacity",  "demote/split advisory",
     "headroom_frac, target, bytes_at_stake"),
    ("cache",     "stale-version rung",
     "degrade_level, bound"),
)


def events_enabled() -> bool:
    from dingo_tpu.common.config import FLAGS

    try:
        return bool(FLAGS.get("events_enabled"))
    except KeyError:
        return True


def events_max_entries() -> int:
    from dingo_tpu.common.config import FLAGS

    try:
        return max(16, int(FLAGS.get("events_max_entries")))
    except (KeyError, TypeError, ValueError):
        return 1024


def events_heartbeat_batch() -> int:
    from dingo_tpu.common.config import FLAGS

    try:
        return max(0, int(FLAGS.get("events_heartbeat_batch")))
    except (KeyError, TypeError, ValueError):
        return 128


@persist.register
@dataclasses.dataclass
class Event:
    """One control-plane decision. persist-registered because events ride
    the heartbeat snapshot, which the replicated coordinator raft-proposes
    (the RegionMetricsSnapshot contract)."""

    actor: str              #: which controller decided (ACTORS table)
    region_id: int          #: the region it actuated (0 = store-wide)
    knob: str               #: what moved (nprobe / degrade_level / tier...)
    old: str                #: stringified prior value
    new: str                #: stringified new value
    trigger: str            #: why, one word (tighten/escalate/demote/...)
    #: compact JSON snapshot of the exact inputs the controller read when
    #: it decided — the evidence, not a re-derivation
    evidence: str = ""
    ts_ms: int = 0          #: emitter wall clock (normalized on merge)
    actor_seq: int = 0      #: per-(node, actor) monotone, restart-safe
    node_id: str = ""       #: stamped at harvest (store_id) or merge
    trace_id: str = ""      #: hex trace id when a sampled span was live
    flight_bundle_id: str = ""   #: bundle that snapshotted this episode

    def evidence_dict(self) -> Dict[str, Any]:
        if not self.evidence:
            return {}
        try:
            return json.loads(self.evidence)
        except ValueError:
            return {"_raw": self.evidence}


_EVENT_FIELDS = tuple(f.name for f in dataclasses.fields(Event))


class EventLedger:
    """Bounded per-node ring of control-plane decisions (``EVENTS``)."""

    def __init__(self, registry=METRICS):
        self._reg = registry
        self._lock = threading.Lock()
        self._ring: deque = deque()
        #: actor -> next sequence number. Seeded from the wall clock
        #: (epoch_ms * 1000) so a restarted process continues ABOVE every
        #: seq its predecessor could have minted — coordinator dedupe
        #: stays a per-(node, actor) max-seq watermark, no epochs needed
        self._seq: Dict[str, int] = {}
        #: ring indices below this were already harvested into a heartbeat
        self._harvested = 0
        self._dropped = 0
        #: lifetime accounting (bench overhead attribution): total emits
        #: and wall seconds spent inside emit() while enabled
        self._emitted = 0
        self._emit_s = 0.0

    # -- emit ---------------------------------------------------------------
    def emit(self, actor: str, region_id: int, knob: str, old, new,
             trigger: str, evidence: Optional[Dict[str, Any]] = None,
             trace_id: str = "", flight_bundle_id: str = "",
             ) -> Optional[Event]:
        """Record one actuation. Returns the Event, or None when the
        ledger is off (one flag read, nothing allocated)."""
        if not events_enabled():
            return None
        t_emit = time.perf_counter()
        if not trace_id:
            from dingo_tpu.trace.span import current_span

            sp = current_span()
            tid = getattr(sp, "trace_id", 0) if sp is not None else 0
            if tid:
                trace_id = format(tid, "x")
        ev = Event(
            actor=str(actor),
            region_id=int(region_id),
            knob=str(knob),
            old=str(old),
            new=str(new),
            trigger=str(trigger),
            evidence=json.dumps(evidence, sort_keys=True,
                                separators=(",", ":"), default=str)
            if evidence else "",
            ts_ms=int(time.time() * 1000),
            trace_id=trace_id,
            flight_bundle_id=flight_bundle_id,
        )
        cap = events_max_entries()
        with self._lock:
            seq = self._seq.get(actor)
            if seq is None:
                seq = ev.ts_ms * 1000
            ev.actor_seq = seq
            self._seq[actor] = seq + 1
            self._ring.append(ev)
            while len(self._ring) > cap:
                self._ring.popleft()
                if self._harvested > 0:
                    # already shipped to the coordinator: a normal ring
                    # eviction, not a loss
                    self._harvested -= 1
                else:
                    self._dropped += 1
                    self._reg.counter("event.dropped").add(1)
            self._emitted += 1
        self._reg.counter("event.emitted", region_id=int(region_id),
                          labels={"actor": str(actor)}).add(1)
        self._emit_s += time.perf_counter() - t_emit
        return ev

    # -- queries ------------------------------------------------------------
    def recent(self, limit: int = 0, region_id: Optional[int] = None,
               actor: str = "") -> List[Event]:
        """Matching events, oldest first (the ring's natural order)."""
        with self._lock:
            evs = list(self._ring)
        if region_id is not None:
            evs = [e for e in evs if e.region_id == int(region_id)]
        if actor:
            evs = [e for e in evs if e.actor == actor]
        if limit and len(evs) > limit:
            evs = evs[-limit:]
        return evs

    def last_before(self, limit: int) -> List[Event]:
        """The newest `limit` events — the flight-bundle section."""
        return self.recent(limit=limit)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {"entries": len(self._ring), "dropped": self._dropped,
                    "pending": len(self._ring) - self._harvested,
                    "emitted": self._emitted,
                    "emit_s": self._emit_s,
                    "seq": dict(self._seq)}

    # -- heartbeat transport ------------------------------------------------
    def harvest(self, batch: int = 0, node_id: str = "") -> List[Event]:
        """Events not yet shipped, up to `batch` (0 = flag default), each
        returned EXACTLY once across harvests and stamped with the
        harvesting node. Shipped events stay in the ring for local
        EventDump / flight bundles until the bound evicts them."""
        if batch <= 0:
            batch = events_heartbeat_batch()
        if batch <= 0:
            return []
        with self._lock:
            pending = len(self._ring) - self._harvested
            take = min(batch, max(0, pending))
            if take <= 0:
                return []
            start = self._harvested
            out = [self._ring[i] for i in range(start, start + take)]
            self._harvested = start + take
        if node_id:
            for ev in out:
                if not ev.node_id:
                    ev.node_id = node_id
        return out

    # -- lifecycle ----------------------------------------------------------
    def forget_region(self, region_id: int) -> None:
        """Drop a departed region's events (the collector retire loop)."""
        rid = int(region_id)
        with self._lock:
            kept, harvested = [], 0
            for i, ev in enumerate(self._ring):
                if ev.region_id == rid:
                    continue
                if i < self._harvested:
                    harvested += 1
                kept.append(ev)
            self._ring = deque(kept)
            self._harvested = harvested

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq.clear()
            self._harvested = 0
            self._dropped = 0
            self._emitted = 0
            self._emit_s = 0.0


EVENTS = EventLedger()


# -- coordinator-side merge + explain ---------------------------------------

class ClusterTimeline:
    """Causally-ordered cluster-wide merge of per-node event batches.

    Heartbeat clocks skew; the coordinator normalizes each batch by its
    heartbeat's receive offset (``recv_ms - collected_at_ms``, the
    METRICS_STALE_MS receive-clock discipline) so two stores' decisions
    order by the coordinator's clock, not their own. Within one adjusted
    millisecond the (node, actor_seq) pair breaks ties deterministically.
    Re-delivered batches (raft replay, duplicate heartbeats) dedupe on the
    per-(node, actor) max-seq watermark.
    """

    def __init__(self):
        self._lock = threading.Lock()
        #: [(adjusted_ts_ms, node_id, actor_seq, Event)]
        self._events: List[Tuple[int, str, int, Event]] = []
        #: (node_id, actor) -> highest actor_seq merged
        self._seen: Dict[Tuple[str, str], int] = {}

    def merge(self, node_id: str, events: List[Event],
              offset_ms: int = 0) -> int:
        """Fold one node's batch in; returns how many were new."""
        if not events:
            return 0
        cap = events_max_entries()
        added = 0
        with self._lock:
            for ev in events:
                nid = ev.node_id or node_id
                key = (nid, ev.actor)
                if ev.actor_seq <= self._seen.get(key, -1):
                    continue
                self._seen[key] = ev.actor_seq
                self._events.append(
                    (int(ev.ts_ms + offset_ms), nid, ev.actor_seq, ev))
                added += 1
            if added:
                self._events.sort(key=lambda t: (t[0], t[1], t[2]))
                if len(self._events) > cap:
                    del self._events[: len(self._events) - cap]
        return added

    def events(self, region_id: Optional[int] = None, actor: str = "",
               limit: int = 0) -> List[Event]:
        """Merged timeline, oldest first; filters compose."""
        with self._lock:
            rows = list(self._events)
        out = []
        for adj, nid, _seq, ev in rows:
            if region_id is not None and ev.region_id != int(region_id):
                continue
            if actor and ev.actor != actor:
                continue
            out.append(ev)
        if limit and len(out) > limit:
            out = out[-limit:]
        return out

    def forget_region(self, region_id: int) -> None:
        rid = int(region_id)
        with self._lock:
            self._events = [t for t in self._events
                            if t[3].region_id != rid]

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._seen.clear()


def live_overrides(rm: Any) -> Dict[str, str]:
    """The currently live knobs on one region, from its freshest metrics
    snapshot (pb RegionMetrics or RegionMetricsSnapshot — duck-typed like
    the capacity plane). Keys are the knob names events carry, values are
    stringified current values — the set ``explain`` must account for."""
    live: Dict[str, str] = {}
    raw = str(getattr(rm, "live_knobs", "") or "")
    if raw:
        try:
            parsed = json.loads(raw)
        except ValueError:
            parsed = {}
        for knob, value in (parsed.get("tuning") or {}).items():
            live[str(knob)] = str(value)
        adv = parsed.get("advisory_precision")
        if adv:
            live["precision"] = str(adv)
        base = parsed.get("tier_base")
        tier = parsed.get("tier") or getattr(rm, "serving_tier", "")
        if tier and base and tier != base:
            live["tier"] = str(tier)
    else:
        tier = str(getattr(rm, "serving_tier", "") or "")
        if tier and tier not in ("hbm", "hbm_sq8"):
            # without the live_knobs rollup the base rung is unknown;
            # only unambiguously-demoted rungs count as live overrides
            live["tier"] = tier
    lvl = int(getattr(rm, "qos_degrade_level", 0) or 0)
    if lvl > 0:
        live["degrade_level"] = str(lvl)
    if bool(getattr(rm, "device_degraded", False)):
        live["device_degraded"] = "1"
    return live


def explain_region(region_id: int, live: Dict[str, str],
                   events: List[Event]) -> Dict[str, Any]:
    """Account for every live override/rung/advisory on a region as a
    chain of explaining events.

    For each live knob the newest event whose ``knob`` matches (tier
    rungs match the ``tier`` knob regardless of which rung) anchors the
    chain; the chain then walks older same-knob events (the path the
    controller took) plus the events that triggered it — a shed-degrade
    explains a cache stale-rung engage, a capacity advisory explains a
    tier demote. A live knob with NO matching event is an **orphan**: the
    ring/timeline forgot, or a writer bypassed the ledger (the dingolint
    knob-audit checker makes the latter a lint failure).
    """
    region_events = [e for e in events if e.region_id == int(region_id)]
    entries: List[Dict[str, Any]] = []
    orphans: List[str] = []
    for knob, value in sorted(live.items()):
        matching = [e for e in region_events if e.knob == knob]
        if not matching:
            orphans.append(knob)
            entries.append({"knob": knob, "value": value,
                            "explained": False, "chain": []})
            continue
        anchor = matching[-1]
        chain = list(matching)
        # cross-controller causality: the anchor's trigger may itself be
        # another controller's decision — surface the newest explaining
        # event per linked actor so the chain reads end to end
        linked = {
            "degrade_level": ("shed",),
            "tier": ("capacity",),
        }.get(knob, ())
        for actor in linked:
            hits = [e for e in region_events
                    if e.actor == actor and e is not anchor
                    and e not in chain]
            if hits:
                chain.append(hits[-1])
        chain.sort(key=lambda e: (e.ts_ms, e.node_id, e.actor_seq))
        # tier rung values pass on any anchor (rungs are a ladder walk —
        # the anchor's `new` IS the live rung when nothing was skipped,
        # and a mid-walk heartbeat is not an integrity violation); every
        # other knob must land exactly where its newest event says, else
        # something moved it afterwards without emitting — an orphan
        # WRITE even though the knob has history
        # str() both sides: local ledger events carry typed old/new
        # (ints, rung names) while pb round-tripped ones carry strings
        explained = knob == "tier" or str(anchor.new) == value
        entries.append({
            "knob": knob,
            "value": value,
            "explained": explained,
            "chain": chain,
        })
        if not explained:
            orphans.append(knob)
    return {
        "region_id": int(region_id),
        "live": dict(sorted(live.items())),
        "entries": entries,
        "orphans": sorted(set(orphans)),
    }
