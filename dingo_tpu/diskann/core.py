"""DiskAnnCore: disk-resident vector index with device-side PQ pruning.

Reference role: the separate `--role=diskann` server (src/diskann/
diskann_core.h:35) wraps vendored Microsoft DiskANN — a Vamana graph on
SSD walked with beam search, PQ codes in RAM for pruning. That design is
built around CPU pointer-chasing; a graph walk is the worst possible TPU
program (data-dependent control flow, tiny reads).

TPU-era redesign with the same storage economics (full vectors NEVER
resident in fast memory):
  disk   — raw vectors in an append-only memmap file (float32 [n, d]),
           written during the IMPORT phase.
  memory — coarse centroids [nlist, d] + residual PQ codes [n, m] uint8
           (the same ~1 byte/dim/8 footprint DiskANN keeps in RAM).
  search — device ADC over probed lists (ivf_layout spill buckets +
           the shared _ivfpq_scan_kernel) produces topk*RERANK_FACTOR
           candidates, then ONE strided disk gather reranks them with an
           exact f32 einsum on device. Beam-search hops become a single
           MXU pass + one batched IO.

State machine mirrors DiskANNCoreState (diskann_item.h): UNINIT ->
IMPORTING -> IMPORTED -> BUILDING -> BUILT -> LOADING -> LOADED (+FAILED);
Reset/Close return to earlier states, Destroy removes files.
"""

from __future__ import annotations

import enum
import json
import os
import shutil
import threading
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dingo_tpu.index.base import IndexParameter, InvalidParameter
from dingo_tpu.index.ivf_layout import build_layout, expand_probes_ranked
from dingo_tpu.ops.distance import Metric, np_normalize, squared_norms
from dingo_tpu.ops.kmeans import MAX_POINTS_PER_CENTROID, kmeans_assign, train_kmeans
from dingo_tpu.ops.pq import pq_train, split_subvectors

#: default ADC candidates fetched from disk per requested result; the
#: prune is intentionally over-broad because disk reads scale with k (not
#: n) and one strided gather amortizes: measured 50K x 128 clustered,
#: nprobe=24: factor 8 -> recall@10 0.838, 16 -> 0.947, 32 -> 0.994
RERANK_FACTOR = 32


def _bounded_gather(mmap: np.ndarray, flat_rows: np.ndarray) -> np.ndarray:
    """Gather rows from the on-disk vector file under an IO budget.

    The candidate set is deduplicated and SORTED before reading — near
    neighbors across queries overlap heavily (one read instead of b), and
    ascending offsets turn a random-read burst into a mostly-forward pass
    — then read in diskann_rerank_io_rows-sized batches so one search
    cannot issue an unbounded burst (VERDICT r2 weak #9). The inverse map
    restores the [len(flat_rows), dim] order the caller indexed."""
    from dingo_tpu.common.config import FLAGS

    budget = max(1, int(FLAGS.get("diskann_rerank_io_rows")))
    uniq, inverse = np.unique(flat_rows, return_inverse=True)
    out = np.empty((uniq.shape[0], mmap.shape[1]), dtype=mmap.dtype)
    for i in range(0, uniq.shape[0], budget):
        out[i:i + budget] = mmap[uniq[i:i + budget]]
    return out[inverse]


class CoreState(enum.Enum):
    UNINIT = "uninit"
    IMPORTING = "importing"
    IMPORTED = "imported"
    BUILDING = "building"
    BUILT = "built"
    LOADING = "loading"
    LOADED = "loaded"
    FAILED = "failed"


class DiskAnnError(RuntimeError):
    pass


class DiskAnnCore:
    def __init__(self, index_id: int, parameter: IndexParameter, data_dir: str):
        if parameter.dimension <= 0:
            raise InvalidParameter(f"dimension {parameter.dimension}")
        if parameter.dimension % parameter.nsubvector:
            raise InvalidParameter(
                f"dimension {parameter.dimension} % m={parameter.nsubvector}"
            )
        if parameter.metric not in (Metric.L2, Metric.INNER_PRODUCT,
                                    Metric.COSINE):
            raise InvalidParameter(f"diskann metric {parameter.metric}")
        self.id = index_id
        self.parameter = parameter
        self.dim = parameter.dimension
        self.metric = parameter.metric
        self.nlist = parameter.ncentroids
        self.m = parameter.nsubvector
        self.ksub = 1 << parameter.nbits_per_idx
        self.dir = data_dir
        os.makedirs(self.dir, exist_ok=True)
        self.state = CoreState.UNINIT
        self._lock = threading.Lock()
        self.count = 0
        self._ids: Optional[np.ndarray] = None         # [n] int64
        self._mmap: Optional[np.memmap] = None         # [n, d] f32 on disk
        self.centroids = None
        self._c_sqnorm = None
        self.codebooks = None
        self._codes = None                             # [n, m] uint8 device
        self._layout = None
        self._code_buckets = None
        self.last_error = ""
        self._id_to_row: dict = {}
        # restart recovery: a previous incarnation's import data on disk is
        # adopted (count/ids restored) so appends stay consistent instead of
        # silently pairing stale rows with a fresh count
        if os.path.exists(self._ids_path()):
            prev = np.fromfile(self._ids_path(), np.int64)
            self.count = len(prev)
            self._id_to_row = {int(v): i for i, v in enumerate(prev)}
            # a crash between the row append and the ids append can leave
            # orphan rows in vectors.f32; truncate so future appends align
            want = self.count * self.dim * 4
            if (os.path.exists(self._data_path())
                    and os.path.getsize(self._data_path()) > want):
                with open(self._data_path(), "r+b") as f:
                    f.truncate(want)
            if self.count:
                self.state = CoreState.IMPORTED

    # -- paths ---------------------------------------------------------------
    def _data_path(self) -> str:
        return os.path.join(self.dir, "vectors.f32")

    def _ids_path(self) -> str:
        return os.path.join(self.dir, "ids.bin")   # append-only int64

    def _index_path(self) -> str:
        return os.path.join(self.dir, "pq_index.npz")

    def _meta_path(self) -> str:
        return os.path.join(self.dir, "meta.json")

    # -- import --------------------------------------------------------------
    def push_data(self, ids: np.ndarray, vectors: np.ndarray,
                  has_more: bool) -> int:
        """Append a batch to the disk file (VectorPushData). Returns the
        total row count so far."""
        with self._lock:
            # IMPORTED is re-enterable: restart recovery lands there and a
            # caller may resume pushing before (re)building
            if self.state not in (CoreState.UNINIT, CoreState.IMPORTING,
                                  CoreState.IMPORTED):
                raise DiskAnnError(f"push_data in state {self.state.value}")
            self.state = CoreState.IMPORTING
        vectors = np.asarray(vectors, np.float32)
        ids = np.asarray(ids, np.int64)
        if vectors.ndim != 2 or vectors.shape[1] != self.dim:
            raise InvalidParameter(f"vector shape {vectors.shape}")
        if len(ids) != len(vectors):
            raise InvalidParameter("ids/vectors length mismatch")
        if self.metric is Metric.COSINE:
            vectors = np_normalize(vectors)
        with self._lock:
            # upsert semantics: an already-pushed id overwrites its row in
            # place instead of appending a duplicate physical row
            fresh_rows, fresh_ids = [], []
            replace = []           # (row_index, vector)
            for vid, row in zip(ids, vectors):
                r = self._id_to_row.get(int(vid))
                if r is None:
                    self._id_to_row[int(vid)] = self.count + len(fresh_ids)
                    fresh_ids.append(int(vid))
                    fresh_rows.append(row)
                else:
                    replace.append((r, row))
            if fresh_rows:
                with open(self._data_path(), "ab") as f:
                    f.write(np.stack(fresh_rows).tobytes())
                    f.flush()
            if replace:
                mm = np.memmap(self._data_path(), np.float32, "r+",
                               shape=(self.count + len(fresh_ids), self.dim))
                for r, row in replace:
                    mm[r] = row
                mm.flush()
                del mm
            if fresh_ids:
                # append-only: O(batch) per push, not O(total) rewrites
                with open(self._ids_path(), "ab") as f:
                    f.write(np.asarray(fresh_ids, np.int64).tobytes())
            self.count += len(fresh_ids)
            if not has_more:
                self.state = CoreState.IMPORTED
            return self.count

    # -- build ---------------------------------------------------------------
    def build(self) -> None:
        """Train coarse quantizer + residual PQ on a disk sample, then
        encode every row chunked through the device (VectorBuild)."""
        with self._lock:
            # a Build request while IMPORTING finalizes the import (the
            # serving path streams rows with has_more=True and signals the
            # end by asking for the build)
            if self.state is CoreState.IMPORTING and self.count:
                self.state = CoreState.IMPORTED
            if self.state not in (CoreState.IMPORTED, CoreState.BUILT):
                raise DiskAnnError(f"build in state {self.state.value}")
            self.state = CoreState.BUILDING
        try:
            n = self.count
            if n < max(self.nlist, self.ksub):
                raise DiskAnnError(
                    f"need >= {max(self.nlist, self.ksub)} rows, have {n}"
                )
            mm = np.memmap(self._data_path(), np.float32, "r",
                           shape=(n, self.dim))
            cap = min(n, MAX_POINTS_PER_CENTROID * self.nlist)
            rng = np.random.default_rng(self.id)
            sel = np.sort(rng.choice(n, cap, replace=False)) if cap < n \
                else np.arange(n)
            sample = jnp.asarray(np.array(mm[sel]))
            centroids, _ = train_kmeans(sample, k=self.nlist, iters=10,
                                        seed=self.id)
            assign_s = kmeans_assign(sample, centroids)
            resid = sample - jnp.take(centroids, assign_s, axis=0)
            codebooks = pq_train(resid, m=self.m, ksub=self.ksub, iters=10,
                                 seed=self.id)
            # encode all rows, streaming from disk in chunks
            codes = np.empty((n, self.m), np.uint8)
            assign = np.empty(n, np.int32)
            chunk = 65536
            for i in range(0, n, chunk):
                rows = jnp.asarray(np.array(mm[i:i + chunk]))
                a = kmeans_assign(rows, centroids)
                r = rows - jnp.take(centroids, a, axis=0)
                subs = split_subvectors(r, self.m)       # [m, c, dsub]

                def enc(sub, cb):
                    d2 = (
                        squared_norms(sub)[:, None]
                        - 2.0 * jnp.einsum(
                            "nd,kd->nk", sub, cb,
                            precision=jax.lax.Precision.HIGHEST,
                        )
                        + squared_norms(cb)[None, :]
                    )
                    return jnp.argmin(d2, axis=1)

                c = jax.vmap(enc)(subs, codebooks).T.astype(jnp.uint8)
                codes[i:i + chunk] = np.asarray(c)
                assign[i:i + chunk] = np.asarray(a)
            np.savez(
                self._index_path(),
                centroids=np.asarray(centroids),
                codebooks=np.asarray(codebooks),
                codes=codes,
                assign=assign,
            )
            with open(self._meta_path(), "w") as f:
                json.dump({"count": n, "dim": self.dim, "m": self.m,
                           "nlist": self.nlist,
                           "metric": self.metric.value}, f)
            with self._lock:
                self.state = CoreState.BUILT
        except Exception as e:
            with self._lock:
                self.state = CoreState.FAILED
                self.last_error = str(e)
            raise

    # -- load ----------------------------------------------------------------
    def load(self) -> None:
        """Map the disk file + put codes/centroids on device (VectorLoad)."""
        with self._lock:
            if self.state not in (CoreState.BUILT, CoreState.LOADED,
                                  CoreState.UNINIT, CoreState.IMPORTED):
                raise DiskAnnError(f"load in state {self.state.value}")
            if not os.path.exists(self._index_path()):
                raise DiskAnnError("not built")
            self.state = CoreState.LOADING
        try:
            with open(self._meta_path()) as f:
                meta = json.load(f)
            if meta["dim"] != self.dim or meta["m"] != self.m:
                raise DiskAnnError("index file parameter mismatch")
            n = meta["count"]
            data = np.load(self._index_path())
            self._mmap = np.memmap(self._data_path(), np.float32, "r",
                                   shape=(n, self.dim))
            self._ids = np.fromfile(self._ids_path(), np.int64)[:n]
            self.count = n
            self.centroids = jnp.asarray(data["centroids"])
            self._c_sqnorm = squared_norms(self.centroids)
            self.codebooks = jnp.asarray(data["codebooks"])
            self._codes = jnp.asarray(data["codes"])
            lay = build_layout(
                data["assign"], np.ones(n, bool), self.nlist
            )
            self._layout = lay
            self._code_buckets = lay.gather_rows(self._codes)
            with self._lock:
                self.state = CoreState.LOADED
        except Exception as e:
            with self._lock:
                self.state = CoreState.FAILED
                self.last_error = str(e)
            raise

    def try_load(self) -> bool:
        """Load if an index file exists (VectorTryLoad); False otherwise."""
        if not os.path.exists(self._index_path()):
            return False
        self.load()
        return True

    # -- search --------------------------------------------------------------
    def search(self, queries: np.ndarray, topk: int,
               nprobe: Optional[int] = None,
               rerank_factor: Optional[int] = None,
               ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """ADC prune on device -> exact disk rerank. Returns per-query
        (ids [k], distances [k])."""
        from dingo_tpu.index.flat import _pad_batch
        from dingo_tpu.index.ivf_flat import _probe_lists
        from dingo_tpu.index.ivf_pq import _ivfpq_scan_kernel

        with self._lock:
            if self.state is not CoreState.LOADED:
                raise DiskAnnError(f"search in state {self.state.value}")
            # snapshot device/disk state under the lock: a concurrent
            # close()/reset() nulls the attributes, but these locals keep
            # their objects alive for the duration of this search
            mmap = self._mmap
            ids_arr = self._ids
            lay = self._layout
            code_buckets = self._code_buckets
            centroids = self.centroids
            c_sqnorm = self._c_sqnorm
            codebooks = self.codebooks
            count = self.count
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if self.metric is Metric.COSINE:
            queries = np_normalize(queries)
        b = queries.shape[0]
        k = int(topk)
        kprime = min(count, k * (rerank_factor or RERANK_FACTOR))
        nprobe = min(nprobe or self.parameter.default_nprobe, self.nlist)
        qpad = jnp.asarray(_pad_batch(queries))
        probes = _probe_lists(qpad, centroids, c_sqnorm, nprobe)
        vprobes, coarse_pos = expand_probes_ranked(
            probes, lay.probe_table, nprobe, lay.max_spill
        )
        lut_bytes = qpad.shape[0] * nprobe * self.m * self.ksub * 4
        _, rows = _ivfpq_scan_kernel(
            code_buckets, lay.bucket_valid, lay.bucket_slot,
            lay.bucket_coarse, probes, vprobes, coarse_pos, qpad,
            centroids, codebooks, k=kprime,
            precompute_lut=lut_bytes <= 256 * 1024 * 1024,
        )
        rows = np.asarray(rows)[:b]                   # [b, k'] row indices
        # exact rerank: bounded disk gather + einsum on device
        safe = np.where(rows >= 0, rows, 0)
        cand = _bounded_gather(mmap, safe.reshape(-1)).reshape(
            b, kprime, self.dim
        )
        dc = jnp.asarray(cand)
        qd = jnp.asarray(queries)
        dots = jnp.einsum(
            "bd,bkd->bk", qd, dc,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if self.metric is Metric.L2:
            exact = (
                squared_norms(qd)[:, None] - 2.0 * dots
                + jnp.einsum("bkd,bkd->bk", dc, dc,
                             precision=jax.lax.Precision.HIGHEST)
            )
            order = jnp.argsort(
                jnp.where(jnp.asarray(rows) >= 0, exact, jnp.inf), axis=1
            )[:, :k]
        else:
            exact = dots
            order = jnp.argsort(
                jnp.where(jnp.asarray(rows) >= 0, -exact, jnp.inf), axis=1
            )[:, :k]
        order_h = np.asarray(order)
        exact_h = np.asarray(exact)
        out = []
        for qi in range(b):
            sel = order_h[qi]
            valid = rows[qi][sel] >= 0
            sel = sel[valid]
            out.append((
                ids_arr[rows[qi][sel]],
                exact_h[qi][sel],
            ))
        return out

    # -- lifecycle -----------------------------------------------------------
    def status(self) -> CoreState:
        with self._lock:
            return self.state

    def close(self) -> None:
        """Unload device/memory state; disk files stay (VectorClose)."""
        with self._lock:
            self._mmap = None
            self._codes = None
            self._code_buckets = None
            self._layout = None
            self.centroids = None
            self.codebooks = None
            if self.state in (CoreState.LOADED, CoreState.LOADING):
                self.state = CoreState.BUILT

    def reset(self, delete_data_file: bool = False) -> None:
        """Back to importable state (VectorReset)."""
        self.close()
        with self._lock:
            if delete_data_file:
                for p in (self._data_path(), self._ids_path(),
                          self._index_path(), self._meta_path()):
                    if os.path.exists(p):
                        os.remove(p)
                self.count = 0
                self._id_to_row.clear()
                self.state = CoreState.UNINIT
            else:
                self.state = (
                    CoreState.IMPORTED if self.count else CoreState.UNINIT
                )

    def destroy(self) -> None:
        self.close()
        with self._lock:
            shutil.rmtree(self.dir, ignore_errors=True)
            self.count = 0
            self._id_to_row.clear()
            self.state = CoreState.UNINIT
