"""k-means + PQ kernel tests (training quality, encode/ADC numerics).

Mirrors the reference's IVF/PQ train+boundary suites
(test/unit_test/vector/test_vector_index_ivf_flat.cc,
 test_vector_index_raw_ivf_pq_boundary.cc)."""

import numpy as np
import jax.numpy as jnp

from dingo_tpu.ops.kmeans import kmeans_assign, kmeans_fit, train_kmeans
from dingo_tpu.ops.pq import (
    adc_lut,
    adc_scan,
    pq_encode,
    pq_reconstruct,
    pq_train,
)


def make_blobs(rng, k=8, per=200, d=32, spread=0.05):
    centers = rng.standard_normal((k, d)).astype(np.float32) * 3
    x = np.concatenate(
        [c + spread * rng.standard_normal((per, d)).astype(np.float32) for c in centers]
    )
    return x, centers


def test_kmeans_recovers_blobs():
    rng = np.random.default_rng(1)
    x, centers = make_blobs(rng)
    c, counts = train_kmeans(jnp.array(x), k=8, iters=15)
    c = np.asarray(c)
    # Every true center has a learned centroid nearby.
    d = ((centers[:, None, :] - c[None, :, :]) ** 2).sum(-1)
    assert (d.min(axis=1) < 0.5).all(), d.min(axis=1)
    assert np.asarray(counts).sum() == len(x)


def test_kmeans_assign_consistent():
    rng = np.random.default_rng(2)
    x, _ = make_blobs(rng, k=4, per=100)
    c, _ = train_kmeans(jnp.array(x), k=4, iters=10)
    a = np.asarray(kmeans_assign(jnp.array(x), c))
    # numpy argmin agreement
    d = ((x[:, None, :] - np.asarray(c)[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(a, d.argmin(axis=1))


def test_kmeans_empty_cluster_reseed():
    rng = np.random.default_rng(3)
    # 2 tight blobs but ask for 4 clusters: forces empties; should not NaN.
    x, _ = make_blobs(rng, k=2, per=50, d=8, spread=0.01)
    seed = np.array([0, 1, 2, 3], np.int32)
    c, _ = kmeans_fit(jnp.array(x), jnp.array(seed), k=4, iters=8)
    assert np.isfinite(np.asarray(c)).all()


def test_pq_encode_decode_error_small():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((2000, 64)).astype(np.float32)
    cb = pq_train(jnp.array(x), m=8, iters=8)
    codes = pq_encode(jnp.array(x), cb)
    assert codes.shape == (2000, 8) and codes.dtype == jnp.uint8
    recon = np.asarray(pq_reconstruct(codes, cb))
    rel = np.linalg.norm(recon - x) / np.linalg.norm(x)
    assert rel < 0.75, rel  # 8 bytes for 256 f32 dims: coarse but bounded


def test_adc_matches_reconstruction_distance():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((512, 32)).astype(np.float32)
    q = rng.standard_normal((4, 32)).astype(np.float32)
    cb = pq_train(jnp.array(x), m=4, iters=8)
    codes = pq_encode(jnp.array(x), cb)
    lut = adc_lut(jnp.array(q), cb)
    d_adc = np.asarray(adc_scan(lut, codes))
    recon = np.asarray(pq_reconstruct(codes, cb))
    d_exact = ((q[:, None, :] - recon[None, :, :]) ** 2).sum(-1)
    # ADC == exact distance to the reconstruction, up to bf16 matmul noise.
    np.testing.assert_allclose(d_adc, d_exact, rtol=2e-2, atol=2e-1)


def test_adc_recall_vs_exact():
    rng = np.random.default_rng(6)
    x = rng.standard_normal((4096, 64)).astype(np.float32)
    q = rng.standard_normal((16, 64)).astype(np.float32)
    cb = pq_train(jnp.array(x), m=16, iters=10)
    codes = pq_encode(jnp.array(x), cb)
    lut = adc_lut(jnp.array(q), cb)
    d_adc = np.asarray(adc_scan(lut, codes))
    d_exact = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    got = np.argsort(d_adc, 1)[:, :10]
    want = np.argsort(d_exact, 1)[:, :10]
    recall = np.mean([len(set(g) & set(w)) / 10 for g, w in zip(got, want)])
    assert recall >= 0.5, recall  # PQ16 on random gaussian data
