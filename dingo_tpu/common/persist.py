"""Typed persistence codec for local disk/meta-CF state.

Round-1/2 persisted coordinator, region, and document state with pickle:
restoring a tampered backup or snapshot was arbitrary code execution, and
the format was version-fragile. The wire TLV codec (raft/wire.py) already
covers plain trees; this module adds the typed layer — a REGISTRY of
allowed dataclasses and enums, encoded as tagged plain trees — so decoding
allocates only registered types and never executes code (the reference
persists typed protobuf everywhere for the same reason).

Envelope forms inside the plain tree:
  {"__dc": "Name", "f": {field: value}}   registered dataclass
  {"__en": "Name", "v": value}            registered enum
  {"__d": [[k, v], ...]}                  dict with non-str keys
  {"__t": [items]}                        tuple (lists encode bare)

Legacy pickle blobs are NOT readable by default; set
DINGO_ALLOW_PICKLE_MIGRATION=1 for a one-time migration load of data you
trust (the flag exists so old deployments can upgrade, not as a mode).
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Any, Dict, Type

from dingo_tpu.raft import wire

_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator: allow this dataclass/enum in persisted state."""
    prior = _REGISTRY.get(cls.__name__)
    if prior is not None and prior is not cls:
        raise TypeError(
            f"persist name collision: {cls.__name__} already registered "
            f"from {prior.__module__} — persisted blobs are keyed by class "
            "name, rename one of them"
        )
    _REGISTRY[cls.__name__] = cls
    return cls


def _ensure_registered(cls: type) -> str:
    name = cls.__name__
    if _REGISTRY.get(name) is not cls:
        raise TypeError(
            f"{name} is not persist.register()ed — refusing to serialize "
            "an unvetted type"
        )
    return name


def to_plain(v: Any) -> Any:
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        name = _ensure_registered(type(v))
        return {
            "__dc": name,
            "f": {
                f.name: to_plain(getattr(v, f.name))
                for f in dataclasses.fields(v)
            },
        }
    if isinstance(v, enum.Enum):
        return {"__en": _ensure_registered(type(v)), "v": v.value}
    if isinstance(v, dict):
        if all(isinstance(k, str) for k in v) and not (
            set(v) & {"__dc", "__en", "__d", "__t"}
        ):
            return {k: to_plain(x) for k, x in v.items()}
        return {"__d": [[to_plain(k), to_plain(x)] for k, x in v.items()]}
    if isinstance(v, tuple):
        return {"__t": [to_plain(i) for i in v]}
    if isinstance(v, list):
        return [to_plain(i) for i in v]
    return v


def from_plain(v: Any) -> Any:
    if isinstance(v, dict):
        if "__dc" in v:
            cls = _REGISTRY.get(v["__dc"])
            if cls is None or not dataclasses.is_dataclass(cls):
                raise wire.WireError(f"unknown dataclass {v.get('__dc')!r}")
            try:
                fields = {k: from_plain(x) for k, x in v["f"].items()}
                known = {f.name for f in dataclasses.fields(cls)}
                # forward/backward compat: drop unknown fields, let
                # defaults fill missing ones
                return cls(**{k: x for k, x in fields.items() if k in known})
            except wire.WireError:
                raise
            except Exception as e:
                # corrupt/version-skewed state keeps the documented error
                # contract (callers catch WireError, not constructor noise)
                raise wire.WireError(
                    f"malformed {v['__dc']} envelope: {e}"
                ) from e
        if "__en" in v:
            cls = _REGISTRY.get(v["__en"])
            if cls is None or not issubclass(cls, enum.Enum):
                raise wire.WireError(f"unknown enum {v.get('__en')!r}")
            try:
                return cls(v["v"])
            except Exception as e:
                raise wire.WireError(
                    f"malformed {v['__en']} envelope: {e}"
                ) from e
        if "__d" in v:
            return {from_plain(k): from_plain(x) for k, x in v["__d"]}
        if "__t" in v:
            return tuple(from_plain(i) for i in v["__t"])
        return {k: from_plain(x) for k, x in v.items()}
    if isinstance(v, list):
        return [from_plain(i) for i in v]
    return v


def dumps(obj: Any) -> bytes:
    return wire.encode(to_plain(obj))


def loads(blob: bytes) -> Any:
    try:
        tree = wire.decode(blob)
    except wire.WireError:
        if os.environ.get("DINGO_ALLOW_PICKLE_MIGRATION") == "1":
            import pickle  # noqa: S403 — explicit operator opt-in

            return pickle.loads(blob)  # noqa: S301
        raise wire.WireError(
            "blob is not in the typed persist format (legacy pickle "
            "state? set DINGO_ALLOW_PICKLE_MIGRATION=1 for a one-time "
            "trusted migration load)"
        )
    return from_plain(tree)
