"""Fused Pallas distance+topk kernel tests (interpret mode on CPU; the
same program compiles for TPU via Mosaic)."""

import numpy as np
import jax.numpy as jnp
import pytest

from dingo_tpu.ops.pallas_topk import fused_search


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    n, d = 3000, 32
    x = rng.standard_normal((n, d), dtype=np.float32)
    q = x[:6] + 0.05 * rng.standard_normal((6, d)).astype(np.float32)
    xd = jnp.asarray(x)
    xsq = jnp.einsum("nd,nd->n", xd, xd)
    return x, q, xd, xsq


def test_l2_exact_with_mask(data):
    x, q, xd, xsq = data
    valid = np.ones(len(x), bool)
    valid[::5] = False
    vals, ids = fused_search(q, xd, xsq, jnp.asarray(valid), 10, block=512)
    d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    d2[:, ~valid] = np.inf
    want = np.argsort(d2, 1)[:, :10]
    np.testing.assert_array_equal(np.asarray(ids), want)
    np.testing.assert_allclose(
        -np.asarray(vals), np.take_along_axis(d2, want, 1),
        rtol=5e-3, atol=5e-2,
    )


def test_ip_exact(data):
    x, q, xd, xsq = data
    valid = np.ones(len(x), bool)
    vals, ids = fused_search(q, xd, xsq, jnp.asarray(valid), 5, block=512,
                             ascending=False)
    ip = q @ x.T
    want = np.argsort(-ip, 1)[:, :5]
    np.testing.assert_array_equal(np.asarray(ids), want)


def test_padding_and_small_k(data):
    x, q, xd, xsq = data
    # n=3000 pads to 3072 with block 1024; padded rows must never win
    valid = np.ones(len(x), bool)
    vals, ids = fused_search(q, xd, xsq, jnp.asarray(valid), 3, block=1024)
    assert (np.asarray(ids) < 3000).all()
    d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    want = np.argsort(d2, 1)[:, :3]
    np.testing.assert_array_equal(np.asarray(ids), want)


def test_fully_masked_returns_minus_one(data):
    x, q, xd, xsq = data
    vals, ids = fused_search(q, xd, xsq, jnp.zeros(len(x)), 4, block=512)
    assert (np.asarray(ids) == -1).all()


def test_fewer_valid_than_k_pads_with_minus_one(data):
    """k > number of valid vectors: the surplus picks are -inf and must
    come back as -1, not as leaked/duplicated real slot ids (round-1
    advisor repro: 3 valid over 2 blocks, k=5 returned [0, 2, 1, 0, 0])."""
    x, q, xd, xsq = data
    valid = np.zeros(len(x), bool)
    valid[[0, 1, 600]] = True  # spans two 512-blocks
    vals, ids = fused_search(q, xd, xsq, jnp.asarray(valid), 5, block=512)
    ids = np.asarray(ids)
    assert set(ids[:, :3].ravel()) <= {0, 1, 600}
    # each query returns the 3 valid ids exactly once, then -1 padding
    for row in ids:
        assert sorted(row[:3]) == [0, 1, 600]
        assert (row[3:] == -1).all()
