"""Raft wire codec: round-trip + malformed-input rejection.

Replaces round-1 pickle (advisor: raft-port RCE). The codec must cover
exactly the payload shapes RaftNode sends (dicts of scalars, entry tuples,
snapshot blobs) and reject anything malformed instead of executing it.
"""

import pytest

from dingo_tpu.raft import wire


CASES = [
    None,
    True,
    False,
    0,
    -1,
    2**62,
    -(2**62),
    1.5,
    float("inf"),
    "",
    "héllo",
    b"",
    b"\x00\xff" * 100,
    [],
    {},
    [1, "a", b"b", None, [2, 3]],
    {"from": "s1/r7", "term": 3, "entries": [(1, 1, b"x"), (2, 1, b"y")],
     "commit": 2, "ok": True, "blob": b"\x00" * 1000},
]


@pytest.mark.parametrize("obj", CASES, ids=range(len(CASES)))
def test_roundtrip(obj):
    got = wire.decode(wire.encode(obj))

    def norm(o):
        if isinstance(o, (list, tuple)):
            return [norm(i) for i in o]
        if isinstance(o, dict):
            return {k: norm(v) for k, v in o.items()}
        return o

    assert norm(got) == norm(obj)


def test_append_entries_shape_survives():
    """The exact message _replicate_to sends: entries unpack as 3-tuples."""
    msg = {"from": "a", "term": 5, "prev_index": 9, "prev_term": 4,
           "entries": [(10, 5, b"p1"), (11, 5, b"p2")], "commit": 9}
    got = wire.decode(wire.encode(msg))
    for index, term, payload in got["entries"]:
        assert isinstance(index, int) and isinstance(payload, bytes)


@pytest.mark.parametrize("bad", [
    b"",                      # empty
    b"\x63",                  # unknown tag
    b"\x03\x00",              # truncated int
    b"\x05\x00\x00\x00\x00\x00\x00\x00\x09abc",  # str len 9, 3 bytes
    wire.encode({"a": 1}) + b"x",                # trailing garbage
    b"\x07" + b"\xff" * 8,    # list claims 2^64 items
    b"\x08\x00\x00\x00\x00\x00\x00\x00\x01" + b"\x03" + b"\x00" * 8 + b"\x00",
    # ^ dict with non-str (int) key
])
def test_malformed_rejected(bad):
    with pytest.raises(wire.WireError):
        wire.decode(bad)


def test_unsupported_type_rejected():
    with pytest.raises(wire.WireError):
        wire.encode(object())
    with pytest.raises(wire.WireError):
        wire.encode({1: "non-str-key"})
