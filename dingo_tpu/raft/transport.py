"""Raft message transport.

The reference replicates over brpc (braft's TCP stack). Here the transport is
pluggable: LocalTransport delivers RPCs in-process with optional fault
injection (drop/partition/delay) — the single-process multi-peer topology the
reference's raft tests use (test_raft_node.cc: 3 braft peers on one
127.0.0.1 server distinguished by peer index). A grpc transport slots in for
multi-process deployments (server/ layer).

Fault injection is generalized by ``TransportFaults``: a seeded per-peer-pair
rule set (drop probability, delay, duplicate probability, partitions) that
both LocalTransport and GrpcRaftTransport consult on every send. Rules key
on STORE ids (the prefix of "<store_id>/r<region_id>" node addresses) so one
rule covers every region-pair between two stores; the chaos harness
(tools/chaos.py) drives it deterministically via the seed.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Set, Tuple


def _store_of(node_id: str) -> str:
    """Store prefix of a raft node address ("s0/r7" -> "s0")."""
    return node_id.split("/")[0]


class LinkRule:
    """Fault parameters for one directed (src_store, dst_store) link."""

    __slots__ = ("drop", "delay_ms", "duplicate")

    def __init__(self, drop: float = 0.0, delay_ms: float = 0.0,
                 duplicate: float = 0.0):
        self.drop = drop
        self.delay_ms = delay_ms
        self.duplicate = duplicate


class TransportFaults:
    """Seeded, deterministic per-peer-pair fault rules.

    Verdicts are rolled on the SENDER's thread under one lock so a chaos
    run with a fixed seed and a fixed send order replays exactly. The
    ``decide`` contract: returns (deliver, delay_s, copies) — copies > 1
    means the transport should send the message that many times (duplicate
    delivery; raft must dedupe by term/index, which is the invariant the
    fault exists to exercise).
    """

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._partitions: Set[Tuple[str, str]] = set()
        self._links: Dict[Tuple[str, str], LinkRule] = {}
        self._default = LinkRule()
        self.injected = 0   # faults that actually fired (drop/delay/dup)

    def set_seed(self, seed: int) -> None:
        with self._lock:
            self._rng = random.Random(seed)

    # -- rules ---------------------------------------------------------------
    def partition(self, a: str, b: str) -> None:
        """Cut the store-pair a<->b (both directions)."""
        with self._lock:
            self._partitions.add((a, b))
            self._partitions.add((b, a))

    def heal(self, a: Optional[str] = None, b: Optional[str] = None) -> None:
        """Heal one store-pair (both directions) or, with no args, every
        partition AND every link rule."""
        with self._lock:
            if a is None:
                self._partitions.clear()
                self._links.clear()
                self._default = LinkRule()
            else:
                self._partitions.discard((a, b))
                self._partitions.discard((b, a))

    def set_link(self, src: str, dst: str, drop: float = 0.0,
                 delay_ms: float = 0.0, duplicate: float = 0.0) -> None:
        """Directed per-pair rule ("*" wildcard = the default rule)."""
        rule = LinkRule(drop, delay_ms, duplicate)
        with self._lock:
            if src == "*" and dst == "*":
                self._default = rule
            else:
                self._links[(src, dst)] = rule

    def is_partitioned(self, src: str, dst: str) -> bool:
        with self._lock:
            return (src, dst) in self._partitions

    # -- verdict -------------------------------------------------------------
    def decide(self, src: str, dst: str) -> Tuple[bool, float, int]:
        """(deliver, delay_s, copies) for one message src_store->dst_store.

        Counter emission happens AFTER the lock is released: the metrics
        registry has its own lock, and nesting registry acquisition under
        this one while other code observes transport state under the
        registry lock is a lock-order cycle (dingolint: lock-order)."""
        fired: list = []
        with self._lock:
            if (src, dst) in self._partitions:
                self.injected += 1
                verdict = (False, 0.0, 0)
                fired.append("partition")
            else:
                rule = self._links.get((src, dst), self._default)
                if rule.drop and self._rng.random() < rule.drop:
                    self.injected += 1
                    verdict = (False, 0.0, 0)
                    fired.append("drop")
                else:
                    copies = 1
                    if rule.duplicate \
                            and self._rng.random() < rule.duplicate:
                        self.injected += 1
                        fired.append("duplicate")
                        copies = 2
                    delay_s = (rule.delay_ms / 1000.0
                               if rule.delay_ms else 0.0)
                    if delay_s:
                        self.injected += 1
                        fired.append("delay")
                    verdict = (True, delay_s, copies)
        for kind in fired:
            self._count(kind)
        return verdict

    @staticmethod
    def _count(kind: str) -> None:
        from dingo_tpu.common.metrics import METRICS

        METRICS.counter("fault.transport_faults",
                        labels={"kind": kind}).add(1)


class Transport:
    def send(self, target: str, method: str, msg: dict) -> Optional[dict]:
        """Synchronous RPC; returns response dict or None on network error."""
        raise NotImplementedError

    def register(self, node_id: str, handler: Callable[[str, dict], dict]) -> None:
        raise NotImplementedError


class LocalTransport(Transport):
    """In-process delivery with fault injection for tests."""

    def __init__(self, seed: int = 0):
        self._handlers: Dict[str, Callable[[str, dict], dict]] = {}
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self.drop_rate = 0.0
        self._partitions: Set[Tuple[str, str]] = set()
        self.delay_s = 0.0
        #: optional generalized per-peer-pair rules (store-id keyed);
        #: consulted IN ADDITION to the legacy node-id fields above
        self.faults: Optional[TransportFaults] = None

    def register(self, node_id: str, handler) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._handlers.pop(node_id, None)

    def partition(self, a: str, b: str) -> None:
        """Cut the link a<->b (both directions; node-id granularity)."""
        self._partitions.add((a, b))
        self._partitions.add((b, a))

    def heal(self) -> None:
        self._partitions.clear()
        if self.faults is not None:
            self.faults.heal()

    def _deliver(self, target: str, method: str, msg: dict) -> Optional[dict]:
        with self._lock:
            handler = self._handlers.get(target)
        if handler is None:
            return None
        try:
            return handler(method, msg)
        except Exception:
            return None

    def send(self, target: str, method: str, msg: dict) -> Optional[dict]:
        src = msg.get("from", "?")
        if (src, target) in self._partitions:
            return None
        if self.drop_rate and self._rng.random() < self.drop_rate:
            return None
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.faults is not None:
            deliver, delay_s, copies = self.faults.decide(
                _store_of(src), _store_of(target))
            if not deliver:
                return None
            if delay_s:
                time.sleep(delay_s)
            if copies > 1:
                # duplicate delivery: the receiver sees the message twice;
                # the FIRST response is what the sender acts on
                first = self._deliver(target, method, msg)
                self._deliver(target, method, msg)
                return first
        return self._deliver(target, method, msg)
