"""lock-order: extract lock acquisitions into a graph; flag cycles and
declared-order reversals.

The repo's locking discipline grew by accretion: ``store.device_lock``
(PR 3) serializes every device mutation against donated-buffer searches;
the wrapper lock (index/wrapper.py) serializes raft apply against
rebuild swaps and is held AROUND device work (add -> store.put ->
device_lock is the canonical nesting); the obs planes (hbm / flight /
pressure / quality / integrity) each have a plane lock that must stay
subordinate to the serving locks it observes (the integrity scrub and
the quality shadow lane both take ``store.device_lock`` — if they did so
while holding their plane lock, AND a serving path ever called into the
plane while holding the device lock, two threads would deadlock in a way
no unit test reproduces); the coalescer queue lock brackets admission
accounting. None of this was written down as an order, so nothing
stopped a new call site from inverting it.

This checker derives the order instead of trusting convention: every
``with <lock>`` region is classified into a lock *category* (static
analysis can't see instances, but the categories — device lock, one per
(class, attr) plane/queue/wrapper lock — are exactly the deadlock-
relevant equivalence classes), nested acquisitions (lexical nesting plus
calls whose transitive callees acquire) become edges, and the checker
flags (a) any cycle among distinct categories, (b) a self-edge on a
category backed by a non-reentrant ``threading.Lock`` (an RLock
re-entering itself is legal; a plain Lock doing so is a guaranteed
single-thread deadlock), and (c) reversals of the declared known-order
pairs below.

Resolution notes: receivers the analysis can't root (``e.lock`` on a
loop variable) are skipped rather than guessed — a false alias would
manufacture cycles. Transitive acquisition propagates over exact call
edges PLUS capped fuzzy basename edges: cross-object lock nesting
(``wrapper.add -> store.put -> device_lock``) is invisible to exact
resolution, and an exact-only graph came back empty on the very repo
whose discipline it exists to check. The callgraph's FUZZY_STOPLIST
keeps builtin-collision names (``append``/``get``/...) from welding
unrelated subsystems together; on the current tree the fuzzy graph has
~54 edges and is verifiably acyclic.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.dingolint.callgraph import dotted_name
from tools.dingolint.core import Checker, Finding, Module, Repo

#: declared partial order: (outer, inner) pairs that are the sanctioned
#: nesting — the REVERSED edge is a violation even without a full cycle
#: (the cycle only materializes once both paths run concurrently, which
#: is exactly too late). Derived from the PR 3 discipline: the wrapper
#: lock wraps device work, never the other way; the coalescer queue lock
#: and the obs plane locks are leaves with respect to the device lock.
KNOWN_ORDER: List[Tuple[str, str]] = [
    ("wrapper.VectorIndexWrapper._lock", "store.device_lock"),
    ("integrity.IntegrityPlane._lock", "integrity.ArtifactLedger.lock"),
]

#: attrs that denote a lock when they terminate a with-item expression
_LOCK_ATTRS = {"_lock", "lock", "_mu", "device_lock", "_device_lock"}


def classify_lock(module: Module, node: ast.AST,
                  cls: Optional[str]) -> Optional[str]:
    """Map a with-item context expression to a lock category, or None
    when it isn't a lock / can't be rooted confidently."""
    parts = dotted_name(node)
    if parts is None or len(parts) < 2:
        return None
    attr = parts[-1]
    if attr not in _LOCK_ATTRS:
        return None
    if attr in ("device_lock", "_device_lock"):
        # every SlotStore-family device lock shares one discipline (the
        # sharded tier's _device_lock plays the same donation-safety role)
        return "store.device_lock"
    if parts[0] == "self" and len(parts) == 2 and cls is not None:
        short = module.name.rsplit(".", 1)[-1]
        return f"{short}.{cls}.{attr}"
    # a known lock attr on a non-self receiver: root it only when the
    # receiver is a module-level singleton name (METRICS, PRESSURE, ...)
    if len(parts) == 2 and parts[0].isupper():
        return f"{parts[0]}.{attr}"
    return None


class LockOrderChecker(Checker):
    name = "lock-order"
    description = ("lock-acquisition graph must stay acyclic and respect "
                   "the declared nesting order")

    def __init__(self):
        #: (outer, inner) -> list of witness strings "path:line via ..."
        self.edges: Dict[Tuple[str, str], List[str]] = {}
        self._direct: Dict[str, Set[str]] = {}
        self._reentrant: Set[str] = set()

    # -- per-function direct acquisitions ---------------------------------
    def _locks_in(self, module: Module, fn: ast.AST, qual: str
                  ) -> List[Tuple[ast.With, str]]:
        cg = self.repo.callgraph()
        info = cg.funcs.get(f"{module.name}.{qual}")
        cls = info.cls if info else None
        out = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            if module.qualname_of(node) != qual:
                continue  # belongs to a nested def
            for item in node.items:
                cat = classify_lock(module, item.context_expr, cls)
                if cat:
                    out.append((node, cat))
        return out

    def _collect_direct(self) -> None:
        """Per-function directly-acquired categories + RLock census."""
        cg = self.repo.callgraph()
        for gqual, info in cg.funcs.items():
            local = gqual[len(info.module.name) + 1:]
            cats = {c for _, c in self._locks_in(info.module, info.node,
                                                 local)}
            if cats:
                self._direct[gqual] = cats
        # reentrancy census: self.<attr> = threading.RLock()
        for module in self.repo.modules:
            for node in ast.walk(module.tree):
                if not (isinstance(node, ast.Assign) and len(node.targets)
                        == 1 and isinstance(node.value, ast.Call)):
                    continue
                vparts = dotted_name(node.value.func)
                if not vparts or vparts[-1] != "RLock":
                    continue
                tparts = dotted_name(node.targets[0])
                if not tparts or tparts[0] != "self":
                    continue
                cnode = module.enclosing_class(node)
                if cnode is None:
                    continue
                cat = classify_lock(module, node.targets[0],
                                    getattr(cnode, "_dl_qual", cnode.name))
                if cat:
                    self._reentrant.add(cat)

    def _transitive_closure(self) -> Dict[str, Set[str]]:
        """Fixed-point transitive acquire sets. Kleene iteration rather
        than recursive memoization: a recursive memo caches INCOMPLETE
        closures for members of call-graph cycles (the cycle guard
        returns an empty set mid-expansion, which then gets memoized),
        silently dropping lock edges exactly where mutual recursion makes
        the graph interesting. Iteration converges in a few passes (the
        lock-set lattice is tiny) and is order-insensitive."""
        cg = self.repo.callgraph()
        acq: Dict[str, Set[str]] = {
            q: set(s) for q, s in self._direct.items()
        }
        callees = {q: cg.callees(q, fuzzy=True) for q in cg.funcs}
        changed = True
        while changed:
            changed = False
            for q, cs in callees.items():
                cur = acq.get(q)
                for c in cs:
                    extra = acq.get(c)
                    if not extra:
                        continue
                    if cur is None:
                        cur = acq[q] = set()
                    before = len(cur)
                    cur |= extra
                    if len(cur) != before:
                        changed = True
        return acq

    # -- edge extraction ---------------------------------------------------
    def _add_edge(self, outer: str, inner: str, witness: str) -> None:
        if outer == inner and outer in self._reentrant:
            return
        self.edges.setdefault((outer, inner), []).append(witness)

    def _scan_function(self, module: Module, qual: str, fn: ast.AST,
                       acq: Dict[str, Set[str]]) -> None:
        cg = self.repo.callgraph()
        gqual = f"{module.name}.{qual}"
        info = cg.funcs.get(gqual)
        cls = info.cls if info else None
        withs = self._locks_in(module, fn, qual)
        for wnode, outer in withs:
            # multi-item `with a, b:` — later items acquire under earlier
            cats = [classify_lock(module, i.context_expr, cls)
                    for i in wnode.items]
            cats = [c for c in cats if c]
            for i, a in enumerate(cats):
                for b in cats[i + 1:]:
                    self._add_edge(a, b, f"{module.rel}:{wnode.lineno}")
            for node in ast.walk(wnode):
                if node is wnode:
                    continue
                if module.qualname_of(node) != qual:
                    continue  # nested def body: defined, not run, here
                if isinstance(node, ast.With):
                    for item in node.items:
                        inner = classify_lock(module, item.context_expr,
                                              cls)
                        if inner:
                            self._add_edge(
                                outer, inner,
                                f"{module.rel}:{node.lineno}")
                elif isinstance(node, ast.Call):
                    exact, fuzzy = cg.resolve_call(module, node, cls)
                    for callee in exact | fuzzy:
                        for inner in acq.get(callee, ()):
                            self._add_edge(
                                outer, inner,
                                f"{module.rel}:{node.lineno} via "
                                f"{callee}")

    # -- verdicts ----------------------------------------------------------
    def check_repo(self, repo: Repo) -> List[Finding]:
        self.repo = repo
        self.edges.clear()
        self._direct.clear()
        self._reentrant.clear()
        self._collect_direct()
        acq = self._transitive_closure()
        cg = repo.callgraph()
        for gqual, info in cg.funcs.items():
            local = gqual[len(info.module.name) + 1:]
            self._scan_function(info.module, local, info.node, acq)

        findings: List[Finding] = []
        # (a) self-deadlock on a non-reentrant Lock
        for (a, b), wits in sorted(self.edges.items()):
            if a == b and a not in self._reentrant:
                findings.append(Finding(
                    self.name, wits[0].split(":")[0],
                    int(wits[0].split(":")[1].split(" ")[0]), "",
                    f"lock {a!r} re-acquired while held — it is a plain "
                    f"threading.Lock (not RLock); this deadlocks the "
                    f"holding thread",
                ))
        # (b) declared-order reversals
        for outer, inner in KNOWN_ORDER:
            wits = self.edges.get((inner, outer))
            if wits:
                findings.append(Finding(
                    self.name, wits[0].split(":")[0],
                    int(wits[0].split(":")[1].split(" ")[0]), "",
                    f"lock order reversal: {inner!r} is held while "
                    f"acquiring {outer!r}, but the sanctioned nesting is "
                    f"{outer!r} -> {inner!r} (see KNOWN_ORDER in "
                    f"tools/dingolint/checkers/lock_order.py)",
                ))
        # (c) cycles among distinct categories
        findings.extend(self._cycle_findings())
        # inline suppressions: the witness line owns the edge
        kept = []
        for f in findings:
            mod = next((m for m in repo.modules if m.rel == f.path), None)
            if mod is not None and mod.suppressed(f.lineno, self.name):
                continue
            kept.append(f)
        return kept

    def _cycle_findings(self) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        # Tarjan SCC, iterative
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(graph.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(graph.get(w, ())))))
                        advanced = True
                        break
                    elif w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(sorted(scc))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        out: List[Finding] = []
        for scc in sccs:
            members = set(scc)
            wits = [
                w for (a, b), ws in sorted(self.edges.items())
                if a in members and b in members and a != b for w in ws[:1]
            ]
            loc = wits[0] if wits else "dingo_tpu:0"
            out.append(Finding(
                self.name, loc.split(":")[0],
                int(loc.split(":")[1].split(" ")[0]), "",
                f"lock-order cycle among {scc}: these locks are acquired "
                f"in both nesting orders — a deadlock needs only two "
                f"concurrent threads (re-run with --json for every "
                f"witness edge)",
            ))
        return out
