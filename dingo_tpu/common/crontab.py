"""CrontabManager: periodic background jobs.

Reference: src/crontab/crontab.{h,cc} (CrontabManager on bthread_timer_add,
crontab.h:62); the full production schedule registers in server.cc:506-700
(heartbeat, metrics collection, scan GC, split/merge checkers, coordinator
update/job/recycle/lease/compaction tasks, vector-index scrub).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from dingo_tpu.common.log import get_logger

_log = get_logger("crontab")


class Crontab:
    def __init__(self, name: str, interval_s: float,
                 func: Callable[[], None], immediately: bool = False):
        self.name = name
        self.interval_s = interval_s
        self.func = func
        self.immediately = immediately
        self.run_count = 0
        self.error_count = 0
        self.last_run_ms = 0
        self.last_error = ""
        self._next_due = 0.0


class CrontabManager:
    def __init__(self, tick_s: float = 0.05):
        self._tick = tick_s
        self._lock = threading.Lock()
        self._crontabs: Dict[str, Crontab] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add(self, name: str, interval_s: float, func: Callable[[], None],
            immediately: bool = False) -> None:
        with self._lock:
            tab = Crontab(name, interval_s, func, immediately)
            now = time.monotonic()
            tab._next_due = now if immediately else now + interval_s
            self._crontabs[name] = tab

    def remove(self, name: str) -> None:
        with self._lock:
            self._crontabs.pop(name, None)

    def set_interval(self, name: str, interval_s: float) -> bool:
        """Hot-change a crontab's period (takes effect when the tab next
        comes due — crontab bodies that advertise a hot-changeable
        interval flag re-apply it here per tick). False if unknown."""
        with self._lock:
            tab = self._crontabs.get(name)
            if tab is None:
                return False
            if tab.interval_s != interval_s:
                tab.interval_s = interval_s
                tab._next_due = min(
                    tab._next_due, time.monotonic() + interval_s
                )
            return True

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="crontab")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def run_pending(self) -> int:
        """Manual pump (tests / single-threaded drivers).

        Failure isolation contract: one crontab's exception must neither
        stop the remaining due crontabs this tick nor unschedule the
        failing one — a buggy metrics collector silently killing the
        heartbeat crontab would partition the store. Errors are counted,
        logged, and mirrored into the metrics registry."""
        now = time.monotonic()
        due: List[Crontab] = []
        with self._lock:
            for tab in self._crontabs.values():
                if now >= tab._next_due:
                    tab._next_due = now + tab.interval_s
                    due.append(tab)
        for tab in due:
            try:
                tab.func()
                tab.run_count += 1
            except Exception as e:  # noqa: BLE001
                tab.error_count += 1
                tab.last_error = f"{type(e).__name__}: {e}"
                _log.exception("crontab %r failed (run %d, error %d)",
                               tab.name, tab.run_count, tab.error_count)
                try:
                    from dingo_tpu.common.metrics import METRICS

                    METRICS.counter(
                        "crontab.errors", labels={"name": tab.name}
                    ).add(1)
                except Exception:  # noqa: BLE001 — never amplify
                    pass
            tab.last_run_ms = int(time.time() * 1000)
        return len(due)

    def _loop(self) -> None:
        while not self._stop.wait(self._tick):
            try:
                self.run_pending()
            except Exception:  # noqa: BLE001
                # run_pending already isolates per-tab errors; this guards
                # the scheduler itself (e.g. an exotic failure inside the
                # due-computation) — the thread must outlive any bug
                _log.exception("crontab scheduler tick failed")

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "interval_s": t.interval_s,
                    "runs": t.run_count,
                    "errors": t.error_count,
                    "last_error": t.last_error,
                }
                for name, t in self._crontabs.items()
            }
