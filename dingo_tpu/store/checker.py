"""Pre-split / pre-merge checkers.

Reference: src/split/ (PreSplitChecker — policies by approximate size/keys,
config_helper.h:27-35) and src/merge/ (PreMergeChecker); both crontab-driven
(server.cc:583-616): leaders inspect their regions, pick split keys at the
size/keys midpoint, and ask the coordinator to split/merge.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from dingo_tpu.common.config import FLAGS
from dingo_tpu.index import codec as vcodec
from dingo_tpu.store.region import Region, RegionState


@dataclasses.dataclass
class SplitProposal:
    region_id: int
    split_key: bytes
    reason: str


@dataclasses.dataclass
class MergeProposal:
    source_region_id: int
    target_region_id: int
    reason: str


class PreSplitChecker:
    """Propose splits for oversized regions (split policy by approximate
    keys — the reference also supports size-based policies)."""

    def __init__(self, node, max_keys: Optional[int] = None):
        self.node = node
        self.max_keys = max_keys or FLAGS.get("split_check_approximate_keys")

    def check_region(self, region: Region) -> Optional[SplitProposal]:
        raft = self.node.engine.get_node(region.id)
        if raft is None or not raft.is_leader():
            return None
        if region.state is not RegionState.NORMAL:
            return None
        if region.definition.index_parameter is None:
            return None  # KV split policy needs key sampling; index regions
            # use the id midpoint below
        reader = self.node.engine.new_vector_reader(region)
        count = reader.vector_count()
        if count < self.max_keys:
            return None
        # split at the median id (HALF_SPLIT policy analog); scan only up to
        # the median — no need to materialize the full region
        rows = reader.vector_scan_query(
            0, limit=count // 2 + 1, with_vector_data=False
        )
        mid_id = rows[-1].id
        lo, hi = region.id_window()
        if not (lo < mid_id < hi):
            return None
        return SplitProposal(
            region.id,
            vcodec.encode_vector_key(region.definition.partition_id, mid_id),
            f"keys {count} >= {self.max_keys}",
        )

    def run(self) -> List[SplitProposal]:
        """Crontab entry: propose splits to the coordinator."""
        out = []
        for region in self.node.meta.get_all_regions():
            p = self.check_region(region)
            if p is None:
                continue
            out.append(p)
            if self.node.coordinator is not None:
                try:
                    self.node.coordinator.split_region(p.region_id, p.split_key)
                except (KeyError, ValueError):
                    pass
        return out


class PreMergeChecker:
    """Propose merging undersized sibling regions (PreMergeChecker)."""

    def __init__(self, node, min_keys: int = 1024):
        self.node = node
        self.min_keys = min_keys

    def run(self) -> List[MergeProposal]:
        out = []
        regions = sorted(
            (r for r in self.node.meta.get_all_regions()
             if r.state is RegionState.NORMAL
             and r.definition.index_parameter is not None),
            key=lambda r: r.definition.start_key,
        )
        for a, b in zip(regions, regions[1:]):
            if a.definition.end_key != b.definition.start_key:
                continue  # not adjacent
            raft = self.node.engine.get_node(a.id)
            if raft is None or not raft.is_leader():
                continue
            ca = self.node.engine.new_vector_reader(a).vector_count()
            cb = self.node.engine.new_vector_reader(b).vector_count()
            if ca + cb < self.min_keys:
                p = MergeProposal(b.id, a.id, f"{ca}+{cb} < {self.min_keys}")
                out.append(p)
                if self.node.coordinator is not None:
                    try:
                        self.node.coordinator.merge_region(
                            p.target_region_id, p.source_region_id
                        )
                    except (KeyError, ValueError):
                        pass
        return out
