"""Device-runtime observability (dingo_tpu/obs): recompile sentinel, HBM
watermark ledger, and the flight recorder.

Acceptance (ISSUE 5): the sentinel proves the steady-state no-recompile
invariant end-to-end (warmup + mixed upsert/search leaves xla.recompiles
unchanged; a novel shape increments it and records an xla.compile span);
a slow-query fault yields a FlightDump bundle tools/flight_report.py
renders with the triggering trace's spans, metric deltas, and kernel
cache state; and the Prometheus exposition carries a matching exemplar
trace id.
"""

import importlib
import itertools
import json
import logging
import time
import zlib

import grpc
import numpy as np
import pytest

import jax.numpy as jnp

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.failpoint import FAILPOINTS
from dingo_tpu.common.metrics import METRICS, MetricsRegistry
from dingo_tpu.obs import FLIGHT, HBM, SENTINEL, looks_like_oom, sentinel_jit
from dingo_tpu.obs import flight as flight_mod
from dingo_tpu.trace import TRACE_BUFFER, TRACER

flight_report = importlib.import_module("tools.flight_report")

_seq = itertools.count()


def _kname():
    """Unique kernel name per test (the sentinel registry is process-global)."""
    return f"test.kernel_{next(_seq)}"


@pytest.fixture()
def obs_env():
    """Clean flight/trace state + restored observability flags."""
    saved = {k: FLAGS.get(k) for k in (
        "trace_sampling_rate", "slow_query_ms", "obs_flight_max_bundles",
        "obs_flight_buffer_s", "obs_exemplars",
    )}
    FLIGHT.clear()
    TRACE_BUFFER.clear()
    try:
        yield
    finally:
        for k, v in saved.items():
            FLAGS.set(k, v)
        FLIGHT.clear()
        TRACE_BUFFER.clear()


# ---------------------------------------------------------------------------
# recompile sentinel
# ---------------------------------------------------------------------------

def test_sentinel_counts_traces_and_hits(obs_env):
    name = _kname()

    @sentinel_jit(name, static_argnames=("k",))
    def scaled_sum(x, k):
        return jnp.sum(x) * k

    total0 = METRICS.counter("xla.recompiles").get()
    kern_c = METRICS.counter("xla.recompiles_by_kernel",
                             labels={"kernel": name})
    hits_c = METRICS.counter("xla.cache_hits", labels={"kernel": name})

    scaled_sum(jnp.ones(8), 2)          # trace 1 (static k positional)
    scaled_sum(jnp.ones(8), 2)          # hit
    scaled_sum(jnp.ones(8), 2)          # hit
    scaled_sum(jnp.ones(16), 2)         # trace 2: new shape
    scaled_sum(jnp.ones(8), 3)          # trace 3: new static value

    assert kern_c.get() == 3
    assert hits_c.get() == 2
    assert METRICS.counter("xla.recompiles").get() - total0 == 3
    st = SENTINEL.state()[name]
    assert st["calls"] == 5 and st["traces"] == 3 and st["cache_hits"] == 2
    assert st["compile_ms_total"] > 0
    # signature labels carry dtype + shape of the novel call
    assert any("float32[16]" in s for s in st["signatures"])
    # each compile recorded an xla.compile span (sampling-independent)
    compiles = [s for s in TRACE_BUFFER.snapshot()
                if s["name"] == "xla.compile"
                and s["attrs"].get("kernel") == name]
    assert len(compiles) == 3
    assert all(s["attrs"]["ms"] > 0 for s in compiles)


def test_sentinel_compile_span_joins_sampled_trace(obs_env):
    FLAGS.set("trace_sampling_rate", 1.0)
    name = _kname()

    @sentinel_jit(name)
    def double(x):
        return x * 2

    with TRACER.start_span("test.compile_parent") as root:
        double(jnp.ones(4))
        trace_id = f"{root.trace_id:016x}"
    spans = TRACE_BUFFER.snapshot(trace_id=trace_id)
    compile_spans = [s for s in spans if s["name"] == "xla.compile"]
    assert len(compile_spans) == 1
    # parented under the victim request, not a fragment root
    assert compile_spans[0]["parent_id"] == \
        next(s for s in spans if s["name"] == "test.compile_parent")["span_id"]


def test_sentinel_donation_still_works(obs_env):
    name = _kname()

    @sentinel_jit(name, donate_argnums=(0,))
    def bump(v, delta):
        return v + delta

    v = jnp.ones(4)
    out = bump(v, jnp.ones(4))
    assert float(out[0]) == 2.0
    assert SENTINEL.state()[name]["traces"] == 1


def test_steady_state_invariant_end_to_end(obs_env):
    """THE acceptance invariant: after warmup (searches AND one write
    round), a mixed upsert/delete/search workload never touches the XLA
    compile cache; a deliberately novel shape does, and records the
    compile as an xla.compile span."""
    from dingo_tpu.index import IndexParameter, IndexType, new_index

    rng = np.random.default_rng(5)
    n, d = 2048, 24
    x = rng.standard_normal((n, d), dtype=np.float32)
    ids = np.arange(n, dtype=np.int64)
    idx = new_index(950, IndexParameter(
        index_type=IndexType.IVF_FLAT, dimension=d,
        ncentroids=8, default_nprobe=4,
    ))
    idx.store.reserve(n + 512)
    idx.upsert(ids, x)
    idx.train()
    idx.warmup(batches=(8,), topk=10, nprobe=4)
    # force every list onto its spill chain NOW: the dense build packs
    # each bucket full, so the first writes allocate spill buckets and
    # step the alloc ladder — that step must happen during warmup, not
    # mid-measurement
    extra = np.arange(n, n + 400, dtype=np.int64)
    idx.upsert(extra, rng.standard_normal((400, d)).astype(np.float32))

    def mixed_round():
        sel = rng.choice(n, 48, replace=False)
        idx.delete(ids[sel[:24]])
        idx.upsert(ids[sel], x[sel])
        res = idx.search(x[:8], 10, nprobe=4)
        assert len(res) == 8

    # write-path warmup: search warmup can't reach the scatter/tombstone
    # buckets (and the per-round append sizes land in a couple of pow2
    # pads). Steady state is reached when two consecutive rounds leave
    # the jit cache untouched; 12 rounds is the failure bound.
    c = METRICS.counter("xla.recompiles")
    clean = 0
    for _ in range(12):
        before = c.get()
        mixed_round()
        clean = clean + 1 if c.get() == before else 0
        if clean >= 2:
            break
    else:
        pytest.fail(
            "mixed workload never reached trace-free rounds:"
            f" {dict((k, v) for k, v in SENTINEL.state().items() if v['traces'])}"
        )

    # THE invariant: once steady, sustained mixed traffic stays trace-free
    before = c.get()
    for _ in range(4):
        mixed_round()
    assert c.get() - before == 0, (
        "steady-state mixed workload recompiled:"
        f" {dict((k, v) for k, v in SENTINEL.state().items() if v['traces'])}"
    )

    # novel batch shape (beyond every warmed bucket) must recompile and
    # leave compile evidence
    TRACE_BUFFER.clear()
    idx.search(x[:200], 10, nprobe=4)
    assert c.get() - before >= 1
    compiles = [s for s in TRACE_BUFFER.snapshot()
                if s["name"] == "xla.compile"]
    assert compiles and all(s["attrs"]["kernel"] for s in compiles)


# ---------------------------------------------------------------------------
# hbm ledger
# ---------------------------------------------------------------------------

def test_hbm_ledger_owner_attribution_and_watermark(obs_env):
    from dingo_tpu.index import IndexParameter, IndexType, new_index

    rid = 960
    HBM.forget_region(rid)
    idx = new_index(rid, IndexParameter(
        index_type=IndexType.FLAT, dimension=16,
    ))
    idx.upsert(np.arange(64, dtype=np.int64),
               np.ones((64, 16), np.float32))
    idx.search(np.ones((2, 16), np.float32), 4)
    owners = HBM.account_index(rid, idx)
    assert owners.get("slot_store", 0) > 0
    total = sum(owners.values())
    assert HBM.region_peak(rid) == total
    # shrink the region: current gauges drop, the watermark holds
    HBM.update_region(rid, {"slot_store": 10})
    assert HBM.region_peak(rid) == total
    g = METRICS.gauge("hbm.region.bytes", rid, labels={"owner": "slot_store"})
    assert g.get() == 10
    assert METRICS.gauge("hbm.region.total_peak_bytes", rid).get() == total
    st = HBM.state()
    assert st["regions"][rid]["total_peak_bytes"] == total
    HBM.forget_region(rid)
    assert HBM.region_peak(rid) == 0


def test_hbm_owner_attribution_dedupes_shared_arrays(obs_env):
    from types import SimpleNamespace

    arr = jnp.ones((32, 8))
    # the walker recurses plain containers and dingo_tpu objects; the
    # SAME buffer reachable from both owners must be charged exactly once
    fake = SimpleNamespace(store=[arr], _view=[arr])
    owners = HBM.account_index(961, fake)
    # charged once: view walks first (most-specific), store sees the dup
    assert owners.get("ivf_view", 0) == arr.nbytes
    assert owners.get("slot_store", 0) == 0
    HBM.forget_region(961)


def test_hbm_alloc_failure_hook(obs_env):
    FLIGHT.clear()
    c0 = METRICS.counter("hbm.alloc_failures").get()
    assert HBM.on_alloc_failure(ValueError("bad nprobe")) is None
    assert METRICS.counter("hbm.alloc_failures").get() == c0
    bid = HBM.on_alloc_failure(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                     "allocate 137438953472 bytes"),
        context="VectorSearch", region_id=7,
    )
    assert bid
    assert METRICS.counter("hbm.alloc_failures").get() == c0 + 1
    metas = FLIGHT.bundles_meta()
    assert metas[-1]["reason"] == "device_oom"
    assert metas[-1]["region_id"] == 7
    bundle = FLIGHT.get_json(bid)
    assert "RESOURCE_EXHAUSTED" in bundle["trigger"]["error"]
    assert "hbm" in bundle and "kernel_cache" in bundle


def test_oom_rpc_path_keeps_trace_linked_bundle(obs_env):
    """rpc error arm ordering: the trace-linked device_oom bundle wins;
    the ledger hook only counts (capture=False) instead of burning the
    per-reason rate limit on a trace-less bundle."""
    FLAGS.set("trace_sampling_rate", 1.0)
    oom = RuntimeError("RESOURCE_EXHAUSTED: Out of memory")
    c0 = METRICS.counter("hbm.alloc_failures").get()
    with TRACER.start_span("rpc.IndexService.VectorSearch") as span:
        trace_id = f"{span.trace_id:016x}"
        bid = FLIGHT.on_rpc_error("rpc.IndexService.VectorSearch", oom, span)
        assert HBM.on_alloc_failure(oom, capture=False) is None
    assert bid
    meta = FLIGHT.bundles_meta()[-1]
    assert meta["reason"] == "device_oom"
    assert meta["trace_id"] == trace_id
    assert METRICS.counter("hbm.alloc_failures").get() == c0 + 1


def test_prometheus_exemplars_stripped_for_classic_scrape(obs_env):
    m = MetricsRegistry()
    lr = m.latency("span.rpc.classic_probe")
    lr.observe_us(5000.0, trace_id="abcdef0123456789")
    assert "trace_id=" in m.render_prometheus()            # in-band default
    assert "trace_id=" not in m.render_prometheus(exemplars=False)


def test_metrics_http_exemplars_opt_in(obs_env):
    import urllib.request

    from dingo_tpu.metrics.http import MetricsHttpServer

    m = MetricsRegistry()
    m.latency("span.rpc.scrape_probe").observe_us(
        7000.0, trace_id="feed0123feed0123")
    srv = MetricsHttpServer(registry=m)
    port = srv.start()
    try:
        # a plain Prometheus scrape (even one whose Accept header offers
        # OpenMetrics) gets clean classic text — no exemplar suffix
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/metrics",
            headers={"Accept": "application/openmetrics-text;version=1.0.0;"
                               "q=0.75,text/plain;version=0.0.4;q=0.5"},
        )
        classic = urllib.request.urlopen(req, timeout=5)
        body = classic.read().decode()
        assert "version=0.0.4" in classic.headers["Content-Type"]
        assert "trace_id=" not in body          # classic parser survives
        assert "span_rpc_scrape_probe" in body
        # explicit opt-in serves the nonstandard exemplar suffix
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics?exemplars=1", timeout=5,
        ).read().decode()
        assert 'trace_id="feed0123feed0123"' in body
    finally:
        srv.stop()


def test_looks_like_oom():
    assert looks_like_oom(RuntimeError("RESOURCE_EXHAUSTED: ..."))
    assert looks_like_oom(RuntimeError("Failed to allocate 1GB"))
    assert not looks_like_oom(ValueError("dimension mismatch"))


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_slow_query_trigger_and_exemplar(obs_env, monkeypatch):
    FLAGS.set("trace_sampling_rate", 1.0)
    FLAGS.set("slow_query_ms", 0.001)
    lines = []
    monkeypatch.setattr(
        "dingo_tpu.trace.span._log",
        type("L", (), {"warning": lambda self, msg, *a: lines.append(msg % a)})(),
    )
    FLIGHT.tick()
    # a bigger earlier sample (a warmup compile, say) must NOT keep the
    # exemplar: the slow path pins its own (bundled) sample
    METRICS.latency("span.rpc.TestService.Slow").observe_us(
        10_000_000.0, trace_id="feedfacefeedface")
    with TRACER.start_span("rpc.TestService.Slow") as span:
        time.sleep(0.004)
        trace_id = f"{span.trace_id:016x}"
    metas = FLIGHT.bundles_meta()
    assert metas and metas[-1]["reason"] == "slow_query"
    assert metas[-1]["trace_id"] == trace_id
    # satellite: the slow-query log line carries trace id AND bundle id
    assert lines and trace_id in lines[-1]
    assert metas[-1]["id"] in lines[-1]
    # bundle carries the triggering trace's spans
    bundle = FLIGHT.get_json(metas[-1]["id"])
    assert any(s["name"] == "rpc.TestService.Slow" for s in bundle["spans"])
    # the Prometheus exposition carries a matching exemplar trace id on
    # the span's p99 series
    text = METRICS.render_prometheus()
    assert f'# {{trace_id="{trace_id}"}}' in text
    line = next(l for l in text.splitlines()
                if l.startswith("span_rpc_TestService_Slow")
                and 'quantile="0.99"' in l)
    assert f'trace_id="{trace_id}"' in line


def test_flight_unsampled_slow_query_still_bundles(obs_env, monkeypatch):
    FLAGS.set("trace_sampling_rate", 1e-12)   # armed, never samples
    FLAGS.set("slow_query_ms", 0.001)
    lines = []
    monkeypatch.setattr(
        "dingo_tpu.trace.span._log",
        type("L", (), {"warning": lambda self, msg, *a: lines.append(msg % a)})(),
    )
    t0 = TRACER.slow_watch_start()
    assert t0
    time.sleep(0.004)
    TRACER.slow_watch_end("rpc.TestService.Unsampled", t0)
    metas = FLIGHT.bundles_meta()
    assert metas and metas[-1]["reason"] == "slow_query"
    assert metas[-1]["trace_id"] == ""
    assert metas[-1]["name"] == "rpc.TestService.Unsampled"
    assert lines and metas[-1]["id"] in lines[-1]


def test_error_bundle_contains_inflight_root_span(obs_env):
    """The failing ingress span hasn't ended when the error trigger
    fires; its in-flight record must still appear in the bundle even when
    child spans of the trace already ended (no ring-tail fallback)."""
    FLAGS.set("trace_sampling_rate", 1.0)
    with TRACER.start_span("rpc.TestService.Fails") as root:
        with TRACER.start_span("child.work"):
            pass                      # child ENDS before the failure
        bid = FLIGHT.on_rpc_error("rpc.TestService.Fails",
                                  ValueError("boom"), root)
    assert bid
    bundle = FLIGHT.get_json(bid)
    names = {s["name"]: s for s in bundle["spans"]}
    assert "child.work" in names
    root_rec = names["rpc.TestService.Fails"]
    assert root_rec["attrs"]["in_flight"] is True
    assert root_rec["status"].startswith("error")
    assert not bundle["spans_fallback"]


def test_flight_metrics_delta_window(obs_env):
    FLIGHT.tick()
    METRICS.counter("flighttest.delta_probe").add(7)
    bid = FLIGHT.trigger("manual", name="delta-test")
    bundle = FLIGHT.get_json(bid)
    assert bundle["metrics"]["deltas"]["flighttest.delta_probe"] == 7
    assert bundle["metrics"]["window_s"] >= 0.0


def test_flight_rate_limit_and_retention(obs_env):
    bid1 = FLIGHT.trigger("stormy")
    bid2 = FLIGHT.trigger("stormy")            # < 1s later: suppressed
    assert bid1 and bid2 == ""
    assert METRICS.counter(
        "flight.suppressed", labels={"reason": "stormy"}).get() >= 1
    # retention honors obs.flight_max_bundles
    FLAGS.set("obs_flight_max_bundles", 2)
    for i, reason in enumerate(("r_a", "r_b", "r_c")):
        FLIGHT.trigger(reason)
    metas = FLIGHT.bundles_meta()
    assert len(metas) == 2
    assert [m["reason"] for m in metas] == ["r_b", "r_c"]
    # 0 disables capturing entirely
    FLAGS.set("obs_flight_max_bundles", 0)
    assert FLIGHT.trigger("r_d") == ""


def test_flight_eviction_preserves_singleton_reasons(obs_env, monkeypatch):
    """A storm of one reason evicts its own duplicates, never the lone
    device_oom/slow_query bundle an operator came for."""
    monkeypatch.setattr(flight_mod, "MIN_TRIGGER_INTERVAL_S", 0.0)
    FLAGS.set("obs_flight_max_bundles", 3)
    oom_id = FLIGHT.trigger("device_oom")
    for _ in range(5):
        FLIGHT.trigger("error")
    metas = FLIGHT.bundles_meta()
    assert len(metas) == 3
    assert metas[0]["id"] == oom_id          # survived the storm
    assert [m["reason"] for m in metas[1:]] == ["error", "error"]
    # pin-on-capture only: a rate-limited slow query must not move the
    # exemplar to a bundle-less trace
    monkeypatch.setattr(flight_mod, "MIN_TRIGGER_INTERVAL_S", 60.0)
    FLAGS.set("trace_sampling_rate", 1.0)
    FLAGS.set("slow_query_ms", 0.001)
    with TRACER.start_span("rpc.TestService.Pinned") as s1:
        time.sleep(0.003)
        t1 = f"{s1.trace_id:016x}"
    with TRACER.start_span("rpc.TestService.Pinned") as s2:
        time.sleep(0.02)                     # slower, but rate-limited
    ex = METRICS.latency("span.rpc.TestService.Pinned").exemplar()
    assert ex is not None and ex[1] == t1


def test_flight_report_roundtrip(obs_env, tmp_path):
    name = _kname()

    @sentinel_jit(name)
    def triple(x):
        return x * 3

    triple(jnp.ones(4))
    FLIGHT.tick()
    METRICS.counter("flighttest.report_probe").add(3)
    HBM.update_region(962, {"slot_store": 4096, "ivf_view": 1024})
    bid = FLIGHT.trigger("manual", name="report-test", region_id=962)
    path = tmp_path / "bundle.bin"
    path.write_bytes(FLIGHT.get(bid))
    bundle = flight_report.parse_bundle(str(path))
    assert bundle["id"] == bid
    text = flight_report.render(bundle)
    assert "-- metric deltas" in text
    assert "flighttest.report_probe" in text
    assert "-- kernel cache state" in text and name in text
    assert "-- hbm ledger" in text and "slot_store" in text
    # uncompressed JSON parses too
    jpath = tmp_path / "bundle.json"
    jpath.write_text(json.dumps(bundle))
    assert flight_report.parse_bundle(str(jpath))["id"] == bid
    HBM.forget_region(962)


# ---------------------------------------------------------------------------
# heartbeat / cluster-top plumbing for the hbm watermark
# ---------------------------------------------------------------------------

def test_region_metrics_pb_roundtrip_device_peak():
    from dingo_tpu.metrics.snapshot import RegionMetricsSnapshot
    from dingo_tpu.server import convert

    rm = RegionMetricsSnapshot(region_id=4, device_peak_bytes=123456)
    again = convert.region_metrics_from_pb(convert.region_metrics_to_pb(rm))
    assert again.device_peak_bytes == 123456


def test_cluster_top_shows_devpeak():
    from dingo_tpu.client.cli import format_cluster_top
    from dingo_tpu.server import pb

    resp = pb.GetStoreMetricsResponse()
    entry = resp.stores.add()
    entry.store_id = "s0"
    rm = entry.metrics.regions.add()
    rm.region_id = 1
    rm.vector_count = 10
    rm.device_memory_bytes = 1024
    rm.device_peak_bytes = 4096
    out = format_cluster_top(resp)
    assert "DEVPEAK" in out
    assert "4.0KB" in out


# ---------------------------------------------------------------------------
# grpc end-to-end: fault injection -> FlightDump -> flight_report
# ---------------------------------------------------------------------------

def test_flight_grpc_end_to_end(obs_env, tmp_path, monkeypatch):
    """Full acceptance chain: a slow search captures a bundle with the
    trace's spans; an injected failpoint error captures another; both
    export through FlightDump; tools/flight_report.py renders the slow
    bundle; the Prometheus exposition (MetricsDump) carries the matching
    exemplar trace id."""
    from dingo_tpu.client import DingoClient
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.coordinator.kv_control import KvControl
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.raft import LocalTransport
    from dingo_tpu.server import pb
    from dingo_tpu.server.rpc import DingoServer
    from dingo_tpu.store.node import StoreNode

    FLAGS.set("trace_sampling_rate", 1.0)
    # at a micro slow_query_ms EVERY rpc is "slow" (region-map refreshes
    # included); disable the per-reason rate limit so the search's own
    # bundle is captured rather than suppressed behind a neighbor's
    monkeypatch.setattr(flight_mod, "MIN_TRIGGER_INTERVAL_S", 0.0)
    me = MemEngine()
    control = CoordinatorControl(me, replication=1)
    cs = DingoServer()
    cs.host_coordinator_role(control, TsoControl(me), KvControl(me))
    cport = cs.start()
    node = StoreNode("s0", LocalTransport(), control, raft_kw={"seed": 0})
    srv = DingoServer()
    srv.host_store_role(node)
    port = srv.start()
    node.start_heartbeat(0.1)
    client = DingoClient(f"127.0.0.1:{cport}", {"s0": f"127.0.0.1:{port}"})
    try:
        param = pb.VectorIndexParameter(
            index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
            metric_type=pb.METRIC_TYPE_L2,
        )
        client.create_index_region(0, 0, 1 << 30, param)
        time.sleep(1.0)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((40, 8)).astype(np.float32)
        client.vector_add(0, list(range(40)), x)

        FLIGHT.clear()
        FLIGHT.tick()
        # --- slow query: every search now crosses the threshold ---
        FLAGS.set("slow_query_ms", 0.0001)
        res = client.vector_search(0, x[[3]], topk=3)
        assert res[0][0][0] == 3
        FLAGS.set("slow_query_ms", 500.0)
        slow_metas = [m for m in FLIGHT.bundles_meta()
                      if m["reason"] == "slow_query"
                      and m["name"] == "rpc.IndexService.VectorSearch"]
        assert slow_metas, FLIGHT.bundles_meta()
        slow = slow_metas[-1]
        assert slow["trace_id"]

        # --- injected search error via the failpoint ---
        FAILPOINTS.configure("before_vector_search", "1*panic")
        try:
            with pytest.raises(Exception):
                client.vector_search(0, x[[3]], topk=3)
        finally:
            FAILPOINTS.remove("before_vector_search")
        err_metas = [m for m in FLIGHT.bundles_meta()
                     if m["reason"] == "error"]
        assert err_metas
        assert "VectorSearch" in err_metas[-1]["name"]

        # --- FlightDump RPC round-trip ---
        dbg = client._stub("s0", "DebugService")
        resp = dbg.FlightDump(pb.FlightDumpRequest())
        assert {m.reason for m in resp.bundles} >= {"slow_query", "error"}
        resp = dbg.FlightDump(pb.FlightDumpRequest(
            bundle_id=slow["id"], include_payload=True,
        ))
        assert resp.payload_bundle_id == slow["id"]
        assert resp.payload
        path = tmp_path / "slow_bundle.bin"
        path.write_bytes(resp.payload)

        # --- flight_report parse-back + render ---
        bundle = flight_report.parse_bundle(str(path))
        assert bundle["id"] == slow["id"]
        assert bundle["trace_id"] == slow["trace_id"]
        span_names = {s["name"] for s in bundle["spans"]}
        assert "rpc.IndexService.VectorSearch" in span_names
        text = flight_report.render(bundle)
        assert "rpc.IndexService.VectorSearch" in text
        assert "-- metric deltas" in text
        assert "-- kernel cache state" in text
        assert "index.flat.search" in text

        # --- exemplar: scrape links the bad bucket to the same trace ---
        prom = dbg.MetricsDump(
            pb.MetricsDumpRequest(format="prometheus")).json
        assert f'trace_id="{slow["trace_id"]}"' in prom

        # unknown bundle id answers in-band
        resp = dbg.FlightDump(pb.FlightDumpRequest(
            bundle_id="fb-nope", include_payload=True))
        assert resp.error.errcode == 50003
    finally:
        client.close()
        srv.stop()
        cs.stop()
        node.stop()
