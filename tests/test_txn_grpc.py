"""Full txn wire surface over gRPC: pessimistic flow, cross-region 2PC,
orphan-lock recovery, maintenance RPCs (reference store_service.h exposes
16 Txn RPCs; engine semantics in engine/txn.py, client 2PC in client/txn.py)."""

import time

import pytest

from dingo_tpu.client.client import ClientError, DingoClient
from dingo_tpu.client.txn import TxnClientError
from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.coordinator.kv_control import KvControl
from dingo_tpu.coordinator.tso import TsoControl
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.raft import LocalTransport
from dingo_tpu.server import pb
from dingo_tpu.server.rpc import DingoServer
from dingo_tpu.store.node import StoreNode


@pytest.fixture()
def cluster():
    transport = LocalTransport()
    me = MemEngine()
    control = CoordinatorControl(me, replication=3)
    coord_server = DingoServer()
    coord_server.host_coordinator_role(control, TsoControl(me), KvControl(me))
    coord_port = coord_server.start()

    nodes, servers, addrs = {}, [], {}
    for i, sid in enumerate(["s0", "s1", "s2"]):
        node = StoreNode(sid, transport, control, raft_kw={"seed": i})
        server = DingoServer()
        server.host_store_role(node)
        port = server.start()
        node.start_heartbeat(0.1)
        nodes[sid] = node
        servers.append(server)
        addrs[sid] = f"127.0.0.1:{port}"

    client = DingoClient(f"127.0.0.1:{coord_port}", addrs)
    # two KV regions so 2PC crosses a region boundary: [a, m) and [m, z)
    for start, end in ((b"a", b"m"), (b"m", b"z")):
        req = pb.CreateRegionRequest()
        req.range.start_key = start
        req.range.end_key = end
        resp = client.coordinator.CreateRegion(req)
        assert resp.error.errcode == 0, resp.error.errmsg
    time.sleep(1.2)   # heartbeats create + elect
    yield client, nodes
    client.close()
    for s in servers:
        s.stop()
    coord_server.stop()
    for n in nodes.values():
        n.stop()


def test_pessimistic_flow_end_to_end(cluster):
    """lock -> put -> commit, plus for-update conflict detection."""
    client, nodes = cluster
    t = client.begin_txn(pessimistic=True)
    t.lock([b"acct1", b"acct2"])
    t.put(b"acct1", b"90")
    t.put(b"acct2", b"110")
    commit_ts = t.commit()
    assert commit_ts > t.start_ts

    r = client.begin_txn()
    assert r.get(b"acct1") == b"90"
    assert r.get(b"acct2") == b"110"

    # a second pessimistic txn must block on the same keys while locked
    t1 = client.begin_txn(pessimistic=True)
    t1.lock([b"acct1"])
    t2 = client.begin_txn(pessimistic=True)
    with pytest.raises(ClientError):
        t2.lock([b"acct1"])
    t1.rollback()
    # after rollback the key is lockable again
    t3 = client.begin_txn(pessimistic=True)
    t3.lock([b"acct1"])
    t3.put(b"acct1", b"42")
    t3.commit()
    r2 = client.begin_txn()
    assert r2.get(b"acct1") == b"42"


def test_cross_region_commit_and_batch_get(cluster):
    """One txn spanning both regions commits atomically; TxnBatchGet sees
    the committed snapshot."""
    client, nodes = cluster
    t = client.begin_txn()
    t.put(b"bob", b"1")      # region [a, m)
    t.put(b"sue", b"2")      # region [m, z)
    t.commit()

    r = client.begin_txn()
    got = r.batch_get([b"bob", b"sue", b"nope"])
    assert got == {b"bob": b"1", b"sue": b"2"}


def test_orphan_lock_discovery_and_resolve(cluster):
    """Client 'crashes' between prewrite and commit: another client finds
    the leftover locks (TxnScanLock), checks the primary's fate
    (TxnCheckStatus -> rolled back after TTL), resolves every region
    (TxnResolveLock), and the keys become writable again."""
    client, nodes = cluster
    dead = client.begin_txn(pessimistic=True, lock_ttl_ms=150)
    dead.lock([b"crash1", b"mcrash2"])   # spans both regions
    dead.put(b"crash1", b"x")
    dead.put(b"mcrash2", b"y")
    # prewrite WITHOUT commit = the crash window
    primary = dead._primary()
    for d, group in client._group_keys_by_region([b"crash1", b"mcrash2"]):
        req = pb.TxnPrewriteRequest()
        req.context.region_id = d.region_id
        for key in group:
            m = req.mutations.add()
            m.op = "put"
            m.key = key
            m.value = b"zz"
        req.primary_lock = primary
        req.start_ts = dead.start_ts
        req.lock_ttl_ms = 150
        req.for_update_ts = dead.for_update_ts
        client._call_leader(d, "StoreService", "TxnPrewrite", req)

    # discovery: the leftover locks are visible
    locks = client.txn_scan_lock()
    assert {li.key for li in locks} >= {b"crash1", b"mcrash2"}

    time.sleep(0.25)   # let the TTL expire

    # recovery around any discovered lock
    lock = next(li for li in locks if li.key == b"mcrash2")
    resolved = client.txn_resolve_leftovers(lock)
    assert resolved >= 1
    st = client.txn_check_status(primary, dead.start_ts)
    assert st["action"] in ("rolled_back", "lock_not_exist_rollback")
    assert client.txn_scan_lock() == []

    # the keys are free again
    t = client.begin_txn(pessimistic=True)
    t.lock([b"crash1"])
    t.put(b"crash1", b"alive")
    t.commit()
    assert client.begin_txn().get(b"crash1") == b"alive"


def test_heart_beat_extends_ttl(cluster):
    client, nodes = cluster
    t = client.begin_txn(pessimistic=True, lock_ttl_ms=200)
    t.lock([b"hb1"])
    ttl = t.heart_beat(advise_ttl_ms=60000)
    assert ttl >= 60000
    time.sleep(0.3)   # would have expired without the heartbeat
    st = client.txn_check_status(b"hb1", t.start_ts)
    assert st["action"] == "locked"
    t.rollback()


def test_check_secondary_locks_and_dump_and_gc(cluster):
    client, nodes = cluster
    # committed txn with history to GC
    t = client.begin_txn()
    t.put(b"gckey", b"v1")
    t.commit()
    t2 = client.begin_txn()
    t2.put(b"gckey", b"v2")
    commit2 = t2.commit()

    # a txn mid-prewrite: secondaries report its locks
    t3 = client.begin_txn()
    d, group = client._group_keys_by_region([b"sec1"])[0]
    req = pb.TxnPrewriteRequest()
    req.context.region_id = d.region_id
    m = req.mutations.add()
    m.op = "put"
    m.key = b"sec1"
    m.value = b"s"
    req.primary_lock = b"sec1"
    req.start_ts = t3.start_ts
    req.lock_ttl_ms = 5000
    client._call_leader(d, "StoreService", "TxnPrewrite", req)

    creq = pb.TxnCheckSecondaryLocksRequest()
    creq.context.region_id = d.region_id
    creq.keys.extend([b"sec1", b"sec_absent"])
    creq.start_ts = t3.start_ts
    cresp = client._call_leader(
        d, "StoreService", "TxnCheckSecondaryLocks", creq)
    assert [li.key for li in cresp.locks] == [b"sec1"]
    assert list(cresp.missing_keys) == [b"sec_absent"]
    client.txn_resolve_lock(t3.start_ts, 0)

    # dump shows writes; gc below a safe point past commit2 drops v1
    gk = client._region_for_key(b"gckey")
    dump = client.txn_dump(gk.region_id)
    assert any(w.key == b"gckey" for w in dump.writes)
    deleted = client.txn_gc(commit2 + 1)
    assert deleted >= 1
    # newest version survives GC
    assert client.begin_txn().get(b"gckey") == b"v2"


def test_cli_txn_verbs(cluster, capsys):
    """Operator CLI: txn put/get/scan-locks/resolve/gc/dump verbs."""
    import json as _json

    from dingo_tpu.client.cli import main

    client, nodes = cluster
    base = ["--coordinator", client._coordinator_addr]
    for sid, addr in client._store_addrs.items():
        base += ["--store", f"{sid}={addr}"]

    def retry_cli(args, attempts=3):
        # election churn under single-core suite load can outlast the
        # SDK's built-in retry window; the CLI exits 1 then — retry
        import time as _t

        for i in range(attempts):
            if main(args) == 0:
                return capsys.readouterr().out
            capsys.readouterr()
            _t.sleep(0.5)
        raise AssertionError(f"CLI failed {attempts}x: {args}")

    out = _json.loads(retry_cli(base + ["txn", "put", "k1", "v1"]))
    assert out["commit_ts"] > out["start_ts"]
    retry_cli(base + ["txn", "put", "k2", "v2", "--pessimistic"])
    assert retry_cli(base + ["txn", "get", "k2"]).strip() == "v2"
    out = retry_cli(base + ["txn", "scan-locks"])
    assert _json.loads(out.strip().splitlines()[-1])["locks"] == 0
    retry_cli(base + ["txn", "resolve", "--start-ts", "1"])
    retry_cli(base + ["txn", "gc", "--safe-ts", "1"])
    rid = client._region_for_key(b"k1").region_id
    assert main(base + ["txn", "dump", "--region", str(rid)]) == 0
    d = _json.loads(capsys.readouterr().out)
    assert d["writes"] >= 1


def test_concurrent_pessimistic_lock_single_winner(cluster):
    """Two txns racing TxnPessimisticLock on one key: exactly one wins
    (the per-region TxnEngine's key latches serialize check-then-write;
    a per-request engine would let both 'succeed')."""
    import threading

    client, nodes = cluster
    results = []

    def worker():
        t = client.begin_txn(pessimistic=True)
        try:
            t.lock([b"contested"])
            results.append(("ok", t))
        except ClientError as e:
            results.append(("err", str(e)))

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    winners = [r for r in results if r[0] == "ok"]
    assert len(winners) == 1, results
    winners[0][1].rollback()
