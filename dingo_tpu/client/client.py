"""DingoClient: cluster-aware SDK over the grpc services.

Plays the role of the reference's Java SDK (java/dingo-sdk — "C++ provides
distributed storage and computing, Java layer provides basic API interfaces",
README.md:41): keeps a region map from the coordinator, routes requests to
region leaders, retries on NotLeader errors, and scatter-gathers multi-region
vector searches client-side (the server returns per-region results only —
SURVEY.md §5).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import grpc
import numpy as np

from dingo_tpu.client import retry as retry_mod
from dingo_tpu.common.coord_channel import RotatingCoordinatorChannel
from dingo_tpu.index import codec as vcodec
from dingo_tpu.server import pb
from dingo_tpu.server.convert import region_def_from_pb, scalar_from_pb
from dingo_tpu.server.rpc import ServiceStub
from dingo_tpu.raft import wire


class ClientError(RuntimeError):
    pass


class _HedgeMiss(ClientError):
    """Internal: the hedged fast path didn't settle the call (stale
    leader hint, follower rejected) — fall back to the rotation loop."""


class _CoordServiceFacade:
    """Duck-types ServiceStub for one coordinator-side service over the
    failover-aware group channel (common/coord_channel.py)."""

    def __init__(self, chan: "RotatingCoordinatorChannel", service: str):
        self._chan = chan
        self._service = service

    def __getattr__(self, method: str):
        return lambda req: self._chan.call(self._service, method, req)


class DingoClient:
    def __init__(self, coordinator_addr: str,
                 store_addrs: Dict[str, str]):
        """store_addrs: store_id -> grpc address. `coordinator_addr` may
        be a comma-separated list of the replicated coordinator group's
        endpoints; the client rotates on NotLeader/connect failure."""
        self._coordinator_addr = coordinator_addr
        self._coord_channel = RotatingCoordinatorChannel(
            coordinator_addr, ClientError)
        self.coordinator = _CoordServiceFacade(
            self._coord_channel, "CoordinatorService")
        self.version = _CoordServiceFacade(
            self._coord_channel, "VersionService")
        self.meta = _CoordServiceFacade(self._coord_channel, "MetaService")
        self._store_addrs = dict(store_addrs)
        self._retry = retry_mod.RetryPolicy.from_flags(rounds=4)
        self._channels: Dict[str, grpc.Channel] = {}
        self._regions: List = []           # RegionDefinition list
        self._leader_hint: Dict[int, str] = {}
        self._table_cache: Dict[str, object] = {}
        self._cache_gen = 0   # bumped by every watcher invalidation
        self._meta_watch_thread = None
        self._meta_watch_stop = None

    def coordinator_service(self, service: str) -> "_CoordServiceFacade":
        """Failover-aware stub for any coordinator-side service (used by
        the CLI for JobService / ClusterStatService)."""
        return _CoordServiceFacade(self._coord_channel, service)

    # ---------------- plumbing ----------------
    def _stub(self, store_id: str, service: str) -> ServiceStub:
        chan = self._channels.get(store_id)
        if chan is None:
            chan = grpc.insecure_channel(self._store_addrs[store_id])
            self._channels[store_id] = chan
        return ServiceStub(chan, service)

    def refresh_region_map(self) -> None:
        resp = self.coordinator.GetRegionMap(pb.GetRegionMapRequest())
        self._regions = [region_def_from_pb(d) for d in resp.regions]

    def _regions_for_vector_ids(self, partition_id: int, refresh: bool = True):
        if refresh or not self._regions:
            self.refresh_region_map()
        return [
            d for d in self._regions
            if d.partition_id == partition_id and d.index_parameter is not None
        ]

    def _region_for_id(self, partition_id: int, vector_id: int,
                       regions=None):
        key = vcodec.encode_vector_key(partition_id, vector_id)
        for d in (regions if regions is not None
                  else self._regions_for_vector_ids(partition_id)):
            if d.start_key <= key < d.end_key:
                return d
        raise ClientError(f"no region covers vector id {vector_id}")

    def _leader_order(self, definition) -> List[str]:
        order = [self._leader_hint.get(definition.region_id)] if \
            self._leader_hint.get(definition.region_id) else []
        order += [p for p in definition.peers if p not in order]
        return order

    def _call_leader(self, definition, service: str, method: str, req,
                     retries: int = 4, hedge: bool = False):
        """Leader routing with NotLeader retry (SDK behavior), through the
        shared RetryPolicy: grpc never-served failures rotate with
        equal-jitter backoff + per-store circuit breaker, in-band NotLeader
        (20001, updating the leader hint from the errmsg) and region-busy
        (10001) rotate, any other application error fails fast — the node
        that actually served the request answered (lock conflict,
        validation, ...) and rotating peers can't change the answer.

        ``hedge=True`` (idempotent reads only) additionally races a
        second attempt at the next peer after a p99-derived delay when
        retry.hedge_enabled — falling back to the plain rotation loop if
        the hedged pair can't settle it (stale hint, follower rejects)."""
        order = self._leader_order(definition)
        last_store = {}

        def _attempt(store_id, attempt):
            last_store["id"] = store_id
            stub = self._stub(store_id, service)
            return getattr(stub, method)(
                req, metadata=retry_mod.attempt_metadata(attempt))

        def _classify(resp):
            code = resp.error.errcode
            if code == 0:
                self._leader_hint[definition.region_id] = \
                    last_store.get("id")
                return retry_mod.OK
            if code == 20001 and ":" in resp.error.errmsg:
                hint = resp.error.errmsg.split(":")[-1].strip()
                if "/" in hint:
                    self._leader_hint[definition.region_id] = \
                        hint.split("/")[0]
            if code in (20001, 10001):
                return (retry_mod.ROTATE, resp.error.errmsg)
            return (retry_mod.FATAL, resp.error.errmsg)

        if hedge and len(order) >= 2 and self._hedge_enabled():
            try:
                return self._retry.call_hedged(
                    order, _attempt, classify=_classify, op=method,
                    error_cls=_HedgeMiss)
            except _HedgeMiss:
                pass   # stale hint / slow pair: the rotation loop decides
        # NotLeader rotation waits on raft elections (O(100ms)), not on
        # transport blips — scale the round gap to the election, matching
        # the reference SDK's fixed 100ms inter-round sleep
        return self._retry.call(
            order, _attempt, classify=_classify, op=method,
            error_cls=ClientError, idempotent=True, rounds=retries,
            base_backoff_ms=100.0)

    @staticmethod
    def _hedge_enabled() -> bool:
        from dingo_tpu.common.config import FLAGS

        return bool(FLAGS.get("retry_hedge_enabled"))

    # ---------------- admin ----------------
    def create_index_region(self, partition_id: int, id_lo: int, id_hi: int,
                            index_parameter: pb.VectorIndexParameter,
                            replication: int = 0):
        req = pb.CreateRegionRequest()
        req.range.start_key = vcodec.encode_vector_key(partition_id, id_lo)
        req.range.end_key = vcodec.encode_vector_key(partition_id, id_hi)
        req.partition_id = partition_id
        req.region_type = 1
        req.index_parameter.CopyFrom(index_parameter)
        req.replication = replication
        resp = self.coordinator.CreateRegion(req)
        if resp.error.errcode:
            raise ClientError(resp.error.errmsg)
        return region_def_from_pb(resp.definition)

    def split_region(self, region_id: int, split_vector_id: int,
                     partition_id: int = 0) -> int:
        req = pb.SplitRegionRequest()
        req.region_id = region_id
        req.split_key = vcodec.encode_vector_key(partition_id, split_vector_id)
        resp = self.coordinator.SplitRegion(req)
        if resp.error.errcode:
            raise ClientError(resp.error.errmsg)
        return resp.child_region_id

    def create_document_region(self, partition_id: int, id_lo: int,
                               id_hi: int,
                               schema: Optional[Dict[str, str]] = None,
                               replication: int = 0):
        """DOCUMENT region with an optional typed column schema
        (name -> text/i64/f64/bytes/bool — validated on add, backs
        range/eq predicates in query syntax)."""
        req = pb.CreateRegionRequest()
        req.range.start_key = vcodec.encode_vector_key(partition_id, id_lo)
        req.range.end_key = vcodec.encode_vector_key(partition_id, id_hi)
        req.partition_id = partition_id
        req.region_type = 2
        req.replication = replication
        for name, ftype in (schema or {}).items():
            col = req.document_schema.add()
            col.name = name
            col.sql_type = ftype
        resp = self.coordinator.CreateRegion(req)
        if resp.error.errcode:
            raise ClientError(resp.error.errmsg)
        return region_def_from_pb(resp.definition)

    def merge_region(self, target_region_id: int,
                     source_region_id: int) -> None:
        """Operator region op: target absorbs the adjacent source."""
        resp = self.coordinator.MergeRegion(pb.MergeRegionRequest(
            target_region_id=target_region_id,
            source_region_id=source_region_id,
        ))
        if resp.error.errcode:
            raise ClientError(resp.error.errmsg)

    def change_peer_region(self, region_id: int,
                           new_peers: Sequence[str]) -> None:
        """Operator region op: replace the region's peer set."""
        req = pb.ChangePeerRegionRequest(region_id=region_id)
        req.new_peers.extend(new_peers)
        resp = self.coordinator.ChangePeerRegion(req)
        if resp.error.errcode:
            raise ClientError(resp.error.errmsg)

    def transfer_leader_region(self, region_id: int,
                               target_store: str) -> None:
        """Operator region op: hand region leadership to target_store."""
        resp = self.coordinator.TransferLeaderRegion(
            pb.TransferLeaderRegionRequest(
                region_id=region_id, target_store=target_store,
            ))
        if resp.error.errcode:
            raise ClientError(resp.error.errmsg)

    def vector_import(self, partition_id: int,
                      ids: Optional[Sequence[int]] = None,
                      vectors: Optional[np.ndarray] = None,
                      scalars: Optional[List[Dict[str, Any]]] = None,
                      delete_ids: Optional[Sequence[int]] = None,
                      ttl_ms: int = 0) -> dict:
        """Bulk import (VectorImport RPC): upserts and/or deletes routed
        per owning region. Returns {"added": n, "deleted": n}."""
        if ids is not None and vectors is None:
            raise ClientError("vector_import: ids given without vectors")
        regions = self._regions_for_vector_ids(partition_id)
        added = deleted = 0
        groups: Dict[int, dict] = {}
        for i, vid in enumerate(ids if ids is not None else []):
            d = self._region_for_id(partition_id, int(vid), regions)
            groups.setdefault(d.region_id, {"add": [], "del": []})[
                "add"].append(i)
        for vid in (delete_ids if delete_ids is not None else []):
            d = self._region_for_id(partition_id, int(vid), regions)
            groups.setdefault(d.region_id, {"add": [], "del": []})[
                "del"].append(int(vid))
        by_region = {d.region_id: d for d in self._regions}
        for rid, g in groups.items():
            req = pb.VectorImportRequest()
            req.context.region_id = rid
            for i in g["add"]:
                v = req.vectors.add()
                v.vector.id = int(ids[i])
                v.vector.values.extend(
                    np.asarray(vectors[i], np.float32).tolist())
                if scalars is not None:
                    for k, val in scalars[i].items():
                        e = v.scalar_data.add()
                        e.key = k
                        e.value = wire.encode_obj(val)
            req.delete_ids.extend(g["del"])
            req.ttl_ms = ttl_ms
            resp = self._call_leader(
                by_region[rid], "IndexService", "VectorImport", req)
            added += resp.added
            deleted += resp.deleted
        return {"added": added, "deleted": deleted}

    # ---------------- table meta API (reference Java SDK table ops) -------
    def create_schema(self, name: str) -> None:
        resp = self.meta.CreateSchema(pb.CreateSchemaRequest(schema_name=name))
        if resp.error.errcode:
            raise ClientError(resp.error.errmsg)

    def get_schemas(self) -> List[str]:
        return list(self.meta.GetSchemas(pb.GetSchemasRequest()).schema_names)

    def create_vector_table(
        self, schema: str, name: str,
        index_parameter: "pb.VectorIndexParameter",
        partitions: Sequence[Tuple[int, int, int]] = ((0, 0, 1 << 40),),
        replication: int = 0,
    ):
        """Create an index table: partitions = [(partition_id, id_lo, id_hi)].
        Returns the TableDef pb (with region ids filled in)."""
        req = pb.CreateTableRequest()
        d = req.definition
        d.schema_name, d.name = schema, name
        d.table_type = 1
        d.replication = replication
        d.index_parameter.CopyFrom(index_parameter)
        for pid, lo, hi in partitions:
            p = d.partitions.add()
            p.partition_id, p.id_lo, p.id_hi = pid, lo, hi
        resp = self.meta.CreateTable(req)
        if resp.error.errcode:
            raise ClientError(resp.error.errmsg)
        self.refresh_region_map()
        return resp.definition

    def get_table(self, schema: str, name: str, cached: bool = False):
        """cached=True serves from the SDK table cache (filled on miss).
        Start the meta watcher (start_meta_watch) to have the cache
        invalidate on coordinator-pushed change events instead of
        serving stale definitions forever."""
        if cached:
            key = f"{schema}.{name}"
            hit = self._table_cache.get(key)
            if hit is not None:
                return hit
        gen = self._cache_gen
        resp = self.meta.GetTable(pb.GetTableRequest(
            schema_name=schema, table_name=name))
        t = resp.definition if resp.found else None
        # only cache if no invalidation raced the RPC: a drop event
        # processed mid-flight must not be overwritten by the stale reply
        if cached and t is not None and gen == self._cache_gen:
            self._table_cache[f"{schema}.{name}"] = t
        return t

    def start_meta_watch(self, poll_timeout_ms: int = 2000) -> None:
        """Background long-poll on MetaWatch: each schema/table change
        event invalidates the SDK table cache (and the region map on
        table create/drop) — the reference SDK's meta-watch cache story
        without client polling of table definitions."""
        if self._meta_watch_thread is not None:
            return
        self._meta_watch_stop = threading.Event()

        def loop():
            start = 0   # 0 = from now (server fills current+1)
            registered = False
            while not self._meta_watch_stop.is_set():
                try:
                    resp = self.meta.MetaWatch(pb.MetaWatchRequest(
                        start_revision=start,
                        timeout_ms=poll_timeout_ms,
                    ))
                except Exception:
                    self._meta_watch_stop.wait(0.5)
                    continue
                if resp.error.errcode:
                    # e.g. watcher slots exhausted — back off, don't hammer
                    self._meta_watch_stop.wait(0.5)
                    continue
                # ALWAYS pin the window: a timed-out poll reports where it
                # watched up to, so events landing between polls replay on
                # the next call instead of being skipped by "from now"
                start = resp.revision + 1
                if not registered:
                    # entries cached between start_meta_watch() and this
                    # first pinned window may predate events the watch
                    # never saw (the first poll starts "from now") —
                    # drop them so nothing stale survives the gap. The
                    # region map is as stale as the cache (a missed
                    # create/drop moved regions), so refresh it too,
                    # exactly like the resync branch.
                    registered = True
                    self._cache_gen += 1
                    self._table_cache.clear()
                    try:
                        self.refresh_region_map()
                    except Exception:
                        pass
                if not resp.fired:
                    continue
                self._cache_gen += 1
                if resp.event == "resync":
                    self._table_cache.clear()
                    # the lost events may include table create/drop
                    try:
                        self.refresh_region_map()
                    except Exception:
                        pass
                    continue
                key = f"{resp.schema_name}.{resp.table_name}"
                self._table_cache.pop(key, None)
                if resp.event in ("create_table", "drop_table"):
                    try:
                        self.refresh_region_map()
                    except Exception:
                        pass

        self._meta_watch_thread = threading.Thread(
            target=loop, daemon=True, name="meta-watch"
        )
        self._meta_watch_thread.start()

    def stop_meta_watch(self) -> None:
        if self._meta_watch_thread is None:
            return
        self._meta_watch_stop.set()
        self._meta_watch_thread.join(timeout=5)
        self._meta_watch_thread = None

    def list_tables(self, schema: str):
        return list(self.meta.GetTables(
            pb.GetTablesRequest(schema_name=schema)).definitions)

    def drop_table(self, schema: str, name: str) -> None:
        resp = self.meta.DropTable(pb.DropTableRequest(
            schema_name=schema, table_name=name))
        if resp.error.errcode:
            raise ClientError(resp.error.errmsg)
        self.refresh_region_map()

    def table_vector_add(self, table, ids, vectors, scalars=None) -> None:
        """Route rows to the owning partition by id window; ids outside
        every partition's window are an error, not a silent drop."""
        import numpy as _np

        ids = _np.asarray(ids, _np.int64)
        routing = []
        routed = _np.zeros(len(ids), bool)
        for p in table.partitions:
            sel = [i for i, vid in enumerate(ids)
                   if p.id_lo <= vid < p.id_hi]
            if sel:
                routed[sel] = True
                routing.append((p, sel))
        # validate the whole batch BEFORE the first write so a routing
        # error cannot leave a partial batch behind
        if not routed.all():
            orphans = ids[~routed][:5].tolist()
            raise ClientError(
                f"ids outside every partition window: {orphans}"
            )
        for p, sel in routing:
            self.vector_add(
                p.partition_id, ids[sel].tolist(),
                _np.asarray(vectors)[sel],
                [scalars[i] for i in sel] if scalars is not None else None,
            )

    def table_vector_search(self, table, queries, topk: int = 10, **params):
        """Scatter over every partition, merge top-k client-side
        (metric-aware: IP/COSINE similarity descends)."""
        asc = table.index_parameter.metric_type in (
            pb.METRIC_TYPE_L2, pb.METRIC_TYPE_HAMMING
        )
        per_part = [
            self.vector_search(p.partition_id, queries, topk, **params)
            for p in table.partitions
        ]
        out = []
        for qi in range(len(per_part[0])):
            allhits = [h for part in per_part for h in part[qi]]
            allhits.sort(key=lambda t: t[1], reverse=not asc)
            out.append(allhits[:topk])
        return out

    def tso(self, count: int = 1) -> int:
        resp = self.coordinator.Tso(pb.TsoRequest(count=count))
        return resp.first_ts

    # ---------------- vectors ----------------
    def vector_add(self, partition_id: int, ids: Sequence[int],
                   vectors: np.ndarray,
                   scalars: Optional[List[Dict[str, Any]]] = None,
                   table_values: Optional[Sequence[bytes]] = None) -> None:
        """Batch add routed per owning region. `table_values[i]` is an
        optional serial-encoded table row per vector (the TABLE
        coprocessor filter's data source)."""
        groups: Dict[int, List[int]] = {}
        regions = self._regions_for_vector_ids(partition_id)  # ONE refresh
        for i, vid in enumerate(ids):
            d = self._region_for_id(partition_id, int(vid), regions)
            groups.setdefault(d.region_id, []).append(i)
        by_region = {d.region_id: d for d in self._regions}
        for rid, idxs in groups.items():
            d = by_region[rid]
            req = pb.VectorAddRequest()
            req.context.region_id = rid
            for i in idxs:
                v = req.vectors.add()
                v.vector.id = int(ids[i])
                v.vector.values.extend(np.asarray(vectors[i], np.float32).tolist())
                if scalars is not None:
                    for k, val in scalars[i].items():
                        e = v.scalar_data.add()
                        e.key = k
                        e.value = wire.encode_obj(val)
                if table_values is not None and table_values[i] is not None:
                    # explicit b"" clears the row (optional-field presence)
                    v.table_data = table_values[i]
            self._call_leader(d, "IndexService", "VectorAdd", req)

    def vector_search(
        self, partition_id: int, queries: np.ndarray, topk: int = 10,
        with_scalar_data: bool = False, deadline_ms: float = None,
        tenant: str = "", priority: int = None, **params,
    ) -> List[List[Tuple[int, float]]]:
        """Scatter to every region of the partition, gather + merge top-k
        client-side (the reference SDK's cross-region story).

        ``deadline_ms``/``tenant``/``priority`` attach a QoS budget to the
        calls: the stub injects it as gRPC metadata (remaining-ms form)
        next to the trace context, so a qos.enabled store can admit,
        prioritize, or shed the request against ITS clock."""
        if deadline_ms or tenant or priority is not None:
            from dingo_tpu.obs.pressure import (
                DEFAULT_PRIORITY,
                budget_scope,
            )

            with budget_scope(
                # no deadline given: a full day — effectively "account
                # tenant/priority, never expire"
                deadline_ms if deadline_ms else 86_400_000.0,
                tenant=tenant or "default",
                priority=DEFAULT_PRIORITY if priority is None else priority,
            ):
                return self._vector_search_budgeted(
                    partition_id, queries, topk, with_scalar_data, params
                )
        return self._vector_search_budgeted(
            partition_id, queries, topk, with_scalar_data, params
        )

    def _vector_search_budgeted(self, partition_id, queries, topk,
                                with_scalar_data, params):
        regions = self._regions_for_vector_ids(partition_id)
        if not regions:
            raise ClientError("no index regions")
        queries = np.asarray(queries, np.float32)
        merged: List[List[Tuple[int, float]]] = [[] for _ in queries]
        # wire convention: L2/HAMMING distances ascend, IP/COSINE similarity
        # descends (ops/distance.py metric_ascending) — merge accordingly
        from dingo_tpu.ops.distance import Metric, metric_ascending

        metric = (regions[0].index_parameter.metric
                  if regions[0].index_parameter else Metric.L2)
        ascending = metric_ascending(metric)
        for d in regions:
            req = pb.VectorSearchRequest()
            req.context.region_id = d.region_id
            for q in queries:
                v = req.vectors.add()
                v.values.extend(q.tolist())
            req.parameter.top_n = topk
            req.parameter.with_scalar_data = with_scalar_data
            if "nprobe" in params:
                req.parameter.nprobe = params["nprobe"]
            if "ef_search" in params:
                req.parameter.ef_search = params["ef_search"]
            if "filter" in params:
                req.parameter.filter = params["filter"]
            if "filter_type" in params:
                req.parameter.filter_type = params["filter_type"]
            if "coprocessor" in params:   # pb.Coprocessor (TABLE filter)
                req.parameter.coprocessor.CopyFrom(params["coprocessor"])
            resp = self._call_leader(d, "IndexService", "VectorSearch", req,
                                     hedge=True)
            for qi, row in enumerate(resp.batch_results):
                for item in row.results:
                    merged[qi].append((item.vector.id, item.distance))
        out = []
        for row in merged:
            row.sort(key=lambda t: t[1], reverse=not ascending)
            out.append(row[:topk])
        return out

    def vector_count(self, partition_id: int) -> int:
        total = 0
        for d in self._regions_for_vector_ids(partition_id):
            req = pb.VectorCountRequest()
            req.context.region_id = d.region_id
            resp = self._call_leader(d, "IndexService", "VectorCount", req)
            total += resp.count
        return total

    # ---------------- kv ----------------
    def _region_for_key(self, key: bytes):
        self.refresh_region_map()
        for d in self._regions:
            if d.start_key <= key < d.end_key:
                return d
        raise ClientError(f"no region covers key {key!r}")

    def _group_keys_by_region(self, keys):
        """[(region_definition, [keys])] — one group per hosting region."""
        groups = {}
        for key in keys:
            d = self._region_for_key(key)
            groups.setdefault(d.region_id, (d, []))[1].append(key)
        return list(groups.values())

    # ---------------- transactions (reference Java SDK txn API) ----------
    def begin_txn(self, pessimistic: bool = False,
                  lock_ttl_ms: int = 3000):
        """Start a Percolator transaction (client/txn.py drives the 2PC)."""
        from dingo_tpu.client.txn import Transaction

        return Transaction(self, self.tso(1), pessimistic=pessimistic,
                           lock_ttl_ms=lock_ttl_ms)

    def txn_scan_lock(self, start_key: bytes = b"", end_key: bytes = b"",
                      max_ts: int = 0, limit: int = 0):
        """Leftover locks across every region intersecting the range."""
        self.refresh_region_map()
        out = []
        for d in self._regions:
            req = pb.TxnScanLockRequest()
            req.context.region_id = d.region_id
            req.range.start_key = start_key
            req.range.end_key = end_key
            req.max_ts = max_ts
            req.limit = limit
            resp = self._call_leader(d, "StoreService", "TxnScanLock", req)
            out.extend(resp.locks)
            if limit and len(out) >= limit:
                return out[:limit]
        return out

    def txn_check_status(self, primary: bytes, lock_ts: int) -> dict:
        d = self._region_for_key(primary)
        req = pb.TxnCheckStatusRequest()
        req.context.region_id = d.region_id
        req.primary_key = primary
        req.lock_ts = lock_ts
        req.caller_start_ts = self.tso(1)
        resp = self._call_leader(d, "StoreService", "TxnCheckStatus", req)
        return {"action": resp.action, "commit_ts": resp.commit_ts}

    def txn_resolve_lock(self, start_ts: int, commit_ts: int = 0,
                         keys: Optional[Sequence[bytes]] = None) -> int:
        """Commit (commit_ts > 0) or roll back leftover locks of a txn on
        every region (or just the regions hosting `keys`)."""
        resolved = 0
        if keys:
            groups = self._group_keys_by_region(keys)
        else:
            self.refresh_region_map()
            groups = [(d, []) for d in self._regions]
        for d, group in groups:
            req = pb.TxnResolveLockRequest()
            req.context.region_id = d.region_id
            req.start_ts = start_ts
            req.commit_ts = commit_ts
            req.keys.extend(group)
            resp = self._call_leader(d, "StoreService", "TxnResolveLock", req)
            resolved += resp.resolved
        return resolved

    def txn_resolve_leftovers(self, lock) -> int:
        """Crash recovery around one leftover lock (pb.TxnLockInfo): ask
        the primary's region for the txn's fate, then resolve accordingly
        on every region. Returns locks resolved."""
        st = self.txn_check_status(lock.primary_lock, lock.lock_ts)
        commit_ts = st["commit_ts"] if st["action"] == "committed" else 0
        if st["action"] == "locked":
            return 0   # still alive — nothing to resolve
        return self.txn_resolve_lock(lock.lock_ts, commit_ts)

    def txn_gc(self, safe_point_ts: int) -> int:
        """MVCC garbage collection below the safe point, all regions."""
        self.refresh_region_map()
        deleted = 0
        for d in self._regions:
            req = pb.TxnGcRequest()
            req.context.region_id = d.region_id
            req.safe_point_ts = safe_point_ts
            resp = self._call_leader(d, "StoreService", "TxnGc", req)
            deleted += resp.deleted
        return deleted

    def txn_dump(self, region_id: int, limit: int = 0):
        """Debug dump of a region's txn CFs (TxnDump)."""
        self.refresh_region_map()
        d = next((r for r in self._regions if r.region_id == region_id),
                 None)
        if d is None:
            raise ClientError(f"region {region_id} not found")
        req = pb.TxnDumpRequest()
        req.context.region_id = region_id
        req.limit = limit
        return self._call_leader(d, "StoreService", "TxnDump", req)

    def kv_put(self, key: bytes, value: bytes) -> None:
        d = self._region_for_key(key)
        req = pb.KvBatchPutRequest()
        req.context.region_id = d.region_id
        kv = req.kvs.add()
        kv.key = key
        kv.value = value
        self._call_leader(d, "StoreService", "KvBatchPut", req)

    def kv_get(self, key: bytes) -> Optional[bytes]:
        d = self._region_for_key(key)
        req = pb.KvGetRequest()
        req.context.region_id = d.region_id
        req.key = key
        resp = self._call_leader(d, "StoreService", "KvGet", req)
        return resp.value if resp.found else None

    def close(self) -> None:
        self.stop_meta_watch()
        self._coord_channel.close()
        for chan in self._channels.values():
            chan.close()
