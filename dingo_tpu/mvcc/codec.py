"""MVCC key/value codec.

Reference: src/mvcc/codec.h:30-106 — keys are memcomparable-encoded user keys
with an inverted-timestamp suffix (so for one user key, newer versions sort
first in an ascending scan); values carry a trailing flag byte
{kPut, kPutTTL, kDelete}, with kPutTTL holding an 8-byte expire-ms field
before the flag. The dingo-serial submodule defines the memcomparable byte
encoding; we reproduce the standard group-of-8 scheme (pad each 8-byte group
with NULs and append marker 0xFF - pad_count) which preserves lexicographic
order through the ts suffix.
"""

from __future__ import annotations

import enum
import struct
from typing import Optional, Tuple

MAX_TS = (1 << 64) - 1
_GROUP = 8
_MARKER_FULL = 0xFF


class ValueFlag(enum.IntEnum):
    """codec.h:30-34."""

    PUT = 0
    PUT_TTL = 1
    DELETE = 2


class Codec:
    # -- memcomparable bytes -------------------------------------------------
    @staticmethod
    def encode_bytes(data: bytes) -> bytes:
        """Order-preserving encoding: groups of 8 bytes, each followed by a
        marker 0xFF - pad (a shorter key is a prefix group with pad > 0 and
        sorts before any longer key sharing the prefix)."""
        out = bytearray()
        i = 0
        while i <= len(data):  # <=: an exact multiple emits a final pad group
            group = data[i : i + _GROUP]
            pad = _GROUP - len(group)
            out += group + b"\x00" * pad
            out.append(_MARKER_FULL - pad)
            i += _GROUP
        return bytes(out)

    @staticmethod
    def decode_bytes(enc: bytes) -> Tuple[bytes, int]:
        """Returns (data, bytes_consumed)."""
        out = bytearray()
        i = 0
        while True:
            if i + _GROUP + 1 > len(enc):
                raise ValueError("truncated memcomparable bytes")
            group = enc[i : i + _GROUP]
            marker = enc[i + _GROUP]
            pad = _MARKER_FULL - marker
            if not 0 <= pad <= _GROUP:
                raise ValueError(f"bad marker {marker:#x}")
            out += group[: _GROUP - pad]
            i += _GROUP + 1
            if pad > 0:
                return bytes(out), i

    # -- versioned keys --------------------------------------------------------
    @staticmethod
    def encode_key(user_key: bytes, ts: int) -> bytes:
        """encoded user key + inverted big-endian ts (newer sorts first)."""
        return Codec.encode_bytes(user_key) + struct.pack(">Q", MAX_TS - ts)

    @staticmethod
    def decode_key(enc: bytes) -> Tuple[bytes, int]:
        user_key, consumed = Codec.decode_bytes(enc)
        if len(enc) - consumed != 8:
            raise ValueError("missing ts suffix")
        (inv,) = struct.unpack(">Q", enc[consumed:])
        return user_key, MAX_TS - inv

    @staticmethod
    def max_ts_key(user_key: bytes) -> bytes:
        """Seek key positioned at the NEWEST version of user_key."""
        return Codec.encode_key(user_key, MAX_TS)

    @staticmethod
    def min_ts_key(user_key: bytes) -> bytes:
        return Codec.encode_key(user_key, 0)

    # -- values ----------------------------------------------------------------
    @staticmethod
    def package_value(
        payload: bytes, flag: ValueFlag = ValueFlag.PUT, ttl_ms: int = 0
    ) -> bytes:
        if flag is ValueFlag.PUT_TTL:
            return payload + struct.pack(">Q", ttl_ms) + bytes([flag])
        if flag is ValueFlag.DELETE:
            return bytes([flag])
        return payload + bytes([flag])

    @staticmethod
    def unpackage_value(value: bytes) -> Tuple[ValueFlag, bytes, int]:
        """Returns (flag, payload, ttl_ms)."""
        if not value:
            raise ValueError("empty mvcc value")
        flag = ValueFlag(value[-1])
        if flag is ValueFlag.DELETE:
            return flag, b"", 0
        if flag is ValueFlag.PUT_TTL:
            if len(value) < 9:
                raise ValueError("short PUT_TTL value")
            (ttl,) = struct.unpack(">Q", value[-9:-1])
            return flag, value[:-9], ttl
        return flag, value[:-1], 0
