"""Common runtime tests: config/flags, crontab, failpoints, tracker,
metrics, streams, worker sets (reference test/unit_test/common + misc)."""

import time

import pytest

from dingo_tpu.common.config import FLAGS, Config, FlagRegistry
from dingo_tpu.common.crontab import CrontabManager
from dingo_tpu.common.failpoint import (
    FailPointError,
    FailPointManager,
)
from dingo_tpu.common.metrics import MetricsRegistry
from dingo_tpu.common.runnable import WorkerSet
from dingo_tpu.common.stream import StreamManager
from dingo_tpu.common.tracker import Tracker


def test_flags_defaults_and_mutability():
    flags = FlagRegistry()
    flags.define("a", 5)
    flags.define("b", 10, mutable=True)
    assert flags.get("a") == 5
    with pytest.raises(PermissionError):
        flags.set("a", 6)
    flags.set("b", 20)
    assert flags.get("b") == 20
    flags.set("a", 7, boot=True)  # boot-time override allowed
    assert flags.get("a") == 7


def test_reference_limit_flags_present():
    assert FLAGS.get("vector_max_batch_count") == 4096
    assert FLAGS.get("vector_index_bruteforce_batch_count") == 2048


def test_config_file_and_overrides(tmp_path):
    p = tmp_path / "index.conf"
    p.write_text(
        "# role config\n"
        "server.heartbeat_interval_s = 3\n"
        "vector.index_path = /tmp/idx\n"
        "raft.snapshot_threshold = 500\n"
        "flag.bool = true\n"
    )
    cfg = Config.load(str(p))
    assert cfg.get_int("server.heartbeat_interval_s") == 3
    assert cfg.get("vector.index_path") == "/tmp/idx"
    assert cfg.get_bool("flag.bool")
    assert cfg.get("missing", "dflt") == "dflt"
    flags = FlagRegistry()
    flags.define("server_heartbeat_interval_s", 10)
    n = cfg.apply_flag_overrides(flags)
    assert n >= 1 and flags.get("server_heartbeat_interval_s") == 3


def test_crontab_runs_and_counts():
    mgr = CrontabManager(tick_s=0.01)
    hits = []
    mgr.add("fast", 0.02, lambda: hits.append(1), immediately=True)
    mgr.add("boom", 0.02, lambda: 1 / 0, immediately=True)
    for _ in range(5):
        mgr.run_pending()
        time.sleep(0.025)
    stats = mgr.stats()
    assert stats["fast"]["runs"] >= 3
    assert stats["boom"]["errors"] >= 3
    mgr.remove("fast")
    assert "fast" not in mgr.stats()


def test_failpoint_actions():
    fps = FailPointManager()
    fps.configure("p1", "panic")
    with pytest.raises(FailPointError):
        fps.apply("p1")
    fps.configure("limited", "100%2*panic")
    for _ in range(2):
        with pytest.raises(FailPointError):
            fps.apply("limited")
    fps.apply("limited")  # budget exhausted: no-op
    fps.configure("never", "0%panic")
    fps.apply("never")
    fps.remove("p1")
    fps.apply("p1")
    assert "limited" in fps.list()


def test_tracker_spans():
    t = Tracker()
    with t.span("raft_commit"):
        time.sleep(0.01)
    with t.span("store_write"):
        time.sleep(0.005)
    rep = t.report()
    assert rep["raft_commit"] >= 9_000       # us
    assert rep["store_write"] >= 4_000
    assert rep["total_us"] >= rep["raft_commit"]


def test_metrics_registry():
    m = MetricsRegistry()
    m.counter("req", region_id=7).add(3)
    m.gauge("cap").set(0.5)
    with m.latency("search", region_id=7).time():
        time.sleep(0.002)
    dump = m.dump()
    assert dump["req{region=7}"] == 3
    assert dump["cap"] == 0.5
    assert dump["search{region=7}"]["count"] == 1
    assert dump["search{region=7}"]["p99_us"] >= 1500


def test_latency_recorder_empty_window_is_safe():
    """percentile()/stats() on a fresh recorder return zeros, never raise
    (metrics endpoints poll before the first request lands)."""
    from dingo_tpu.common.metrics import LatencyRecorder

    lr = LatencyRecorder()
    assert lr.percentile(50) == 0.0
    assert lr.percentile(99) == 0.0
    assert lr.percentile(100) == 0.0
    st = lr.stats()
    assert st["count"] == 0
    assert st["avg_us"] == 0.0
    assert st["p50_us"] == 0.0 and st["p99_us"] == 0.0
    assert st["qps"] >= 0.0


def test_metrics_dump_per_region_dimension():
    """dump() keeps the region dimension distinct from the global series
    and from other regions (StoreBvarMetrics multi-dimension contract)."""
    m = MetricsRegistry()
    m.counter("req").add(1)
    m.counter("req", region_id=1).add(2)
    m.counter("req", region_id=2).add(5)
    m.latency("lat", region_id=1).observe_us(100.0)
    m.latency("lat")  # empty window rides along in the dump
    dump = m.dump()
    assert dump["req"] == 1
    assert dump["req{region=1}"] == 2
    assert dump["req{region=2}"] == 5
    assert dump["lat{region=1}"]["count"] == 1
    assert dump["lat{region=1}"]["avg_us"] == 100.0
    assert dump["lat"]["count"] == 0          # empty window dumps as zeros
    # same (name, region) resolves to the same instance
    m.counter("req", region_id=1).add(1)
    assert m.dump()["req{region=1}"] == 3


def test_stream_paging():
    sm = StreamManager(idle_timeout_s=0.05)
    s = sm.open(iter(range(25)), limit=10)
    page1, more1 = s.next_page()
    assert page1 == list(range(10)) and more1
    page2, more2 = s.next_page()
    page3, more3 = s.next_page()
    assert page3 == list(range(20, 25)) and not more3
    assert sm.get(s.id) is s
    # finished streams are recycled
    assert sm.recycle_idle() == 1
    assert sm.get(s.id) is None
    # idle timeout recycles unfinished streams
    s2 = sm.open(iter(range(100)), limit=1)
    time.sleep(0.07)
    assert sm.recycle_idle() == 1


def test_worker_set_policies():
    ws = WorkerSet("t", workers=3)
    import threading

    done = []
    lock = threading.Lock()

    def task(i):
        def run():
            with lock:
                done.append(i)
        return run

    for i in range(30):
        ws.execute_least_queue(task(i))
    for i in range(30, 40):
        ws.execute_hash(7, task(i))   # same key -> same worker, ordered
    deadline = time.monotonic() + 3
    while len(done) < 40 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(done) == 40
    # hash dispatch preserved ordering for the same key
    hash_part = [i for i in done if i >= 30]
    assert hash_part == sorted(hash_part)
    ws.stop()


def test_serial_roundtrip_and_ordering():
    from dingo_tpu.common.serial import (
        decode_row_key,
        encode_row_key,
        encode_value,
    )
    import random

    values = [None, False, True, -(1 << 40), -1, 0, 7, 1 << 40,
              -1e300, -2.5, -0.0, 0.0, 1.5, 3e7, "", "abc", "abd", "ab\x00"]
    # roundtrip
    for v in values:
        got = decode_row_key(encode_value(v))
        assert len(got) == 1
        a = got[0]
        assert (a == v) or (v is None and a is None) or (
            isinstance(v, float) and a == v
        ), (v, a)
    # ordering: encoded bytes sort exactly like a (tag, value) tuple sort
    def sort_key(v):
        if v is None:
            return (0,)
        if isinstance(v, bool):
            return (1, v)
        if isinstance(v, int):
            return (2, v)
        if isinstance(v, float):
            return (3, v)
        return (4, v if isinstance(v, str) else v.decode())

    want = sorted(values, key=sort_key)
    got = sorted(values, key=lambda v: encode_value(v))
    assert [sort_key(v) for v in got] == [sort_key(v) for v in want]
    # composite keys order like tuples
    rows = [(1, "b"), (1, "a"), (0, "z"), (2, ""), (1, "ab")]
    enc = sorted(rows, key=lambda r: encode_row_key(r))
    assert enc == sorted(rows)
    assert decode_row_key(encode_row_key((7, "x", None, 2.5))) == \
        [7, "x", None, 2.5]
