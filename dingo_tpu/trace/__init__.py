"""Distributed tracing & query profiling.

The reference attributes latency with ad-hoc bvar recorders and a
per-request Tracker (src/common/tracker.h) that never leaves the process.
This package adds real causality: every RPC ingress mints (or adopts) a
trace id, spans nest through contextvars across the coalescer's thread
handoffs, gRPC metadata carries the context between processes, and a
bounded ring buffer retains sampled traces for the DebugService JSON dump
and a Chrome ``trace_event`` file (chrome://tracing / Perfetto).

Overhead contract: with ``trace_sampling_rate = 0`` every instrumented
site costs ONE sampled-check (a contextvar read + flag read) and returns
the shared no-op span — no allocations on the hot path.
"""

from dingo_tpu.trace.buffer import TRACE_BUFFER, TraceBuffer
from dingo_tpu.trace.export import (
    dump_chrome_trace,
    to_chrome_trace,
    to_json,
)
from dingo_tpu.trace.span import (
    NOOP_SPAN,
    TRACE_METADATA_KEY,
    UNSAMPLED_HEADER,
    Span,
    SpanContext,
    TRACER,
    Tracer,
    current_span,
    extract_metadata,
    inject_metadata,
)

__all__ = [
    "NOOP_SPAN",
    "Span",
    "SpanContext",
    "TRACER",
    "TRACE_BUFFER",
    "TRACE_METADATA_KEY",
    "TraceBuffer",
    "Tracer",
    "UNSAMPLED_HEADER",
    "current_span",
    "dump_chrome_trace",
    "extract_metadata",
    "inject_metadata",
    "to_chrome_trace",
    "to_json",
]
