"""Region export/import transfer sessions + BR meta-restore error handling
(round-3 advisor findings: eof used to destroy the export session, and
_restore_meta swallowed every meta error as a name collision)."""

import json
import time
import types

import pytest

from dingo_tpu.server import pb


def test_region_export_final_chunk_refetchable(tmp_path, capsys):
    """A lost final-chunk response must not kill the whole pull: the export
    session survives eof and the client can re-request the last chunk."""
    from dingo_tpu.client.cli import main
    from dingo_tpu.server.services import RegionControlService
    from tests.test_document_br_cli import _mk_grpc_cluster

    base, nodes, servers = _mk_grpc_cluster(
        seed=11, snapdir=str(tmp_path / "snap"))
    try:
        assert main(base + ["region", "create-index", "--dim", "8"]) == 0
        rid = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])["region_id"]
        time.sleep(0.8)
        assert main(base + ["vector", "add-random", "--dim", "8",
                            "--count", "40"]) == 0
        capsys.readouterr()

        # drive the service on whichever store leads the region
        deadline = time.monotonic() + 5.0
        leader_node = None
        while time.monotonic() < deadline and leader_node is None:
            for n in nodes.values():
                raft = n.engine.get_node(rid)
                if raft is not None and raft.is_leader():
                    leader_node = n
                    break
            time.sleep(0.05)
        assert leader_node is not None, "no leader for exported region"
        svc = RegionControlService(leader_node)

        chunk = 512
        resp = svc.RegionExport(pb.RegionExportRequest(
            region_id=rid, offset=0, export_id=0, max_bytes=chunk))
        assert resp.error.errcode == 0, resp.error.errmsg
        export_id, total = resp.export_id, resp.total_bytes
        assert total > chunk, "need a multi-chunk export for this test"
        offset = len(resp.data)
        last = resp
        while not last.eof:
            last = svc.RegionExport(pb.RegionExportRequest(
                region_id=rid, offset=offset, export_id=export_id,
                max_bytes=chunk))
            assert last.error.errcode == 0, last.error.errmsg
            offset += len(last.data)
        assert last.eof and last.checksum

        # the eof response "was lost": re-pull the final chunk
        again = svc.RegionExport(pb.RegionExportRequest(
            region_id=rid, offset=offset - len(last.data),
            export_id=export_id, max_bytes=chunk))
        assert again.error.errcode == 0, (
            "export session died on eof; final chunk unrecoverable: "
            + again.error.errmsg
        )
        assert again.eof
        assert again.data == last.data
        assert again.checksum == last.checksum
    finally:
        for s in servers:
            s.stop()
        for n in nodes.values():
            n.stop()


def _resp_with(resp, code, msg=""):
    resp.error.errcode = code
    resp.error.errmsg = msg
    return resp


def test_restore_meta_propagates_real_errors():
    """_restore_meta skips genuine name collisions (errcode 40002) but any
    other meta error fails the restore loudly."""
    from dingo_tpu.br.remote import BrError, RemoteBr

    br = RemoteBr.__new__(RemoteBr)

    class _MetaBoom:
        def CreateSchema(self, req):
            return _resp_with(pb.CreateSchemaResponse(), 40001, "boom")

    br.client = types.SimpleNamespace(meta=_MetaBoom())
    with pytest.raises(BrError, match="boom"):
        br._restore_meta({"schemas": ["s1"], "tables": []}, {})

    class _MetaCollide:
        def CreateSchema(self, req):
            return _resp_with(pb.CreateSchemaResponse(), 40002, "exists")

        def ImportTable(self, req):
            return _resp_with(pb.ImportTableResponse(), 40002, "exists")

    br.client = types.SimpleNamespace(meta=_MetaCollide())
    br._restore_meta({"schemas": ["s1"], "tables": []}, {})  # no raise

    class _MetaTableBoom(_MetaCollide):
        def ImportTable(self, req):
            return _resp_with(pb.ImportTableResponse(), 40001, "table boom")

    br.client = types.SimpleNamespace(meta=_MetaTableBoom())
    d = pb.TableDef()
    d.name = "t1"
    manifest = {"schemas": [],
                "tables": [{"definition_pb": d.SerializeToString().hex()}]}
    with pytest.raises(BrError, match="table boom"):
        br._restore_meta(manifest, {})
