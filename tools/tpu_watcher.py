"""Round-long TPU lease watcher (round-4 VERDICT Next #1).

The axon TPU lease is intermittently available: round 3 saw the chip answer
mid-round while every end-of-round bench probe timed out. This watcher runs
for the whole round as a detached background process, probing cheaply every
few minutes; the moment the chip answers it runs the on-chip work queue
(smoke suite, BASELINE row-2 bench, then the rest of the BASELINE matrix)
and PERSISTS every result so the end-of-round driver run of bench.py can
serve a real TPU number even if the lease is wedged at that moment.

    python tools/tpu_watcher.py &        # normally launched via nohup

State:    TPU_WATCHER_STATE.json   (repo root; progress + results)
Log:      tools/tpu_watcher.log
Results:  SMOKE_r05.json, TPU_BENCH_CACHE.json (written by bench.py),
          BASELINE_RESULTS.jsonl (appended by tools/bench_matrix.py)

Round-5 hardening (round-4 VERDICT Weak #1: the watcher "was down most of
the round" and its log was silent): every probe attempt now logs its
outcome + failure reason, and `tools/tpu_supervisor.py` respawns this
process if it ever exits before the round deadline.

Lease etiquette: never SIGKILL a process holding the chip (the lease wedges
for minutes). Steps get generous timeouts, then SIGTERM + a long grace
period; SIGKILL only as a last resort.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: watcher/supervisor scratch (logs, state, pids) lives OUTSIDE the repo
#: tree — earlier rounds committed accumulating tools/*.log artifacts.
#: DINGO_RUNTIME_DIR overrides (e.g. a persistent volume).
RUNTIME_DIR = os.environ.get("DINGO_RUNTIME_DIR") or os.path.join(
    tempfile.gettempdir(), "dingo-tpu"
)
os.makedirs(RUNTIME_DIR, exist_ok=True)

#: rotate a log once it exceeds this (keep one .1 generation): a round-long
#: probe loop must not grow a file without bound
LOG_ROTATE_BYTES = 1 << 20

STATE_PATH = os.path.join(RUNTIME_DIR, "TPU_WATCHER_STATE.json")
LOG_PATH = os.path.join(RUNTIME_DIR, "tpu_watcher.log")
PID_PATH = os.path.join(RUNTIME_DIR, "tpu_watcher.pid")


def append_log(path: str, line: str) -> None:
    """Size-capped append shared by watcher and supervisor."""
    try:
        if os.path.getsize(path) > LOG_ROTATE_BYTES:
            os.replace(path, path + ".1")
    except OSError:
        pass
    with open(path, "a") as f:
        f.write(line + "\n")

PROBE_TIMEOUT_S = 120
PROBE_INTERVAL_S = 240
# single source of truth for the round deadline (tpu_supervisor.py imports
# this constant — editing it here adjusts both processes in lockstep)
ROUND_DEADLINE_S = 11.75 * 3600  # stop probing near end of round

# (name, argv, timeout_s). Ordered by value: the row-2 bench IS the round
# deliverable; smoke first because it validates the Pallas kernels the bench
# may route through. Matrix rows fill BASELINE.md opportunistically.
QUEUE = [
    ("smoke", [sys.executable, "tpu_smoke.py"], 2400),
    ("bench_row2", [sys.executable, "bench.py"], 7200),
    ("row1_flat", [sys.executable, "tools/bench_matrix.py", "--row", "1"], 2400),
    ("row4_hnsw", [sys.executable, "tools/bench_matrix.py", "--row", "4"], 5400),
    ("row3_ivfpq", [sys.executable, "tools/bench_matrix.py", "--row", "3"], 9000),
]


def log(msg: str) -> None:
    append_log(LOG_PATH, f"[{time.strftime('%H:%M:%S')}] {msg}")


def load_state() -> dict:
    fresh = {"done": {}, "probes": 0, "started": time.time()}
    try:
        with open(STATE_PATH) as f:
            st = json.load(f)
    except (OSError, ValueError):
        return fresh
    # a state file left by a PREVIOUS round must not satisfy this one: its
    # 'done' results came from old code. Only discard CLEARLY old state
    # (two deadlines) — state merely past THIS round's deadline must
    # survive, or a deadline-exit + supervisor respawn would reset
    # 'started' and grant a whole new probing window bleeding into the
    # next round (r5 review finding)
    if time.time() - st.get("started", 0) > 2 * ROUND_DEADLINE_S:
        log("discarding stale watcher state from a previous round")
        return fresh
    return st


def save_state(st: dict) -> None:
    tmp = STATE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f, indent=1)
    os.replace(tmp, STATE_PATH)


def probe_tpu() -> tuple[bool, str]:
    code = (
        "import jax; d = jax.devices(); import jax.numpy as jnp; "
        "(jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready(); "
        "print('PLATFORM=' + d[0].platform)"
    )
    # same lease etiquette as run_step: the probe child itself holds the
    # lease mid-acquisition, so a SIGKILL (what subprocess.run's timeout
    # sends) would wedge the very lease we are waiting for
    p = subprocess.Popen(
        [sys.executable, "-c", code], cwd=REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
    )
    try:
        out, _ = p.communicate(timeout=PROBE_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        p.send_signal(signal.SIGTERM)
        try:
            p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            threading.Thread(target=p.communicate, daemon=True).start()
            return False, f"timeout>{PROBE_TIMEOUT_S}s, ignored SIGTERM 120s"
        return False, f"timeout>{PROBE_TIMEOUT_S}s"
    if p.returncode != 0:
        return False, f"rc={p.returncode}"
    if "PLATFORM=tpu" in (out or "") or "PLATFORM=axon" in (out or ""):
        return True, "hit"
    return False, f"platform={(out or '').strip()[-40:]}"


def run_step(name: str, argv: list[str], timeout_s: int) -> tuple[int, str]:
    """Run one on-chip step with graceful termination (no surprise SIGKILL
    of a lease holder)."""
    env = dict(os.environ)
    env.setdefault("DINGO_BENCH_PROBE_S", "90")
    env.setdefault("DINGO_SMOKE_PROBE_S", "90")
    p = subprocess.Popen(
        argv, cwd=REPO, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        out, _ = p.communicate(timeout=timeout_s)
        return p.returncode, out or ""
    except subprocess.TimeoutExpired:
        log(f"step {name}: timeout after {timeout_s}s, SIGTERM")
        p.send_signal(signal.SIGTERM)
        try:
            out, _ = p.communicate(timeout=180)
            return -signal.SIGTERM, out or ""
        except subprocess.TimeoutExpired:
            log(f"step {name}: still alive 180s after SIGTERM, SIGKILL "
                "(lease may wedge for a few minutes)")
            p.kill()
            out, _ = p.communicate()
            return -signal.SIGKILL, out or ""


def step_done(name: str, rc: int, out: str) -> bool:
    """Did this step produce a real TPU result (vs a CPU fallback)?"""
    if name == "smoke":
        if rc in (0, 1):  # 1 = ran on chip but a check failed: evidence too
            with open(os.path.join(REPO, "SMOKE_r05.json"), "w") as f:
                json.dump({"rc": rc, "ts": time.time(),
                           "output": out[-4000:]}, f, indent=1)
            return True
        return False  # rc==2 no TPU → requeue
    # bench steps: last stdout line should be the JSON with platform=tpu;
    # a served cache ("cached": true) is NOT a fresh measurement — requeue
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{") and '"platform"' in line:
            try:
                parsed = json.loads(line)
            except ValueError:
                return False
            return parsed.get("platform") == "tpu" and not parsed.get("cached")
    return False


def main() -> None:
    with open(PID_PATH, "w") as f:
        f.write(str(os.getpid()))
    st = load_state()
    start = st.get("started", time.time())
    log(f"watcher up pid={os.getpid()} done={list(st['done'])}")
    while time.time() - st.get("started", start) < ROUND_DEADLINE_S:
        pending = [q for q in QUEUE if q[0] not in st["done"]]
        if not pending:
            log("queue complete; watcher exiting")
            break
        st["probes"] += 1
        hit, why = probe_tpu()
        if not hit:
            st["last_probe"] = f"miss ({why})"
            save_state(st)
            log(f"probe #{st['probes']}: miss ({why}); "
                f"sleeping {PROBE_INTERVAL_S}s")
            time.sleep(PROBE_INTERVAL_S)
            continue
        log(f"TPU ANSWERED (probe #{st['probes']}); running "
            f"{[q[0] for q in pending]}")
        st["last_probe"] = "hit"
        save_state(st)
        for name, argv, timeout_s in pending:
            t0 = time.time()
            rc, out = run_step(name, argv, timeout_s)
            dt = time.time() - t0
            ok = step_done(name, rc, out)
            log(f"step {name}: rc={rc} {dt:.0f}s done={ok}; "
                f"tail={out[-400:]!r}")
            if ok:
                st["done"][name] = {"rc": rc, "secs": round(dt), "ts": time.time()}
                save_state(st)
            else:
                # lease lost mid-queue — go back to probing
                log(f"step {name}: no TPU result; re-probing")
                break
        time.sleep(30)
    log("watcher done")


if __name__ == "__main__":
    main()
