"""Typed persistence codec (common/persist.py): round-trips registered
types, refuses unregistered/unknown types, and never unpickles by default
(round-2 VERDICT weak 5: local disk state was pickle => restoring a
tampered snapshot was arbitrary code execution)."""

import dataclasses
import pickle

import pytest

from dingo_tpu.common import persist
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.raft.wire import WireError
from dingo_tpu.store.region import (
    Region,
    RegionDefinition,
    RegionEpoch,
    RegionState,
    RegionType,
)


def test_roundtrip_region_definition():
    d = RegionDefinition(
        region_id=7, start_key=b"a", end_key=b"z", partition_id=3,
        peers=[1, 2, 3], epoch=RegionEpoch(conf_version=2, version=5),
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(
            index_type=IndexType.IVF_FLAT, dimension=128, ncentroids=64,
        ),
    )
    got = persist.loads(persist.dumps(d))
    assert got == d
    assert isinstance(got.region_type, RegionType)
    assert isinstance(got.index_parameter.index_type, IndexType)


def test_roundtrip_non_str_dict_keys():
    v = {"postings": {3: [1, 2], 9: [0]}, "n": 2}
    assert persist.loads(persist.dumps(v)) == v


def test_region_serialize_roundtrip():
    region = Region(RegionDefinition(
        region_id=9, start_key=b"a", end_key=b"", partition_id=0,
    ))
    region.state = RegionState.NORMAL
    got = Region.deserialize(region.serialize())
    assert got.definition == region.definition
    assert got.state is RegionState.NORMAL


def test_unregistered_type_refused():
    @dataclasses.dataclass
    class Rogue:
        x: int = 1

    with pytest.raises(TypeError, match="not persist.register"):
        persist.dumps(Rogue())


def test_unknown_tag_refused():
    from dingo_tpu.raft import wire

    blob = wire.encode({"__dc": "OsSystem", "f": {"cmd": "rm -rf /"}})
    with pytest.raises(WireError, match="unknown dataclass"):
        persist.loads(blob)


def test_pickle_blob_refused_by_default(monkeypatch):
    monkeypatch.delenv("DINGO_ALLOW_PICKLE_MIGRATION", raising=False)
    blob = pickle.dumps({"definition": 1})
    with pytest.raises(WireError, match="typed persist format"):
        persist.loads(blob)


def test_forward_compat_unknown_field_dropped():
    blob = persist.dumps(RegionEpoch(conf_version=3, version=4))
    # simulate a future version adding a field
    from dingo_tpu.raft import wire

    tree = wire.decode(blob)
    tree["f"]["future_field"] = 42
    got = persist.loads(wire.encode(tree))
    assert got == RegionEpoch(conf_version=3, version=4)
