"""Pallas IVF list-DMA kernel: stream ONLY probed buckets through VMEM.

The XLA IVF path (`ivf_flat._ivf_scan_kernel`) gathers each probed bucket
into a fresh [b, cap_list, d] HBM array per probe rank and then reads it
again for the distance einsum — 3x the necessary HBM traffic, plus it
cannot skip padded ranks. This kernel uses scalar-prefetched probe ids as
the BlockSpec index_map, so the Pallas pipeline DMAs exactly one probed
bucket [cap_list, d] from HBM to VMEM per grid step (double-buffered), and
the distance + running top-k merge happen in VMEM with nothing written
back but the final [b, k].

Replaces the hot loop the reference runs through faiss's IVF scanners over
src/simd/hook.cc kernels (vector_index_ivf_flat.cc search path).

Grid: (b, budget) — query-major, so the output block for query q stays
resident in VMEM across its inner rank loop (accumulate-in-output pattern).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dingo_tpu.ops.pallas_topk import _select_topk
from dingo_tpu.obs.sentinel import sentinel_jit

NEG_INF = float("-inf")
#: output lane padding (TPU lane width; k slots live in the first k lanes)
OUT_PAD = 128
#: sublane-aligned row blocking for per-query arrays (batch padded to this)
ROW_BLOCK = 8


def _ivf_kernel(vp_ref, q_ref, qsq_ref, x_ref, xsq_ref, val_ref, slot_ref,
                outv_ref, outi_ref, *, k, ascending):
    # Mosaic's tiling rule rejects blocks with a size-1 sublane dim on a
    # larger array (observed on-chip round 3), so queries/qsq/outputs
    # arrive as 8-row sublane-aligned blocks (index q // 8) and the kernel
    # addresses its query's row within the block with a dynamic slice —
    # VMEM stays O(1) in the batch, unlike full-batch blocks. The grid is
    # query-major, so all 8 rows of an output block are initialized and
    # filled by their own queries before the block index advances.
    qi = pl.program_id(0)
    r = pl.program_id(1)
    row = pl.ds(jax.lax.rem(qi, ROW_BLOCK), 1)

    @pl.when(r == 0)
    def _init():
        outv_ref[row, :] = jnp.full(
            (1, outv_ref.shape[1]), NEG_INF, jnp.float32
        )
        outi_ref[row, :] = jnp.full(
            (1, outi_ref.shape[1]), -1, jnp.int32
        )

    @pl.when(vp_ref[qi, r] >= 0)
    def _scan_bucket():
        q = q_ref[row, :]                                # [1, d]
        x = x_ref[0].astype(jnp.float32)                 # [cap, d]
        dots = jax.lax.dot_general(
            q, x, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )                                                # [1, cap]
        if ascending:   # L2 score = -(||q||^2 - 2qx + ||x||^2)
            scores = -(qsq_ref[row, :] - 2.0 * dots + xsq_ref[0])
        else:           # IP
            scores = dots
        scores = jnp.where(val_ref[0] > 0.5, scores, NEG_INF)
        slot = slot_ref[0].astype(jnp.int32)             # [1, cap]
        blk_v, blk_i = _select_topk(scores, slot, k)
        cur_v = outv_ref[row, :]
        cur_i = outi_ref[row, :]
        cat_v = jnp.concatenate([cur_v[:, :k], blk_v], axis=1)
        cat_i = jnp.concatenate([cur_i[:, :k], blk_i], axis=1)
        new_v, new_i = _select_topk(cat_v, cat_i, k)
        pad = outv_ref.shape[1] - k
        outv_ref[row, :] = jnp.concatenate(
            [new_v, jnp.full((1, pad), NEG_INF, jnp.float32)], axis=1
        )
        outi_ref[row, :] = jnp.concatenate(
            [new_i, jnp.full((1, pad), -1, jnp.int32)], axis=1
        )

    @pl.when(r == pl.num_programs(1) - 1)
    def _finish():
        fv = outv_ref[row, :]
        # -inf picks carry arbitrary slots; normalize to -1 like the XLA path
        outi_ref[row, :] = jnp.where(jnp.isneginf(fv), -1, outi_ref[row, :])


@sentinel_jit("ops.pallas.ivf_list_topk",
              static_argnames=("k", "ascending", "interpret"))
def ivf_list_topk(
    vprobes: jax.Array,        # [b, budget] int32 virtual bucket ids (-1 pad)
    queries: jax.Array,        # [b, d] f32
    buckets: jax.Array,        # [B, cap, d]
    bucket_sqnorm: jax.Array,  # [B, cap] f32
    bucket_valid: jax.Array,   # [B, cap] bool/float
    bucket_slot: jax.Array,    # [B, cap] int32
    k: int,
    ascending: bool = True,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused probed-bucket scan -> (scores[b, k], slots[b, k]).

    Scores follow the 'larger is better' convention (negated L2 when
    ascending); slots are -1 where fewer than k valid rows were probed.
    """
    b, d = queries.shape
    nb, cap, _ = buckets.shape
    budget = vprobes.shape[1]
    q32 = queries.astype(jnp.float32)
    qsq = jnp.einsum(
        "bd,bd->b", q32, q32, precision=jax.lax.Precision.HIGHEST
    )[:, None]
    # index_map reads the prefetched probes; clamp padded (-1) ranks to
    # bucket 0 — the kernel body skips them via pl.when
    def bucket_map(q, r, vp):
        return (jnp.maximum(vp[q, r], 0), 0, 0)

    # row metadata rides as [B, 1, cap] so each block is (1, 1, cap): the
    # last two dims equal the array's — Mosaic rejects (1, cap) blocks on
    # [B, cap] (size-1 sublane on a larger array). Per-query arrays ride
    # as ROW_BLOCK-row blocks so VMEM stays O(1) in the batch.
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, budget),
        in_specs=[
            pl.BlockSpec(
                (ROW_BLOCK, d), lambda q, r, vp: (q // ROW_BLOCK, 0)
            ),                                                    # queries
            pl.BlockSpec(
                (ROW_BLOCK, 1), lambda q, r, vp: (q // ROW_BLOCK, 0)
            ),                                                    # qsq
            pl.BlockSpec((1, cap, d), bucket_map),                # bucket data
            pl.BlockSpec((1, 1, cap), bucket_map),                # sqnorm
            pl.BlockSpec((1, 1, cap), bucket_map),                # valid
            pl.BlockSpec((1, 1, cap), bucket_map),                # slots
        ],
        out_specs=[
            pl.BlockSpec(
                (ROW_BLOCK, OUT_PAD), lambda q, r, vp: (q // ROW_BLOCK, 0)
            ),
            pl.BlockSpec(
                (ROW_BLOCK, OUT_PAD), lambda q, r, vp: (q // ROW_BLOCK, 0)
            ),
        ],
    )
    out_v, out_i = pl.pallas_call(
        functools.partial(_ivf_kernel, k=k, ascending=ascending),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, OUT_PAD), jnp.float32),
            jax.ShapeDtypeStruct((b, OUT_PAD), jnp.int32),
        ],
        interpret=interpret,
    )(
        vprobes,
        q32,
        qsq,
        buckets,
        bucket_sqnorm[:, None, :],
        bucket_valid.astype(jnp.float32)[:, None, :],
        bucket_slot[:, None, :],
    )
    return out_v[:, :k], out_i[:, :k]


def ivf_list_search(
    vprobes, queries, buckets, bucket_sqnorm, bucket_valid, bucket_slot,
    k: int, ascending: bool = True,
):
    """Backend-aware wrapper: interpret mode off-TPU (Mosaic is TPU-only);
    pads the batch to ROW_BLOCK (padded queries probe nothing: vprobes -1)."""
    b = queries.shape[0]
    pad = (-b) % ROW_BLOCK
    if pad:
        queries = jnp.concatenate(
            [queries, jnp.zeros((pad, queries.shape[1]), queries.dtype)]
        )
        vprobes = jnp.concatenate(
            [vprobes, jnp.full((pad, vprobes.shape[1]), -1, vprobes.dtype)]
        )
    interpret = jax.default_backend() not in ("tpu", "axon")
    vals, slots = ivf_list_topk(
        vprobes, queries, buckets, bucket_sqnorm, bucket_valid, bucket_slot,
        k=k, ascending=ascending, interpret=interpret,
    )
    from dingo_tpu.ops.distance import device_wait_span

    vals, slots = device_wait_span("pallas_ivf_search", (vals, slots))
    return vals[:b], slots[:b]
