"""Vector index families (TPU-resident), mirroring reference src/vector/.

Index types match pb::common::VectorIndexType:
  FLAT        -> TpuFlat         (vector_index_flat.{h,cc})
  IVF_FLAT    -> TpuIvfFlat      (vector_index_ivf_flat.{h,cc})
  IVF_PQ      -> TpuIvfPq        (vector_index_ivf_pq.{h,cc}, hybrid flat->pq)
  HNSW        -> TpuHnsw         (vector_index_hnsw.{h,cc}, CPU graph + TPU rerank)
  BRUTEFORCE  -> TpuBruteforce   (vector_index_bruteforce.{h,cc})
  BINARY_FLAT -> TpuBinaryFlat   (faiss::IndexBinaryFlat equivalent)
"""

from dingo_tpu.index.base import (  # noqa: F401
    FilterSpec,
    IndexParameter,
    IndexType,
    SearchResult,
    VectorIndex,
    VectorIndexError,
    InvalidParameter,
    NotSupported,
    NotTrained,
)
from dingo_tpu.index.factory import new_index  # noqa: F401
