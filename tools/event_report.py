"""Render a control-plane event dump as a per-region decision timeline.

Input is JSON from any of the ledger's faces:

- the ``events`` section of a flight bundle (``tools/flight_report.py
  BUNDLE --json | jq .events``),
- an ``EventDumpResponse`` dumped as a JSON list of event objects, or
- a bench scenario's ``events`` list (bench.py records the ledger
  trajectory for the convergence scenarios).

    python tools/event_report.py EVENTS_FILE [--region N] [--actor A] [--json]

The report groups events per region, renders each as TIME NODE ACTOR
KNOB old->new (trigger) evidence, and summarizes per-actor decision
counts — the offline twin of ``cluster events`` for post-incident work
on an exported bundle.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import zlib
from typing import Any, Dict, List


def load_events(path: str) -> List[Dict[str, Any]]:
    """Accepts a JSON list of events, a flight bundle (raw zlib or JSON —
    the ``events`` section is extracted), or an EventDumpResponse-shaped
    object ({"events": [...]})."""
    with open(path, "rb") as f:
        raw = f.read()
    try:
        raw = zlib.decompress(raw)
    except zlib.error:
        pass            # plain JSON already
    doc = json.loads(raw.decode("utf-8"))
    if isinstance(doc, list):
        return doc
    if isinstance(doc, dict) and isinstance(doc.get("events"), list):
        return doc["events"]
    raise SystemExit(f"{path}: no event list found")


def _fmt_time(ts_ms: int) -> str:
    if not ts_ms:
        return "-"
    return time.strftime("%H:%M:%S", time.localtime(ts_ms / 1000.0)) + (
        ".%03d" % (int(ts_ms) % 1000))


def _table(headers: List[str], rows: List[List[str]]) -> List[str]:
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths)),
           "  ".join("-" * w for w in widths)]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return out


def render(events: List[Dict[str, Any]], region_id: int = 0,
           actor: str = "") -> str:
    """Pure render (tests drive this directly): per-region timelines +
    a per-actor decision tally."""
    events = [
        e for e in events
        if (not region_id or int(e.get("region_id", 0)) == region_id)
        and (not actor or e.get("actor") == actor)
    ]
    if not events:
        return "no matching control-plane events"
    events.sort(key=lambda e: (int(e.get("ts_ms", 0)),
                               str(e.get("node_id", "")),
                               int(e.get("actor_seq", 0))))
    out: List[str] = []
    by_region: Dict[int, List[Dict[str, Any]]] = {}
    for e in events:
        by_region.setdefault(int(e.get("region_id", 0)), []).append(e)
    for rid in sorted(by_region):
        evs = by_region[rid]
        out.append(f"region {rid} — {len(evs)} decision(s)")
        rows = []
        for e in evs:
            rows.append([
                _fmt_time(int(e.get("ts_ms", 0))),
                str(e.get("node_id", "") or "-"),
                str(e.get("actor", "")),
                str(e.get("knob", "")),
                f"{e.get('old') or '-'} -> {e.get('new') or '-'}",
                str(e.get("trigger", "")),
                str(e.get("evidence", "") or "-"),
            ])
        out += _table(
            ["TIME", "NODE", "ACTOR", "KNOB", "CHANGE", "TRIGGER",
             "EVIDENCE"], rows)
        out.append("")
    tally: Dict[str, int] = {}
    for e in events:
        tally[str(e.get("actor", ""))] = tally.get(
            str(e.get("actor", "")), 0) + 1
    out.append("decisions by actor: " + ", ".join(
        f"{a}={n}" for a, n in sorted(tally.items())))
    return "\n".join(out)


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        description="render a control-plane event dump")
    ap.add_argument("path")
    ap.add_argument("--region", type=int, default=0)
    ap.add_argument("--actor", default="")
    ap.add_argument("--json", action="store_true",
                    help="dump the filtered events as JSON (for jq)")
    args = ap.parse_args(argv)
    events = load_events(args.path)
    if args.json:
        events = [
            e for e in events
            if (not args.region
                or int(e.get("region_id", 0)) == args.region)
            and (not args.actor or e.get("actor") == args.actor)
        ]
        print(json.dumps(events, indent=2, default=str))
        return 0
    print(render(events, region_id=args.region, actor=args.actor))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
