"""Typed write payloads (raft proposal bodies).

Reference: src/engine/write_data.h (762 LoC) — WriteDataBuilder::BuildWrite
constructs typed RaftCmdRequest payloads (KV puts, vector adds with cf/ts/ttl,
deletes); the same payload is applied by the raft state machine on every
replica (handler/raft_apply_handler.h:29-193).

These dataclasses are the wire-neutral equivalents; raft serializes them with
pickle for replication (a protobuf schema lands with the grpc service layer).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class KvPutData:
    """PutHandler payload."""

    cf: str
    ts: int
    kvs: List[Tuple[bytes, bytes]]
    ttl_ms: int = 0


@dataclasses.dataclass
class KvDeleteData:
    """DeleteBatchHandler payload (tombstone versions)."""

    cf: str
    ts: int
    keys: List[bytes]


@dataclasses.dataclass
class KvDeleteRangeData:
    """DeleteRangeHandler payload."""

    cf: str
    ts: int
    ranges: List[Tuple[bytes, bytes]]


@dataclasses.dataclass
class VectorAddData:
    """VectorAddHandler payload (raft_apply_handler.cc:1115): vector rows +
    scalar data; handler writes data/scalar/table CFs then updates the
    in-memory index through the wrapper."""

    ts: int
    ids: np.ndarray                       # [n] int64
    vectors: np.ndarray                   # [n, d] f32
    scalars: Optional[List[Dict[str, Any]]] = None
    is_update: bool = True                # upsert vs add
    ttl_ms: int = 0


@dataclasses.dataclass
class VectorDeleteData:
    """VectorDeleteHandler payload (raft_apply_handler.cc:1374)."""

    ts: int
    ids: np.ndarray


@dataclasses.dataclass
class RebuildVectorIndexData:
    """RebuildVectorIndexHandler (raft_apply_handler.cc:1546): replicated
    marker that a rebuild cutover happened at this log position."""

    cutover_log_id: int = 0


@dataclasses.dataclass
class SplitRegionData:
    """SplitHandler payload (raft_apply_handler.cc:702)."""

    child_region_id: int
    split_key: bytes


@dataclasses.dataclass
class DocumentAddData:
    """DocumentAdd/BatchAddHandler payload (handler list,
    raft_apply_handler.h: DocumentAdd/Delete/BatchAddHandler)."""

    ts: int
    ids: List[int]
    documents: List[Dict[str, Any]]
    is_update: bool = True


@dataclasses.dataclass
class DocumentDeleteData:
    ts: int
    ids: List[int]


@dataclasses.dataclass
class MergeRegionData:
    """CommitMergeHandler payload (raft_apply_handler.cc:78-99,1021):
    target absorbs the source region's range; the source's in-memory index
    becomes the target's sibling until the target rebuilds."""

    source_region_id: int
    source_end_key: bytes


@dataclasses.dataclass
class TxnRaftData:
    """TxnHandler payload (raft_apply_handler_txn.cc): pre-encoded CF writes
    produced by the Percolator helper (engine/txn.py)."""

    puts: List[Tuple[str, bytes, bytes]]
    deletes: List[Tuple[str, bytes]]


WriteData = Any  # union of the payload dataclasses above
