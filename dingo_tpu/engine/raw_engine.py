"""Raw KV engine interface + implementations.

Reference: src/engine/raw_engine.h defines the abstract RawEngine over named
column families (common/constant.h:43-55: default, vector_scalar,
vector_scalar_key_speed_up, vector_table, txn data/lock/write, meta), with
RocksRawEngine as the production engine (rocks_raw_engine.{h,cc}) and
MemEngine for tests (mem_engine.h).

Here: MemEngine is a sorted in-memory CF map (tests + raft apply target);
WalEngine adds crash-safe persistence via an append-only WAL + checkpoint
snapshots — functionally covering RocksRawEngine's role (persistence,
checkpoint for raft snapshots, ingest) with a pure-Python LSM-lite. A C++
LSM engine is a planned upgrade; the interface below is what the rest of
the stack codes against.
"""

from __future__ import annotations

import bisect
import io
import json
import os
import struct

from dingo_tpu.raft import wire
import threading
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from dingo_tpu.trace import TRACER

# Column family names (common/constant.h:43-55)
CF_DEFAULT = "default"
CF_META = "meta"
CF_VECTOR_SCALAR = "vector_scalar"
CF_VECTOR_SCALAR_SPEEDUP = "vector_scalar_key_speed_up"
CF_VECTOR_TABLE = "vector_table"
CF_TXN_DATA = "data"
CF_TXN_LOCK = "lock"
CF_TXN_WRITE = "write"

ALL_CFS = (
    CF_DEFAULT,
    CF_META,
    CF_VECTOR_SCALAR,
    CF_VECTOR_SCALAR_SPEEDUP,
    CF_VECTOR_TABLE,
    CF_TXN_DATA,
    CF_TXN_LOCK,
    CF_TXN_WRITE,
)


class SortedKv:
    """Sorted byte-key map with range scans (one column family)."""

    __slots__ = ("_keys", "_map")

    def __init__(self):
        self._keys: List[bytes] = []
        self._map: Dict[bytes, bytes] = {}

    def put(self, key: bytes, value: bytes) -> None:
        if key not in self._map:
            bisect.insort(self._keys, key)
        self._map[key] = value

    def get(self, key: bytes) -> Optional[bytes]:
        return self._map.get(key)

    def delete(self, key: bytes) -> bool:
        if key in self._map:
            del self._map[key]
            i = bisect.bisect_left(self._keys, key)
            del self._keys[i]
            return True
        return False

    def scan(
        self, start: bytes = b"", end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, bytes]]:
        """[start, end) ascending."""
        i = bisect.bisect_left(self._keys, start)
        while i < len(self._keys):
            k = self._keys[i]
            if end is not None and k >= end:
                return
            yield k, self._map[k]
            i += 1

    def scan_reverse(
        self, start: bytes = b"", end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, bytes]]:
        """[start, end) descending."""
        hi = bisect.bisect_left(self._keys, end) if end is not None else len(self._keys)
        lo = bisect.bisect_left(self._keys, start)
        for i in range(hi - 1, lo - 1, -1):
            k = self._keys[i]
            yield k, self._map[k]

    def delete_range(self, start: bytes, end: Optional[bytes]) -> int:
        """[start, end); end None = to the end of the CF."""
        lo = bisect.bisect_left(self._keys, start)
        hi = (bisect.bisect_left(self._keys, end) if end is not None
              else len(self._keys))
        doomed = self._keys[lo:hi]
        for k in doomed:
            del self._map[k]
        del self._keys[lo:hi]
        return len(doomed)

    def count(self, start: bytes = b"", end: Optional[bytes] = None) -> int:
        lo = bisect.bisect_left(self._keys, start)
        hi = bisect.bisect_left(self._keys, end) if end is not None else len(self._keys)
        return hi - lo

    def __len__(self) -> int:
        return len(self._map)


class WriteBatch:
    """Atomic multi-CF mutation (RocksDB WriteBatch equivalent)."""

    def __init__(self):
        self.ops: List[Tuple[str, str, bytes, bytes]] = []

    def put(self, cf: str, key: bytes, value: bytes) -> "WriteBatch":
        self.ops.append(("put", cf, key, value))
        return self

    def delete(self, cf: str, key: bytes) -> "WriteBatch":
        self.ops.append(("del", cf, key, b""))
        return self

    def delete_range(
        self, cf: str, start: bytes, end: Optional[bytes]
    ) -> "WriteBatch":
        """end None = unbounded (to the end of the CF) — an encoded empty
        key sorts BELOW every real key, so it must never be used as an
        upper bound."""
        self.ops.append(("delr", cf, start, end))
        return self


class RawEngine:
    """Abstract raw engine (raw_engine.h)."""

    def get(self, cf: str, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def write(self, batch: WriteBatch) -> None:
        raise NotImplementedError

    def scan(self, cf, start=b"", end=None):
        raise NotImplementedError

    def scan_reverse(self, cf, start=b"", end=None):
        raise NotImplementedError

    def count(self, cf, start=b"", end=None) -> int:
        raise NotImplementedError

    # convenience single ops
    def put(self, cf: str, key: bytes, value: bytes) -> None:
        self.write(WriteBatch().put(cf, key, value))

    def delete(self, cf: str, key: bytes) -> None:
        self.write(WriteBatch().delete(cf, key))

    def checkpoint(self, path: str) -> None:
        raise NotImplementedError

    def restore_checkpoint(self, path: str) -> None:
        raise NotImplementedError

    def close(self) -> None:  # noqa: B027
        pass


class MemEngine(RawEngine):
    """In-memory engine (reference mem_engine.h) — also the memtable of
    WalEngine and the raft-apply target in tests."""

    def __init__(self):
        self._cfs: Dict[str, SortedKv] = {cf: SortedKv() for cf in ALL_CFS}
        self._lock = threading.RLock()

    def cf(self, name: str) -> SortedKv:
        kv = self._cfs.get(name)
        if kv is None:
            with self._lock:
                kv = self._cfs.setdefault(name, SortedKv())
        return kv

    def get(self, cf, key):
        with self._lock:
            return self.cf(cf).get(key)

    def write(self, batch: WriteBatch) -> None:
        with TRACER.start_span("engine.write") as span:
            span.set_attr("ops", len(batch.ops))
            with self._lock:
                for op, cf, a, b in batch.ops:
                    kv = self.cf(cf)
                    if op == "put":
                        kv.put(a, b)
                    elif op == "del":
                        kv.delete(a)
                    elif op == "delr":
                        kv.delete_range(a, b)

    def scan(self, cf, start=b"", end=None):
        with self._lock:
            return list(self.cf(cf).scan(start, end))

    def scan_reverse(self, cf, start=b"", end=None):
        with self._lock:
            return list(self.cf(cf).scan_reverse(start, end))

    def count(self, cf, start=b"", end=None):
        with self._lock:
            return self.cf(cf).count(start, end)

    def snapshot_state(self) -> Dict[str, List[Tuple[bytes, bytes]]]:
        with self._lock:
            return {
                name: list(kv.scan()) for name, kv in self._cfs.items() if len(kv)
            }

    def load_state(self, state: Dict[str, List[Tuple[bytes, bytes]]]) -> None:
        with self._lock:
            self._cfs = {cf: SortedKv() for cf in ALL_CFS}
            for name, pairs in state.items():
                kv = self.cf(name)
                for k, v in pairs:
                    kv.put(k, v)

    def checkpoint(self, path: str) -> None:
        """Atomic: state is written to a temp file and renamed, so a crash
        mid-checkpoint leaves the previous checkpoint intact."""
        os.makedirs(path, exist_ok=True)
        target = os.path.join(path, "mem.ckpt")
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(wire.encode(self.snapshot_state()))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)

    def restore_checkpoint(self, path: str) -> None:
        with open(os.path.join(path, "mem.ckpt"), "rb") as f:
            self.load_state(wire.decode(f.read()))


_WAL_MAGIC = 0xD1460A11


class WalEngine(MemEngine):
    """Crash-safe engine: MemEngine + append-only WAL + checkpoints.

    Write path: serialize the batch, append to WAL (fsync optional), apply to
    the memtable. Recovery: load last checkpoint, replay WAL tail. Covers the
    RocksRawEngine duties the stack needs today (durability, checkpoint for
    raft snapshots); compaction == checkpoint + WAL truncation.
    """

    def __init__(self, path: str, fsync: bool = False,
                 checkpoint_threshold_bytes: Optional[int] = None):
        super().__init__()
        from dingo_tpu.common.config import FLAGS

        self.path = path
        self.fsync = fsync
        self.checkpoint_threshold_bytes = (
            checkpoint_threshold_bytes
            if checkpoint_threshold_bytes is not None
            else FLAGS.get("wal_checkpoint_bytes")
        )
        os.makedirs(path, exist_ok=True)
        self._wal_path = os.path.join(path, "wal.log")
        self._ckpt_dir = os.path.join(path, "checkpoint")
        import threading

        self._wal_lock = threading.Lock()
        self._recover()
        self._wal = open(self._wal_path, "ab")
        self._wal_bytes = os.path.getsize(self._wal_path)

    def _recover(self) -> None:
        if os.path.isdir(self._ckpt_dir):
            try:
                super().restore_checkpoint(self._ckpt_dir)
            except FileNotFoundError:
                pass
        if os.path.exists(self._wal_path):
            good = 0
            with open(self._wal_path, "rb") as f:
                while True:
                    hdr = f.read(8)
                    if len(hdr) < 8:
                        break
                    magic, ln = struct.unpack(">II", hdr)
                    if magic != _WAL_MAGIC:
                        break  # torn/corrupt tail
                    blob = f.read(ln)
                    if len(blob) < ln:
                        break
                    try:
                        ops = wire.decode(blob)
                    except wire.WireError:
                        break  # torn/corrupt tail
                    batch = WriteBatch()
                    batch.ops = [tuple(op) for op in ops]
                    MemEngine.write(self, batch)
                    good = f.tell()
            # truncate the torn tail BEFORE reopening for append: new
            # records written after garbage would be unreachable by the
            # next restart's replay (silent loss of acked writes)
            if os.path.getsize(self._wal_path) > good:
                with open(self._wal_path, "r+b") as f:
                    f.truncate(good)

    def write(self, batch: WriteBatch) -> None:
        blob = wire.encode([list(op) for op in batch.ops])
        # one lock serializes WAL append + memtable apply + rotation:
        # multiple raft apply threads share this engine, and a rotation
        # closing self._wal mid-append would drop an acked write
        with TRACER.start_span("engine.wal_write") as span, self._wal_lock:
            span.set_attr("bytes", len(blob))
            self._wal.write(struct.pack(">II", _WAL_MAGIC, len(blob)) + blob)
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())
            self._wal_bytes += 8 + len(blob)
            super().write(batch)
            # bounded restart: once the WAL outgrows the threshold, fold it
            # into a checkpoint and truncate (RocksDB flush+compaction
            # analog; round-1 replayed an unbounded WAL on every start)
            if self._wal_bytes >= self.checkpoint_threshold_bytes:
                self._checkpoint_locked()

    def checkpoint(self, path: Optional[str] = None) -> None:
        """Checkpoint + truncate WAL (RocksDB checkpoint analog used by the
        raft snapshot path, dingo_filesystem_adaptor.h:42-115)."""
        if path is not None and path != self._ckpt_dir:
            super().checkpoint(path)   # snapshot elsewhere; WAL untouched
            return
        with self._wal_lock:
            self._checkpoint_locked()

    def _checkpoint_locked(self) -> None:
        super().checkpoint(self._ckpt_dir)
        self._wal.close()
        self._wal = open(self._wal_path, "wb")
        self._wal_bytes = 0

    def close(self) -> None:
        self._wal.close()
