"""RetryPolicy unit tests (client/retry.py): error classification,
rotation, budget exhaustion, circuit breaker lifecycle, hedge dedupe."""

import threading
import time

import grpc
import pytest

from dingo_tpu.client.retry import (
    ATTEMPT_METADATA_KEY,
    FATAL,
    OK,
    ROTATE,
    CircuitBreaker,
    RetryPolicy,
    attempt_metadata,
)
from dingo_tpu.obs.pressure import Budget, attach_budget, detach_budget


class _RpcError(grpc.RpcError):
    def __init__(self, code):
        self._code = code

    def code(self):
        return self._code


@pytest.fixture()
def policy():
    # seeded + tiny backoff: deterministic and fast
    return RetryPolicy(rounds=3, base_backoff_ms=1.0, max_backoff_ms=2.0,
                       breaker_threshold=3, breaker_cooldown_s=0.05,
                       seed=7)


# -- classification ----------------------------------------------------------

def test_never_served_codes_rotate_even_for_mutations():
    for code in (grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.CANCELLED):
        exc = _RpcError(code)
        assert RetryPolicy.classify_exception(exc, idempotent=False) is ROTATE
        assert RetryPolicy.classify_exception(exc, idempotent=True) is ROTATE


def test_deadline_exceeded_is_ambiguous():
    exc = _RpcError(grpc.StatusCode.DEADLINE_EXCEEDED)
    # a read may re-send; a mutation must not (may have committed)
    assert RetryPolicy.classify_exception(exc, idempotent=True) is ROTATE
    assert RetryPolicy.classify_exception(exc, idempotent=False) is FATAL


def test_non_grpc_exception_is_fatal():
    assert RetryPolicy.classify_exception(ValueError("x"), True) is FATAL


# -- rotation / in-band verdicts ---------------------------------------------

def test_rotates_past_unavailable_target(policy):
    calls = []

    def fn(target, attempt):
        calls.append((target, attempt))
        if target == "a":
            raise _RpcError(grpc.StatusCode.UNAVAILABLE)
        return f"ok-{target}"

    assert policy.call(["a", "b"], fn, op="t") == "ok-b"
    assert calls == [("a", 0), ("b", 1)]


def test_inband_rotate_verdict_moves_on(policy):
    def fn(target, attempt):
        return target

    def classify(resp):
        return OK if resp == "c" else (ROTATE, f"{resp} not leader")

    assert policy.call(["a", "b", "c"], fn, classify=classify) == "c"


def test_inband_fatal_verdict_raises(policy):
    def classify(resp):
        return (FATAL, "bad argument")

    with pytest.raises(KeyError):
        policy.call(["a"], lambda t, a: "r", classify=classify,
                    error_cls=KeyError)


def test_fatal_exception_reraises_immediately(policy):
    calls = []

    def fn(target, attempt):
        calls.append(target)
        raise ValueError("boom")

    with pytest.raises(ValueError):
        policy.call(["a", "b"], fn)
    assert calls == ["a"]   # no second target tried


def test_exhaustion_raises_error_cls(policy):
    def fn(target, attempt):
        raise _RpcError(grpc.StatusCode.UNAVAILABLE)

    with pytest.raises(RuntimeError, match="retries exhausted"):
        policy.call(["a", "b"], fn, op="op")


# -- budget ------------------------------------------------------------------

def test_budget_exhaustion_stops_retries(policy):
    calls = []

    def fn(target, attempt):
        calls.append(attempt)
        time.sleep(0.02)
        raise _RpcError(grpc.StatusCode.UNAVAILABLE)

    token = attach_budget(Budget(deadline_ms=30.0))
    try:
        with pytest.raises(ValueError, match="budget exhausted"):
            policy.call(["a", "b"], fn, op="op", error_cls=ValueError,
                        rounds=50)
    finally:
        detach_budget(token)
    # far fewer attempts than 50 rounds x 2 targets: the budget cut it
    assert len(calls) < 8


def test_expired_budget_prevents_first_attempt(policy):
    token = attach_budget(Budget(deadline_ms=-1.0))
    try:
        with pytest.raises(RuntimeError, match="budget exhausted"):
            policy.call(["a"], lambda t, a: "r")
    finally:
        detach_budget(token)


def test_no_budget_means_no_budget_gate(policy):
    assert policy.call(["a"], lambda t, a: "r") == "r"


# -- circuit breaker ---------------------------------------------------------

def test_breaker_open_half_open_close():
    br = CircuitBreaker(threshold=3, cooldown_s=0.05)
    for _ in range(3):
        br.on_failure("t")
    st = br._state("t")
    assert st.state == st.OPEN
    assert not br.allow("t")            # open: rejected
    time.sleep(0.06)
    assert br.allow("t")                # cooldown: one half-open probe
    assert not br.allow("t")            # second concurrent probe rejected
    br.on_success("t")
    assert br._state("t").state == st.CLOSED
    assert br.allow("t")


def test_breaker_half_open_failure_reopens():
    br = CircuitBreaker(threshold=2, cooldown_s=0.05)
    br.on_failure("t")
    br.on_failure("t")
    time.sleep(0.06)
    assert br.allow("t")                # half-open probe
    br.on_failure("t")                  # probe failed
    st = br._state("t")
    assert st.state == st.OPEN
    assert not br.allow("t")


def test_open_breaker_skips_target_but_final_round_probes(policy):
    # break target "a" hard
    for _ in range(3):
        policy.breaker.on_failure("a")
    for _ in range(3):
        policy.breaker.on_failure("b")
    calls = []

    def fn(target, attempt):
        calls.append(target)
        return "r"

    # both breakers open and inside cooldown: the final-round force-probe
    # still reaches a target instead of failing without a single attempt
    assert policy.call(["a", "b"], fn) == "r"
    assert calls   # at least one probe fired


def test_inband_response_closes_breaker(policy):
    policy.breaker.on_failure("a")
    policy.breaker.on_failure("a")

    def classify(resp):
        return (ROTATE, "not leader")   # in-band verdict, endpoint alive

    with pytest.raises(RuntimeError):
        policy.call(["a"], lambda t, a: "r", classify=classify, rounds=1)
    st = policy.breaker._state("a")
    assert st.failures == 0 and st.state == st.CLOSED


# -- hedging -----------------------------------------------------------------

def test_attempt_metadata_stamping():
    assert attempt_metadata(0) is None
    assert attempt_metadata(0, [("k", "v")]) == [("k", "v")]
    assert attempt_metadata(2) == [(ATTEMPT_METADATA_KEY, "2")]
    assert attempt_metadata(1, [("k", "v")]) == [
        ("k", "v"), (ATTEMPT_METADATA_KEY, "1")]


def test_hedge_fires_after_delay_and_dedupes_by_attempt(policy):
    """Slow primary -> hedge fires at the backup stamped attempt=1; the
    server side can dedupe on the attempt metadata."""
    seen = []
    release = threading.Event()

    def fn(target, attempt):
        seen.append((target, attempt))
        if target == "slow":
            release.wait(1.0)
        return f"ok-{target}"

    out = policy.call_hedged(["slow", "fast"], fn, op="read")
    release.set()
    assert out == "ok-fast"
    # primary went out as attempt 0, hedge as attempt 1 — distinct stamps
    assert ("slow", 0) in seen and ("fast", 1) in seen


def test_hedge_not_used_when_primary_fast(policy):
    seen = []

    def fn(target, attempt):
        seen.append(target)
        return "ok"

    # prime the latency sensor so the hedge delay is well above the
    # primary's actual (instant) response time
    for _ in range(16):
        policy.note_latency("a", 50.0)
    assert policy.call_hedged(["a", "b"], fn) == "ok"
    assert seen == ["a"]


def test_hedge_single_target_falls_back_to_plain_call(policy):
    assert policy.call_hedged(["only"], lambda t, a: "r") == "r"


def test_hedge_skipped_when_budget_too_small(policy):
    seen = []

    def fn(target, attempt):
        seen.append(target)
        return "ok"

    for _ in range(16):
        policy.note_latency("a", 40.0)
    token = attach_budget(Budget(deadline_ms=50.0))  # < 2x hedge delay
    try:
        assert policy.call_hedged(["a", "b"], fn) == "ok"
    finally:
        detach_budget(token)
    assert seen == ["a"]    # plain call path, no hedge thread


def test_hedge_primary_error_falls_to_hedge(policy):
    def fn(target, attempt):
        if target == "bad":
            raise _RpcError(grpc.StatusCode.UNAVAILABLE)
        return "ok"

    assert policy.call_hedged(["bad", "good"], fn) == "ok"


# -- p99 sensor --------------------------------------------------------------

def test_hedge_delay_uses_p99_with_floor(policy):
    assert policy.hedge_delay_ms("cold") == policy.hedge_min_delay_ms
    for i in range(100):
        policy.note_latency("warm", float(i))
    assert policy.hedge_delay_ms("warm") >= 90.0
