"""BR: backup & restore tool (reference src/br/, 20.7K LoC — backs up
coordinator meta + per-region data via SST export, restores via ingest,
fanning RPCs to all stores through an InteractionManager)."""

from dingo_tpu.br.backup import backup_cluster, restore_cluster  # noqa: F401
