"""BINARY_IVF_FLAT: hamming list-scan IVF over bit-packed vectors
(reference NewBinaryIVFFlat factory arm, vector_index_factory.h:37-68;
faiss::IndexBinaryIVF at vector_index_ivf_flat.cc:60-62)."""

import numpy as np
import pytest

from dingo_tpu.index.base import (
    IndexParameter,
    IndexType,
    InvalidParameter,
    Metric,
    NotTrained,
    FilterSpec,
)
from dingo_tpu.index.factory import new_index

DIM_BITS = 128
NBYTES = DIM_BITS // 8


def make(nlist=8, index_id=1):
    return new_index(index_id, IndexParameter(
        index_type=IndexType.BINARY_IVF_FLAT,
        dimension=DIM_BITS,
        metric=Metric.HAMMING,
        ncentroids=nlist,
    ))


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(7)
    # clustered binary corpus: flip few bits around cluster prototypes
    protos = rng.integers(0, 256, (8, NBYTES), dtype=np.uint8)
    rows = []
    for i in range(2000):
        base = protos[i % 8].copy()
        flip = rng.integers(0, NBYTES, 2)
        base[flip] ^= rng.integers(1, 256, 2).astype(np.uint8)
        rows.append(base)
    x = np.stack(rows)
    return np.arange(len(x), dtype=np.int64), x


def hamming(a, b):
    return np.unpackbits(a ^ b, axis=-1).sum(-1)


def test_untrained_raises_not_trained(corpus):
    ids, x = corpus
    idx = make()
    idx.upsert(ids[:100], x[:100])
    with pytest.raises(NotTrained):
        idx.search(x[:1], 3)


def test_trained_search_exact_at_full_probe(corpus):
    ids, x = corpus
    idx = make()
    idx.upsert(ids, x)
    idx.train()
    q = x[[5, 900, 1500]]
    res = idx.search(q, 5, nprobe=idx.nlist)
    for qi, r in enumerate(res):
        hd = hamming(q[qi][None, :], x)
        want = np.sort(hd)[:5]
        np.testing.assert_array_equal(np.sort(r.distances), want)
        assert r.ids[0] == ids[[5, 900, 1500][qi]] or r.distances[0] == 0.0


def test_nprobe_subset_recall(corpus):
    ids, x = corpus
    idx = make()
    idx.upsert(ids, x)
    idx.train()
    q = x[:16]
    res = idx.search(q, 10, nprobe=2)
    hits = 0
    for qi, r in enumerate(res):
        hd = hamming(q[qi][None, :], x)
        gt = set(ids[np.argsort(hd, kind="stable")[:10]])
        hits += len(set(r.ids) & gt) / 10
    assert hits / len(q) > 0.5  # clustered corpus: 2/8 lists covers most


def test_filter_and_delete(corpus):
    ids, x = corpus
    idx = make(index_id=2)
    idx.upsert(ids, x)
    idx.train()
    res = idx.search(x[[5]], 5, nprobe=idx.nlist,
                     filter_spec=FilterSpec(ranges=[(100, 1000)]))
    assert all(100 <= i < 1000 for i in res[0].ids)
    idx.delete(ids[:10])
    res = idx.search(x[[5]], 5, nprobe=idx.nlist)
    assert 5 not in res[0].ids


def test_save_load_roundtrip(tmp_path, corpus):
    ids, x = corpus
    idx = make(index_id=3)
    idx.upsert(ids[:500], x[:500])
    idx.train()
    want = [(list(r.ids), list(r.distances))
            for r in idx.search(x[:4], 5, nprobe=idx.nlist)]
    idx.save(str(tmp_path / "b"))
    idx2 = make(index_id=3)
    idx2.load(str(tmp_path / "b"))
    got = [(list(r.ids), list(r.distances))
           for r in idx2.search(x[:4], 5, nprobe=idx2.nlist)]
    assert want == got


def test_bad_dimension_rejected():
    with pytest.raises(InvalidParameter):
        make_bad = new_index(4, IndexParameter(
            index_type=IndexType.BINARY_IVF_FLAT, dimension=65,
            metric=Metric.HAMMING, ncentroids=4,
        ))
    idx = make()
    with pytest.raises(InvalidParameter):
        idx.upsert(np.arange(2, dtype=np.int64), np.zeros((2, 5), np.uint8))
