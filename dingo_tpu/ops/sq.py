"""SQ8 scalar quantizer: per-dim min/max train, uint8 codes, quantized
distance kernels with fp32 accumulation.

The faiss analog is IndexScalarQuantizer / IndexIVFScalarQuantizer with
QT_8bit ("The Faiss library" §4.2): store 1 byte/dim instead of 4, decode
on the fly inside the distance kernel, and let a cheap exact rerank absorb
the quantization noise. On TPU the decode is VPU elementwise work fused
ahead of an MXU contraction, so the win is pure HBM capacity + bandwidth:
4x fewer bytes per region vector (the binding constraint on how many
vectors fit per chip — ISSUE 4 / ROADMAP north star).

Codec (faiss QT_8bit convention, per-dimension affine):

    scale[d] = (vmax[d] - vmin[d]) / 255        (floored at EPS_SPAN)
    code     = round((x - vmin) / scale)  clipped to [0, 255]
    decode   = vmin + scale * code

Training is per-dim min/max over a sample with a small symmetric MARGIN so
values slightly outside the training range still encode without clipping
(train-once-clip-later, faiss's RangeStat_minmax behavior). Distances are
computed against the DECODED surrogate x̂: the multiplies run in a compute
dtype (bf16 on the MXU by default) while every accumulation stays fp32 via
``preferred_element_type`` — the same accumulate contract as
ops/distance.py. PQ's fp32 LUT rule (ops/pq.py:124) is unaffected: SQ8
applies to coarse/flat distance evaluation, never to LUT accumulation.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dingo_tpu.ops.distance import Metric, squared_norms

#: minimum per-dim span — a constant dimension still gets a valid scale
EPS_SPAN = 1e-12
#: symmetric range widening applied at train time (fraction of the span)
TRAIN_MARGIN = 0.05


class SqParams(NamedTuple):
    """Trained per-dim affine codec; both arrays are [d] float32 (host
    numpy — they ride persistence as plain npz arrays and upload per
    kernel call, like centroids)."""

    vmin: np.ndarray
    scale: np.ndarray

    @property
    def dim(self) -> int:
        return int(self.vmin.shape[0])


def sq_train(x: np.ndarray, margin: float = TRAIN_MARGIN) -> SqParams:
    """Per-dim min/max over the sample, widened by `margin` per side."""
    x = np.asarray(x, np.float32)
    if x.ndim != 2 or not len(x):
        raise ValueError(f"sq_train needs [n, d] rows, got {x.shape}")
    vmin = x.min(axis=0)
    vmax = x.max(axis=0)
    span = vmax - vmin
    vmin = vmin - margin * span
    span = span * (1.0 + 2.0 * margin)
    scale = np.maximum(span, EPS_SPAN) / 255.0
    return SqParams(vmin.astype(np.float32), scale.astype(np.float32))


def sq_encode(x: np.ndarray, params: SqParams) -> np.ndarray:
    """f32 rows [n, d] -> uint8 codes [n, d]; out-of-range values clip."""
    x = np.asarray(x, np.float32)
    q = np.rint((x - params.vmin[None, :]) / params.scale[None, :])
    return np.clip(q, 0.0, 255.0).astype(np.uint8)


def sq_decode(codes: np.ndarray, params: SqParams) -> np.ndarray:
    """uint8 codes -> decoded f32 surrogate x̂ (host side)."""
    return (
        np.asarray(codes, np.float32) * params.scale[None, :]
        + params.vmin[None, :]
    )


def sq_decode_device(
    codes: jax.Array,
    vmin: jax.Array,
    scale: jax.Array,
    dtype=jnp.bfloat16,
) -> jax.Array:
    """On-device decode [..., d] -> compute-dtype surrogate rows.

    uint8 values are exactly representable in bf16 (integers <= 256), so
    the only rounding is the affine itself — decode in f32, THEN downcast,
    so vmin/scale precision isn't lost before the multiply-add."""
    deq = codes.astype(jnp.float32) * scale + vmin
    return deq.astype(dtype)


def sq_score_matrix(
    q: jax.Array,
    codes: jax.Array,
    vmin: jax.Array,
    scale: jax.Array,
    metric: Metric,
    x_sqnorm: Optional[jax.Array] = None,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """'Larger is better' score matrix [b, n] over SQ8 codes [n, d].

    The dot runs compute_dtype x compute_dtype with fp32 accumulation
    (preferred_element_type) — on TPU that is a native bf16 MXU matmul fed
    by 1-byte HBM reads. x_sqnorm must be ||x̂||^2 of the DECODED rows
    (SqSlotStore caches exactly that), so L2/cosine stay consistent with
    what the kernel actually scans."""
    xhat = sq_decode_device(codes, vmin, scale, compute_dtype)
    qd = q.astype(jnp.float32)
    dots = jnp.einsum(
        "bd,nd->bn",
        qd.astype(compute_dtype),
        xhat,
        preferred_element_type=jnp.float32,
    )
    if metric is Metric.L2:
        if x_sqnorm is None:
            x_sqnorm = squared_norms(xhat)
        return -(squared_norms(qd)[:, None] - 2.0 * dots + x_sqnorm[None, :])
    if metric is Metric.INNER_PRODUCT:
        return dots
    if metric is Metric.COSINE:
        # queries arrive pre-normalized (index _prep); decoded rows are
        # only approximately unit, so divide by the cached decoded norm
        if x_sqnorm is None:
            x_sqnorm = squared_norms(xhat)
        inv = jax.lax.rsqrt(jnp.maximum(x_sqnorm, 1e-30))
        return dots * inv[None, :]
    raise ValueError(f"SQ8 does not support metric {metric}")


def sq_bucket_scores(
    queries: jax.Array,
    data: jax.Array,
    sq: jax.Array,
    vmin: jax.Array,
    scale: jax.Array,
    metric: Metric,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Per-query bucket scores [b, cap] for the IVF list scan: data is the
    gathered uint8 code bucket [b, cap, d], sq the decoded-norm cache
    [b, cap]. Mirrors the float arm of ivf_flat.ivf_scan_scores."""
    xhat = sq_decode_device(data, vmin, scale, compute_dtype)
    qd = queries.astype(jnp.float32)
    dots = jnp.einsum(
        "bd,bcd->bc",
        qd.astype(compute_dtype),
        xhat,
        preferred_element_type=jnp.float32,
    )
    if metric is Metric.L2:
        return -(squared_norms(qd)[:, None] - 2.0 * dots + sq)
    if metric is Metric.COSINE:
        inv = jax.lax.rsqrt(jnp.maximum(sq, 1e-30))
        return dots * inv
    return dots


def params_close(a: SqParams, b: SqParams, atol: float = 0.0) -> bool:
    """Exact-enough equality for persistence round-trip checks."""
    return (
        a.vmin.shape == b.vmin.shape
        and np.allclose(a.vmin, b.vmin, atol=atol)
        and np.allclose(a.scale, b.scale, atol=atol)
    )
