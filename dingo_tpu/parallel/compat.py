"""jax version compatibility for the mesh-sharded index family.

`shard_map` moved over jax releases: newer jax exports `jax.shard_map`
(keyword `check_vma`), while 0.4.x only ships
`jax.experimental.shard_map.shard_map` (keyword `check_rep`). The bare
`from jax import shard_map` used to take down every `parallel/sharded_*`
module — and with them four whole tier-1 test files — at import time on
0.4.37. This shim presents ONE surface: the modern keyword names, mapped
onto whichever implementation exists.
"""

from __future__ import annotations

try:  # jax >= 0.5: top-level export, `check_vma` keyword
    from jax import shard_map as _shard_map

    _REP_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental module, `check_rep` keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    _REP_KW = "check_rep"


def shard_map(f, mesh, in_specs, out_specs, check_vma=True, **kwargs):
    """`jax.shard_map` signature regardless of the installed jax."""
    kwargs[_REP_KW] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
