"""Raft tests: single-process multi-peer groups (the reference's approach —
test_raft_node.cc:125-199 runs 3 braft peers in one process)."""

import pickle
import time

import numpy as np
import pytest

from dingo_tpu.raft import LocalTransport, NotLeader, RaftNode
from dingo_tpu.raft.log import RaftLog


def make_cluster(n=3, transport=None, applied=None, **kw):
    transport = transport or LocalTransport()
    applied = applied if applied is not None else {}
    nodes = {}
    for i in range(n):
        nid = f"n{i}"
        applied.setdefault(nid, [])

        def apply_fn(index, payload, nid=nid):
            applied[nid].append((index, payload))

        nodes[nid] = RaftNode(
            nid, [f"n{j}" for j in range(n)], transport,
            apply_fn=apply_fn, seed=i, **kw,
        )
    for node in nodes.values():
        node.start()
    return transport, nodes, applied


def wait_leader(nodes, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [n for n in nodes.values() if n.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no unique leader elected")


def stop_all(nodes):
    for n in nodes.values():
        n.stop()


def test_election_and_replication():
    transport, nodes, applied = make_cluster()
    try:
        leader = wait_leader(nodes)
        for i in range(5):
            leader.propose(f"cmd{i}".encode())
        time.sleep(0.3)  # followers catch up on next heartbeats
        for nid, log in applied.items():
            assert [p for _, p in log] == [f"cmd{i}".encode() for i in range(5)], nid
    finally:
        stop_all(nodes)


def test_propose_on_follower_raises():
    transport, nodes, applied = make_cluster()
    try:
        leader = wait_leader(nodes)
        follower = next(n for n in nodes.values() if not n.is_leader())
        with pytest.raises(NotLeader):
            follower.propose(b"x")
    finally:
        stop_all(nodes)


def test_leader_failover_and_rejoin():
    transport, nodes, applied = make_cluster()
    try:
        leader = wait_leader(nodes)
        leader.propose(b"before")
        old_id = leader.id
        # cut the leader off from both followers (braft-style network fault)
        for other in nodes:
            if other != old_id:
                transport.partition(old_id, other)
        survivors = {k: v for k, v in nodes.items() if k != old_id}
        new_leader = wait_leader(survivors, timeout=5)
        assert new_leader.id != old_id
        new_leader.propose(b"after")
        # heal: old leader rejoins as follower and catches up
        transport.heal()
        time.sleep(0.5)
        assert [p for _, p in applied[old_id]] == [b"before", b"after"]
        assert not nodes[old_id].is_leader()
    finally:
        stop_all(nodes)


def test_log_persistence_and_recovery(tmp_path):
    log = RaftLog(str(tmp_path / "raft.log"))
    i1 = log.append(1, b"a")
    i2 = log.append(1, b"b")
    log.append(2, b"c")
    log.close()
    log2 = RaftLog(str(tmp_path / "raft.log"))
    assert log2.last_index() == 3
    assert log2.entry_at(i1) == (1, b"a")
    assert log2.term_at(3) == 2
    log2.compact(2)
    assert log2.first_index == 3
    log2.close()
    log3 = RaftLog(str(tmp_path / "raft.log"))
    assert log3.snapshot_index == 2
    assert log3.entry_at(3) == (2, b"c")
    log3.close()


def test_snapshot_install_for_lagging_follower():
    """Follower behind a compacted log receives a full snapshot
    (braft InstallSnapshot / DingoFileSystemAdaptor flow)."""
    transport = LocalTransport()
    state = {f"n{i}": [] for i in range(3)}

    def mk(nid):
        def apply_fn(index, payload):
            state[nid].append(payload)

        def save():
            return pickle.dumps(state[nid])

        def install(blob):
            state[nid][:] = pickle.loads(blob)

        return RaftNode(
            nid, ["n0", "n1", "n2"], transport, apply_fn=apply_fn,
            snapshot_save_fn=save, snapshot_install_fn=install,
            snapshot_threshold=5, seed=int(nid[1]),
        )

    nodes = {f"n{i}": mk(f"n{i}") for i in range(3)}
    for n in nodes.values():
        n.start()
    try:
        leader = wait_leader(nodes)
        lagger = next(k for k in nodes if k != leader.id)
        for other in nodes:
            if other != lagger:
                transport.partition(lagger, other)
        for i in range(20):   # exceeds snapshot_threshold -> log compacts
            leader.propose(f"v{i}".encode())
        time.sleep(0.2)
        assert leader.log.snapshot_index > 0
        transport.heal()
        deadline = time.monotonic() + 5
        want = [f"v{i}".encode() for i in range(20)]
        while time.monotonic() < deadline:
            if state[lagger] == want:
                break
            time.sleep(0.05)
        assert state[lagger] == want
    finally:
        stop_all(nodes)


def test_no_commit_without_quorum():
    transport, nodes, applied = make_cluster()
    try:
        leader = wait_leader(nodes)
        for other in nodes:
            if other != leader.id:
                transport.partition(leader.id, other)
        from dingo_tpu.raft.core import ProposalFailed

        with pytest.raises(ProposalFailed):
            leader.propose(b"lost", timeout=0.5)
    finally:
        stop_all(nodes)


def test_hard_state_survives_restart(tmp_path):
    """Regression: term/vote persistence (election safety across restart)."""
    log = RaftLog(str(tmp_path / "r.log"))
    log.set_hard_state(5, "n2")
    log.close()
    log2 = RaftLog(str(tmp_path / "r.log"))
    assert log2.hard_state() == (5, "n2")
    log2.close()


def test_get_data_entries_respects_bounds(tmp_path):
    log = RaftLog()
    for i in range(10):
        log.append(1, f"p{i}".encode())
    log.compact(2)
    got = log.get_data_entries(1, 5)
    assert [i for i, _, _ in got] == [3, 4, 5]
    assert log.get_data_entries(1, 1) == []


def test_pre_vote_prevents_term_inflation():
    """A partitioned node that keeps timing out must NOT inflate its term
    (pre-vote, braft parity): on rejoin the stable leader keeps leading at
    its original term instead of being deposed by a big term number."""
    transport, nodes, _ = make_cluster()
    try:
        leader = wait_leader(nodes)
        term_before = leader.current_term
        victim = next(n for n in nodes.values() if n is not leader)
        for other in nodes:
            if other != victim.id:
                transport.partition(victim.id, other)
        time.sleep(1.5)   # many election timeouts pass
        assert victim.current_term <= term_before + 1  # no runaway terms
        # heal; the old leader must still lead at (about) its old term
        transport.heal()
        time.sleep(1.0)
        assert leader.is_leader()
        assert leader.current_term <= term_before + 1
    finally:
        stop_all(nodes)


def test_pre_vote_failover_latency():
    """Review repro: survivors must not mutually refuse pre-votes after a
    leader failure (the leader-contact timestamp, not the self-reset
    deadline, drives stickiness) — failover completes promptly."""
    transport, nodes, _ = make_cluster()
    try:
        leader = wait_leader(nodes)
        for other in nodes:
            if other != leader.id:
                transport.partition(leader.id, other)
        survivors = {k: v for k, v in nodes.items() if k != leader.id}
        t0 = time.monotonic()
        wait_leader(survivors, timeout=3.0)
        assert time.monotonic() - t0 < 3.0
    finally:
        stop_all(nodes)


def test_check_quorum_deposes_partitioned_leader():
    """A leader cut off from every peer steps down within ~2 election
    timeouts instead of serving leader-gated reads forever (check-quorum,
    braft parity). The majority side elects a fresh leader; after heal the
    old leader rejoins as follower."""
    transport, nodes, _applied = make_cluster(
        election_timeout=(0.1, 0.2), heartbeat_interval=0.03,
    )
    try:
        leader = wait_leader(nodes)

        for p in nodes:
            if p != leader.id:
                transport.partition(leader.id, p)

        # the old leader must step down on its own (no higher term can
        # reach it through the partition)
        deadline = time.monotonic() + 3.0
        while leader.is_leader() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not leader.is_leader(), (
            "partitioned leader kept serving as leader (check-quorum)")

        # majority side elected a replacement
        deadline = time.monotonic() + 3.0
        new_leader = None
        while new_leader is None and time.monotonic() < deadline:
            new_leader = next(
                (n for n in nodes.values()
                 if n is not leader and n.is_leader()), None)
            time.sleep(0.02)
        assert new_leader is not None

        transport.heal()
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if not leader.is_leader() and leader.leader_id == new_leader.id:
                break
            time.sleep(0.02)
        assert not leader.is_leader()
    finally:
        stop_all(nodes)
