"""Schema/table meta layer over grpc: MetaService + client table API
(reference meta_service.cc; coordinator_control.h:187 schema/table state)."""

import time

import numpy as np
import pytest

from dingo_tpu.client import DingoClient
from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.coordinator.kv_control import KvControl
from dingo_tpu.coordinator.meta import MetaControl, MetaError
from dingo_tpu.coordinator.tso import TsoControl
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.raft import LocalTransport
from dingo_tpu.server import pb
from dingo_tpu.server.rpc import DingoServer
from dingo_tpu.store.node import StoreNode


@pytest.fixture(scope="module")
def cluster():
    transport = LocalTransport()
    meta_engine = MemEngine()
    control = CoordinatorControl(meta_engine, replication=3)
    tso = TsoControl(meta_engine)
    kv_control = KvControl(meta_engine)
    meta = MetaControl(meta_engine, control)

    coord_server = DingoServer()
    coord_server.host_coordinator_role(control, tso, kv_control, meta=meta)
    coord_port = coord_server.start()

    nodes, servers, addrs = {}, [], {}
    for i, sid in enumerate(["s0", "s1", "s2"]):
        node = StoreNode(sid, transport, control, raft_kw={"seed": i})
        server = DingoServer()
        server.host_store_role(node)
        port = server.start()
        node.start_heartbeat(0.1)
        nodes[sid] = node
        servers.append(server)
        addrs[sid] = f"127.0.0.1:{port}"

    client = DingoClient(f"127.0.0.1:{coord_port}", addrs)
    yield client, control, meta, nodes
    client.close()
    for s in servers:
        s.stop()
    coord_server.stop()
    for n in nodes.values():
        n.stop()


def test_default_schemas_and_schema_crud(cluster):
    client, control, meta, nodes = cluster
    schemas = client.get_schemas()
    for s in ("root", "meta", "dingo"):  # reference's built-ins
        assert s in schemas
    client.create_schema("app")
    assert "app" in client.get_schemas()
    with pytest.raises(Exception):
        client.create_schema("app")  # duplicate


def test_create_vector_table_end_to_end(cluster):
    """Create a 2-partition vector table, add/search through the table API."""
    client, control, meta, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=16,
        metric_type=pb.METRIC_TYPE_L2,
    )
    table = client.create_vector_table(
        "dingo", "emb", param,
        partitions=[(11, 0, 1000), (12, 1000, 2000)],
    )
    assert table.table_id > 0
    assert [p.region_id for p in table.partitions] != [0, 0]
    time.sleep(1.2)  # heartbeats create + elect

    rng = np.random.default_rng(0)
    x = rng.standard_normal((200, 16)).astype(np.float32)
    ids = list(range(900, 1100))  # spans both partitions
    client.table_vector_add(table, ids, x)

    res = client.table_vector_search(table, x[[0, 150]], topk=3)
    assert res[0][0][0] == 900
    assert res[1][0][0] == 1050
    assert res[0][0][1] == pytest.approx(0.0, abs=1e-3)

    got = client.get_table("dingo", "emb")
    assert got is not None and got.name == "emb"
    assert len(client.list_tables("dingo")) == 1


def test_drop_table_drops_regions(cluster):
    import time as _t

    client, control, meta, nodes = cluster
    table = client.get_table("dingo", "emb")
    rids = [p.region_id for p in table.partitions]
    client.drop_table("dingo", "emb")
    assert client.get_table("dingo", "emb") is None
    # region teardown can lag the RPC under suite load — bounded wait
    deadline = _t.monotonic() + 5.0
    while _t.monotonic() < deadline and any(
        rid in control.regions for rid in rids
    ):
        _t.sleep(0.05)
    for rid in rids:
        assert rid not in control.regions


def test_meta_persistence_across_restart(cluster):
    """MetaControl recovers schemas/tables from the meta CF."""
    client, control, meta, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    client.create_vector_table("app", "t2", param,
                               partitions=[(21, 0, 100)])
    meta2 = MetaControl(meta.engine, control)
    assert "app" in meta2.schemas
    t = meta2.get_table("app", "t2")
    assert t is not None and t.table_id > 0
    assert t.index_parameter.dimension == 8
    assert t.partitions[0].region_id > 0


def test_drop_schema_rules(cluster):
    client, control, meta, nodes = cluster
    with pytest.raises(MetaError):
        meta.drop_schema("root")           # built-in
    with pytest.raises(MetaError):
        meta.drop_schema("app")            # not empty (t2)
    meta.create_schema("tmp")
    meta.drop_schema("tmp")
    assert "tmp" not in meta.get_schemas()


def test_binary_ivf_table_over_grpc(cluster):
    """BINARY_IVF_FLAT creatable via the table API; bit-packed rows travel
    as Vector.binary_values; untrained search falls back to a temp binary
    flat scan (EVECTOR_NOT_SUPPORT contract)."""
    client, control, meta, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_BINARY_IVF_FLAT,
        dimension=128, metric_type=pb.METRIC_TYPE_HAMMING, ncentroids=4,
    )
    client.create_vector_table("dingo", "bin", param,
                               partitions=[(31, 0, 10000)])
    time.sleep(1.2)
    rng = np.random.default_rng(0)
    protos = rng.integers(0, 256, (4, 16), dtype=np.uint8)
    xb = protos[rng.integers(0, 4, 600)] ^ rng.integers(
        0, 2, (600, 16)).astype(np.uint8)
    d = next(r for r in client._regions if r.partition_id == 31)
    req = pb.VectorAddRequest()
    req.context.region_id = d.region_id
    for i in range(600):
        v = req.vectors.add()
        v.vector.id = i
        v.vector.binary_values = xb[i].tobytes()
    resp = client._call_leader(d, "IndexService", "VectorAdd", req)
    assert resp.error.errcode == 0, resp.error.errmsg

    sreq = pb.VectorSearchRequest()
    sreq.context.region_id = d.region_id
    q = sreq.vectors.add()
    q.binary_values = xb[7].tobytes()
    sreq.parameter.top_n = 3
    sresp = client._call_leader(d, "IndexService", "VectorSearch", sreq)
    assert sresp.error.errcode == 0, sresp.error.errmsg
    top = sresp.batch_results[0].results[0]
    assert top.vector.id == 7 and top.distance == 0.0


def test_introspection_services(cluster):
    """Job / ClusterStat / RegionControl introspection (main.cc service
    registry rows)."""
    client, control, meta, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    client.create_vector_table("dingo", "intros", param,
                               partitions=[(41, 0, 100)])
    time.sleep(1.2)
    cs = client.coordinator_service("ClusterStatService")
    resp = cs.GetClusterStat(pb.GetClusterStatRequest())
    assert resp.store_count == 3
    assert resp.alive_store_count == 3
    assert resp.region_count >= 1
    assert len(resp.stores) == 3

    js = client.coordinator_service("JobService")
    jobs = js.ListJobs(pb.ListJobsRequest(include_done=True))
    assert len(jobs.jobs) >= 1  # region creates flowed through the queue
    assert all(j.cmd_type for j in jobs.jobs)

    # region detail on a store hosting an index region (write one row so
    # the raft log has a committed entry)
    client.refresh_region_map()
    d = next(r for r in client._regions if r.partition_id == 41)
    client.vector_add(41, [1], np.zeros((1, 8), np.float32))
    leader = control.region_leaders.get(d.region_id, "s0")
    rc = client._stub(leader, "RegionControlService")
    detail = rc.RegionDetail(pb.RegionDetailRequest(region_id=d.region_id))
    assert detail.error.errcode == 0
    assert detail.definition.region_id == d.region_id
    assert detail.is_leader
    assert detail.raft_commit_index >= 1
    missing = rc.RegionDetail(pb.RegionDetailRequest(region_id=999999))
    assert missing.error.errcode == 10001

    rb = rc.RegionRebuildIndex(
        pb.RegionRebuildIndexRequest(region_id=d.region_id))
    assert rb.error.errcode == 0


def test_create_table_rejects_overlapping_ranges(cluster):
    """Two tables must not cover the same key space: client routing matches
    the first covering range, so overlap silently cross-writes tables."""
    client, control, meta, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    client.create_vector_table("dingo", "ov1", param,
                               partitions=[(51, 0, 1000)])
    with pytest.raises(Exception, match="overlaps"):
        client.create_vector_table("dingo", "ov2", param,
                                   partitions=[(51, 500, 1500)])
    # disjoint partition id is fine
    client.create_vector_table("dingo", "ov3", param,
                               partitions=[(52, 0, 1000)])


def test_meta_watch_replay_and_longpoll(cluster):
    """MetaWatch RPC (VERDICT item 9, reference meta-watch): change
    events replay from a past revision, long-poll fires on a concurrent
    create, and the SDK cache invalidates without polling."""
    import threading
    import time as _time

    client, control, meta, nodes = cluster
    rev0 = meta.meta_revision
    client.create_schema("watchme")
    # replay: watching from rev0+1 sees the create_schema event
    resp = client.meta.MetaWatch(pb.MetaWatchRequest(start_revision=rev0 + 1))
    assert resp.fired and resp.event == "create_schema"
    assert resp.schema_name == "watchme"

    # long-poll fires on a concurrent change
    def later():
        _time.sleep(0.15)
        client.create_schema("watchme2")

    t = threading.Thread(target=later)
    t.start()
    resp = client.meta.MetaWatch(pb.MetaWatchRequest(timeout_ms=3000))
    t.join()
    assert resp.fired and resp.schema_name == "watchme2"

    # timeout path: no event -> not fired, watcher unregistered
    resp = client.meta.MetaWatch(pb.MetaWatchRequest(timeout_ms=50))
    assert not resp.fired
    assert meta._watchers == []

    # a watch from before the ring/restart horizon resyncs
    resp = client.meta.MetaWatch(pb.MetaWatchRequest(start_revision=1))
    assert resp.fired
    assert resp.event in ("resync", "create_schema", "create_table",
                          "drop_table", "drop_schema")


def test_sdk_cache_invalidation_via_meta_watch(cluster):
    import time as _time

    client, control, meta, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    client.create_vector_table("dingo", "cachetab", param,
                               partitions=((60, 0, 1 << 20),))
    client.start_meta_watch(poll_timeout_ms=500)
    try:
        t = client.get_table("dingo", "cachetab", cached=True)
        assert t is not None
        assert "dingo.cachetab" in client._table_cache
        client.drop_table("dingo", "cachetab")
        deadline = _time.time() + 5
        while ("dingo.cachetab" in client._table_cache
               and _time.time() < deadline):
            _time.sleep(0.05)
        assert "dingo.cachetab" not in client._table_cache
    finally:
        client.stop_meta_watch()


def test_meta_watch_registration_gap_invalidates(cluster):
    """Entries cached between start_meta_watch() and the watcher's first
    server-side registration could predate events the watch never sees
    (the first poll starts "from now") — the first pinned window must
    flush the cache so nothing stale survives the gap."""
    import time as _time

    client, control, meta, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    client.create_vector_table("dingo", "gaptab", param,
                               partitions=((61, 0, 1 << 20),))
    try:
        # cache BEFORE the watcher exists: this entry predates any window
        assert client.get_table("dingo", "gaptab", cached=True) is not None
        assert "dingo.gaptab" in client._table_cache
        gen0 = client._cache_gen
        client.start_meta_watch(poll_timeout_ms=200)
        deadline = _time.time() + 5
        while client._cache_gen == gen0 and _time.time() < deadline:
            _time.sleep(0.05)
        assert client._cache_gen > gen0
        assert "dingo.gaptab" not in client._table_cache
    finally:
        client.stop_meta_watch()
        client.drop_table("dingo", "gaptab")
