"""Schema / table / index metadata layer on the coordinator.

Reference: CoordinatorControl's schema+table meta and MetaService RPCs
(src/coordinator/coordinator_control.h:187 schema/table state;
src/server/meta_service.cc CreateTable/DropTable/GetTables/...). The
reference seeds default schemas (root/meta/dingo) and stores table
definitions whose partitions map to regions; the SDK then speaks in
tables rather than raw regions.

Here a table is a named definition whose partitions each own one region:
vector/document partitions own an id-window region (vector key codec),
plain TABLE partitions own a raw key-range region. Region placement,
replication, split/merge stay CoordinatorControl's job — dropping a table
drops its regions.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

from dingo_tpu.common import persist
from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.engine.raw_engine import CF_META, RawEngine
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import IndexParameter
from dingo_tpu.raft import wire
from dingo_tpu.store.region import RegionType

_PREFIX_SCHEMA = b"meta/schema/"
_PREFIX_TABLE = b"meta/table/"
_KEY_TABLE_ID = b"meta/next_table_id"

#: reference's built-in schemas (coordinator seeds root/meta/dingo)
DEFAULT_SCHEMAS = ("root", "meta", "dingo")


class MetaError(RuntimeError):
    pass


@persist.register
@dataclasses.dataclass
class ColumnDefinition:
    name: str
    sql_type: str = "VARCHAR"
    nullable: bool = True
    primary: bool = False


@persist.register
@dataclasses.dataclass
class PartitionDefinition:
    partition_id: int
    #: vector/document partitions: [id_lo, id_hi) vector-id window
    id_lo: int = 0
    id_hi: int = 0
    #: plain TABLE partitions: raw key range
    start_key: bytes = b""
    end_key: bytes = b""
    region_id: int = 0


@persist.register
@dataclasses.dataclass
class TableDefinition:
    table_id: int
    schema_name: str
    name: str
    table_type: RegionType = RegionType.STORE
    columns: List[ColumnDefinition] = dataclasses.field(default_factory=list)
    partitions: List[PartitionDefinition] = dataclasses.field(
        default_factory=list
    )
    index_parameter: Optional[IndexParameter] = None
    replication: int = 0


class MetaControl:
    """Schema/table registry persisted in the coordinator's meta CF."""

    def __init__(self, engine: RawEngine, control: CoordinatorControl):
        self.engine = engine
        self.control = control
        self._lock = threading.Lock()
        self.schemas: Dict[str, List[str]] = {}     # schema -> table names
        self.tables: Dict[str, TableDefinition] = {}  # "schema.table" -> def
        self._creating: set = set()   # names reserved by in-flight creates
        self._next_table_id = 1
        self._recover()
        for s in DEFAULT_SCHEMAS:
            if s not in self.schemas:
                self._put_schema(s)

    # -- persistence ---------------------------------------------------------
    def _recover(self) -> None:
        blob = self.engine.get(CF_META, _KEY_TABLE_ID)
        if blob:
            self._next_table_id = wire.decode(blob)
        for k, v in self.engine.scan(CF_META, _PREFIX_SCHEMA,
                                     _PREFIX_SCHEMA + b"\xff"):
            self.schemas[wire.decode(v)] = []
        for k, v in self.engine.scan(CF_META, _PREFIX_TABLE,
                                     _PREFIX_TABLE + b"\xff"):
            t = persist.loads(v)
            self.tables[f"{t.schema_name}.{t.name}"] = t
            self.schemas.setdefault(t.schema_name, []).append(t.name)

    def _put_schema(self, name: str) -> None:
        self.schemas[name] = self.schemas.get(name, [])
        self.engine.put(CF_META, _PREFIX_SCHEMA + name.encode(),
                        wire.encode(name))

    def _put_table(self, t: TableDefinition) -> None:
        self.engine.put(
            CF_META, _PREFIX_TABLE + str(t.table_id).encode(),
            persist.dumps(t),
        )

    # -- schemas -------------------------------------------------------------
    def create_schema(self, name: str) -> None:
        if not name:
            raise MetaError("empty schema name")
        with self._lock:
            if name in self.schemas:
                raise MetaError(f"schema {name!r} exists")
            self._put_schema(name)

    def drop_schema(self, name: str) -> None:
        with self._lock:
            if name not in self.schemas:
                raise MetaError(f"schema {name!r} not found")
            in_flight = any(k.startswith(name + ".") for k in self._creating)
            if self.schemas[name] or in_flight:
                raise MetaError(f"schema {name!r} not empty")
            if name in DEFAULT_SCHEMAS:
                raise MetaError(f"schema {name!r} is built-in")
            del self.schemas[name]
            self.engine.delete(CF_META, _PREFIX_SCHEMA + name.encode())

    def get_schemas(self) -> List[str]:
        with self._lock:
            return sorted(self.schemas)

    # -- tables --------------------------------------------------------------
    def create_table(
        self,
        schema_name: str,
        name: str,
        partitions: List[PartitionDefinition],
        columns: Optional[List[ColumnDefinition]] = None,
        index_parameter: Optional[IndexParameter] = None,
        table_type: Optional[RegionType] = None,
        replication: int = 0,
    ) -> TableDefinition:
        """CreateTable (meta_service.cc): allocate the table id, create one
        region per partition, persist the definition."""
        if table_type is None:
            table_type = (
                RegionType.INDEX if index_parameter is not None
                else RegionType.STORE
            )
        key = f"{schema_name}.{name}"
        with self._lock:
            if schema_name not in self.schemas:
                raise MetaError(f"schema {schema_name!r} not found")
            if key in self.tables or key in self._creating:
                raise MetaError(f"table {key} exists")
            if not partitions:
                raise MetaError("table needs >= 1 partition")
            # reserve the name: region creation below runs outside the lock
            # (it is slow), and a concurrent same-name create must fail now
            self._creating.add(key)
            table_id = self._next_table_id
            self._next_table_id += 1
            self.engine.put(CF_META, _KEY_TABLE_ID,
                            wire.encode(self._next_table_id))
        created = []
        try:
            for p in partitions:
                if table_type in (RegionType.INDEX, RegionType.DOCUMENT):
                    start = vcodec.encode_vector_key(p.partition_id, p.id_lo)
                    end = vcodec.encode_vector_key(p.partition_id, p.id_hi)
                else:
                    start, end = p.start_key, p.end_key
                # overlap rejection happens inside create_region (under
                # the control lock, so concurrent creates cannot race it)
                d = self.control.create_region(
                    start, end,
                    partition_id=p.partition_id,
                    region_type=table_type,
                    index_parameter=index_parameter,
                    replication=replication or None,
                )
                p.region_id = d.region_id
                created.append(d.region_id)
        except Exception:
            for rid in created:
                self.control.drop_region(rid)
            with self._lock:
                self._creating.discard(key)
            raise
        t = TableDefinition(
            table_id=table_id,
            schema_name=schema_name,
            name=name,
            table_type=table_type,
            columns=columns or [],
            partitions=partitions,
            index_parameter=index_parameter,
            replication=replication,
        )
        with self._lock:
            self._creating.discard(key)
            self.tables[key] = t
            self.schemas[schema_name].append(name)
            self._put_table(t)
        return t

    def import_table(self, t: TableDefinition) -> TableDefinition:
        """Register an externally built definition (restore path): assigns
        a fresh table id, persists the id counter and the definition under
        the same invariants create_table maintains. Partition region ids
        must already point at live regions."""
        key = f"{t.schema_name}.{t.name}"
        with self._lock:
            if t.schema_name not in self.schemas:
                self._put_schema(t.schema_name)
            if key in self.tables or key in self._creating:
                raise MetaError(f"table {key} exists")
            t.table_id = self._next_table_id
            self._next_table_id += 1
            self.engine.put(CF_META, _KEY_TABLE_ID,
                            wire.encode(self._next_table_id))
            self.tables[key] = t
            self.schemas[t.schema_name].append(t.name)
            self._put_table(t)
        return t

    def drop_table(self, schema_name: str, name: str) -> None:
        key = f"{schema_name}.{name}"
        with self._lock:
            t = self.tables.get(key)
            if t is None:
                raise MetaError(f"table {key} not found")
            del self.tables[key]
            self.schemas[schema_name].remove(name)
            self.engine.delete(
                CF_META, _PREFIX_TABLE + str(t.table_id).encode()
            )
        for p in t.partitions:
            self.control.drop_region(p.region_id)

    def get_table(self, schema_name: str, name: str) -> Optional[TableDefinition]:
        with self._lock:
            return self.tables.get(f"{schema_name}.{name}")

    def get_tables(self, schema_name: str) -> List[TableDefinition]:
        with self._lock:
            return [t for t in self.tables.values()
                    if t.schema_name == schema_name]
