"""Memory-tier ladder (index/tiering.py, ISSUE 19).

Round-trip parity is the load-bearing claim: demote -> serve -> promote
must return BYTE-identical top-k to a never-demoted region at equal
state, per index family x precision. That holds because every rung move
is either a deterministic engine rebuild (same WAL order -> same slot
layout -> same kernel tie-breaks) or a byte-exact code transcription,
and the digest gate refuses any destination copy whose recomputed rows
artifact disagrees with the source ledger before the swap.

The process-kill-mid-transition story lives in tools/chaos.py
(tier_kill scenario, auto-parametrized by test_chaos.py); the policy
tick and bench gates in bench.py memory_pressure.
"""

import numpy as np
import pytest

from dingo_tpu.index.base import IndexType
from dingo_tpu.index.tiering import (
    RUNG_HBM_SQ8,
    RUNGS,
    TIERING,
    HostSqFlat,
    TierRefused,
)
from tools.chaos import DIM, cluster


@pytest.fixture(autouse=True)
def _fresh_ladder():
    TIERING.reset()
    yield
    TIERING.reset()


def _fill(node, region, n=96, seed=5):
    rng = np.random.default_rng(seed)
    ids = np.arange(1, n + 1, dtype=np.int64)
    x = rng.standard_normal((n, DIM)).astype(np.float32)
    for lo in range(0, n, 16):
        node.storage.vector_add(region, ids[lo:lo + 16], x[lo:lo + 16])
    return ids, x


def _topk(node, region, queries, k=10):
    res = node.storage.vector_batch_search(region, queries, k)
    return ([[r.id for r in row] for row in res],
            [[r.distance for r in row] for row in res])


MATRIX = [
    (IndexType.FLAT, "fp32"),
    (IndexType.FLAT, "bf16"),
    (IndexType.FLAT, "sq8"),
    (IndexType.IVF_FLAT, "fp32"),
    (IndexType.IVF_FLAT, "bf16"),
    (IndexType.IVF_FLAT, "sq8"),
]


@pytest.mark.parametrize(
    "index_type,precision", MATRIX,
    ids=[f"{t.value}-{p}" for t, p in MATRIX])
def test_round_trip_parity(index_type, precision):
    """Walk the full ladder down and back; every rung serves all acked
    rows, and the promoted-back region answers byte-identically to the
    never-demoted baseline."""
    param_kw = {}
    if index_type == IndexType.IVF_FLAT:
        param_kw = {"ncentroids": 4, "default_nprobe": 4}
    with cluster(1, replication=1, seed=7) as c:
        rid = c.create_region(index_type=index_type, precision=precision,
                              **param_kw)
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        ids, x = _fill(node, region)
        q = x[:8]
        # Normalize the baseline through ONE canonical rebuild (the same
        # shared arm every precision-crossing promotion rides): byte-
        # identity is a claim about deterministic rebuilds from the WAL,
        # not about incremental-build float-reduction order (IVF trains
        # centroids differently mid-stream vs full-corpus).
        assert node.index_manager.rebuild_at_precision(
            region, raft_log=TIERING._raft_log(node, rid), precision=None)
        base_ids, base_dists = _topk(node, region, q)
        assert [row[0] for row in base_ids] == [int(i) for i in ids[:8]]

        st = TIERING._state(region)
        base_rung = st.base
        # ---- down the ladder, serving at every rung -------------------
        while st.rung < len(RUNGS) - 1:
            rep = TIERING.demote(node, region)
            assert rep["ok"], rep
            got_ids, _ = _topk(node, region, q)
            # all acked rows searchable at every point: exact self-hit
            assert [row[0] for row in got_ids] == [int(i) for i in ids[:8]]
        assert RUNGS[st.rung] == "mmap_sq8"
        w = region.vector_index_wrapper
        assert isinstance(w.own_index, HostSqFlat)
        # retire hook: a region out of HBM has zero device residency and
        # the ledger forgot it (no ghost hbm.region.bytes / DEVPEAK)
        from dingo_tpu.obs.hbm import HBM

        assert w.get_device_memory_size() == 0
        assert rid not in HBM.state()["regions"]

        # ---- back up to the base rung ---------------------------------
        while st.rung > base_rung:
            rep = TIERING.promote(node, region)
            assert rep["ok"], rep
        rt_ids, rt_dists = _topk(node, region, q)
        assert rt_ids == base_ids
        for a, b in zip(rt_dists, base_dists):
            assert np.array_equal(np.asarray(a), np.asarray(b))


def test_digest_gate_refuses_corrupted_copy():
    """Flip one destination byte between the copy and the verify: the
    swap must be refused, the OLD tier keeps serving byte-identically,
    and tier.digest_refusals ticks."""
    from dingo_tpu.common.metrics import METRICS

    with cluster(1, replication=1, seed=9) as c:
        rid = c.create_region(precision="sq8")
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        ids, x = _fill(node, region, n=64)
        q = x[:4]
        before_ids, before_dists = _topk(node, region, q)
        st = TIERING._state(region)
        assert st.rung == RUNG_HBM_SQ8

        def corrupt(stage, ctx=None):
            if stage == "copied" and ctx is not None:
                ctx.store.vecs[0, 0] ^= 1   # one flipped destination byte

        TIERING.test_hook = corrupt
        try:
            rep = TIERING.demote(node, region)
        finally:
            TIERING.test_hook = None
        assert rep["ok"] is False
        assert "digest" in rep["reason"]
        # rung unchanged, old tier still serving, byte-identical
        assert st.rung == RUNG_HBM_SQ8
        assert not isinstance(region.vector_index_wrapper.own_index,
                              HostSqFlat)
        after_ids, after_dists = _topk(node, region, q)
        assert after_ids == before_ids
        for a, b in zip(after_dists, before_dists):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        refusals = METRICS.counter("tier.digest_refusals",
                                   region_id=rid).get()
        assert refusals >= 1


def test_clean_copy_passes_digest_gate_and_swaps():
    """Control for the corruption test: the same transition with no
    interference verifies and installs (the gate is exact, not noisy)."""
    with cluster(1, replication=1, seed=9) as c:
        rid = c.create_region(precision="sq8")
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        _fill(node, region, n=64)
        fired = []
        TIERING.test_hook = lambda stage, ctx=None: fired.append(stage)
        try:
            rep = TIERING.demote(node, region)
        finally:
            TIERING.test_hook = None
        assert rep["ok"], rep
        assert fired == ["copied", "mid_demote"]
        assert isinstance(region.vector_index_wrapper.own_index, HostSqFlat)


def test_hamming_region_refuses_ladder():
    """Binary regions have no sq8 codec: the policy never picks them,
    the transcription arm refuses (old tier keeps serving), and the
    host index constructor rejects the metric outright."""
    from dingo_tpu.index.base import IndexParameter, InvalidParameter
    from dingo_tpu.ops.distance import Metric

    with cluster(1, replication=1, seed=13) as c:
        rid = c.create_region(index_type=IndexType.BINARY_FLAT,
                              metric=Metric.HAMMING)
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        ids = np.arange(1, 17, dtype=np.int64)
        rng = np.random.default_rng(13)
        packed = rng.integers(0, 256, size=(16, DIM // 8), dtype=np.uint8)
        node.storage.vector_add(region, ids, packed)
        # the policy never even nominates a binary region
        assert TIERING._pick_demote({rid: region}, {rid: 0.0}, 5.0) is None
        st = TIERING._state(region)
        st.rung = RUNG_HBM_SQ8   # force the transcription arm anyway
        rep = TIERING.demote(node, region)
        assert rep["ok"] is False
        res = node.storage.vector_batch_search(region, packed[:2], 3)
        assert [r[0].id for r in res] == [1, 2]
    with pytest.raises(InvalidParameter):
        HostSqFlat(1, IndexParameter(
            index_type=IndexType.FLAT, dimension=DIM,
            metric=Metric.HAMMING), store=None)


def test_advisory_flags_region_and_policy_tick_demotes():
    """The coordinator handshake end state: note_advisory flags the
    region; with tiering enabled and a synthetic HBM budget that leaves
    no headroom, one policy tick demotes exactly that region one rung."""
    from dingo_tpu.common.config import FLAGS

    with cluster(1, replication=1, seed=21) as c:
        rid = c.create_region()
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        _fill(node, region, n=64)
        TIERING.note_advisory(rid)
        assert TIERING.state()[rid]["advisory"]
        FLAGS.set("tier_enabled", True)
        TIERING.budget_override = 1   # 1-byte budget: zero headroom
        try:
            rep = TIERING.tick(node)
        finally:
            FLAGS.set("tier_enabled", False)
            TIERING.budget_override = None
        assert rep.get("ok"), rep
        assert rep["action"] == "demote" and rep["region"] == rid
        assert not TIERING.state()[rid]["advisory"]   # consumed


def test_tick_noop_when_disabled():
    with cluster(1, replication=1, seed=23) as c:
        rid = c.create_region()
        _sid, node = c.wait_leader(rid)
        assert TIERING.tick(node) == {}
        assert TIERING.region_tier(rid) == "hbm"


def test_region_tier_reporting_defaults():
    """Untracked regions report their resident precision's base rung;
    tracked ones report the live rung (heartbeat serving_tier source)."""
    assert TIERING.region_tier(999) == "hbm"
    assert TIERING.region_tier(999, precision="sq8") == "hbm_sq8"


def test_host_sq_flat_matches_device_sq8_ranking():
    """Demoting FLAT-sq8 one rung serves the SAME codes: the host paged
    scan decodes them exactly in f32, the device kernel accumulates the
    same decoded surrogate in bf16 compute (flat.py). So wire distances
    agree to bf16 tolerance (host is the tighter of the two) and the
    ranking agrees except across sub-bf16-resolution near-ties. Rerank
    disabled: that stage is device bookkeeping the retire hook releases,
    so the comparable surface is the pure over-codes distance."""
    from dingo_tpu.common.config import FLAGS

    old_rows = FLAGS.get("rerank_cache_rows")
    FLAGS.set("rerank_cache_rows", 0)
    try:
        _host_vs_device_sq8()
    finally:
        FLAGS.set("rerank_cache_rows", old_rows)


def _host_vs_device_sq8():
    with cluster(1, replication=1, seed=31) as c:
        rid = c.create_region(precision="sq8")
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        _ids, x = _fill(node, region, n=80)
        q = x[:6]
        dev_ids, dev_dists = _topk(node, region, q, k=7)
        assert TIERING.demote(node, region)["ok"]
        host_ids, host_dists = _topk(node, region, q, k=7)
        for hi, di, hd, dd in zip(host_ids, dev_ids, host_dists,
                                  dev_dists):
            # atol scales with the ~|x|^2-magnitude terms bf16 cancels
            # on near-zero distances, not with the distance itself
            np.testing.assert_allclose(np.asarray(hd), np.asarray(dd),
                                       rtol=2e-2, atol=0.2)
            assert hi[0] == di[0]           # self-hit survives the tier
            overlap = len(set(hi) & set(di))
            assert overlap >= 6, (hi, di)   # ≥6/7 modulo bf16 near-ties


def test_snapshot_source_refuses_non_sq_store():
    class _Wrapper:
        class _Idx:
            store = object()

        own_index = _Idx()
        apply_log_id = 0

        import threading as _t

        _lock = _t.RLock()

    with pytest.raises(TierRefused):
        TIERING._snapshot_source(_Wrapper())
