// Native LSM raw-KV engine: memtable + WAL + sorted immutable SSTs with
// tombstones and compaction.
//
// Plays RocksRawEngine's role (reference src/engine/rocks_raw_engine.{h,cc}:
// the store's persistent KV under raft apply and MVCC) as an ORIGINAL
// implementation — this is not a RocksDB wrapper and shares no code with it.
// Scope matches what the dingo_tpu stack needs: atomic batch writes through
// a torn-tail-safe WAL, sorted range scans (both directions), tombstoned
// deletes, size-triggered flush to numbered SST files, threshold-triggered
// full compaction, and checkpoint-by-flush (the Python side copies the
// immutable files). SST payloads are kept resident after load (the
// working-set assumption the rest of the stack already makes); recovery cost
// is bounded by the WAL tail, not history.
//
// C ABI for ctypes (dingo_tpu/native/__init__.py builds it with g++).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint32_t kWalMagic = 0xD146157A;
constexpr uint32_t kTombstone = 0xFFFFFFFFu;
constexpr uint8_t kOpPut = 1;
constexpr uint8_t kOpDelete = 2;

struct Entry {
  std::string key;
  std::string value;
  bool tombstone;
};

struct Sst {
  uint64_t id = 0;
  std::vector<Entry> entries;  // sorted by key, unique

  const Entry* find(const std::string& key) const {
    auto it = std::lower_bound(
        entries.begin(), entries.end(), key,
        [](const Entry& e, const std::string& k) { return e.key < k; });
    if (it != entries.end() && it->key == key) return &*it;
    return nullptr;
  }
};

struct Db {
  std::string dir;
  uint64_t memtable_limit = 8ull << 20;
  uint64_t memtable_bytes = 0;
  std::map<std::string, std::optional<std::string>> memtable;
  std::vector<std::unique_ptr<Sst>> ssts;  // oldest..newest
  uint64_t next_sst_id = 1;
  FILE* wal = nullptr;
  std::recursive_mutex mu;
  int compact_trigger = 8;

  std::string wal_path() const { return dir + "/wal.log"; }
  std::string sst_path(uint64_t id) const {
    char buf[32];
    snprintf(buf, sizeof(buf), "/%012llu.sst", (unsigned long long)id);
    return dir + buf;
  }
};

bool write_all(FILE* f, const void* p, size_t n) {
  return fwrite(p, 1, n, f) == n;
}

// ---- framed op buffers (shared by WAL payloads and the batch ABI) --------
// op buffer: repeated [u8 op][u32 klen][u32 vlen][key][value]
bool apply_ops(Db* db, const char* buf, size_t len) {
  size_t off = 0;
  while (off < len) {
    if (off + 9 > len) return false;
    uint8_t op = (uint8_t)buf[off];
    uint32_t kl, vl;
    memcpy(&kl, buf + off + 1, 4);
    memcpy(&vl, buf + off + 5, 4);
    off += 9;
    if (off + kl > len) return false;
    std::string key(buf + off, kl);
    off += kl;
    std::string value;
    if (op == kOpPut) {
      if (off + vl > len) return false;
      value.assign(buf + off, vl);
      off += vl;
    }
    uint64_t delta = key.size() + value.size() + 48;
    auto it = db->memtable.find(key);
    if (it != db->memtable.end()) {
      db->memtable_bytes -=
          it->first.size() + (it->second ? it->second->size() : 0) + 48;
    }
    if (op == kOpPut) {
      db->memtable[key] = std::move(value);
    } else {
      db->memtable[key] = std::nullopt;  // tombstone (may mask SST rows)
    }
    db->memtable_bytes += delta;
  }
  return true;
}

bool load_sst(Db* db, uint64_t id) {
  std::string path = db->sst_path(id);
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return false;
  auto sst = std::make_unique<Sst>();
  sst->id = id;
  for (;;) {
    uint32_t kl, vl;
    if (fread(&kl, 1, 4, f) != 4) break;
    if (fread(&vl, 1, 4, f) != 4) break;
    Entry e;
    e.key.resize(kl);
    if (kl && fread(&e.key[0], 1, kl, f) != kl) break;
    e.tombstone = (vl == kTombstone);
    if (!e.tombstone) {
      e.value.resize(vl);
      if (vl && fread(&e.value[0], 1, vl, f) != vl) break;
    }
    sst->entries.push_back(std::move(e));
  }
  fclose(f);
  db->ssts.push_back(std::move(sst));
  return true;
}

bool write_sst_file(const std::string& path,
                    const std::vector<Entry>& entries) {
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return false;
  for (const auto& e : entries) {
    uint32_t kl = (uint32_t)e.key.size();
    uint32_t vl = e.tombstone ? kTombstone : (uint32_t)e.value.size();
    if (!write_all(f, &kl, 4) || !write_all(f, &vl, 4) ||
        !write_all(f, e.key.data(), kl) ||
        (!e.tombstone && !write_all(f, e.value.data(), e.value.size()))) {
      fclose(f);
      return false;
    }
  }
  fflush(f);
  fsync(fileno(f));
  fclose(f);
  return rename(tmp.c_str(), path.c_str()) == 0;
}

int flush_locked(Db* db);

// full-merge compaction: newest-wins, tombstones dropped
int compact_locked(Db* db) {
  if (flush_locked(db) != 0) return -1;
  std::map<std::string, Entry> merged;  // oldest applied first, newest wins
  for (const auto& sst : db->ssts) {
    for (const auto& e : sst->entries) merged[e.key] = e;
  }
  std::vector<Entry> out;
  out.reserve(merged.size());
  for (auto& [k, e] : merged) {
    if (!e.tombstone) out.push_back(std::move(e));
  }
  uint64_t id = db->next_sst_id++;
  if (!write_sst_file(db->sst_path(id), out)) return -1;
  for (const auto& sst : db->ssts) unlink(db->sst_path(sst->id).c_str());
  db->ssts.clear();
  auto sst = std::make_unique<Sst>();
  sst->id = id;
  sst->entries = std::move(out);
  db->ssts.push_back(std::move(sst));
  return 0;
}

int flush_locked(Db* db) {
  if (db->memtable.empty()) return 0;
  std::vector<Entry> entries;
  entries.reserve(db->memtable.size());
  for (const auto& [k, v] : db->memtable) {
    Entry e;
    e.key = k;
    e.tombstone = !v.has_value();
    if (v) e.value = *v;
    entries.push_back(std::move(e));
  }
  uint64_t id = db->next_sst_id++;
  if (!write_sst_file(db->sst_path(id), entries)) return -1;
  auto sst = std::make_unique<Sst>();
  sst->id = id;
  sst->entries = std::move(entries);
  db->ssts.push_back(std::move(sst));
  db->memtable.clear();
  db->memtable_bytes = 0;
  // truncate the WAL: its contents are now durable in the SST
  if (db->wal) fclose(db->wal);
  db->wal = fopen(db->wal_path().c_str(), "wb");
  if ((int)db->ssts.size() >= db->compact_trigger) return compact_locked(db);
  return db->wal ? 0 : -1;
}

int append_wal(Db* db, const char* ops, size_t len) {
  uint32_t magic = kWalMagic, l = (uint32_t)len;
  if (!db->wal) return -1;
  if (!write_all(db->wal, &magic, 4) || !write_all(db->wal, &l, 4) ||
      !write_all(db->wal, ops, len)) {
    return -1;
  }
  fflush(db->wal);
  return 0;
}

void replay_wal(Db* db) {
  FILE* f = fopen(db->wal_path().c_str(), "rb");
  if (!f) return;
  long good = 0;
  std::vector<char> buf;
  for (;;) {
    uint32_t magic, len;
    if (fread(&magic, 1, 4, f) != 4) break;
    if (magic != kWalMagic) break;
    if (fread(&len, 1, 4, f) != 4) break;
    buf.resize(len);
    if (len && fread(buf.data(), 1, len, f) != len) break;
    if (!apply_ops(db, buf.data(), len)) break;
    good = ftell(f);
  }
  fclose(f);
  // torn-tail truncation: appends after garbage would be unreachable on
  // the next replay (same contract as the Python WalEngine)
  struct stat st;
  if (stat(db->wal_path().c_str(), &st) == 0 && st.st_size > good) {
    truncate(db->wal_path().c_str(), good);
  }
}

// merged view of a range: newest-wins across memtable + SSTs
std::vector<std::pair<std::string, std::string>> scan_locked(
    Db* db, const std::string& start, const std::string& end, bool has_end) {
  std::map<std::string, std::pair<int, const Entry*>> best;  // key -> (age, e)
  std::map<std::string, Entry> mem_entries;
  int age = 0;
  for (const auto& sst : db->ssts) {
    auto it = std::lower_bound(
        sst->entries.begin(), sst->entries.end(), start,
        [](const Entry& e, const std::string& k) { return e.key < k; });
    for (; it != sst->entries.end(); ++it) {
      if (has_end && it->key >= end) break;
      auto f = best.find(it->key);
      if (f == best.end() || f->second.first <= age) {
        best[it->key] = {age, &*it};
      }
    }
    ++age;
  }
  for (auto it = db->memtable.lower_bound(start); it != db->memtable.end();
       ++it) {
    if (has_end && it->first >= end) break;
    Entry e;
    e.key = it->first;
    e.tombstone = !it->second.has_value();
    if (it->second) e.value = *it->second;
    mem_entries[it->first] = std::move(e);
    best[it->first] = {age, &mem_entries[it->first]};
  }
  std::vector<std::pair<std::string, std::string>> out;
  for (auto& [k, v] : best) {
    if (!v.second->tombstone) out.emplace_back(k, v.second->value);
  }
  return out;
}

struct Iter {
  std::vector<std::pair<std::string, std::string>> rows;
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* lsm_open(const char* dir, uint64_t memtable_bytes) {
  auto* db = new Db();
  db->dir = dir;
  if (memtable_bytes) db->memtable_limit = memtable_bytes;
  mkdir(dir, 0755);
  // load SSTs in id order
  std::vector<uint64_t> ids;
  if (DIR* d = opendir(dir)) {
    while (dirent* e = readdir(d)) {
      std::string name = e->d_name;
      if (name.size() == 16 && name.substr(12) == ".sst") {
        ids.push_back(strtoull(name.c_str(), nullptr, 10));
      }
    }
    closedir(d);
  }
  std::sort(ids.begin(), ids.end());
  for (uint64_t id : ids) {
    load_sst(db, id);
    db->next_sst_id = std::max(db->next_sst_id, id + 1);
  }
  replay_wal(db);
  db->wal = fopen(db->wal_path().c_str(), "ab");
  if (!db->wal) {
    delete db;
    return nullptr;
  }
  return db;
}

void lsm_close(void* h) {
  auto* db = (Db*)h;
  if (!db) return;
  if (db->wal) fclose(db->wal);
  delete db;
}

int lsm_write(void* h, const char* ops, uint64_t len) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  if (append_wal(db, ops, len) != 0) return -1;
  if (!apply_ops(db, ops, len)) return -2;
  if (db->memtable_bytes >= db->memtable_limit) return flush_locked(db);
  return 0;
}

int lsm_get(void* h, const char* k, uint64_t kl, char** out, uint64_t* outl) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  std::string key(k, kl);
  auto it = db->memtable.find(key);
  if (it != db->memtable.end()) {
    if (!it->second) return 1;  // tombstone
    *outl = it->second->size();
    *out = (char*)malloc(*outl);
    memcpy(*out, it->second->data(), *outl);
    return 0;
  }
  for (auto r = db->ssts.rbegin(); r != db->ssts.rend(); ++r) {
    if (const Entry* e = (*r)->find(key)) {
      if (e->tombstone) return 1;
      *outl = e->value.size();
      *out = (char*)malloc(*outl);
      memcpy(*out, e->value.data(), *outl);
      return 0;
    }
  }
  return 1;
}

void lsm_free_buf(char* p) { free(p); }

void* lsm_scan(void* h, const char* s, uint64_t sl, const char* e,
               uint64_t el, int has_end, int reverse) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  auto* it = new Iter();
  it->rows = scan_locked(db, std::string(s, sl), std::string(e, el),
                         has_end != 0);
  if (reverse) std::reverse(it->rows.begin(), it->rows.end());
  return it;
}

int lsm_iter_next(void* h, const char** k, uint64_t* kl, const char** v,
                  uint64_t* vl) {
  auto* it = (Iter*)h;
  if (it->pos >= it->rows.size()) return 1;
  const auto& row = it->rows[it->pos++];
  *k = row.first.data();
  *kl = row.first.size();
  *v = row.second.data();
  *vl = row.second.size();
  return 0;
}

void lsm_iter_close(void* h) { delete (Iter*)h; }

uint64_t lsm_count(void* h, const char* s, uint64_t sl, const char* e,
                   uint64_t el, int has_end) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  return scan_locked(db, std::string(s, sl), std::string(e, el), has_end != 0)
      .size();
}

int lsm_flush(void* h) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  return flush_locked(db);
}

int lsm_compact(void* h) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  return compact_locked(db);
}

uint64_t lsm_sst_count(void* h) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  return db->ssts.size();
}

}  // extern "C"
