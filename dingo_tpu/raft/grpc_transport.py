"""grpc raft transport: multi-process replication.

The reference replicates over brpc/braft TCP; this transport carries the
same RaftNode RPCs (request_vote / append_entries / install_snapshot /
timeout_now) between store PROCESSES over grpc. Raft node addresses stay
"<store_id>/r<region_id>"; the transport maps the store prefix to a grpc
endpoint and the receiving server dispatches to the locally-registered
handler. Local targets short-circuit in process.
"""

from __future__ import annotations

import hmac
import threading
import time
from typing import Callable, Dict, Optional

import grpc

from dingo_tpu.raft import wire
from dingo_tpu.raft.transport import Transport, TransportFaults
from dingo_tpu.server import pb
from dingo_tpu.server.rpc import ServiceStub


class GrpcRaftTransport(Transport):
    def __init__(self, store_id: str,
                 peer_addrs: Optional[Dict[str, str]] = None,
                 cluster_token: str = ""):
        self.store_id = store_id
        #: shared cluster secret rejecting out-of-cluster senders; payloads
        #: themselves are a typed TLV codec (raft/wire.py) that can only
        #: produce plain data, so a forged message cannot execute code
        self.cluster_token = cluster_token
        self._peer_addrs = dict(peer_addrs or {})
        self._handlers: Dict[str, Callable[[str, dict], dict]] = {}
        self._channels: Dict[str, grpc.Channel] = {}
        self._stubs: Dict[str, ServiceStub] = {}
        self._lock = threading.Lock()
        #: injectable per-peer-pair faults (drop/delay/duplicate/partition,
        #: raft/transport.py TransportFaults) — None = no fault layer, the
        #: send path pays one attribute check
        self.faults: Optional[TransportFaults] = None

    # -- wiring --------------------------------------------------------------
    def set_peer(self, store_id: str, addr: str) -> None:
        with self._lock:
            self._peer_addrs[store_id] = addr
            self._channels.pop(store_id, None)
            self._stubs.pop(store_id, None)

    def register(self, node_id: str, handler) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._handlers.pop(node_id, None)

    # -- server side (RaftService dispatch) ----------------------------------
    def dispatch(self, target: str, method: str, msg: dict) -> Optional[dict]:
        with self._lock:
            handler = self._handlers.get(target)
        if handler is None:
            return None
        try:
            return handler(method, msg)
        except Exception:
            return None

    # -- client side ----------------------------------------------------------
    def _stub(self, store_id: str) -> Optional[ServiceStub]:
        with self._lock:
            stub = self._stubs.get(store_id)
            if stub is not None:
                return stub
            addr = self._peer_addrs.get(store_id)
            if addr is None:
                return None
            chan = grpc.insecure_channel(addr)
            self._channels[store_id] = chan
            stub = ServiceStub(chan, "RaftService")
            self._stubs[store_id] = stub
            return stub

    def send(self, target: str, method: str, msg: dict) -> Optional[dict]:
        store_id = target.split("/")[0]
        if store_id == self.store_id:
            return self.dispatch(target, method, msg)
        copies = 1
        if self.faults is not None:
            deliver, delay_s, copies = self.faults.decide(
                self.store_id, store_id)
            if not deliver:
                return None
            if delay_s:
                time.sleep(delay_s)
        stub = self._stub(store_id)
        if stub is None:
            return None
        req = pb.RaftMessageRequest(
            target=target, method=method,
            payload=wire.encode(msg),
            cluster_token=self.cluster_token,
        )
        resp = None
        for _ in range(copies):
            # duplicate fault: the peer processes the message twice; the
            # FIRST response is the one the raft node acts on (raft must
            # dedupe re-delivery by term/index — the invariant exercised)
            try:
                r = stub.RaftMessage(req, timeout=2.0)
            except grpc.RpcError:
                r = None
            if resp is None:
                resp = r
        if resp is None or not resp.delivered:
            return None
        try:
            return wire.decode(resp.payload)
        except wire.WireError:
            return None

    def close(self) -> None:
        with self._lock:
            for chan in self._channels.values():
                chan.close()
            self._channels.clear()


class RaftService:
    """Server-side receiver (registered on the store's DingoServer)."""

    def __init__(self, transport: GrpcRaftTransport):
        self.transport = transport

    def RaftMessage(self, req: pb.RaftMessageRequest) -> pb.RaftMessageResponse:
        resp = pb.RaftMessageResponse()
        if not hmac.compare_digest(
            req.cluster_token.encode(), self.transport.cluster_token.encode()
        ):
            resp.delivered = False
            resp.error.errcode = 95001
            resp.error.errmsg = "cluster token mismatch"
            return resp
        try:
            msg = wire.decode(req.payload)
        except wire.WireError:
            resp.delivered = False
            resp.error.errcode = 95002
            resp.error.errmsg = "malformed raft payload"
            return resp
        out = self.transport.dispatch(req.target, req.method, msg)
        if out is None:
            resp.delivered = False
        else:
            resp.delivered = True
            resp.payload = wire.encode(out)
        return resp
