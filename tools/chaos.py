"""Deterministic chaos harness: declarative fault scenarios with gates.

Every resilience claim the stack has accumulated — WAL-durable acked
writes (PR 3), raft failover, the QoS goodput floor (PR 10), digest-clean
state (PR 11), and the device-OOM recovery ladder (index/recovery.py) —
is exercised here against REAL injected faults and turned into a
machine-checked verdict. Scenarios run the in-process cluster topology
the integration tests use (LocalTransport + CoordinatorControl +
StoreNode) with the fault planes this PR added:

  * TransportFaults    — seeded drop/delay/duplicate/partition per
                         store-pair (raft/transport.py)
  * DEVFAULT           — synthetic RESOURCE_EXHAUSTED at the sentinel_jit
                         dispatch chokepoint (ops/devfault.py)
  * process kill       — node.stop() + engine close, the in-proc
                         equivalent of SIGKILL; restart goes through the
                         real recovery path (StoreNode.recover)
  * flipped byte       — host-side corruption of a device array, caught
                         by the PR 11 scrub and healed by the recovery
                         plane's rebuild-from-engine

Gates (per scenario): ZERO acknowledged-write loss — every id whose
vector_add returned is re-read after recovery AND the integrity scrub
reports digest-clean state; bounded recovery time; a goodput floor for
read traffic during the fault window; and zero steady-state recompiles
after recovery (warm searches must not re-trace).

Determinism: every randomized actor is seeded (numpy corpus, raft
election jitter via raft_kw seeds, TransportFaults rng, DEVFAULT count
arming) so a failing run replays exactly from its printed seed.

CLI:  python tools/chaos.py [--seed N] [--json] [scenario ...]
      (no scenario args = the full suite)
Bench: `python bench.py chaos` runs the suite and emits the bench-schema
JSON consumed by tools/bench_diff.py (recovery_ms / goodput kinds).
"""

from __future__ import annotations

import contextlib
import json
import shutil
import sys
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# scenario time bounds (seconds) — generous for the CPU smoke arm; the
# signal is "bounded at all", not a latency benchmark
RECOVERY_BOUND_S = 15.0
#: read goodput floor during the fault window for scenarios that keep
#: replicas serving (leader failover / partition: follower reads and the
#: survivor majority must keep answering)
GOODPUT_FLOOR = 0.9

DIM = 16


def _log(msg: str) -> None:
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# cluster scaffolding
# --------------------------------------------------------------------------

class Cluster:
    """In-process store cluster with the fault planes attached."""

    def __init__(self, n_stores: int, replication: int, seed: int,
                 data_dir: Optional[str] = None):
        from dingo_tpu.coordinator.control import CoordinatorControl
        from dingo_tpu.engine.raw_engine import MemEngine, WalEngine
        from dingo_tpu.raft.transport import LocalTransport, TransportFaults
        from dingo_tpu.store.node import StoreNode

        self.seed = seed
        self.data_dir = data_dir
        self.transport = LocalTransport(seed=seed)
        self.faults = TransportFaults(seed=seed)
        self.transport.faults = self.faults
        self.coord = CoordinatorControl(MemEngine(), replication=replication)
        self.nodes: Dict[str, StoreNode] = {}
        self._engines: Dict[str, Any] = {}
        for i in range(n_stores):
            sid = f"s{i}"
            if data_dir is not None:
                raw = WalEngine(f"{data_dir}/{sid}",
                                checkpoint_threshold_bytes=1 << 20)
            else:
                raw = MemEngine()
            self._engines[sid] = raw
            self.nodes[sid] = StoreNode(
                sid, self.transport, self.coord,
                raw_engine=raw, raft_kw={"seed": seed + i})

    def create_region(self, index_type=None, precision: str = "",
                      part: int = 0, **param_kw):
        """One region over partition `part`'s whole id range — pass
        distinct parts to host several regions on one store (ranges may
        not overlap)."""
        from dingo_tpu.index import codec as vcodec
        from dingo_tpu.index.base import IndexParameter, IndexType
        from dingo_tpu.store.region import RegionType

        param = IndexParameter(
            index_type=index_type or IndexType.FLAT, dimension=DIM,
            precision=precision, **param_kw)
        d = self.coord.create_region(
            start_key=vcodec.encode_vector_key(part, 0),
            end_key=vcodec.encode_vector_key(part, 1 << 40),
            partition_id=part,
            region_type=RegionType.INDEX,
            index_parameter=param,
        )
        self.drive(rounds=3)
        return d.region_id

    def drive(self, rounds: int = 1, sleep: float = 0.03) -> None:
        for _ in range(rounds):
            for n in self.nodes.values():
                with contextlib.suppress(Exception):
                    n.heartbeat_once()
            time.sleep(sleep)

    def leader(self, region_id: int):
        """(store_id, node) currently claiming leadership, or None."""
        for sid, n in self.nodes.items():
            rn = n.engine.get_node(region_id)
            if rn is not None and rn.is_leader():
                return sid, n
        return None

    def wait_leader(self, region_id: int, timeout: float = 10.0,
                    exclude: Tuple[str, ...] = ()):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.drive(rounds=1, sleep=0.02)
            got = self.leader(region_id)
            if got is not None and got[0] not in exclude:
                return got
        raise AssertionError(f"no leader for region {region_id}")

    def kill(self, store_id: str) -> None:
        """In-proc SIGKILL: stop raft (unregisters transport handlers),
        close the engine. Nothing is flushed beyond what was acked."""
        node = self.nodes.pop(store_id)
        node.stop()
        with contextlib.suppress(Exception):
            self._engines[store_id].close()

    def restart(self, store_id: str, seed_offset: int = 100):
        """Bring a killed store back through the real recovery path."""
        from dingo_tpu.engine.raw_engine import WalEngine
        from dingo_tpu.store.node import StoreNode

        assert self.data_dir is not None, "restart needs durable engines"
        raw = WalEngine(f"{self.data_dir}/{store_id}",
                        checkpoint_threshold_bytes=1 << 20)
        self._engines[store_id] = raw
        node = StoreNode(store_id, self.transport, self.coord,
                         raw_engine=raw,
                         raft_kw={"seed": self.seed + seed_offset})
        node.recover()
        self.nodes[store_id] = node
        return node

    def close(self) -> None:
        from dingo_tpu.index.recovery import RECOVERY
        from dingo_tpu.index.tiering import TIERING
        from dingo_tpu.obs.integrity import INTEGRITY

        for n in self.nodes.values():
            with contextlib.suppress(Exception):
                n.stop()
        self.transport.heal()
        # the planes are process-global: scrub scenario state so the next
        # scenario (or the surrounding test process) starts clean
        RECOVERY.clear()
        INTEGRITY.clear()
        TIERING.reset()


@contextlib.contextmanager
def cluster(n_stores: int, replication: int, seed: int,
            durable: bool = False):
    tmp = tempfile.mkdtemp(prefix="chaos-") if durable else None
    c = Cluster(n_stores, replication, seed, data_dir=tmp)
    try:
        yield c
    finally:
        c.close()
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


# --------------------------------------------------------------------------
# verification helpers
# --------------------------------------------------------------------------

def _corpus(seed: int, n: int) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    return (np.arange(n, dtype=np.int64),
            rng.standard_normal((n, DIM)).astype(np.float32))


def _acked_lost(node, region, acked: Dict[int, np.ndarray]) -> List[int]:
    """Ids that were acked but are NOT readable after recovery."""
    ids = sorted(acked)
    got = node.storage.vector_batch_query(region, ids)
    lost = []
    for vid, v in zip(ids, got):
        if v is None or v.vector is None:
            lost.append(vid)
            continue
        if not np.allclose(np.asarray(v.vector), acked[vid], atol=1e-5):
            lost.append(vid)
    return lost


def _digest_clean(node) -> bool:
    """One scrub sweep over the node: every artifact must verify against
    the incremental ledger (the PR 11 'state is what the log says' gate)."""
    from dingo_tpu.obs.integrity import INTEGRITY

    results = INTEGRITY.scrub_node(node)
    for per_artifact in results.values():
        for r in per_artifact.values():
            if r.get("status") not in ("ok", "skipped", "advisory"):
                return False
    return True


def _steady_recompiles(node, region, queries: np.ndarray,
                       reps: int = 3) -> int:
    """Recompile delta across repeated identical searches AFTER one
    warmup (the steady-state invariant: warm serving never re-traces)."""
    from dingo_tpu.obs.sentinel import SENTINEL

    node.storage.vector_batch_search(region, queries, 3)  # warm
    before = SENTINEL.recompiles()
    for _ in range(reps):
        node.storage.vector_batch_search(region, queries, 3)
    return SENTINEL.recompiles() - before


def _result(name: str, seed: int, **kw) -> Dict[str, Any]:
    gates = kw.pop("gates")
    out = {"name": name, "seed": seed, **kw, "gates": gates,
           "passed": all(gates.values())}
    verdict = "PASS" if out["passed"] else "FAIL"
    _log(f"{name}: {verdict} "
         + " ".join(f"{g}={'ok' if v else 'VIOLATED'}"
                    for g, v in gates.items()))
    return out


# --------------------------------------------------------------------------
# scenarios
# --------------------------------------------------------------------------

def scenario_kill_restart(seed: int) -> Dict[str, Any]:
    """Kill a store mid-write-batch (engine closed un-flushed beyond acks),
    restart through StoreNode.recover(). Gate: every acked write survives,
    digest-clean, bounded recovery, post-restart writes work."""
    with cluster(1, replication=1, seed=seed, durable=True) as c:
        rid = c.create_region()
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        ids, x = _corpus(seed, 96)
        acked: Dict[int, np.ndarray] = {}
        # write in small batches; the kill lands between two acks, which
        # is exactly "mid-write-batch" from the client's point of view
        for lo in range(0, 64, 8):
            sl = slice(lo, lo + 8)
            node.storage.vector_add(region, ids[sl], x[sl])
            for i in range(lo, lo + 8):
                acked[int(ids[i])] = x[i]
        c.kill("s0")

        t0 = time.perf_counter()
        node2 = c.restart("s0")
        c.wait_leader(rid)
        region2 = node2.get_region(rid)
        # recovered = first read answered
        node2.storage.vector_batch_search(region2, x[:1], 3)
        recovery_ms = (time.perf_counter() - t0) * 1e3

        lost = _acked_lost(node2, region2, acked)
        clean = _digest_clean(node2)
        # still writable after recovery
        node2.storage.vector_add(region2, ids[64:72], x[64:72])
        got = node2.storage.vector_batch_query(region2, [int(ids[64])])
        writable = got[0] is not None
        recompiles = _steady_recompiles(node2, region2, x[:4])
        return _result(
            "kill_restart", seed,
            acked=len(acked), lost=len(lost), lost_ids=lost[:8],
            recovery_ms=round(recovery_ms, 1),
            recovery_bound_ms=RECOVERY_BOUND_S * 1e3,
            steady_recompiles=recompiles,
            gates={
                "zero_acked_loss": not lost,
                "digest_clean": clean,
                "recovery_bounded": recovery_ms <= RECOVERY_BOUND_S * 1e3,
                "writable_after_recovery": writable,
                "zero_steady_recompiles": recompiles == 0,
            })


def _traffic_window(c: Cluster, rid: int, queries: np.ndarray,
                    duration_s: float, exclude: Tuple[str, ...] = ()
                    ) -> Tuple[int, int]:
    """Fire read traffic at every live replica for `duration_s` while
    driving heartbeats; returns (served, attempted)."""
    served = attempted = 0
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        c.drive(rounds=1, sleep=0.01)
        for sid, n in list(c.nodes.items()):
            if sid in exclude:
                continue
            region = n.get_region(rid)
            if region is None:
                continue
            attempted += 1
            try:
                res = n.storage.vector_batch_search(region, queries[:1], 3)
                if res and res[0]:
                    served += 1
            except Exception:  # noqa: BLE001 — counted as unserved
                pass
    return served, attempted


def _write_until_ok(c: Cluster, rid: int, ids, vecs,
                    timeout_s: float, exclude: Tuple[str, ...] = ()
                    ) -> float:
    """Retry one write batch against whichever node claims leadership
    until it lands; returns elapsed ms (the write-recovery time)."""
    from dingo_tpu.raft.core import NotLeader, ProposalFailed

    t0 = time.perf_counter()
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        c.drive(rounds=1, sleep=0.02)
        got = c.leader(rid)
        if got is None or got[0] in exclude:
            continue
        _sid, node = got
        region = node.get_region(rid)
        if region is None:
            continue
        try:
            node.storage.vector_add(region, ids, vecs)
            return (time.perf_counter() - t0) * 1e3
        except (NotLeader, ProposalFailed):
            continue
    raise AssertionError("write never recovered inside the bound")


def scenario_leader_failover(seed: int) -> Dict[str, Any]:
    """Kill the raft leader under live traffic. Gates: survivors keep
    serving reads (goodput floor), a new leader accepts writes inside the
    bound, no acked write is lost, replicas stay digest-clean."""
    with cluster(3, replication=3, seed=seed) as c:
        rid = c.create_region()
        lsid, lnode = c.wait_leader(rid)
        region = lnode.get_region(rid)
        ids, x = _corpus(seed, 96)
        acked: Dict[int, np.ndarray] = {}
        for lo in range(0, 48, 8):
            sl = slice(lo, lo + 8)
            lnode.storage.vector_add(region, ids[sl], x[sl])
            for i in range(lo, lo + 8):
                acked[int(ids[i])] = x[i]
        c.drive(rounds=3)  # let followers apply

        c.kill(lsid)
        # fault window: read traffic against the survivors
        served, attempted = _traffic_window(c, rid, x, duration_s=1.0)
        recovery_ms = _write_until_ok(
            c, rid, ids[48:56], x[48:56], RECOVERY_BOUND_S)
        for i in range(48, 56):
            acked[int(ids[i])] = x[i]

        _sid2, node2 = c.wait_leader(rid)
        region2 = node2.get_region(rid)
        lost = _acked_lost(node2, region2, acked)
        clean = all(_digest_clean(n) for n in c.nodes.values())
        goodput = served / attempted if attempted else 0.0
        recompiles = _steady_recompiles(node2, region2, x[:4])
        return _result(
            "leader_failover", seed,
            acked=len(acked), lost=len(lost), lost_ids=lost[:8],
            recovery_ms=round(recovery_ms, 1),
            recovery_bound_ms=RECOVERY_BOUND_S * 1e3,
            goodput=round(goodput, 4), goodput_floor=GOODPUT_FLOOR,
            reads_served=served, reads_attempted=attempted,
            steady_recompiles=recompiles,
            gates={
                "zero_acked_loss": not lost,
                "digest_clean": clean,
                "recovery_bounded": recovery_ms <= RECOVERY_BOUND_S * 1e3,
                "goodput_floor": goodput >= GOODPUT_FLOOR,
                "zero_steady_recompiles": recompiles == 0,
            })


def scenario_partition_heal(seed: int) -> Dict[str, Any]:
    """Partition the leader away from both followers; the majority side
    elects, keeps serving and accepting writes; heal; the old leader
    rejoins and catches up to byte-identical state."""
    with cluster(3, replication=3, seed=seed) as c:
        rid = c.create_region()
        lsid, lnode = c.wait_leader(rid)
        region = lnode.get_region(rid)
        ids, x = _corpus(seed, 96)
        acked: Dict[int, np.ndarray] = {}
        for lo in range(0, 32, 8):
            sl = slice(lo, lo + 8)
            lnode.storage.vector_add(region, ids[sl], x[sl])
            for i in range(lo, lo + 8):
                acked[int(ids[i])] = x[i]
        c.drive(rounds=3)

        others = [sid for sid in c.nodes if sid != lsid]
        for sid in others:
            c.faults.partition(lsid, sid)
        served, attempted = _traffic_window(
            c, rid, x, duration_s=1.0, exclude=(lsid,))
        recovery_ms = _write_until_ok(
            c, rid, ids[32:40], x[32:40], RECOVERY_BOUND_S, exclude=(lsid,))
        for i in range(32, 40):
            acked[int(ids[i])] = x[i]

        c.faults.heal()
        # old leader steps down and catches up; poll until it holds every
        # acked write (raft log replay through the real apply path)
        deadline = time.monotonic() + RECOVERY_BOUND_S
        caught_up = False
        while time.monotonic() < deadline and not caught_up:
            c.drive(rounds=2, sleep=0.03)
            old = c.nodes[lsid]
            r_old = old.get_region(rid)
            caught_up = r_old is not None and not _acked_lost(
                old, r_old, acked)
        lost_each = {sid: len(_acked_lost(n, n.get_region(rid), acked))
                     for sid, n in c.nodes.items()}
        clean = all(_digest_clean(n) for n in c.nodes.values())
        goodput = served / attempted if attempted else 0.0
        return _result(
            "partition_heal", seed,
            acked=len(acked), lost=max(lost_each.values()),
            lost_by_store=lost_each,
            recovery_ms=round(recovery_ms, 1),
            recovery_bound_ms=RECOVERY_BOUND_S * 1e3,
            goodput=round(goodput, 4), goodput_floor=GOODPUT_FLOOR,
            old_leader_caught_up=caught_up,
            gates={
                "zero_acked_loss": max(lost_each.values()) == 0,
                "digest_clean": clean,
                "recovery_bounded": recovery_ms <= RECOVERY_BOUND_S * 1e3,
                "goodput_floor": goodput >= GOODPUT_FLOOR,
                "partitioned_leader_caught_up": caught_up,
            })


def scenario_oom_storm(seed: int) -> Dict[str, Any]:
    """Arm the device-fault shim for EVERY dispatch: writes and reads must
    keep being served (ladder -> degraded -> host path), never raise; on
    disarm the background re-materialization restores device serving with
    zero steady-state recompiles."""
    from dingo_tpu.index.recovery import RECOVERY
    from dingo_tpu.ops.devfault import DEVFAULT

    with cluster(1, replication=1, seed=seed) as c:
        rid = c.create_region()
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        ids, x = _corpus(seed, 96)
        node.storage.vector_add(region, ids[:32], x[:32])
        acked = {int(ids[i]): x[i] for i in range(32)}

        DEVFAULT.arm(1 << 30)
        try:
            served = attempted = 0
            unhandled: List[str] = []
            for lo in range(32, 64, 8):
                sl = slice(lo, lo + 8)
                attempted += 1
                try:
                    node.storage.vector_add(region, ids[sl], x[sl])
                    for i in range(lo, lo + 8):
                        acked[int(ids[i])] = x[i]
                    served += 1
                except Exception as e:  # noqa: BLE001 — the gate itself
                    unhandled.append(f"write: {type(e).__name__}: {e}")
                attempted += 1
                try:
                    res = node.storage.vector_batch_search(
                        region, x[lo:lo + 1], 3)
                    if res and res[0] and res[0][0].id == int(ids[lo]):
                        served += 1
                except Exception as e:  # noqa: BLE001
                    unhandled.append(f"search: {type(e).__name__}: {e}")
            degraded = RECOVERY.is_degraded(rid)
        finally:
            DEVFAULT.disarm()

        t0 = time.perf_counter()
        remats = RECOVERY.run_rematerializations(node)
        recovery_ms = (time.perf_counter() - t0) * 1e3
        lost = _acked_lost(node, region, acked)
        clean = _digest_clean(node)
        recompiles = _steady_recompiles(node, region, x[:4])
        goodput = served / attempted if attempted else 0.0
        return _result(
            "oom_storm", seed,
            acked=len(acked), lost=len(lost), lost_ids=lost[:8],
            degraded_during_storm=degraded, rematerializations=remats,
            recovery_ms=round(recovery_ms, 1),
            recovery_bound_ms=RECOVERY_BOUND_S * 1e3,
            goodput=round(goodput, 4), goodput_floor=1.0,
            unhandled=unhandled[:4],
            steady_recompiles=recompiles,
            gates={
                "every_request_served": not unhandled and goodput == 1.0,
                "region_degraded_then_recovered":
                    degraded and remats >= 1
                    and not RECOVERY.is_degraded(rid),
                "zero_acked_loss": not lost,
                "digest_clean": clean,
                "recovery_bounded": recovery_ms <= RECOVERY_BOUND_S * 1e3,
                "zero_steady_recompiles": recompiles == 0,
            })


def scenario_bitflip(seed: int) -> Dict[str, Any]:
    """One flipped byte in a device array: the integrity scrub must catch
    it and the recovery plane must rebuild from the engine instead of
    serving corruption."""
    import jax.numpy as jnp

    from dingo_tpu.index.recovery import RECOVERY
    from dingo_tpu.obs.integrity import INTEGRITY

    with cluster(1, replication=1, seed=seed) as c:
        rid = c.create_region()
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        ids, x = _corpus(seed, 64)
        node.storage.vector_add(region, ids, x)
        acked = {int(ids[i]): x[i] for i in range(len(ids))}
        idx = region.vector_index_wrapper.own_index
        INTEGRITY.scrub_index(idx)
        assert INTEGRITY.region_report(idx)[2] is False

        # flip one byte of one resident row (silent HBM/restore corruption)
        slot = int(idx.store.slots_of(ids[:1])[0])
        arr = np.asarray(idx.store.vecs).copy()
        arr.view(np.uint8)[slot, 0] ^= 1
        with idx.store.device_lock:
            idx.store.vecs = jnp.asarray(arr)

        t0 = time.perf_counter()
        INTEGRITY.scrub_index(idx)
        detected = INTEGRITY.region_report(idx)[2] is True
        rebuilt = RECOVERY.run_rematerializations(node)
        recovery_ms = (time.perf_counter() - t0) * 1e3

        region2 = node.get_region(rid)
        lost = _acked_lost(node, region2, acked)
        res = node.storage.vector_batch_search(region2, x[:4], 1)
        parity = all(r[0].id == int(ids[i]) for i, r in enumerate(res))
        idx2 = region2.vector_index_wrapper.own_index
        INTEGRITY.scrub_index(idx2)
        clean = INTEGRITY.region_report(idx2)[2] is False
        return _result(
            "bitflip", seed,
            acked=len(acked), lost=len(lost),
            detected=detected, rebuilds=rebuilt,
            recovery_ms=round(recovery_ms, 1),
            recovery_bound_ms=RECOVERY_BOUND_S * 1e3,
            search_parity=parity,
            gates={
                "scrub_detected_flip": detected,
                "rebuilt_from_engine": rebuilt >= 1,
                "zero_acked_loss": not lost,
                "search_parity": parity,
                "digest_clean_after_rebuild": clean,
                "recovery_bounded": recovery_ms <= RECOVERY_BOUND_S * 1e3,
            })


class _TierKill(RuntimeError):
    """Sentinel the tier-transition test hook raises after the in-proc
    SIGKILL so the interrupted transition unwinds like the dying process
    would have."""


def scenario_tier_kill(seed: int) -> Dict[str, Any]:
    """Process kill MID-TIER-TRANSITION (ISSUE 19): once between the
    verified destination copy and the swap of a demotion, once inside a
    promotion. The ladder's crash story is that every transition is a
    copy + digest-gated swap over state the WAL already owns, so a kill
    at the worst moment costs nothing: restart rebuilds at the DECLARED
    tier from the engine and every acked write answers. Gates: zero
    acked-write loss after each restart, digest-clean scrub, bounded
    recovery, still writable, zero steady-state recompiles."""
    from dingo_tpu.index.tiering import RUNG_HOST_SQ8, TIERING

    with cluster(1, replication=1, seed=seed, durable=True) as c:
        rid = c.create_region()
        _sid, node = c.wait_leader(rid)
        region = node.get_region(rid)
        ids, x = _corpus(seed, 96)
        acked: Dict[int, np.ndarray] = {}
        for lo in range(0, 64, 8):
            sl = slice(lo, lo + 8)
            node.storage.vector_add(region, ids[sl], x[sl])
            for i in range(lo, lo + 8):
                acked[int(ids[i])] = x[i]

        # reach the device-sq8 rung, then die inside the hbm_sq8 ->
        # host_sq8 transcription: after the digest verify, before the swap
        assert TIERING.demote(node, region)["ok"]

        def kill_at(stage_name):
            def hook(stage, _ctx=None):
                if stage == stage_name:
                    c.kill("s0")
                    raise _TierKill(stage)
            return hook

        TIERING.test_hook = kill_at("mid_demote")
        try:
            TIERING.demote(node, region)
            raise AssertionError("demotion survived the kill hook")
        except _TierKill:
            pass
        finally:
            TIERING.test_hook = None

        t0 = time.perf_counter()
        node2 = c.restart("s0")
        c.wait_leader(rid)
        region2 = node2.get_region(rid)
        node2.storage.vector_batch_search(region2, x[:1], 3)
        recovery1_ms = (time.perf_counter() - t0) * 1e3
        TIERING.reset()   # in-proc restart: a real process loses this too
        lost1 = _acked_lost(node2, region2, acked)
        clean1 = _digest_clean(node2)

        # walk the survivor down to host RAM, then die mid-PROMOTION
        assert TIERING.demote(node2, region2)["ok"]
        assert TIERING.demote(node2, region2)["ok"]
        assert TIERING.state()[rid]["rung"] == "host_sq8"
        assert TIERING._regions[rid].rung == RUNG_HOST_SQ8
        TIERING.test_hook = kill_at("mid_promote")
        try:
            TIERING.promote(node2, region2)
            raise AssertionError("promotion survived the kill hook")
        except _TierKill:
            pass
        finally:
            TIERING.test_hook = None

        t0 = time.perf_counter()
        node3 = c.restart("s0", seed_offset=200)
        c.wait_leader(rid)
        region3 = node3.get_region(rid)
        node3.storage.vector_batch_search(region3, x[:1], 3)
        recovery2_ms = (time.perf_counter() - t0) * 1e3
        TIERING.reset()
        lost2 = _acked_lost(node3, region3, acked)
        clean2 = _digest_clean(node3)
        node3.storage.vector_add(region3, ids[64:72], x[64:72])
        got = node3.storage.vector_batch_query(region3, [int(ids[64])])
        writable = got[0] is not None
        recompiles = _steady_recompiles(node3, region3, x[:4])
        recovery_ms = max(recovery1_ms, recovery2_ms)
        return _result(
            "tier_kill", seed,
            acked=len(acked), lost=len(lost1) + len(lost2),
            lost_ids=(lost1 + lost2)[:8],
            recovery_ms=round(recovery_ms, 1),
            recovery_bound_ms=RECOVERY_BOUND_S * 1e3,
            steady_recompiles=recompiles,
            gates={
                "zero_acked_loss": not lost1 and not lost2,
                "digest_clean": clean1 and clean2,
                "recovery_bounded": recovery_ms <= RECOVERY_BOUND_S * 1e3,
                "writable_after_recovery": writable,
                "zero_steady_recompiles": recompiles == 0,
            })


SCENARIOS: Dict[str, Callable[[int], Dict[str, Any]]] = {
    "kill_restart": scenario_kill_restart,
    "leader_failover": scenario_leader_failover,
    "partition_heal": scenario_partition_heal,
    "oom_storm": scenario_oom_storm,
    "bitflip": scenario_bitflip,
    "tier_kill": scenario_tier_kill,
}


def run_scenarios(names: Optional[List[str]] = None,
                  seed: int = 0) -> Dict[str, Any]:
    """Run the named scenarios (default: all) and aggregate the verdict.
    An exception inside a scenario is a FAIL, not a crash of the suite."""
    picked = names or list(SCENARIOS)
    results: List[Dict[str, Any]] = []
    for name in picked:
        fn = SCENARIOS[name]
        _log(f"running {name} (seed={seed})")
        try:
            results.append(fn(seed))
        except Exception as e:  # noqa: BLE001 — scenario verdict
            _log(f"{name}: ERROR {type(e).__name__}: {e}")
            results.append({"name": name, "seed": seed, "passed": False,
                            "error": f"{type(e).__name__}: {e}",
                            "gates": {"completed": False}})
    return {
        "seed": seed,
        "scenarios": results,
        "passed": all(r["passed"] for r in results),
        # bench_diff-gated aggregates: worst-case recovery + goodput floor
        "max_recovery_ms": max(
            (r.get("recovery_ms", 0.0) for r in results), default=0.0),
        "min_goodput": min(
            (r["goodput"] for r in results if "goodput" in r), default=1.0),
    }


def main(argv: List[str]) -> int:
    seed = 0
    names: List[str] = []
    emit_json = False
    it = iter(argv)
    for a in it:
        if a == "--seed":
            seed = int(next(it))
        elif a == "--json":
            emit_json = True
        elif a in SCENARIOS:
            names.append(a)
        else:
            print(f"unknown scenario {a!r}; known: {', '.join(SCENARIOS)}",
                  file=sys.stderr)
            return 2
    out = run_scenarios(names or None, seed=seed)
    if emit_json:
        print(json.dumps(out, indent=2, default=str))
    else:
        for r in out["scenarios"]:
            status = "PASS" if r["passed"] else "FAIL"
            extra = f" error={r['error']}" if "error" in r else ""
            print(f"{r['name']:<18} {status}"
                  f"  recovery={r.get('recovery_ms', '-')}ms"
                  f"  goodput={r.get('goodput', '-')}{extra}")
        print("chaos:", "PASS" if out["passed"] else "FAIL")
    return 0 if out["passed"] else 1


if __name__ == "__main__":
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main(sys.argv[1:]))
