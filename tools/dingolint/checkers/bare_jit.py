"""bare-jit: every persistent jit goes through ``sentinel_jit``.

PR 3 built the shape-bucketing ladders so steady-state serving never
recompiles; PR 5 made that a *monitored* invariant by wrapping every
persistent jitted entry point in ``sentinel_jit`` (obs/sentinel.py),
which counts calls / cache hits / traces and attributes each compile
stall to a kernel name and argument signature. A ``jax.jit`` or
``pallas_call`` that bypasses the sentinel is a blind spot: its
recompiles don't move ``xla.recompiles``, don't parent ``xla.compile``
spans into the victim's trace, and don't fail the steady-state-recompile
bench gates — the exact failure mode the sentinel exists to catch. Worse,
the bypass pattern that keeps appearing (``jax.jit(lambda ...)(args)``
inline) mints a FRESH jit wrapper per call, so it re-traces every time
and nothing ever reports it.

Rule: any ``jax.jit(...)`` call outside obs/sentinel.py is flagged. A
``pallas_call`` is fine when its enclosing function is sentinel-wrapped
(decorator form, or passed to a ``sentinel_jit(name, fn, ...)`` call in
the same module) — the sentinel then owns the whole traced body.
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.dingolint.callgraph import dotted_name
from tools.dingolint.core import Checker, Finding, Module, Repo

#: the sentinel's own module (the one place jax.jit is allowed)
_EXEMPT_MODULES = {"dingo_tpu.obs.sentinel"}


def _sentinel_wrapped_names(module: Module) -> Set[str]:
    """Local function names owned by a sentinel: decorated with
    ``@sentinel_jit(...)`` or passed (possibly via an inner ``shard_map``
    call) to a ``sentinel_jit(name, fn, ...)`` call form. Only DIRECT
    positional args (and the positional args of one nested call, the
    shard_map idiom) count — harvesting every Name in the expression
    would mark sharding constructors and kwarg values as "wrapped" and
    quietly exempt same-named functions from the rule."""
    wrapped: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                parts = dotted_name(target)
                if parts and parts[-1] == "sentinel_jit":
                    wrapped.add(node.name)
        elif isinstance(node, ast.Call):
            parts = dotted_name(node.func)
            if not parts or parts[-1] != "sentinel_jit":
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    wrapped.add(arg.id)
                elif isinstance(arg, ast.Call):
                    for inner in arg.args:
                        if isinstance(inner, ast.Name):
                            wrapped.add(inner.id)
    return wrapped


def _jit_names(module: Module) -> Set[str]:
    """Local names that ARE jax.jit: ``from jax import jit [as j]``."""
    names: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "jit":
                    names.add(alias.asname or alias.name)
    return names


class BareJitChecker(Checker):
    name = "bare-jit"
    description = ("jax.jit / pallas_call outside sentinel_jit escapes "
                   "recompile accounting")

    def check_module(self, module: Module, repo: Repo) -> List[Finding]:
        if module.name in _EXEMPT_MODULES:
            return []
        wrapped = _sentinel_wrapped_names(module)
        jit_aliases = _jit_names(module)
        out: List[Finding] = []
        jit_msg = (
            "bare jax.jit — wrap it in sentinel_jit "
            "(obs/sentinel.py) so its traces land in the "
            "xla.recompiles accounting; an inline "
            "jax.jit(fn)(args) additionally re-traces on every "
            "call (fresh wrapper, cold cache)"
        )
        # decorator form: @jax.jit / @jit / @jax.jit(...) on a def
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                parts = dotted_name(target)
                if not parts:
                    continue
                is_jit = (parts[-1] == "jit"
                          and ((len(parts) >= 2 and parts[-2] == "jax")
                               or (len(parts) == 1
                                   and parts[0] in jit_aliases)))
                if is_jit:
                    f = module.finding(self.name, dec, jit_msg)
                    if f:
                        out.append(f)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            parent = module.parent(node)
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node in parent.decorator_list:
                continue  # call-form decorator: handled above
            parts = dotted_name(node.func)
            if not parts:
                continue
            tail = parts[-1]
            if tail == "jit" and (
                    (len(parts) >= 2 and parts[-2] == "jax")
                    or (len(parts) == 1 and parts[0] in jit_aliases)):
                f = module.finding(self.name, node, jit_msg)
                if f:
                    out.append(f)
            elif tail == "pallas_call":
                fn = module.enclosing_function(node)
                if fn is not None and fn.name in wrapped:
                    continue
                f = module.finding(
                    self.name, node,
                    "pallas_call outside a sentinel_jit-wrapped function "
                    "— the kernel's compiles escape the recompile "
                    "sentinel; decorate the enclosing function with "
                    "@sentinel_jit(...)",
                )
                if f:
                    out.append(f)
        return out
