"""Module-tagged structured logging with runtime level control.

Reference: src/common/logging.h — glog wrappers emitting module tags like
`[raft.apply][region(N)] ...`, plus the NodeService log-level RPC
(src/server/node_service.h) so operators can flip verbosity on a live
node. Here the same surface rides Python `logging`:

    log = get_logger("raft.core")            # logger "dingo.raft.core"
    log.info("...")                          # [raft.core] ...
    rlog = region_log(log, region_id=7)      # [raft.core][region(7)] ...
    set_level("DEBUG")                       # whole tree at runtime
    set_level("INFO", module="raft")         # one subtree

Every logger lives under the "dingo" root; one stderr handler renders
`HH:MM:SS.mmm LEVEL [module][region(N)] message`. Default level is
WARNING so library users see problems and nothing else; servers/tests
raise it via set_level or the DINGO_LOG env var.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Dict, Optional

_ROOT = "dingo"
_LEVELS = ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL")
_configured = False
_config_lock = threading.Lock()


class _TagFormatter(logging.Formatter):
    """`HH:MM:SS.mmm LEVEL [module][region(N)] message`."""

    def format(self, record: logging.LogRecord) -> str:
        module = record.name
        if module.startswith(_ROOT + "."):
            module = module[len(_ROOT) + 1:]
        elif module == _ROOT:
            module = "core"
        tag = f"[{module}]"
        region = getattr(record, "region_id", None)
        if region is not None:
            tag += f"[region({region})]"
        when = self.formatTime(record, "%H:%M:%S")
        s = (f"{when}.{int(record.msecs):03d} {record.levelname} "
             f"{tag} {record.getMessage()}")
        if record.exc_info:
            s += "\n" + self.formatException(record.exc_info)
        return s


def _ensure_configured() -> None:
    global _configured
    if _configured:
        return
    with _config_lock:
        if _configured:
            return
        root = logging.getLogger(_ROOT)
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_TagFormatter())
        root.addHandler(handler)
        root.propagate = False
        env = os.environ.get("DINGO_LOG", "").upper()
        root.setLevel(env if env in _LEVELS else logging.WARNING)
        _configured = True


def get_logger(module: str) -> logging.Logger:
    """Logger tagged `[module]` (dotted subtags control subtrees)."""
    _ensure_configured()
    return logging.getLogger(f"{_ROOT}.{module}")


class _RegionAdapter(logging.LoggerAdapter):
    def process(self, msg, kwargs):
        kwargs.setdefault("extra", {})["region_id"] = self.extra["region_id"]
        return msg, kwargs


def region_log(log: logging.Logger, region_id: int) -> logging.LoggerAdapter:
    """`[module][region(N)]`-tagged view of a module logger."""
    return _RegionAdapter(log, {"region_id": region_id})


def set_level(level: str, module: Optional[str] = None) -> None:
    """Runtime level control (NodeService log-level RPC backend).
    module=None (or "dingo") sets the whole tree; a dotted module sets
    that subtree. Accepts both bare ("raft.core") and "dingo."-prefixed
    names so get_levels() output pastes back in."""
    _ensure_configured()
    level = level.upper()
    if level not in _LEVELS:
        raise ValueError(f"unknown log level {level!r} (use {_LEVELS})")
    if module and module.startswith(_ROOT + "."):
        module = module[len(_ROOT) + 1:]
    if module in (None, "", _ROOT):
        name = _ROOT
    else:
        name = f"{_ROOT}.{module}"
    logging.getLogger(name).setLevel(level)


def get_levels() -> Dict[str, str]:
    """Effective levels of every live dingo logger (introspection)."""
    _ensure_configured()
    out = {}
    root = logging.getLogger(_ROOT)
    out[_ROOT] = logging.getLevelName(root.getEffectiveLevel())
    for name, logger in list(logging.Logger.manager.loggerDict.items()):
        if name.startswith(_ROOT + ".") and isinstance(
                logger, logging.Logger):
            out[name] = logging.getLevelName(logger.getEffectiveLevel())
    return out
