"""Vector-index snapshot peer transfer, coprocessor expressions, scan
sessions over grpc."""

import time

import numpy as np
import pytest

from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.coprocessor.expr import Expr, ExprError, ExprFilter
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.raft import LocalTransport
from dingo_tpu.server import pb
from dingo_tpu.server.rpc import DingoServer, ServiceStub
from dingo_tpu.store.node import StoreNode
from dingo_tpu.store.region import RegionType


# ---------------- expression VM ----------------


def test_expr_eval_basics():
    e = Expr(["and",
              ["ge", ["field", "age"], ["const", 21]],
              ["in", ["field", "color"], ["const", ["red", "blue"]]]])
    assert e.matches({"age": 30, "color": "red"})
    assert not e.matches({"age": 18, "color": "red"})
    assert not e.matches({"age": 30, "color": "green"})
    assert not e.matches({"color": "red"})  # null age -> filtered


def test_expr_arithmetic_and_not():
    e = Expr(["gt", ["mul", ["field", "w"], ["const", 2]], ["const", 10]])
    assert e.matches({"w": 6})
    assert not e.matches({"w": 5})
    n = Expr(["not", ["eq", ["field", "x"], ["const", 1]]])
    assert n.matches({"x": 2})
    assert Expr(["is_null", ["field", "missing"]]).matches({})


def test_expr_validation():
    with pytest.raises(ExprError):
        Expr(["bogus_op", ["const", 1]])
    with pytest.raises(ExprError):
        Expr(["eq", ["const", 1]])


def test_expr_filter_in_scan():
    """ExprFilter plugs into the scalar-filter slots (TABLE filter mode)."""
    from dingo_tpu.engine.mono_engine import MonoStoreEngine
    from dingo_tpu.engine.storage import Storage
    from dingo_tpu.store.region import Region, RegionDefinition

    region = Region(RegionDefinition(
        region_id=1,
        start_key=vcodec.encode_vector_key(0, 0),
        end_key=vcodec.encode_vector_key(0, 1 << 30),
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(index_type=IndexType.FLAT, dimension=4),
    ))
    w = region.vector_index_wrapper
    w.build_own()
    w.set_own(w.own_index)
    storage = Storage(MonoStoreEngine(MemEngine()))
    x = np.eye(4, dtype=np.float32)[[0, 1, 2, 3]]
    storage.vector_add(region, np.arange(4, dtype=np.int64), x,
                       [{"v": i} for i in range(4)])
    rows = storage.vector_scan_query(
        region, start_id=0, limit=10,
        scalar_filter=ExprFilter(["ge", ["field", "v"], ["const", 2]]),
        with_scalar_data=True,
    )
    assert [r.id for r in rows] == [2, 3]


# ---------------- snapshot transfer + scan sessions ----------------


@pytest.fixture()
def cluster(tmp_path):
    transport = LocalTransport()
    coord = CoordinatorControl(MemEngine(), replication=2)
    nodes, servers, addrs = {}, [], {}
    for i, sid in enumerate(["s0", "s1"]):
        n = StoreNode(sid, transport, coord, raft_kw={"seed": i},
                      snapshot_root=str(tmp_path / sid))
        srv = DingoServer()
        srv.host_store_role(n)
        port = srv.start()
        n.start_heartbeat(0.1)
        nodes[sid] = n
        addrs[sid] = f"127.0.0.1:{port}"
        servers.append(srv)
    yield coord, nodes, addrs
    for s in servers:
        s.stop()
    for n in nodes.values():
        n.stop()


def test_snapshot_peer_pull(cluster):
    coord, nodes, addrs = cluster
    d = coord.create_region(
        start_key=vcodec.encode_vector_key(0, 0),
        end_key=vcodec.encode_vector_key(0, 1 << 30),
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(index_type=IndexType.FLAT, dimension=8),
    )
    time.sleep(1.0)
    leader = next(n for n in nodes.values()
                  if (rn := n.engine.get_node(d.region_id)) and rn.is_leader())
    follower_id = next(s for s in nodes if nodes[s] is not leader)
    region = leader.get_region(d.region_id)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((40, 8)).astype(np.float32)
    leader.storage.vector_add(region, np.arange(40, dtype=np.int64), x)
    leader.index_manager.save_index(region)

    follower = nodes[follower_id]
    # wipe the follower's in-memory index to simulate a cold peer
    freg = follower.get_region(d.region_id)
    freg.vector_index_wrapper.ready = False
    freg.vector_index_wrapper.own_index = None
    assert follower.pull_vector_index_snapshot(
        d.region_id, addrs[next(s for s in nodes if nodes[s] is leader)]
    )
    assert freg.vector_index_wrapper.own_index.get_count() == 40
    res = freg.vector_index_wrapper.search(x[:2], 1)
    assert [r.ids[0] for r in res] == [0, 1]


def test_file_service_rejects_escape(cluster):
    coord, nodes, addrs = cluster
    import grpc

    stub = ServiceStub(grpc.insecure_channel(addrs["s0"]), "FileService")
    resp = stub.ReadFileChunk(pb.FileChunkRequest(
        region_id=1, name="../../../etc/passwd"
    ))
    assert resp.error.errcode == 90003


def test_scan_sessions_over_grpc(cluster):
    coord, nodes, addrs = cluster
    d = coord.create_region(start_key=b"a", end_key=b"z")
    time.sleep(1.0)
    leader = next(n for n in nodes.values()
                  if (rn := n.engine.get_node(d.region_id)) and rn.is_leader())
    region = leader.get_region(d.region_id)
    kvs = [(f"k{i:03d}".encode(), f"v{i}".encode()) for i in range(25)]
    leader.storage.kv_put(region, kvs)
    import grpc

    sid = next(s for s in nodes if nodes[s] is leader)
    stub = ServiceStub(grpc.insecure_channel(addrs[sid]), "StoreService")
    req = pb.KvScanBeginRequest()
    req.context.region_id = d.region_id
    req.range.start_key = b"k"
    req.range.end_key = b"l"
    req.page_size = 10
    r1 = stub.KvScanBegin(req)
    assert len(r1.kvs) == 10 and r1.has_more
    r2 = stub.KvScanContinue(pb.KvScanContinueRequest(scan_id=r1.scan_id))
    assert len(r2.kvs) == 10 and r2.has_more
    r3 = stub.KvScanContinue(pb.KvScanContinueRequest(scan_id=r1.scan_id))
    assert len(r3.kvs) == 5 and not r3.has_more
    got = [kv.key for kv in list(r1.kvs) + list(r2.kvs) + list(r3.kvs)]
    assert got == [k for k, _ in kvs]
    # released on exhaustion: continue now errors
    r4 = stub.KvScanContinue(pb.KvScanContinueRequest(scan_id=r1.scan_id))
    assert r4.error.errcode == 10010


def test_scan_snapshot_isolated_from_writes(cluster):
    """Regression: pages must come from the open-time snapshot even when
    keys are inserted/deleted between pages."""
    coord, nodes, addrs = cluster
    d = coord.create_region(start_key=b"m", end_key=b"n")
    time.sleep(1.0)
    leader_sid = next(s for s, n in nodes.items()
                      if (rn := n.engine.get_node(d.region_id))
                      and rn.is_leader())
    leader = nodes[leader_sid]
    region = leader.get_region(d.region_id)
    leader.storage.kv_put(region, [(b"m%02d" % i, b"v") for i in range(10)])
    import grpc

    stub = ServiceStub(grpc.insecure_channel(addrs[leader_sid]), "StoreService")
    req = pb.KvScanBeginRequest()
    req.context.region_id = d.region_id
    req.range.start_key = b"m"
    req.range.end_key = b"n"
    req.page_size = 4
    r1 = stub.KvScanBegin(req)
    # mutate between pages: insert before the cursor + delete ahead of it
    leader.storage.kv_put(region, [(b"m000", b"new")])
    leader.storage.kv_batch_delete(region, [b"m07"])
    r2 = stub.KvScanContinue(pb.KvScanContinueRequest(scan_id=r1.scan_id))
    r3 = stub.KvScanContinue(pb.KvScanContinueRequest(scan_id=r1.scan_id))
    got = [kv.key for kv in list(r1.kvs) + list(r2.kvs) + list(r3.kvs)]
    assert got == [b"m%02d" % i for i in range(10)]  # open-time snapshot


def test_pull_rejects_traversal_names(cluster, monkeypatch):
    """Regression: peer-supplied snapshot file names must not escape."""
    coord, nodes, addrs = cluster
    d = coord.create_region(
        start_key=vcodec.encode_vector_key(9, 0),
        end_key=vcodec.encode_vector_key(9, 1 << 20),
        partition_id=9,
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(index_type=IndexType.FLAT, dimension=4),
    )
    time.sleep(1.0)
    from dingo_tpu.server.rpc import ServiceStub as _SS

    class EvilMeta:
        class error:
            errcode = 0
        snapshot_log_id = 1
        class _F:
            name = "../evil"
            size = 1
        files = [_F()]

    real_init = _SS.__init__

    def fake_init(self, channel, service):
        real_init(self, channel, service)
        if service == "NodeService":
            self.GetVectorIndexSnapshotMeta = lambda req: EvilMeta()

    monkeypatch.setattr(_SS, "__init__", fake_init)
    follower = nodes["s1"]
    assert not follower.pull_vector_index_snapshot(d.region_id, addrs["s0"])
