"""Network backup/restore: BR fan-out over the grpc services.

Reference: src/br/ — the br binary is a CLIENT: it walks the coordinator's
region map, fans backup RPCs to every store through an InteractionManager,
writes per-region artifacts plus a backupmeta, and restores by re-creating
regions and pushing the data back. This module is that client over
dingo-tpu's RPC surface (RegionExport/RegionImport on RegionControlService,
meta via MetaService/coordinator RPCs).

Resumability (reference br's progress tracking): `progress.json` in the
backup dir records every region's terminal state and is rewritten
atomically after each region completes. A re-run with resume=True skips
regions whose artifact exists with the recorded size+checksum and finishes
the rest — a crashed multi-hour backup of a big cluster loses at most one
region's work.
"""

from __future__ import annotations

import json
import os
import secrets
import time
from typing import Dict, List, Optional

from dingo_tpu.raft.wire import blob_checksum as _crc
from dingo_tpu.server import pb

_CHUNK = 1 << 20


class BrError(RuntimeError):
    pass


def _atomic_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
    os.replace(tmp, path)


class RemoteBr:
    """Backup/restore driver over a DingoClient."""

    def __init__(self, client, path: str):
        self.client = client
        self.path = path
        self.progress_path = os.path.join(path, "progress.json")

    # -- backup --------------------------------------------------------------
    def _load_progress(self) -> Dict[str, dict]:
        if os.path.exists(self.progress_path):
            with open(self.progress_path) as f:
                return json.load(f)
        return {}

    def _region_done(self, entry: Optional[dict]) -> bool:
        """An entry counts as done only if its artifact still matches."""
        if not entry or entry.get("status") != "done":
            return False
        fp = os.path.join(self.path, entry["file"])
        if not os.path.exists(fp) or os.path.getsize(fp) != entry["bytes"]:
            return False
        with open(fp, "rb") as f:
            return _crc(f.read()) == entry["checksum"]

    def _pull_region(self, definition) -> bytes:
        """Chunked RegionExport from the leader (falls back through peers
        via the client's NotLeader-aware routing). The export_id pins the
        whole pull to ONE server-side snapshot blob."""
        blob = bytearray()
        export_id = 0
        while True:
            req = pb.RegionExportRequest(
                region_id=definition.region_id, offset=len(blob),
                max_bytes=_CHUNK, export_id=export_id,
            )
            resp = self.client._call_leader(
                definition, "RegionControlService", "RegionExport", req)
            if resp.error.errcode:
                raise BrError(f"export region {definition.region_id}: "
                              f"{resp.error.errmsg}")
            export_id = resp.export_id
            blob.extend(resp.data)
            if resp.eof:
                if _crc(bytes(blob)) != resp.checksum:
                    raise BrError(
                        f"export region {definition.region_id}: torn "
                        "download (checksum mismatch)")
                return bytes(blob)

    def backup(self, resume: bool = True) -> dict:
        """Fan out over every region in the coordinator's map. Returns the
        manifest. Safe to re-run after a crash: completed regions are
        skipped when their artifacts verify."""
        os.makedirs(self.path, exist_ok=True)
        progress = self._load_progress() if resume else {}
        self.client.refresh_region_map()
        regions = list(self.client._regions)
        manifest = {
            "created_ms": int(time.time() * 1000),
            "regions": [],
            "tso_watermark": None,
            "schemas": [],
            "tables": [],
        }
        for definition in regions:
            rid = str(definition.region_id)
            entry = progress.get(rid)
            if self._region_done(entry):
                manifest["regions"].append(entry)
                continue
            blob = self._pull_region(definition)
            fname = f"region_{definition.region_id}.data"
            with open(os.path.join(self.path, fname), "wb") as f:
                f.write(blob)
            from dingo_tpu.server.convert import region_def_to_pb

            entry = {
                "status": "done",
                "region_id": definition.region_id,
                "file": fname,
                "bytes": len(blob),
                "checksum": _crc(blob),
                "definition_pb": region_def_to_pb(
                    definition).SerializeToString().hex(),
            }
            progress[rid] = entry
            _atomic_json(self.progress_path, progress)   # resume point
            manifest["regions"].append(entry)
        # meta group (schema/table defs + TSO watermark), via RPCs. Tables
        # travel as serialized TableDef pbs so restore re-registers the
        # FULL definition (columns, index params), not a summary.
        try:
            manifest["schemas"] = self.client.get_schemas()
            tables = []
            for schema in manifest["schemas"]:
                resp = self.client.meta.GetTables(
                    pb.GetTablesRequest(schema_name=schema))
                if resp.error.errcode:
                    raise BrError(resp.error.errmsg)
                tables += [
                    {"schema": schema, "name": d.name,
                     "definition_pb": d.SerializeToString().hex()}
                    for d in resp.definitions
                ]
            manifest["tables"] = tables
        except Exception as e:  # noqa: BLE001 — meta role may be absent
            manifest["meta_error"] = str(e)
        try:
            manifest["tso_watermark"] = self.client.tso(1)
        except Exception as e:  # noqa: BLE001
            manifest["tso_error"] = str(e)
        _atomic_json(os.path.join(self.path, "backupmeta.json"), manifest)
        return manifest

    # -- restore -------------------------------------------------------------
    def _push_region(self, definition, blob: bytes, peers: List[str]) -> int:
        """Chunked RegionImport into the region's raft LEADER — the install
        rides the raft log from there, so followers converge through
        replication (pushing each peer directly would race concurrent raft
        traffic and fork replicas). NotLeader rotates to the next peer.
        Returns 1 on success."""
        if not peers:
            raise BrError(
                f"import region {definition.region_id}: no hosting peers")
        crc = _crc(blob)   # once — not per chunk
        self._last_push_err = "all peers answered NotLeader"
        deadline = time.monotonic() + 15.0
        while True:
            n = self._push_region_once(definition, blob, crc, peers)
            if n is not None:
                return n
            # freshly created region may still be electing: retry rotation
            if time.monotonic() >= deadline:
                break
            time.sleep(0.25)
        raise BrError(
            f"import region {definition.region_id}: no leader accepted "
            f"the install (last: {self._last_push_err})")

    def _push_region_once(self, definition, blob: bytes, crc: int,
                          peers: List[str]):
        """One rotation over peers; returns 1 on success, None if every
        peer answered NotLeader (election in progress — caller retries)."""
        for store_id in peers:
            stub = self.client._stub(store_id, "RegionControlService")
            import_id = secrets.randbits(62)   # isolates concurrent pushes
            offset = 0
            while True:
                chunk = blob[offset:offset + _CHUNK]
                offset_next = offset + len(chunk)
                req = pb.RegionImportRequest(
                    region_id=definition.region_id, offset=offset,
                    data=chunk, commit=offset_next >= len(blob),
                    total_bytes=len(blob), checksum=crc,
                    import_id=import_id,
                )
                resp = stub.RegionImport(req)
                if resp.error.errcode == 20001:   # NotLeader: try next peer
                    self._last_push_err = f"{store_id}: {resp.error.errmsg}"
                    break
                if resp.error.errcode:
                    raise BrError(
                        f"import region {definition.region_id} on "
                        f"{store_id}: {resp.error.errmsg}")
                offset = offset_next
                if offset >= len(blob):
                    return 1
        return None

    def restore(self, wait_s: float = 10.0) -> int:
        """Re-create every backed-up region through the coordinator and
        push its data to each region's raft leader (the install replicates
        to followers through the log). Returns regions restored."""
        from dingo_tpu.server import convert

        with open(os.path.join(self.path, "backupmeta.json")) as f:
            manifest = json.load(f)
        restored = 0
        region_id_map: Dict[int, int] = {}
        for entry in manifest["regions"]:
            m = pb.RegionDefinition()
            m.ParseFromString(bytes.fromhex(entry["definition_pb"]))
            old = convert.region_def_from_pb(m)
            req = pb.CreateRegionRequest()
            req.range.start_key = old.start_key
            req.range.end_key = old.end_key
            req.partition_id = old.partition_id
            req.region_type = m.region_type
            if m.index_parameter.index_type != 0:
                req.index_parameter.CopyFrom(m.index_parameter)
            resp = self.client.coordinator.CreateRegion(req)
            if resp.error.errcode:
                raise BrError(f"create region for backup "
                              f"{entry['region_id']}: {resp.error.errmsg}")
            created_id = resp.definition.region_id
            peers = list(resp.definition.peers)
            # wait until every peer materialized the region (heartbeat
            # delivery), probing RegionDetail on each
            deadline = time.monotonic() + wait_s
            while time.monotonic() < deadline:
                ready = 0
                for store_id in peers:
                    stub = self.client._stub(store_id,
                                             "RegionControlService")
                    d = stub.RegionDetail(
                        pb.RegionDetailRequest(region_id=created_id))
                    if d.error.errcode == 0:
                        ready += 1
                if ready == len(peers):
                    break
                time.sleep(0.05)
            else:
                raise BrError(f"region {created_id} never materialized on "
                              f"all peers {peers}")
            with open(os.path.join(self.path, entry["file"]), "rb") as f:
                blob = f.read()
            if _crc(blob) != entry["checksum"]:
                raise BrError(f"backup artifact {entry['file']} corrupt")
            self.client.refresh_region_map()
            definition = next(
                d for d in self.client._regions
                if d.region_id == created_id
            )
            region_id_map[entry["region_id"]] = created_id
            if self._push_region(definition, blob, peers):
                restored += 1
        self._restore_meta(manifest, region_id_map)
        return restored

    def _restore_meta(self, manifest: dict,
                      region_id_map: Dict[int, int]) -> None:
        """Re-register schemas + table definitions (partition region ids
        remapped to the re-created regions) and advance the TSO above the
        backed-up watermark — mirrors the local restore_cluster path."""
        for schema in manifest.get("schemas", []):
            resp = self.client.meta.CreateSchema(
                pb.CreateSchemaRequest(schema_name=schema))
            if resp.error.errcode == 40002:   # built-in / already present
                continue
            if resp.error.errcode:
                raise BrError(
                    f"restore schema {schema!r}: {resp.error.errmsg}")
        for t in manifest.get("tables", []):
            d = pb.TableDef()
            d.ParseFromString(bytes.fromhex(t["definition_pb"]))
            for p in d.partitions:
                p.region_id = region_id_map.get(p.region_id, p.region_id)
            resp = self.client.meta.ImportTable(
                pb.ImportTableRequest(definition=d))
            if resp.error.errcode == 40002:
                # genuine name collision in the target cluster: skip, like
                # the local restore path — any OTHER error is a failed
                # restore and must not be silently dropped
                continue
            if resp.error.errcode:
                raise BrError(f"restore table {d.name!r}: {resp.error.errmsg}")
        watermark = manifest.get("tso_watermark")
        if watermark:
            resp = self.client.coordinator.TsoAdvance(
                pb.TsoAdvanceRequest(ts=int(watermark)))
            if resp.error.errcode:
                raise BrError(f"tso advance: {resp.error.errmsg}")
