"""StoreMetricsCollector: crontab-driven per-region metrics snapshots.

Reference: StoreMetricsManager (src/metrics/store_metrics_manager.{h,cc}) —
CollectStoreRegionMetrics on a crontab, region sizes from the engine,
vector-index state from the wrappers, shipped in every StoreHeartbeat.
Here additionally: device/HBM accounting (live jax.Array bytes per index +
process-level allocator gauges), which the C++ reference has no analog for.

Every figure is double-published:
- into the process MetricsRegistry (region-labeled gauges — /vars,
  /metrics exposition, tools/metrics_report.py), and
- as a StoreMetricsSnapshot cached on the collector, attached to the next
  heartbeat so the coordinator aggregates cluster-wide state.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from dingo_tpu.common.log import get_logger
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.engine.raw_engine import CF_DEFAULT
from dingo_tpu.metrics.snapshot import (
    RegionMetricsSnapshot,
    StoreMetricsSnapshot,
)
from dingo_tpu.obs.flight import FLIGHT
from dingo_tpu.obs.hbm import HBM

_log = get_logger("metrics.collector")

#: bytes estimation samples at most this many kvs per region per tick,
#: then extrapolates by key count (a full scan would be O(dataset) per tick)
SIZE_SAMPLE_KVS = 1024


class StoreMetricsCollector:
    def __init__(self, node, registry=METRICS):
        self.node = node
        self.registry = registry
        self._lock = threading.Lock()
        self._latest: Optional[StoreMetricsSnapshot] = None
        self._latest_mono: float = 0.0
        #: region ids whose gauges were published last pass — the delta
        #: against the current pass drives registry series cleanup
        self._published_regions: set = set()
        self.collect_total = 0
        self.collect_errors = 0

    # ---------------- public API ----------------
    @property
    def latest(self) -> Optional[StoreMetricsSnapshot]:
        with self._lock:
            return self._latest

    def maybe_collect(self, max_age_s: float = 0.0) -> StoreMetricsSnapshot:
        """Return the cached snapshot if younger than max_age_s, else
        collect now (heartbeats without a metrics crontab stay fresh)."""
        with self._lock:
            fresh = (
                self._latest is not None
                and time.monotonic() - self._latest_mono <= max_age_s
            )
            if fresh:
                return self._latest
        return self.collect()

    def collect(self) -> StoreMetricsSnapshot:
        """One collection pass over every hosted region. Never raises —
        a collector bug must not kill the heartbeat/crontab. A FAILED pass
        keeps (and returns) the last good snapshot: shipping the partial,
        near-empty one would zero the coordinator's view of this store and
        make load-aware balancing move leaders TOWARD the malfunction."""
        node = self.node
        snap = StoreMetricsSnapshot(
            store_id=node.store_id,
            collected_at_ms=int(time.time() * 1000),
        )
        ok = True
        try:
            # one allocator query serves both the snapshot and the hbm
            # watermark gauges (the hbm.watermark_interval_s crontab
            # polls between passes)
            dev = HBM.poll_process()
            snap.device_bytes_in_use = dev["bytes_in_use"]
            snap.device_bytes_limit = dev["bytes_limit"]
            snap.device_peak_bytes = dev["peak_bytes_in_use"]
            snap.engine_key_count = node.raw.count(CF_DEFAULT)
            for region in node.meta.get_all_regions():
                try:
                    snap.regions.append(self._collect_region(region))
                except Exception:  # noqa: BLE001
                    self.collect_errors += 1
                    _log.exception("collect failed for region %d", region.id)
            # control-plane flight recorder (obs/events.py): harvest the
            # decision events emitted since the last beat — each ships
            # exactly once; a failed pass before this point leaves them
            # pending for the next one
            from dingo_tpu.obs.events import EVENTS

            evs = EVENTS.harvest(node_id=node.store_id)
            if evs:
                snap.events = list(evs)
                self.registry.gauge("event.heartbeat_bytes").set(sum(
                    len(e.actor) + len(e.knob) + len(e.old) + len(e.new)
                    + len(e.trigger) + len(e.evidence) + len(e.node_id)
                    + len(e.trace_id) + len(e.flight_bundle_id) + 24
                    for e in evs))
            self._publish(snap)
        except Exception:  # noqa: BLE001
            ok = False
            self.collect_errors += 1
            _log.exception("store metrics collection failed")
        with self._lock:
            if ok or self._latest is None:
                self._latest = snap
            # pace retries either way — a persistently failing pass must
            # not burn a full sweep attempt on every single heartbeat
            self._latest_mono = time.monotonic()
            self.collect_total += 1
            latest = self._latest
        # feed the flight recorder's metric-delta ring OUTSIDE the lock
        # (tick dumps the whole registry; bundles diff against it)
        FLIGHT.tick()
        return latest

    # ---------------- per-region ----------------
    def _collect_region(self, region) -> RegionMetricsSnapshot:
        node = self.node
        rm = RegionMetricsSnapshot(region_id=region.id)
        # data-CF keys are memcomparable mvcc-encoded (user_key + ts) —
        # bounds must encode the same way or the range misses everything.
        # Counts are MVCC versions, not live user keys: cheap (engine
        # count, no value decode) and GC keeps the two converging
        from dingo_tpu.mvcc.codec import Codec

        start = Codec.encode_bytes(region.definition.start_key)
        end = (Codec.encode_bytes(region.definition.end_key)
               if region.definition.end_key else None)
        rm.key_count = node.raw.count(CF_DEFAULT, start, end)
        rm.approximate_bytes = self._approximate_bytes(
            start, end, rm.key_count
        )
        raft = node.engine.get_node(region.id)
        if raft is not None:
            rm.is_leader = raft.is_leader()
            rm.apply_lag = max(0, raft.commit_index - raft.last_applied)
        wrapper = region.vector_index_wrapper
        if wrapper is not None:
            rm.index_ready = wrapper.is_ready()
            rm.index_build_error = wrapper.build_error
            rm.index_building = (
                wrapper.is_switching
                or region.id in node.index_manager._rebuilding
            )
            rm.index_apply_log_id = wrapper.apply_log_id
            rm.index_snapshot_log_id = wrapper.snapshot_log_id
            try:
                rm.vector_count = wrapper.get_count()
                rm.vector_memory_bytes = wrapper.get_memory_size()
            except Exception:  # noqa: BLE001 — index mid-build
                pass
            # own index only — a post-split share serves from the PARENT's
            # arrays; counting them on both regions would double-book HBM.
            # One object-graph walk serves both figures: the ledger's
            # owner attribution sums to the index's live device bytes
            # (shared dedup set + 'other' remainder root), so the total
            # comes from the same pass instead of a second walk
            owners = HBM.account_index(region.id, wrapper)
            rm.device_memory_bytes = (
                sum(owners.values()) if owners
                else wrapper.get_device_memory_size()  # share/mid-build
            )
            rm.device_peak_bytes = HBM.region_peak(region.id)
        if region.document_index is not None:
            rm.document_count = region.document_index.count()
        rm.search_qps = self.registry.latency(
            "vector_search", region.id
        ).windowed_qps()
        # live quality estimate (obs/quality.py): rides the heartbeat so
        # the coordinator's rollups/cluster top can see recall per region
        from dingo_tpu.obs.quality import QUALITY

        est = QUALITY.region_estimate(region.id)
        if est is not None:
            rm.quality_recall = est["recall"]
            rm.quality_recall_ci_low = est["ci_low"]
            rm.quality_recall_ci_high = est["ci_high"]
            rm.quality_samples = int(est["queries"])
        # serving-pressure rollup (obs/pressure.py): queue depth, recent
        # queue-wait watermark, cumulative shed+expired — rides the same
        # heartbeat into the coordinator's QDEPTH/PRESS/SHED columns
        from dingo_tpu.obs.pressure import PRESSURE

        qs = PRESSURE.region_stats(region.id)
        rm.qos_queue_depth = int(qs["queue_depth"])
        rm.qos_queue_wait_ms = float(qs["queue_wait_ms"])
        rm.qos_shed_total = int(qs["shed_total"])
        rm.qos_degrade_level = int(self.registry.gauge(
            "qos.degrade_level", region.id).get())
        # state-integrity digest vector (obs/integrity.py), tagged with
        # the raft applied index it corresponds to — the coordinator
        # compares replicas at equal applied indices
        from dingo_tpu.obs.integrity import INTEGRITY

        own = wrapper.own_index if wrapper is not None else None
        applied, digests, mismatch = INTEGRITY.region_report(
            own, region_id=region.id
        )
        rm.integrity_applied_index = applied
        rm.integrity_digests = digests
        rm.integrity_mismatch = mismatch
        from dingo_tpu.index.recovery import RECOVERY

        rm.device_degraded = RECOVERY.is_degraded(region.id)
        # serving-edge cache rollup (dingo_tpu/cache/): hits/misses/live
        # entries ride the heartbeat into the cluster top CACHE column
        from dingo_tpu.cache.edge import CACHE

        cs = CACHE.region_stats(region.id)
        rm.cache_hits = int(cs["hits"])
        rm.cache_misses = int(cs["misses"])
        rm.cache_entries = int(cs["entries"])
        # workload-heat rollup (obs/heat.py): traffic concentration and
        # the working-set curve at the region's own tier — the capacity
        # plane's demand signal (touches == 0 => no evidence)
        from dingo_tpu.obs.cost import COST
        from dingo_tpu.obs.heat import HEAT

        hs = HEAT.region_stats(region.id)
        if hs is not None:
            rm.heat_hot_fraction = float(hs["hot_fraction"])
            rm.heat_gini = float(hs["gini"])
            rm.heat_working_set_p50 = int(hs["ws_bytes"][50])
            rm.heat_working_set_p90 = int(hs["ws_bytes"][90])
            rm.heat_working_set_p99 = int(hs["ws_bytes"][99])
            rm.heat_touches = int(hs["touches"])
        rm.cost_row_us = float(COST.region_row_us(region.id))
        # memory-tier ladder (index/tiering.py): the rung serving reads —
        # untracked regions report their resident precision's base rung
        from dingo_tpu.index.tiering import TIERING

        rm.serving_tier = TIERING.region_tier(
            region.id, getattr(own, "_precision", "") if own else ""
        )
        # control-plane flight recorder (obs/events.py): snapshot the
        # live overrides in force RIGHT NOW as compact JSON — `cluster
        # explain` reconciles these against the merged event timeline
        # (a live knob with no explaining event = orphan)
        from dingo_tpu.obs.events import events_enabled

        if events_enabled():
            ts = TIERING.state().get(region.id)
            advisory = self.registry.gauge(
                "qos.precision_advisory", region.id).get()
            rm.live_knobs = json.dumps({
                "tuning": dict(getattr(own, "tuning", None) or {}),
                "advisory_precision": "sq8" if advisory > 0 else "",
                "tier": rm.serving_tier,
                "tier_base": ts["base"] if ts else rm.serving_tier,
            }, sort_keys=True, separators=(",", ":"))
        last = INTEGRITY.last_verified_ms(region.id)
        self.registry.gauge(
            "consistency.digest_age_s", region.id
        ).set((time.time() * 1000 - last) / 1000.0 if last else -1.0)
        return rm

    def _approximate_bytes(self, start: bytes, end, key_count: int) -> int:
        """Sampled size estimate: sum the first SIZE_SAMPLE_KVS kv sizes in
        the range, extrapolate by key count (ApproximateSize analog —
        RocksDB answers from SST metadata; a sorted-dict engine samples)."""
        if key_count <= 0:
            return 0
        sampled = 0
        n = 0
        for k, v in self.node.raw.scan(CF_DEFAULT, start, end):
            sampled += len(k) + len(v)
            n += 1
            if n >= SIZE_SAMPLE_KVS:
                break
        if n == 0:
            return 0
        return int(sampled * (key_count / n))

    # ---------------- registry publication ----------------
    def _publish(self, snap: StoreMetricsSnapshot) -> None:
        # retire series of regions this store no longer hosts (deleted,
        # merged away, moved) — their gauges would otherwise report the
        # last values forever and scrapers would double-count moved HBM
        from dingo_tpu.obs.quality import QUALITY

        current = {rm.region_id for rm in snap.regions}
        for rid in self._published_regions - current:
            self.registry.drop_region(rid)
            HBM.forget_region(rid)
            QUALITY.forget_region(rid)
            from dingo_tpu.cache.edge import CACHE, CODECS
            from dingo_tpu.obs.integrity import INTEGRITY
            from dingo_tpu.obs.pressure import PRESSURE

            PRESSURE.forget_region(rid)
            INTEGRITY.forget_region(rid)
            CACHE.forget_region(rid)
            CODECS.forget_region(rid)
            from dingo_tpu.obs.cost import COST
            from dingo_tpu.obs.heat import HEAT

            HEAT.forget_region(rid)
            COST.forget_region(rid)
            # event ledger + tier ladder + cache stale-serving memo: a
            # departed region's decision history / rung / engage state
            # must not leak (tiering was missing from this sweep — a
            # region re-created with the same id would inherit its
            # predecessor's rung)
            from dingo_tpu.cache import policy as cache_policy
            from dingo_tpu.index.tiering import TIERING
            from dingo_tpu.obs.events import EVENTS

            EVENTS.forget_region(rid)
            TIERING.forget_region(rid)
            cache_policy.forget_region(rid)
        self._published_regions = current
        g = self.registry.gauge
        g("store.device.bytes_in_use").set(snap.device_bytes_in_use)
        g("store.device.bytes_limit").set(snap.device_bytes_limit)
        g("store.device.peak_bytes").set(snap.device_peak_bytes)
        g("store.engine.key_count").set(snap.engine_key_count)
        g("store.region_count").set(len(snap.regions))
        for rm in snap.regions:
            rid = rm.region_id
            g("store.region.key_count", rid).set(rm.key_count)
            g("store.region.approximate_bytes", rid).set(
                rm.approximate_bytes)
            g("store.region.vector_count", rid).set(rm.vector_count)
            g("store.region.vector_memory_bytes", rid).set(
                rm.vector_memory_bytes)
            g("store.region.device_memory_bytes", rid).set(
                rm.device_memory_bytes)
            # HBM bytes per resident vector: the precision-tier capacity
            # win (fp32 -> bf16 -> sq8) as one scrapeable number; an
            # emptied region reports 0, never its last live value
            g("store.region.device_bytes_per_vector", rid).set(
                rm.device_memory_bytes / rm.vector_count
                if rm.vector_count else 0.0)
            g("store.region.apply_lag", rid).set(rm.apply_lag)
            g("store.region.is_leader", rid).set(1.0 if rm.is_leader else 0.0)
            g("store.region.index_ready", rid).set(
                1.0 if rm.index_ready else 0.0)
            g("store.region.index_building", rid).set(
                1.0 if rm.index_building else 0.0)
            g("store.region.document_count", rid).set(rm.document_count)
            # scrapeable pressure watermark (the harvest the heartbeat
            # ships; the depth gauge itself is maintained live by the
            # coalescer's admit/dequeue accounting)
            g("qos.queue_wait_watermark_ms", rid).set(rm.qos_queue_wait_ms)
