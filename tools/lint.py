#!/usr/bin/env python
"""dingolint entry point: run the repo-native invariant checkers.

Usage:
    python tools/lint.py                  # human-readable report, exit 1
                                          # on unbaselined findings
    python tools/lint.py --json           # machine-readable (CI / diffing)
    python tools/lint.py --baseline-update  # rewrite baseline.json from
                                          # the current findings (existing
                                          # rationales preserved; new
                                          # entries get a TODO that fails
                                          # the lint until adjudicated)
    python tools/lint.py --checker bare-jit --checker host-sync

Exit status 0 iff: no unbaselined findings, no baseline entry without a
rationale. Stale baseline entries (their code was fixed) are warnings.
Wall time is always reported — the full-repo pass must stay under ~30s
to remain tier-1-viable (tests/test_dingolint.py asserts it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from tools.dingolint import baseline as bl  # noqa: E402
from tools.dingolint import checkers as reg  # noqa: E402
from tools.dingolint.core import REPO_ROOT, lint_repo  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite baseline.json from current findings")
    ap.add_argument("--checker", action="append", default=None,
                    help="run only the named checker(s)")
    ap.add_argument("--baseline", default=bl.BASELINE_PATH,
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    checkers = reg.by_name(args.checker) if args.checker else None
    repo, findings = lint_repo(args.root, checkers)
    base = bl.load(args.baseline)
    new, matched, unrationalized, stale = bl.split(findings, base)
    wall_s = time.monotonic() - t0

    if args.baseline_update:
        entries = bl.updated_entries(findings, base)
        if args.checker:
            # partial run: entries owned by checkers that did NOT run
            # carry over untouched — updating one checker's baseline must
            # never delete another's adjudications
            ran = {c.name for c in checkers}
            have = {e["fingerprint"] for e in entries}
            entries += [e for fp, e in base.items()
                        if e.get("checker") not in ran and fp not in have]
        bl.save(entries, args.baseline)
        todo = sum(1 for e in entries
                   if e["rationale"].startswith("TODO"))
        print(f"baseline updated: {len(entries)} entr"
              f"{'y' if len(entries) == 1 else 'ies'}, {todo} TODO "
              f"rationale(s) to adjudicate, {len(stale)} stale dropped "
              f"({wall_s:.1f}s)")
        return 0

    ok = not new and not unrationalized
    if args.as_json:
        print(json.dumps({
            "ok": ok,
            "files": len(repo.modules),
            "checkers": [c.name for c in (checkers
                                          or reg.all_checkers())],
            "wall_s": round(wall_s, 2),
            "findings": [f.to_json() for f in new],
            "baselined": [f.to_json() for f in matched],
            "unrationalized_baseline": unrationalized,
            "stale_baseline": stale,
        }, indent=2))
        return 0 if ok else 1

    for f in new:
        print(f.render(), file=sys.stderr)
    for e in unrationalized:
        print(f"baseline entry {e['fingerprint']} ({e['location']}) has "
              f"no rationale — adjudicate it or fix the code",
              file=sys.stderr)
    for e in stale:
        print(f"note: stale baseline entry {e['fingerprint']} "
              f"({e['location']}) no longer matches — run "
              f"--baseline-update to drop it")
    status = "OK" if ok else f"{len(new) + len(unrationalized)} problem(s)"
    print(f"dingolint: {status} — {len(repo.modules)} files, "
          f"{len(findings)} finding(s) ({len(matched)} baselined), "
          f"{wall_s:.1f}s wall")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
