"""Failover-aware client channel to the replicated coordinator group.

One rotation protocol shared by the SDK (client/client.py) and the store's
remote heartbeat (server/remote_heartbeat.py): hold the raft group's
endpoint list, rotate on NotLeader (errcode 20001) or connection-level
grpc failure, pause briefly between full rotations to ride out an
election.

Retry semantics: UNAVAILABLE / CANCELLED (request never served) and
DEADLINE_EXCEEDED (hung endpoint — rotating is the whole point of the
group) rotate and re-send; every other RpcError and every in-band
application error surfaces to the caller. Caveat a client cannot remove:
a re-sent call whose first attempt committed before the deadline makes
mutations at-least-once — idempotent coordinator ops (create returns
"exists", acks dedupe by cmd_id) absorb this; callers doing
non-idempotent mutations should treat an "exists" answer after a retry
as success.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Type

import grpc

from dingo_tpu.common.log import get_logger
from dingo_tpu.server.rpc import ServiceStub

_log = get_logger("coord_channel")

_ERR_NOT_LEADER = 20001

#: grpc codes that mean "never served here" — safe to rotate + retry
_ROTATE_CODES = (grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.CANCELLED)


class RotatingCoordinatorChannel:
    """Thread-safe; one instance backs every coordinator-side service stub
    so a failover discovered by one call benefits the rest."""

    def __init__(self, addrs: str, error_cls: Type[Exception],
                 timeout_s: float = 10.0, rounds: int = 3):
        self._addrs = [a.strip() for a in addrs.split(",") if a.strip()]
        if not self._addrs:
            raise error_cls("empty coordinator address list")
        self._error_cls = error_cls
        self._timeout_s = timeout_s
        self._rounds = rounds
        self._active = 0
        self._lock = threading.Lock()
        self._channel: Optional[grpc.Channel] = None
        self._stubs: Dict[str, ServiceStub] = {}
        self._connect(0)

    @property
    def addrs(self):
        return list(self._addrs)

    def _connect(self, idx: int) -> None:
        if self._channel is not None:
            self._channel.close()
        self._active = idx % len(self._addrs)
        self._channel = grpc.insecure_channel(self._addrs[self._active])
        self._stubs = {}

    def _stub_for(self, service: str):
        stub = self._stubs.get(service)
        if stub is None:
            stub = self._stubs[service] = ServiceStub(self._channel, service)
        return stub

    def _rotate_from(self, seen_active: int) -> None:
        """Advance past `seen_active` unless another thread already did —
        two threads failing on the same endpoint rotate once, not twice."""
        with self._lock:
            if self._active == seen_active:
                self._connect(seen_active + 1)
                _log.info("rotating coordinator endpoint -> %s",
                          self._addrs[self._active])

    def call(self, service: str, method: str, req,
             timeout_s: Optional[float] = None):
        """Invoke on the active endpoint with a deadline (a hung leader
        must not disable rotation). Application errors return in-band for
        the caller to interpret; exhaustion raises error_cls. The lock
        guards only channel state — a long-poll must not serialize other
        calls."""
        deadline = timeout_s if timeout_s is not None else self._timeout_s
        last_err = "no coordinator reachable"
        for round_i in range(self._rounds):
            for _ in range(len(self._addrs)):
                with self._lock:
                    stub = self._stub_for(service)
                    active = self._active
                try:
                    resp = getattr(stub, method)(req, timeout=deadline)
                except grpc.RpcError as e:
                    code = e.code() if hasattr(e, "code") else None
                    if code not in _ROTATE_CODES and \
                            code is not grpc.StatusCode.DEADLINE_EXCEEDED:
                        raise   # unknown failure: not safe to re-send
                    last_err = f"{self._addrs[active]}: {code}"
                    self._rotate_from(active)
                    continue
                err = getattr(resp, "error", None)
                if err is not None and err.errcode == _ERR_NOT_LEADER:
                    last_err = f"{self._addrs[active]}: {err.errmsg}"
                    self._rotate_from(active)
                    continue
                return resp
            if round_i < self._rounds - 1:
                time.sleep(0.2)   # election in progress
        raise self._error_cls(
            f"coordinator group: {method}: {last_err}")

    def close(self) -> None:
        with self._lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None
                self._stubs = {}
