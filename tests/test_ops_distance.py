"""Kernel-level numeric tests vs numpy reference.

Mirrors the reference's SIMD correctness suites
(test/unit_test/vector/test_vector_index_flat_simd.cc etc.): every distance
kernel is validated against a straightforward numpy implementation.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from dingo_tpu.ops import (
    Metric,
    pairwise_l2sqr,
    pairwise_inner_product,
    pairwise_cosine,
    pairwise_hamming,
    score_matrix,
    scores_to_distances,
    squared_norms,
)
from dingo_tpu.ops.topk import topk_scores, merge_topk, merge_sharded_topk


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    q = rng.standard_normal((7, 64), dtype=np.float32)
    x = rng.standard_normal((200, 64), dtype=np.float32)
    return q, x


def np_l2sqr(q, x):
    return ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)


def test_l2sqr_matches_numpy(data):
    q, x = data
    got = np.asarray(pairwise_l2sqr(jnp.array(q), jnp.array(x)))
    np.testing.assert_allclose(got, np_l2sqr(q, x), rtol=5e-3, atol=5e-2)


def test_l2sqr_with_cached_norms(data):
    q, x = data
    xs = squared_norms(jnp.array(x))
    got = np.asarray(pairwise_l2sqr(jnp.array(q), jnp.array(x), xs))
    np.testing.assert_allclose(got, np_l2sqr(q, x), rtol=5e-3, atol=5e-2)


def test_inner_product_matches_numpy(data):
    q, x = data
    got = np.asarray(pairwise_inner_product(jnp.array(q), jnp.array(x)))
    np.testing.assert_allclose(got, q @ x.T, rtol=2e-3, atol=2e-3)


def test_cosine_matches_numpy(data):
    q, x = data
    qn = q / np.linalg.norm(q, axis=1, keepdims=True)
    xn = x / np.linalg.norm(x, axis=1, keepdims=True)
    got = np.asarray(pairwise_cosine(jnp.array(q), jnp.array(x)))
    np.testing.assert_allclose(got, qn @ xn.T, rtol=5e-3, atol=5e-3)


def test_hamming_matches_numpy():
    rng = np.random.default_rng(0)
    nbits = 128
    a = rng.integers(0, 256, (5, nbits // 8), dtype=np.uint8)
    b = rng.integers(0, 256, (31, nbits // 8), dtype=np.uint8)
    want = np.zeros((5, 31))
    for i in range(5):
        for j in range(31):
            want[i, j] = bin(
                int.from_bytes(a[i].tobytes(), "little")
                ^ int.from_bytes(b[j].tobytes(), "little")
            ).count("1")
    got = np.asarray(pairwise_hamming(jnp.array(a), jnp.array(b), nbits))
    np.testing.assert_allclose(got, want, atol=1e-3)


def test_score_matrix_roundtrip(data):
    q, x = data
    for metric in (Metric.L2, Metric.INNER_PRODUCT, Metric.COSINE):
        s = score_matrix(jnp.array(q), jnp.array(x), metric)
        d = scores_to_distances(s, metric)
        if metric is Metric.L2:
            np.testing.assert_allclose(
                np.asarray(d), np_l2sqr(q, x), rtol=5e-3, atol=5e-2
            )


def test_topk_exact(data):
    q, x = data
    d = np_l2sqr(q, x)
    scores = jnp.array(-d)
    vals, ids = topk_scores(scores, 10)
    want_ids = np.argsort(d, axis=1)[:, :10]
    # Compare distance values (ties can permute ids).
    np.testing.assert_allclose(
        -np.asarray(vals), np.take_along_axis(d, want_ids, 1), rtol=5e-3, atol=5e-2
    )


def test_topk_mask_and_external_ids(data):
    q, x = data
    d = np_l2sqr(q, x)
    valid = np.ones(200, bool)
    valid[::2] = False  # mask half
    ext_ids = np.arange(1000, 1200, dtype=np.int64)
    vals, ids = topk_scores(
        jnp.array(-d), 5, valid=jnp.array(valid), ids=jnp.array(ext_ids)
    )
    ids = np.asarray(ids)
    assert ((ids - 1000) % 2 == 1).all()  # only odd slots survive
    dm = np.where(valid[None, :], d, np.inf)
    want = np.sort(dm, axis=1)[:, :5]
    np.testing.assert_allclose(-np.asarray(vals), want, rtol=5e-3, atol=5e-2)


def test_topk_k_larger_than_n():
    scores = jnp.array([[1.0, 0.5]])
    vals, ids = topk_scores(scores, 4)
    assert np.asarray(ids).tolist()[0][:2] == [0, 1]
    assert (np.asarray(ids)[0, 2:] == -1).all()


def test_topk_fully_masked_returns_minus_one():
    scores = jnp.zeros((2, 8))
    vals, ids = topk_scores(scores, 3, valid=jnp.zeros(8, bool))
    assert (np.asarray(ids) == -1).all()


def test_merge_topk(data):
    q, x = data
    d = np_l2sqr(q, x)
    half = 100
    v1, i1 = topk_scores(jnp.array(-d[:, :half]), 10, ids=jnp.arange(half))
    v2, i2 = topk_scores(
        jnp.array(-d[:, half:]), 10, ids=jnp.arange(half, 200)
    )
    vals, ids = merge_topk(v1, i1, v2, i2, 10)
    want = np.sort(d, axis=1)[:, :10]
    np.testing.assert_allclose(-np.asarray(vals), want, rtol=5e-3, atol=5e-2)


def test_merge_sharded_topk(data):
    q, x = data
    d = np_l2sqr(q, x)
    shards = []
    for s in range(4):
        sl = slice(s * 50, (s + 1) * 50)
        v, i = topk_scores(jnp.array(-d[:, sl]), 10, ids=jnp.arange(200)[sl])
        shards.append((v, i))
    sv = jnp.stack([v for v, _ in shards])
    si = jnp.stack([i for _, i in shards])
    vals, ids = merge_sharded_topk(sv, si, 10)
    want = np.sort(d, axis=1)[:, :10]
    np.testing.assert_allclose(-np.asarray(vals), want, rtol=5e-3, atol=5e-2)
