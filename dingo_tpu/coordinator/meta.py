"""Schema / table / index metadata layer on the coordinator.

Reference: CoordinatorControl's schema+table meta and MetaService RPCs
(src/coordinator/coordinator_control.h:187 schema/table state;
src/server/meta_service.cc CreateTable/DropTable/GetTables/...). The
reference seeds default schemas (root/meta/dingo) and stores table
definitions whose partitions map to regions; the SDK then speaks in
tables rather than raw regions.

Here a table is a named definition whose partitions each own one region:
vector/document partitions own an id-window region (vector key codec),
plain TABLE partitions own a raw key-range region. Region placement,
replication, split/merge stay CoordinatorControl's job — dropping a table
drops its regions.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Tuple

from dingo_tpu.common import persist
from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.engine.raw_engine import CF_META, RawEngine
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import IndexParameter
from dingo_tpu.raft import wire
from dingo_tpu.store.region import RegionType

_PREFIX_SCHEMA = b"meta/schema/"
_PREFIX_TABLE = b"meta/table/"
_KEY_TABLE_ID = b"meta/next_table_id"
_KEY_META_REV = b"meta/revision"

#: in-memory meta-event ring size; watchers older than the ring get a
#: resync signal instead of replay (meta churn is low, 1024 is ~forever)
_EVENT_RING = 1024

#: reference's built-in schemas (coordinator seeds root/meta/dingo)
DEFAULT_SCHEMAS = ("root", "meta", "dingo")


class MetaError(RuntimeError):
    pass


class MetaExistsError(MetaError):
    """Name collision (schema/table already registered). Distinct class so
    idempotent callers (BR restore re-runs) can skip collisions without
    string-matching — every other MetaError stays fatal for them."""


@persist.register
@dataclasses.dataclass
class ColumnDefinition:
    name: str
    sql_type: str = "VARCHAR"
    nullable: bool = True
    primary: bool = False


@persist.register
@dataclasses.dataclass
class PartitionDefinition:
    partition_id: int
    #: vector/document partitions: [id_lo, id_hi) vector-id window
    id_lo: int = 0
    id_hi: int = 0
    #: plain TABLE partitions: raw key range
    start_key: bytes = b""
    end_key: bytes = b""
    region_id: int = 0


@persist.register
@dataclasses.dataclass
class TableDefinition:
    table_id: int
    schema_name: str
    name: str
    table_type: RegionType = RegionType.STORE
    columns: List[ColumnDefinition] = dataclasses.field(default_factory=list)
    partitions: List[PartitionDefinition] = dataclasses.field(
        default_factory=list
    )
    index_parameter: Optional[IndexParameter] = None
    replication: int = 0


class MetaControl:
    """Schema/table registry persisted in the coordinator's meta CF."""

    def __init__(self, engine: RawEngine, control: CoordinatorControl):
        self.engine = engine
        self.control = control
        self._lock = threading.Lock()
        self.schemas: Dict[str, List[str]] = {}     # schema -> table names
        self.tables: Dict[str, TableDefinition] = {}  # "schema.table" -> def
        self._creating: set = set()   # names reserved by in-flight creates
        self._next_table_id = 1
        #: meta-watch state (reference meta-watch RPCs + crontab entry,
        #: src/server/meta_service.cc; server.cc:506-700): change events
        #: carry a monotonic meta revision so SDK caches can invalidate
        #: without polling. The ring is memory-only — a restarted
        #: coordinator replays nothing and watchers resync.
        self._meta_revision = 1
        self._events: List[dict] = []
        self._watchers: List[Tuple[int, Callable[[dict], None]]] = []
        self._recover()
        for s in DEFAULT_SCHEMAS:
            if s not in self.schemas:
                self._put_schema(s)

    # -- persistence ---------------------------------------------------------
    def _recover(self) -> None:
        blob = self.engine.get(CF_META, _KEY_TABLE_ID)
        if blob:
            self._next_table_id = wire.decode(blob)
        blob = self.engine.get(CF_META, _KEY_META_REV)
        if blob:
            # revision survives restart (monotonic); the event ring does
            # not — watchers from pre-restart revisions get a resync
            self._meta_revision = wire.decode(blob)
        for k, v in self.engine.scan(CF_META, _PREFIX_SCHEMA,
                                     _PREFIX_SCHEMA + b"\xff"):
            self.schemas[wire.decode(v)] = []
        for k, v in self.engine.scan(CF_META, _PREFIX_TABLE,
                                     _PREFIX_TABLE + b"\xff"):
            t = persist.loads(v)
            self.tables[f"{t.schema_name}.{t.name}"] = t
            self.schemas.setdefault(t.schema_name, []).append(t.name)

    def _put_schema(self, name: str) -> None:
        self.schemas[name] = self.schemas.get(name, [])
        self.engine.put(CF_META, _PREFIX_SCHEMA + name.encode(),
                        wire.encode(name))

    def _put_table(self, t: TableDefinition) -> None:
        self.engine.put(
            CF_META, _PREFIX_TABLE + str(t.table_id).encode(),
            persist.dumps(t),
        )

    # -- schemas -------------------------------------------------------------
    def create_schema(self, name: str) -> None:
        if not name:
            raise MetaError("empty schema name")
        with self._lock:
            if name in self.schemas:
                raise MetaExistsError(f"schema {name!r} exists")
            self._put_schema(name)
            self._emit("create_schema", name)

    def drop_schema(self, name: str) -> None:
        with self._lock:
            if name not in self.schemas:
                raise MetaError(f"schema {name!r} not found")
            in_flight = any(k.startswith(name + ".") for k in self._creating)
            if self.schemas[name] or in_flight:
                raise MetaError(f"schema {name!r} not empty")
            if name in DEFAULT_SCHEMAS:
                raise MetaError(f"schema {name!r} is built-in")
            del self.schemas[name]
            self.engine.delete(CF_META, _PREFIX_SCHEMA + name.encode())
            self._emit("drop_schema", name)

    def get_schemas(self) -> List[str]:
        with self._lock:
            return sorted(self.schemas)

    # -- tables --------------------------------------------------------------
    def create_table(
        self,
        schema_name: str,
        name: str,
        partitions: List[PartitionDefinition],
        columns: Optional[List[ColumnDefinition]] = None,
        index_parameter: Optional[IndexParameter] = None,
        table_type: Optional[RegionType] = None,
        replication: int = 0,
    ) -> TableDefinition:
        """CreateTable (meta_service.cc): allocate the table id, create one
        region per partition, persist the definition."""
        if table_type is None:
            table_type = (
                RegionType.INDEX if index_parameter is not None
                else RegionType.STORE
            )
        key = f"{schema_name}.{name}"
        with self._lock:
            if schema_name not in self.schemas:
                raise MetaError(f"schema {schema_name!r} not found")
            if key in self.tables or key in self._creating:
                raise MetaExistsError(f"table {key} exists")
            if not partitions:
                raise MetaError("table needs >= 1 partition")
            # reserve the name: region creation below runs outside the lock
            # (it is slow), and a concurrent same-name create must fail now
            self._creating.add(key)
            table_id = self._next_table_id
            self._next_table_id += 1
            self.engine.put(CF_META, _KEY_TABLE_ID,
                            wire.encode(self._next_table_id))
        created = []
        try:
            for p in partitions:
                if table_type in (RegionType.INDEX, RegionType.DOCUMENT):
                    start = vcodec.encode_vector_key(p.partition_id, p.id_lo)
                    end = vcodec.encode_vector_key(p.partition_id, p.id_hi)
                else:
                    start, end = p.start_key, p.end_key
                # overlap rejection happens inside create_region (under
                # the control lock, so concurrent creates cannot race it)
                d = self.control.create_region(
                    start, end,
                    partition_id=p.partition_id,
                    region_type=table_type,
                    index_parameter=index_parameter,
                    replication=replication or None,
                )
                p.region_id = d.region_id
                created.append(d.region_id)
        except Exception:
            for rid in created:
                self.control.drop_region(rid)
            with self._lock:
                self._creating.discard(key)
            raise
        t = TableDefinition(
            table_id=table_id,
            schema_name=schema_name,
            name=name,
            table_type=table_type,
            columns=columns or [],
            partitions=partitions,
            index_parameter=index_parameter,
            replication=replication,
        )
        with self._lock:
            self._creating.discard(key)
            self.tables[key] = t
            self.schemas[schema_name].append(name)
            self._put_table(t)
            self._emit("create_table", schema_name, name, t.table_id)
        return t

    def import_table(self, t: TableDefinition) -> TableDefinition:
        """Register an externally built definition (restore path): assigns
        a fresh table id, persists the id counter and the definition under
        the same invariants create_table maintains. Partition region ids
        must already point at live regions."""
        key = f"{t.schema_name}.{t.name}"
        with self._lock:
            if t.schema_name not in self.schemas:
                self._put_schema(t.schema_name)
            if key in self.tables or key in self._creating:
                raise MetaExistsError(f"table {key} exists")
            t.table_id = self._next_table_id
            self._next_table_id += 1
            self.engine.put(CF_META, _KEY_TABLE_ID,
                            wire.encode(self._next_table_id))
            self.tables[key] = t
            self.schemas[t.schema_name].append(t.name)
            self._put_table(t)
            self._emit("create_table", t.schema_name, t.name, t.table_id)
        return t

    def drop_table(self, schema_name: str, name: str) -> None:
        key = f"{schema_name}.{name}"
        with self._lock:
            t = self.tables.get(key)
            if t is None:
                raise MetaError(f"table {key} not found")
            del self.tables[key]
            self.schemas[schema_name].remove(name)
            self.engine.delete(
                CF_META, _PREFIX_TABLE + str(t.table_id).encode()
            )
            self._emit("drop_table", schema_name, name, t.table_id)
        for p in t.partitions:
            self.control.drop_region(p.region_id)

    def get_table(self, schema_name: str, name: str) -> Optional[TableDefinition]:
        with self._lock:
            return self.tables.get(f"{schema_name}.{name}")

    def get_tables(self, schema_name: str) -> List[TableDefinition]:
        with self._lock:
            return [t for t in self.tables.values()
                    if t.schema_name == schema_name]

    # -- meta watch (meta_service.cc meta-watch analog) ----------------------
    @property
    def meta_revision(self) -> int:
        with self._lock:
            return self._meta_revision

    def _emit(self, event: str, schema: str, table: str = "",
              table_id: int = 0) -> None:
        """Record + fan out one change event. Caller holds self._lock."""
        self._meta_revision += 1
        self.engine.put(CF_META, _KEY_META_REV,
                        wire.encode(self._meta_revision))
        ev = {
            "event": event,
            "schema": schema,
            "table": table,
            "table_id": table_id,
            "revision": self._meta_revision,
        }
        self._events.append(ev)
        if len(self._events) > _EVENT_RING:
            del self._events[: len(self._events) - _EVENT_RING]
        still_waiting = []
        for start, cb in self._watchers:
            if ev["revision"] >= start:
                try:
                    cb(ev)
                except Exception:
                    pass
            else:
                still_waiting.append((start, cb))
        self._watchers = still_waiting

    def watch(self, start_revision: int,
              callback: Callable[[dict], None]) -> None:
        """One-time meta watch: fires with the OLDEST event at/after
        start_revision (replayed from the ring when already past), a
        {"event": "resync"} signal when that history is gone (restart or
        ring overflow — re-list and watch from the current revision), or
        registers for the next future event."""
        with self._lock:
            if start_revision <= self._meta_revision:
                # replay only when the ring still covers [start, now] —
                # revisions are contiguous, so a first retained event
                # above start means events were evicted (or predate this
                # process) and a partial replay would silently lose them
                if self._events and \
                        self._events[0]["revision"] <= start_revision:
                    for ev in self._events:
                        if ev["revision"] >= start_revision:
                            callback(ev)
                            return
                callback({
                    "event": "resync",
                    "schema": "",
                    "table": "",
                    "table_id": 0,
                    "revision": self._meta_revision,
                })
                return
            self._watchers.append((start_revision, callback))

    def cancel_watch(self, callback: Callable) -> bool:
        with self._lock:
            for pair in self._watchers:
                if pair[1] is callback:
                    self._watchers.remove(pair)
                    return True
            return False
