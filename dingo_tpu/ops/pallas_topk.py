"""Fused distance + running top-k Pallas kernel.

SURVEY.md §7 kernel layer: "fused distance+top-k Pallas kernel with running
k-selection to avoid materializing [b, n]". The XLA path (ops/distance.py +
lax.top_k) materializes the full [b, n] score matrix in HBM; this kernel
streams the database through VMEM in blocks, keeps a [b, k] running best in
VMEM scratch, and never writes the score matrix out — at 10M x 768 that is
~2.5 GB of HBM traffic saved per query batch (k=10, b=64).

Selection strategy: per block, k rounds of (max, argmax, mask) over the
[b, C] block scores — k/d ≈ 1-2% overhead relative to the distance matmul —
then a merge of the 2k running+block candidates by another k rounds.
Runs under interpret=True on CPU for tests; compiled on TPU.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from dingo_tpu.obs.sentinel import sentinel_jit

NEG_INF = float("-inf")


def _select_topk(scores, idx, k):
    """k rounds of max/argmax/mask over [b, C] -> ([b, k], [b, k]).

    The winner's id is extracted with a masked max reduction rather than
    take_along_axis: Mosaic's gather lowering only accepts indices shaped
    operand+(1,), so a [b,1] gather on [b,C] fails to lower (observed
    on-chip round 3) — and a where+max over the one matching lane is
    vector-unit work anyway, no gather needed.
    """
    vals, ids = [], []
    b, c = scores.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, c), 1)
    for _ in range(k):
        m = jnp.max(scores, axis=1)                      # [b]
        am = jnp.argmax(scores, axis=1)                  # [b]
        hit = cols == am[:, None]
        ids.append(jnp.max(
            jnp.where(hit, idx, jnp.int32(np.iinfo(np.int32).min)), axis=1
        ))
        vals.append(m)
        # mask the winner out
        scores = jnp.where(hit, NEG_INF, scores)
    return jnp.stack(vals, axis=1), jnp.stack(ids, axis=1)


def _fused_kernel(q_ref, qsq_ref, x_ref, xsq_ref, valid_ref,
                  out_v_ref, out_i_ref, best_v, best_i, *, k, block, ascending):
    j = pl.program_id(0)
    nblocks = pl.num_programs(0)

    @pl.when(j == 0)
    def _init():
        best_v[:] = jnp.full_like(best_v, NEG_INF)
        best_i[:] = jnp.full_like(best_i, -1)

    q = q_ref[:]                                          # [b, d]
    x = x_ref[:].astype(jnp.float32)   # bf16 stores promote in VMEM
    # HIGHEST precision: the default bf16-pass matmul measurably costs
    # recall (distance.py pins the same; flat recall@10 0.9875 -> 1.0).
    dots = jax.lax.dot_general(
        q, x, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                     # [b, C]
    if ascending:  # L2: score = -(||q||^2 - 2qx + ||x||^2)
        scores = -(qsq_ref[:] - 2.0 * dots + xsq_ref[:])  # [b,1] + [1,C]
    else:          # IP
        scores = dots
    valid = valid_ref[:]                                  # [1, C] float (1/0)
    scores = jnp.where(valid > 0.5, scores, NEG_INF)

    b = scores.shape[0]
    gidx = (
        jax.lax.broadcasted_iota(jnp.int32, (b, block), 1) + j * block
    )
    blk_v, blk_i = _select_topk(scores, gidx, k)

    cat_v = jnp.concatenate([best_v[:], blk_v], axis=1)   # [b, 2k]
    cat_i = jnp.concatenate([best_i[:], blk_i], axis=1)
    new_v, new_i = _select_topk(cat_v, cat_i, k)
    best_v[:] = new_v
    best_i[:] = new_i

    @pl.when(j == nblocks - 1)
    def _finish():
        fv = best_v[:]
        out_v_ref[:] = fv
        # -inf picks are argmax-of-all-masked artifacts: they carry real
        # (and duplicated) slot ids. Map them to -1 like the XLA path
        # (topk.py maps -inf picks to -1) so filter-excluded ids never leak.
        out_i_ref[:] = jnp.where(jnp.isneginf(fv), -1, best_i[:])


@sentinel_jit("ops.pallas.fused_topk",
              static_argnames=("k", "block", "ascending", "interpret"))
def fused_topk(
    q: jax.Array,
    x: jax.Array,
    x_sqnorm: jax.Array,
    valid: jax.Array,
    k: int,
    block: int = 2048,
    ascending: bool = True,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Streaming fused search: q[b,d] vs x[n,d] -> (scores[b,k], slots[b,k]).

    Returns 'larger is better' scores (negated L2 when ascending) and global
    slot indices (-1 for masked). n must be a multiple of `block` (pad with
    valid=0 rows).
    """
    b, d = q.shape
    n = x.shape[0]
    assert n % block == 0, f"n={n} not a multiple of block={block}"
    qsq = jnp.einsum("bd,bd->b", q.astype(jnp.float32), q.astype(jnp.float32),
                     precision=jax.lax.Precision.HIGHEST)[:, None]   # [b, 1]
    grid = (n // block,)
    out_v, out_i = pl.pallas_call(
        functools.partial(_fused_kernel, k=k, block=block,
                          ascending=ascending),
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, d), lambda j: (0, 0)),         # q (all blocks)
            pl.BlockSpec((b, 1), lambda j: (0, 0)),         # qsq [b,1]
            pl.BlockSpec((block, d), lambda j: (j, 0)),     # x block
            pl.BlockSpec((1, block), lambda j: (0, j)),     # xsq [1, n]
            pl.BlockSpec((1, block), lambda j: (0, j)),     # valid [1, n]
        ],
        out_specs=[
            pl.BlockSpec((b, k), lambda j: (0, 0)),
            pl.BlockSpec((b, k), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, k), jnp.float32),
            pltpu.VMEM((b, k), jnp.int32),
        ],
        interpret=interpret,
    )(q.astype(jnp.float32), qsq, x, x_sqnorm[None, :],
      valid.astype(jnp.float32)[None, :])
    return out_v, out_i


def fused_search(
    q: np.ndarray,
    x: jax.Array,
    x_sqnorm: jax.Array,
    valid: jax.Array,
    k: int,
    block: int = 2048,
    ascending: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Host-friendly wrapper: pads n to the block multiple and picks
    interpret mode off-TPU (Mosaic kernels only compile for TPU)."""
    n = x.shape[0]
    pad = (-n) % block
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)])
        x_sqnorm = jnp.concatenate([x_sqnorm, jnp.zeros((pad,), x_sqnorm.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), valid.dtype)])
    interpret = jax.default_backend() not in ("tpu", "axon")
    return fused_topk(jnp.asarray(q), x, x_sqnorm, valid, k=k, block=block,
                      ascending=ascending, interpret=interpret)
