"""Coordinator capacity plane: HBM headroom vs measured working-set
demand, with tier/split recommendations.

Every store heartbeat now carries the workload-heat rollup each region's
sketch derived on the store (RegionMetrics.heat_* — bytes to serve
{50,90,99}% of traffic at the region's own precision tier, traffic
concentration, per-row dispatch cost). This module turns one store's
snapshot into a capacity view:

- **Headroom** — the HBM ledger's limit minus bytes in use, as absolute
  bytes and as a fraction of the limit. ``capacity.headroom_target``
  (conf, hot-changeable) is the fraction the plane wants free.
- **Demand** — Σ regions' p99 working-set bytes: what the measured
  traffic actually needs resident to serve 99% of itself. Resident
  bytes far above demand are *cold* — the tiering candidate mass.
- **Advisories** — pure recommendations, exactly two kinds:
  - ``demote``: the store is under its headroom target and a region
    holds the most cold bytes (resident − p99 working set). Demoting it
    to a cheaper tier (or host RAM) frees the most HBM at the least
    traffic risk.
  - ``split``: one region concentrates the store's traffic (share ≥
    ``SPLIT_TRAFFIC_SHARE``) onto a hot core (hot_fraction ≥
    ``SPLIT_HOT_FRACTION``) — a hotspot that splitting would spread.

**Contract with ROADMAP items 1–2:** this plane itself never actuates —
it computes. The memory-tier ladder (item 1, index/tiering.py) is now a
live consumer: control.py turns each FRESH ``demote`` advisory into a
TIER_DEMOTE region command, and the advised store's ladder flags the
region for its own policy tick (the store still picks the moment, the
rung, and may decline when local evidence disagrees). ``split`` advice
stays observational until device-aware split/merge (item 2) lands.
Either way the advisories surface what the heat evidence supports —
``capacity.*`` metrics, ``cluster capacity``, flight bundles. The same
pure functions run coordinator-side (heartbeat hook in control.py) and
client-side (cli.py renders the identical plan from GetStoreMetrics),
so the CLI never needs a second RPC or a divergent reimplementation.

All inputs are duck-typed (pb RegionMetrics or RegionMetricsSnapshot
both answer), every function is deterministic, and nothing here takes
locks or touches devices.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

#: a region advises "split" when it carries at least this share of the
#: store's sketch touches...
SPLIT_TRAFFIC_SHARE = 0.5
#: ...concentrated onto a hot core at least this tight (mass on the
#: hottest 10% of heat units)
SPLIT_HOT_FRACTION = 0.6
#: demote advisories require real evidence: at least this many sketch
#: touches on the region (a freshly-started sketch must not demote
#: anything) and at least this many cold bytes to be worth a move
MIN_TOUCHES = 1000
MIN_COLD_BYTES = 1 << 20


def capacity_advise_enabled() -> bool:
    from dingo_tpu.common.config import FLAGS

    try:
        return bool(FLAGS.get("capacity_advise"))
    except KeyError:
        return True


def headroom_target() -> float:
    from dingo_tpu.common.config import FLAGS

    try:
        return max(0.0, min(1.0, float(
            FLAGS.get("capacity_headroom_target"))))
    except KeyError:
        return 0.2


@dataclasses.dataclass
class CapacityAdvice:
    """One advisory recommendation (never an order)."""

    store_id: str
    region_id: int
    kind: str          # "demote" | "split"
    reason: str
    #: bytes the advice is about (cold bytes for demote, p99 working
    #: set for split) — the ranking axis
    bytes_at_stake: int = 0


def region_cold_bytes(rm: Any) -> int:
    """Resident bytes the measured traffic does NOT need: device
    residency minus the p99 working set (floored at 0 — a working set
    estimated above residency just means the sketch prices a tier the
    store doesn't hold)."""
    resident = int(getattr(rm, "device_memory_bytes", 0) or 0)
    ws = int(getattr(rm, "heat_working_set_p99", 0) or 0)
    return max(0, resident - min(ws, resident))


def plan_store(snap: Any, target: Optional[float] = None) -> Dict[str, Any]:
    """Capacity plan for ONE store snapshot (pb StoreMetrics or
    StoreMetricsSnapshot). Returns a dict of rollups + advice list,
    ranked by bytes at stake. Pure and deterministic — the coordinator
    hook and the CLI render call this same function."""
    if target is None:
        target = headroom_target()
    store_id = str(getattr(snap, "store_id", ""))
    limit = int(getattr(snap, "device_bytes_limit", 0) or 0)
    in_use = int(getattr(snap, "device_bytes_in_use", 0) or 0)
    regions = list(getattr(snap, "regions", []) or [])
    headroom = max(0, limit - in_use)
    frac = headroom / limit if limit > 0 else 1.0
    demand = sum(int(getattr(r, "heat_working_set_p99", 0) or 0)
                 for r in regions)
    resident = sum(int(getattr(r, "device_memory_bytes", 0) or 0)
                   for r in regions)
    touches_total = sum(int(getattr(r, "heat_touches", 0) or 0)
                        for r in regions)
    advice: List[CapacityAdvice] = []
    # demote: under the headroom target, recommend the coldest region
    if limit > 0 and frac < target:
        candidates = [
            (region_cold_bytes(r), r) for r in regions
            if int(getattr(r, "heat_touches", 0) or 0) >= MIN_TOUCHES
        ]
        candidates = [(cb, r) for cb, r in candidates
                      if cb >= MIN_COLD_BYTES]
        if candidates:
            cold, r = max(candidates, key=lambda c: c[0])
            advice.append(CapacityAdvice(
                store_id=store_id,
                region_id=int(r.region_id),
                kind="demote",
                bytes_at_stake=cold,
                reason=(
                    f"headroom {frac:.0%} < target {target:.0%}; "
                    f"{cold} resident bytes outside the p99 working set"
                ),
            ))
    # split: a single region hogging the store's traffic on a hot core
    if touches_total > 0:
        for r in regions:
            touches = int(getattr(r, "heat_touches", 0) or 0)
            if touches < MIN_TOUCHES:
                continue
            share = touches / touches_total
            hot = float(getattr(r, "heat_hot_fraction", 0.0) or 0.0)
            if share >= SPLIT_TRAFFIC_SHARE and hot >= SPLIT_HOT_FRACTION \
                    and len(regions) >= 1:
                advice.append(CapacityAdvice(
                    store_id=store_id,
                    region_id=int(r.region_id),
                    kind="split",
                    bytes_at_stake=int(
                        getattr(r, "heat_working_set_p99", 0) or 0),
                    reason=(
                        f"carries {share:.0%} of store traffic with "
                        f"hot_fraction {hot:.2f} — a hotspot splitting "
                        f"would spread"
                    ),
                ))
    advice.sort(key=lambda a: -a.bytes_at_stake)
    return {
        "store_id": store_id,
        "limit_bytes": limit,
        "in_use_bytes": in_use,
        "headroom_bytes": headroom,
        "headroom_frac": frac,
        "demand_p99_bytes": demand,
        "resident_bytes": resident,
        "touches": touches_total,
        "advice": advice,
    }


def plan_cluster(snaps: List[Any],
                 target: Optional[float] = None) -> List[Dict[str, Any]]:
    """Per-store plans for a set of snapshots (cluster view), in
    store-id order for stable rendering."""
    plans = [plan_store(s, target) for s in snaps]
    plans.sort(key=lambda p: p["store_id"])
    return plans
