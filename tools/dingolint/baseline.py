"""Baseline: adjudicated pre-existing findings, each with a rationale.

The baseline is the lint's memory of human judgment: a finding whose
fingerprint appears here is reported as *baselined* and does not fail
the run — but only if its entry carries a non-placeholder rationale.
An entry without a real rationale fails the lint: the file exists to
record WHY each exception is safe, not to be a mute allowlist that
violations quietly accumulate in.

``tools/lint.py --baseline-update`` rewrites the file from the current
findings, preserving rationales for fingerprints that persist and
stamping ``TODO: adjudicate`` on new ones (which then fail until a human
replaces the placeholder). Stale entries (fingerprint no longer found —
the code was fixed or deleted) are dropped on update and reported as
warnings on normal runs.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

from tools.dingolint.core import Finding

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")
TODO_RATIONALE = "TODO: adjudicate"


def load(path: str = BASELINE_PATH) -> Dict[str, dict]:
    """fingerprint -> entry dict. Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("entries", [])}


def save(entries: Sequence[dict], path: str = BASELINE_PATH) -> None:
    entries = sorted(entries, key=lambda e: (e["checker"], e["location"]))
    with open(path, "w") as f:
        json.dump({"version": 1, "entries": list(entries)}, f, indent=2)
        f.write("\n")


def split(findings: Sequence[Finding], baseline: Dict[str, dict]
          ) -> Tuple[List[Finding], List[Finding], List[dict], List[dict]]:
    """Partition into (new, baselined, unrationalized entries, stale
    entries). A baseline entry may match several findings (same checker +
    symbol + message at multiple call sites collapses to one judgment)."""
    new: List[Finding] = []
    matched: List[Finding] = []
    hit: set = set()
    for f in findings:
        entry = baseline.get(f.fingerprint)
        if entry is None:
            new.append(f)
        else:
            matched.append(f)
            hit.add(f.fingerprint)
    unrationalized = [
        e for fp, e in baseline.items()
        if fp in hit and not _has_rationale(e)
    ]
    stale = [e for fp, e in baseline.items() if fp not in hit]
    return new, matched, unrationalized, stale


def _has_rationale(entry: dict) -> bool:
    r = (entry.get("rationale") or "").strip()
    return bool(r) and not r.startswith("TODO")


def updated_entries(findings: Sequence[Finding],
                    baseline: Dict[str, dict]) -> List[dict]:
    """Entries for --baseline-update: one per distinct fingerprint among
    the current findings, rationale carried over when known."""
    out: Dict[str, dict] = {}
    for f in findings:
        if f.fingerprint in out:
            continue
        old = baseline.get(f.fingerprint)
        out[f.fingerprint] = {
            "fingerprint": f.fingerprint,
            "checker": f.checker,
            "location": f"{f.path}:{f.symbol or '<module>'}",
            "message": f.message,
            "rationale": (old or {}).get("rationale", TODO_RATIONALE),
        }
    return list(out.values())
