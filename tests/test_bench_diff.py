"""tools/bench_diff.py wired as a tier-1 gate (ISSUE 9 satellite): the
BENCH_r0*.json trajectory becomes machine-checkable — a synthetic summary
pair round-trips through the CLI with the right exit codes, regression
classification, and thresholds."""

import copy
import importlib
import json

import pytest

bench_diff = importlib.import_module("tools.bench_diff")


BASE = {
    "platform": "cpu",
    "metric": "ivf_flat_qps_200k",
    "value": 40.0,
    "unit": "qps",
    "recall_at_10": 0.96,
    "cpu_baseline_qps": 10.0,
    "steady_state_recompiles": 0,
    "hbm_high_watermark_bytes": 1_000_000,
    "precision_sweep": {
        "fp32": {"qps": 100.0, "recall_at_10": 0.96,
                 "hbm_peak_bytes": 500_000},
        "sq8": {"qps": 120.0, "recall_at_10": 0.95,
                "live_vs_measured_delta": -0.001,
                "hbm_peak_bytes": 200_000},
    },
    "mesh_scaling": {
        "points": [
            {"n_devices": 1, "flat": {"qps": 900.0,
                                      "steady_state_recompiles": 0}},
            {"n_devices": 2, "flat": {"qps": 700.0,
                                      "steady_state_recompiles": 0}},
        ],
    },
}


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def test_identical_summaries_pass(tmp_path, capsys):
    a = _write(tmp_path, "a.json", BASE)
    b = _write(tmp_path, "b.json", BASE)
    assert bench_diff.main([a, b]) == 0
    assert "0 regression(s)" in capsys.readouterr().out


def test_qps_regression_fails_and_names_the_path(tmp_path, capsys):
    worse = copy.deepcopy(BASE)
    worse["precision_sweep"]["fp32"]["qps"] = 60.0     # -40%
    a = _write(tmp_path, "a.json", BASE)
    b = _write(tmp_path, "b.json", worse)
    assert bench_diff.main([a, b]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    assert "precision_sweep.fp32.qps" in out
    # within-threshold drift passes
    mild = copy.deepcopy(BASE)
    mild["precision_sweep"]["fp32"]["qps"] = 95.0      # -5%
    c = _write(tmp_path, "c.json", mild)
    assert bench_diff.main([a, c]) == 0


def test_recall_and_hbm_and_recompile_kinds(tmp_path, capsys):
    worse = copy.deepcopy(BASE)
    worse["recall_at_10"] = 0.91                       # -0.05 absolute
    worse["hbm_high_watermark_bytes"] = 2_000_000      # +100%
    worse["steady_state_recompiles"] = 3               # invariant broken
    a = _write(tmp_path, "a.json", BASE)
    b = _write(tmp_path, "b.json", worse)
    assert bench_diff.main([a, b]) == 1
    out = capsys.readouterr().out
    assert "recall_at_10" in out
    assert "hbm_high_watermark_bytes" in out
    assert "steady_state_recompiles" in out
    # each threshold is CLI-tunable: loosened gates pass (recompile
    # growth stays a regression at any threshold — it is an invariant)
    assert bench_diff.main(
        [a, b, "--recall-drop", "0.1", "--bytes-grow", "2.0"]) == 1
    result = bench_diff.compare(BASE, worse, recall_drop=0.1,
                                bytes_grow=2.0)
    kinds = {r["kind"] for r in result["regressions"]}
    assert kinds == {"recompiles"}


def test_classifier_scope():
    # diagnostics/deltas/baselines never threshold
    assert bench_diff.classify("precision_sweep.sq8.live_vs_measured_delta") \
        is None
    assert bench_diff.classify("cpu_baseline_qps") is None
    assert bench_diff.classify("recall_slo.estimate_vs_measured_delta") \
        is None
    # recall_slo's per-tick convergence trail intentionally starts
    # mistuned: trajectory values are diagnostics, never regressions
    assert bench_diff.classify(
        "recall_slo.trajectory[0].recall_estimate") is None
    assert bench_diff.compare(
        {"recall_slo": {"trajectory": [{"recall_estimate": 0.41}]}},
        {"recall_slo": {"trajectory": [{"recall_estimate": 0.38}]}},
    )["regressions"] == []
    # magnitudes do
    assert bench_diff.classify("mesh_scaling.points[0].flat.qps") == "qps"
    assert bench_diff.classify("hnsw_sweep.device.recall_at_10") == "recall"
    assert bench_diff.classify("mixed_rw.hbm_peak_bytes") == "bytes"
    assert bench_diff.classify(
        "recall_slo.steady_state_recompiles") == "recompiles"
    # top-level bench value classifies through its sibling unit
    assert bench_diff.classify("value", {"unit": "qps"}) == "qps"
    assert bench_diff.classify("value", {"unit": "ms"}) is None


def test_new_and_dropped_coverage_reported_not_regressed(tmp_path, capsys):
    grown = copy.deepcopy(BASE)
    grown["recall_slo"] = {"live_recall_estimate": 0.96,
                           "steady_state_recompiles": 0}
    del grown["mesh_scaling"]
    a = _write(tmp_path, "a.json", BASE)
    b = _write(tmp_path, "b.json", grown)
    assert bench_diff.main([a, b]) == 0
    out = capsys.readouterr().out
    assert "new coverage" in out
    assert "dropped from new" in out


def test_json_output_machine_readable(tmp_path, capsys):
    worse = copy.deepcopy(BASE)
    worse["value"] = 10.0
    a = _write(tmp_path, "a.json", BASE)
    b = _write(tmp_path, "b.json", worse)
    assert bench_diff.main([a, b, "--json"]) == 1
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["regressions"][0]["path"] == "value"
    assert parsed["regressions"][0]["kind"] == "qps"


def test_bad_file_is_usage_error(tmp_path):
    a = _write(tmp_path, "a.json", BASE)
    assert bench_diff.main([a, str(tmp_path / "missing.json")]) == 2
    notjson = tmp_path / "x.json"
    notjson.write_text("{nope")
    assert bench_diff.main([a, str(notjson)]) == 2


def test_live_quality_recall_estimates_are_gated(tmp_path):
    """The new quality plane figures participate in the diff: a live
    recall estimate that collapses between rounds is a regression."""
    old = {"recall_slo": {"live_recall_estimate": 0.96},
           "precision_sweep": {"sq8": {"live_recall_estimate": 0.95}}}
    new = copy.deepcopy(old)
    new["recall_slo"]["live_recall_estimate"] = 0.80
    result = bench_diff.compare(old, new)
    assert [r["path"] for r in result["regressions"]] == [
        "recall_slo.live_recall_estimate"]


def test_overload_goodput_classification():
    """ISSUE 10: the overload scenario's goodput figures regress like QPS
    — but only on the SHAPED arm. The qos_off arm is the intentional
    collapse demonstration (noisy by design), and the raw load
    accounting (shed/expired/offered counts) tracks the offered rate,
    not code quality."""
    assert bench_diff.classify("overload.qos_on.goodput_qps") == "qps"
    assert bench_diff.classify("overload.goodput_ratio_on_vs_off") == "qps"
    assert bench_diff.classify("overload.capacity_qps") == "qps"
    assert bench_diff.classify(
        "overload.qos_on.steady_state_recompiles") == "recompiles"
    # never regression signals:
    assert bench_diff.classify("overload.qos_off.goodput_qps") is None
    assert bench_diff.classify("overload.qos_off.served_p99_ms") is None
    assert bench_diff.classify("overload.qos_on.shed") is None
    assert bench_diff.classify("overload.qos_on.expired") is None
    assert bench_diff.classify("overload.qos_on.offered") is None
    assert bench_diff.classify("overload.deadline_ms") is None


def test_overload_goodput_drop_is_a_regression(tmp_path):
    old = {"overload": {
        "capacity_qps": 1800.0, "deadline_ms": 250.0,
        "qos_on": {"goodput_qps": 1200.0, "shed": 2400, "expired": 10},
        "qos_off": {"goodput_qps": 120.0},
        "goodput_ratio_on_vs_off": 10.0,
    }}
    new = copy.deepcopy(old)
    new["overload"]["qos_on"]["goodput_qps"] = 600.0   # halved: regression
    new["overload"]["qos_off"]["goodput_qps"] = 30.0   # noisy arm: ignored
    new["overload"]["qos_on"]["shed"] = 3100           # load figure: ignored
    new["overload"]["goodput_ratio_on_vs_off"] = 20.0  # improved
    result = bench_diff.compare(old, new)
    assert [r["path"] for r in result["regressions"]] == [
        "overload.qos_on.goodput_qps"]


def test_integrity_overhead_classification():
    """ISSUE 11: the integrity_scrub scenario's p99_overhead_pct is an
    instrumentation-cost figure — a percentage compared in absolute
    points, not a latency magnitude; the raw per-arm p99_ms stays
    unclassified (CPU latency noise must not gate rounds)."""
    assert bench_diff.classify(
        "integrity_scrub.p99_overhead_pct") == "overhead"
    assert bench_diff.classify(
        "integrity_scrub.steady_state_recompiles_on") == "recompiles"
    assert bench_diff.classify("integrity_scrub.p99_ms_on") is None
    assert bench_diff.classify("integrity_scrub.p99_ms_off") is None
    assert bench_diff.classify("integrity_scrub.scrub_passes") is None


def test_integrity_overhead_growth_is_a_regression():
    old = {"integrity_scrub": {
        "p99_overhead_pct": 1.5, "p99_ms_on": 10.0, "p99_ms_off": 9.9,
        "steady_state_recompiles_on": 0,
    }}
    new = copy.deepcopy(old)
    new["integrity_scrub"]["p99_overhead_pct"] = 3.0   # +1.5pt: in band
    result = bench_diff.compare(old, new)
    assert result["regressions"] == []
    new["integrity_scrub"]["p99_overhead_pct"] = 9.0   # +7.5pt: regression
    result = bench_diff.compare(old, new)
    assert [r["path"] for r in result["regressions"]] == [
        "integrity_scrub.p99_overhead_pct"]


def test_pipeline_sweep_classification():
    """ISSUE 15: the pipeline_sweep scenario rides the key-name rules —
    saturation_qps is a throughput figure, dispatch_overhead_pct an
    absolute-points overhead figure, steady_state_recompiles the zero
    invariant; stage fractions and sha strings are diagnostics."""
    assert bench_diff.classify(
        "pipeline_sweep.depths.2.saturation_qps") == "qps"
    assert bench_diff.classify(
        "pipeline_sweep.depths.2.dispatch_overhead_pct") == "overhead"
    assert bench_diff.classify(
        "pipeline_sweep.depths.2.steady_state_recompiles") == "recompiles"
    assert bench_diff.classify(
        "pipeline_sweep.depths.2.stage_fractions.dispatch") is None


def test_pipeline_sweep_regressions(tmp_path):
    old = {"pipeline_sweep": {
        "serial": {"saturation_qps": 4000.0,
                   "steady_state_recompiles": 0},
        "depths": {"2": {"saturation_qps": 5000.0,
                         "dispatch_overhead_pct": 4.0,
                         "steady_state_recompiles": 0}},
    }}
    new = copy.deepcopy(old)
    new["pipeline_sweep"]["depths"]["2"]["saturation_qps"] = 2000.0
    new["pipeline_sweep"]["depths"]["2"]["dispatch_overhead_pct"] = 12.0
    new["pipeline_sweep"]["depths"]["2"]["steady_state_recompiles"] = 1
    result = bench_diff.compare(old, new)
    assert sorted(r["path"] for r in result["regressions"]) == [
        "pipeline_sweep.depths.2.dispatch_overhead_pct",
        "pipeline_sweep.depths.2.saturation_qps",
        "pipeline_sweep.depths.2.steady_state_recompiles",
    ]


def test_event_overhead_classification():
    """ISSUE 20: the flight recorder's bench keys — per-scenario
    decision counts (events_emitted, tuner_events, tier_events) are
    cadence accounting, never a regression signal; the overhead_pct
    keys ride the absolute-points rule; the added-recompiles count
    rides the zero invariant."""
    assert bench_diff.classify("recall_slo.events_emitted") is None
    assert bench_diff.classify("recall_slo.tuner_events") is None
    assert bench_diff.classify("memory_pressure.tier_events") is None
    assert bench_diff.classify("event_overhead.events_emitted") is None
    assert bench_diff.classify(
        "event_overhead.p50_overhead_pct") == "overhead"
    assert bench_diff.classify(
        "mixed_rw.event_overhead_pct") == "overhead"
    assert bench_diff.classify(
        "event_overhead.events_added_recompiles") == "recompiles"
    assert bench_diff.classify("event_overhead.p50_ms_on") is None
    assert bench_diff.classify("event_overhead.p50_ms_off") is None
    # the end-to-end arm comparison is informational — CI-host noise
    # swamps a ~20us emit — and must never gate a round
    assert bench_diff.classify("event_overhead.arm_delta_pct") is None
    assert bench_diff.classify("event_overhead.emit_us_per_event") is None


def test_event_overhead_growth_is_a_regression():
    old = {"event_overhead": {
        "p50_overhead_pct": 0.3, "p50_ms_on": 5.0, "p50_ms_off": 4.99,
        "events_emitted": 240, "events_added_recompiles": 0,
    }}
    new = copy.deepcopy(old)
    new["event_overhead"]["p50_overhead_pct"] = 1.5    # +1.2pt: in band
    new["event_overhead"]["events_emitted"] = 480      # cadence, not perf
    result = bench_diff.compare(old, new)
    assert result["regressions"] == []
    new["event_overhead"]["p50_overhead_pct"] = 9.0    # +8.7pt: regression
    new["event_overhead"]["events_added_recompiles"] = 2
    result = bench_diff.compare(old, new)
    assert sorted(r["path"] for r in result["regressions"]) == [
        "event_overhead.events_added_recompiles",
        "event_overhead.p50_overhead_pct",
    ]
