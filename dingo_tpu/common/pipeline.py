"""Stall-free serving pipeline primitives (ROADMAP open item 5).

Three small pieces the coalescer composes into an overlapped hot path:

- ``StagingRing`` — per-key double-buffered query staging. ``stage()``
  pads the stacked host batch into a reusable pow2-ladder host buffer
  (same shape discipline as ``_pad_batch``: zeroed tail rows) and starts
  the H2D upload off the device-lock critical section, so batch N+1's
  transfer overlaps batch N's compute. Depth-bounded: at most
  ``depth`` staged batches may be outstanding per key; ``stage()``
  blocks when the ring is full (natural backpressure toward admission).

- ``CompletionLane`` — a single drainer thread that owns every
  ``jax.device_get`` of the pipelined path. The flush thread dispatches
  kernels for ALL due batches, hands each a ``resolve()`` thunk here,
  and never blocks on D2H. Handoffs resolve in FIFO order (= dispatch
  order), which keeps per-future completion deterministic.

- the handoff protocol — anything with ``resolve()`` and ``abandon()``
  can ride the lane. ``abandon()`` is the stop(drain=False) contract:
  fail the futures, but still run the fetch so device-side leases are
  released (a dropped resolve must not leak SlotStore limbo slots).

Host-buffer reuse safety: a ring slot is only reissued after its
``StagedBatch.release()``, which the lane calls after the resolve's
``device_get`` completed — by then nothing on the device reads the
buffer, so ``np.copyto`` into it cannot race a pending transfer.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


class StagedBatch:
    """One staged query batch: the original host array it was built from
    plus its padded on-device upload. Index families accept this via the
    ``staged=`` kwarg and use ``take()`` to claim the upload — the
    identity check makes staleness impossible: if `_prep_queries`
    rebound the array (binary bit-unpack, dtype cast), ``take`` returns
    None and the family falls back to its own pad+upload."""

    __slots__ = ("src", "qpad", "rows", "_ring", "_slot", "_released")

    def __init__(self, src: np.ndarray, qpad, rows: int,
                 ring: "StagingRing", slot: int):
        self.src = src
        self.qpad = qpad
        self.rows = rows
        self._ring = ring
        self._slot = slot
        self._released = False

    def take(self, queries) -> Optional[Any]:
        """Return the staged device upload iff ``queries`` is the exact
        array this batch was staged from (post-`_prep_queries` identity
        survives for float families because ``np.asarray`` with a
        matching dtype returns the same object)."""
        if queries is self.src:
            return self.qpad
        return None

    def release(self) -> None:
        """Return the host buffer slot to the ring. Idempotent. Call
        only after the batch's results were fetched to host (or the
        dispatch never happened) — see module docstring."""
        if self._released:
            return
        self._released = True
        self.qpad = None
        ring, self._ring = self._ring, None
        if ring is not None:
            ring._return_slot(self._slot)


class StagingRing:
    """Per-coalescer-key ring of ``depth`` reusable host staging buffers.

    Buffers are pow2-ladder shaped ([_next_pow2(b), *tail], matching
    ``_pad_batch``) and zero-padded on every ``stage`` so the padded
    rows are byte-identical to the serial path's ``np.zeros`` pad. A
    slot whose cached buffer doesn't fit the requested (shape, dtype)
    is reallocated in place — the ladder keeps that rare at steady
    state."""

    def __init__(self, depth: int = 2):
        self.depth = max(1, int(depth))
        self._free = threading.Semaphore(self.depth)
        self._lock = threading.Lock()
        self._slots: List[Optional[np.ndarray]] = [None] * self.depth
        self._avail: deque = deque(range(self.depth))
        self._closed = False

    def stage(self, stacked: np.ndarray) -> StagedBatch:
        """Pad ``stacked`` into a ring buffer and start its device
        upload. Blocks while all ``depth`` slots are in flight."""
        import jax.numpy as jnp

        self._free.acquire()
        with self._lock:
            if self._closed:
                self._free.release()
                raise RuntimeError("staging ring closed")
            slot = self._avail.popleft()
            buf = self._slots[slot]
        b = stacked.shape[0]
        bb = _next_pow2(max(1, b))
        shape = (bb,) + stacked.shape[1:]
        if buf is None or buf.shape != shape or buf.dtype != stacked.dtype:
            buf = np.zeros(shape, stacked.dtype)
            with self._lock:
                self._slots[slot] = buf
        np.copyto(buf[:b], stacked)
        if bb != b:
            buf[b:] = 0
        qpad = jnp.asarray(buf)
        return StagedBatch(stacked, qpad, b, self, slot)

    def _return_slot(self, slot: int) -> None:
        with self._lock:
            self._avail.append(slot)
        self._free.release()

    def close(self) -> None:
        with self._lock:
            self._closed = True


class CompletionLane:
    """Single-thread FIFO drain for pipelined resolves. The lane thread
    is the only place the pipelined path calls ``jax.device_get`` — the
    flush thread stays free to dispatch the next due batch (dingolint's
    resolve-sync checker enforces the flush-thread side)."""

    def __init__(self, name: str = "dingo-completion-lane"):
        self._name = name
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._active = False  # a handoff is mid-resolve on the lane

    def submit(self, handoff) -> bool:
        """Enqueue a handoff for resolution. Returns False once the lane
        is stopped — the caller must resolve (or abandon) inline."""
        with self._cv:
            if self._stopped:
                return False
            self._queue.append(handoff)
            if self._thread is None:
                # each handoff carries its run_span explicitly and
                # _Handoff.resolve() re-attaches it on the lane thread
                # dingolint: ok[context-handoff] span travels in the handoff
                self._thread = threading.Thread(
                    target=self._loop, name=self._name, daemon=True
                )
                self._thread.start()
            self._cv.notify_all()
        return True

    def depth(self) -> int:
        with self._cv:
            return len(self._queue) + (1 if self._active else 0)

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    self._cv.wait(timeout=0.5)
                if not self._queue:
                    if self._stopped:
                        return
                    continue
                handoff = self._queue.popleft()
                self._active = True
            try:
                handoff.resolve()
            except Exception:  # noqa: BLE001 — handoff owns its futures
                pass
            finally:
                with self._cv:
                    self._active = False
                    self._cv.notify_all()

    def stop(self, drain: bool = True, timeout: float = 10.0) -> None:
        """Stop the lane. drain=True resolves everything queued first
        (futures get real results); drain=False abandons queued handoffs
        (futures fail fast, device leases still released)."""
        with self._cv:
            self._stopped = True
            abandoned: Tuple = ()
            if not drain:
                abandoned = tuple(self._queue)
                self._queue.clear()
            self._cv.notify_all()
        for handoff in abandoned:
            try:
                handoff.abandon()
            except Exception:  # noqa: BLE001
                pass
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)


class KeyedStaging:
    """Map coalescer keys to their StagingRing lazily (a key's first
    pipelined flush creates its ring)."""

    def __init__(self, depth: int = 2):
        self.depth = max(1, int(depth))
        self._lock = threading.Lock()
        self._rings: Dict[Any, StagingRing] = {}

    def ring(self, key) -> StagingRing:
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = StagingRing(self.depth)
            return ring

    def close(self) -> None:
        with self._lock:
            rings = list(self._rings.values())
            self._rings.clear()
        for ring in rings:
            ring.close()
