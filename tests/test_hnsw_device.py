"""Device graph tier (ISSUE 8): batched beam-search HNSW on the device.

Host C++ graph path = parity oracle: the device walk must reach at least
the host path's recall at equal ef, adjacency must stay in sync across
upserts/deletes, the ef/beam shape-bucket ladder must keep steady-state
recompiles at zero, the filter pushdown must match the host post-filter,
and the adjacency must survive a snapshot round-trip.
"""

import numpy as np
import pytest

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.index import FilterSpec, IndexParameter, IndexType, new_index
from dingo_tpu.ops.distance import Metric


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    FLAGS.set("hnsw_device_search", "auto")
    FLAGS.set("hnsw_device_beam", 0)


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(11)
    n, d = 2500, 32
    x = rng.standard_normal((n, d)).astype(np.float32)
    ids = np.arange(n, dtype=np.int64)
    q = x[:12] + 0.01 * rng.standard_normal((12, d)).astype(np.float32)
    return ids, x, q


def hnsw_param(**kw):
    defaults = dict(
        index_type=IndexType.HNSW, dimension=32, nlinks=16,
        efconstruction=80,
    )
    defaults.update(kw)
    return IndexParameter(**defaults)


def exact_topk(x, ids, q, k, metric):
    if metric is Metric.L2:
        score = -(((q[:, None, :] - x[None, :, :]) ** 2).sum(-1))
    elif metric is Metric.COSINE:
        xn = x / np.linalg.norm(x, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q, axis=1, keepdims=True)
        score = qn @ xn.T
    else:
        score = q @ x.T
    return ids[np.argsort(-score, axis=1)[:, :k]]


def recall(res, want, k=10):
    return float(np.mean(
        [len(set(r.ids) & set(w)) / k for r, w in zip(res, want)]
    ))


@pytest.mark.parametrize("metric", [Metric.L2, Metric.INNER_PRODUCT,
                                    Metric.COSINE])
@pytest.mark.parametrize("tier", ["fp32", "bf16", "sq8"])
def test_device_recall_at_least_host(corpus, metric, tier):
    """The acceptance gate: device beam recall@10 >= host recall at equal
    ef, per metric x precision tier."""
    ids, x, q = corpus
    idx = new_index(30, hnsw_param(metric=metric, precision=tier))
    idx.add(ids, x)
    want = exact_topk(x, ids, q, 10, metric)
    FLAGS.set("hnsw_device_search", False)
    r_host = recall(idx.search(q, 10, ef=96), want)
    FLAGS.set("hnsw_device_search", True)
    r_dev = recall(idx.search(q, 10, ef=96), want)
    assert r_dev >= r_host - 1e-9
    if metric is Metric.L2:
        assert r_dev >= 0.9     # the walk actually finds neighbors


def test_device_final_order_matches_host_on_agreeing_sets(corpus):
    """Both paths end in the SAME exact device rerank: when recall is
    saturated the final id ordering is byte-identical."""
    ids, x, q = corpus
    idx = new_index(31, hnsw_param())
    idx.add(ids, x)
    FLAGS.set("hnsw_device_search", False)
    host = idx.search(q, 10, ef=128)
    FLAGS.set("hnsw_device_search", True)
    dev = idx.search(q, 10, ef=128)
    want = exact_topk(x, ids, q, 10, Metric.L2)
    if recall(host, want) == 1.0 and recall(dev, want) == 1.0:
        for a, b in zip(host, dev):
            np.testing.assert_array_equal(a.ids, b.ids)
            np.testing.assert_allclose(a.distances, b.distances,
                                       rtol=1e-6, atol=1e-5)


def test_incremental_upsert_delete_adjacency_sync(corpus):
    """Writes dirty the mirror; the next device search re-exports and the
    walk sees the new/removed rows."""
    ids, x, q = corpus
    idx = new_index(32, hnsw_param())
    idx.add(ids[:2000], x[:2000])
    FLAGS.set("hnsw_device_search", True)
    rb = METRICS.counter("hnsw.adjacency_rebuilds", region_id=32)
    idx.search(q, 10, ef=64)
    rb0 = rb.get()
    # repeated read-only searches must NOT re-export
    idx.search(q, 10, ef=64)
    assert rb.get() == rb0
    # new rows become findable after one search-triggered resync
    idx.upsert(ids[2000:2300], x[2000:2300])
    res = idx.search(x[2000:2300:30], 1, ef=64)
    assert rb.get() == rb0 + 1
    hit = np.mean([
        len(r.ids) and r.ids[0] == want_id
        for r, want_id in zip(res, ids[2000:2300:30])
    ])
    assert hit >= 0.9
    # deleted rows disappear from device results
    idx.delete(ids[:500])
    res = idx.search(q, 20, ef=128)
    for r in res:
        assert (r.ids >= 500).all()
    assert rb.get() == rb0 + 2


def test_steady_state_recompiles_zero_under_ladder(corpus):
    """After warmup over the (batch, beam) buckets, serving with any
    ef/batch inside those buckets never retraces (the monitored PR 3/5
    invariant extended to the beam kernel family)."""
    ids, x, q = corpus
    idx = new_index(33, hnsw_param())
    idx.add(ids, x)
    FLAGS.set("hnsw_device_search", True)
    idx.warmup(batches=(1, 8, 32), topk=10, ef=64)
    rc = METRICS.counter("xla.recompiles")
    rc0 = rc.get()
    for b, ef in ((1, 64), (5, 60), (8, 49), (27, 64), (32, 52)):
        idx.search(q[:1].repeat(b, axis=0), 10, ef=ef)
    assert rc.get() - rc0 == 0


def test_filter_pushdown_equivalence(corpus):
    """Masked candidates never enter the result beam: device results
    satisfy the filter, recall matches the host post-filter path, and the
    second identical filter hits the (fingerprint, store version) cache."""
    ids, x, q = corpus
    idx = new_index(34, hnsw_param())
    idx.add(ids, x)
    spec = FilterSpec(ranges=[(500, 1500)])
    sub = (ids >= 500) & (ids < 1500)
    want = exact_topk(x[sub], ids[sub], q, 10, Metric.L2)
    FLAGS.set("hnsw_device_search", False)
    r_host = recall(idx.search(q, 10, spec, ef=160), want)
    FLAGS.set("hnsw_device_search", True)
    hits = METRICS.counter("hnsw.filter_mask_hits", region_id=34)
    h0 = hits.get()
    res = idx.search(q, 10, spec, ef=160)
    for r in res:
        assert ((r.ids >= 500) & (r.ids < 1500)).all()
    assert recall(res, want) >= r_host - 1e-9
    idx.search(q, 10, spec, ef=160)
    assert hits.get() > h0


def test_snapshot_roundtrip_adjacency(tmp_path, corpus):
    """hnsw_adj.npz + meta restore the device mirror without a native
    re-export, and the restored index serves identical device results."""
    ids, x, q = corpus
    idx = new_index(35, hnsw_param())
    idx.add(ids[:2000], x[:2000])
    FLAGS.set("hnsw_device_search", True)
    before = idx.search(q, 10, ef=96)
    idx.save(str(tmp_path))
    idx2 = new_index(35, hnsw_param())
    idx2.load(str(tmp_path))
    assert idx2.store.adj is not None
    np.testing.assert_array_equal(
        np.asarray(idx.store.adj), np.asarray(idx2.store.adj)
    )
    rb = METRICS.counter("hnsw.adjacency_rebuilds", region_id=35)
    rb0 = rb.get()
    after = idx2.search(q, 10, ef=96)
    assert rb.get() == rb0      # mirror restored from the snapshot
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a.ids, b.ids)


def test_sq8_snapshot_keeps_codes(tmp_path, corpus):
    """sq8 persists codes + codec params (no re-encode on load), so the
    restored device walk is bit-identical to the saved one."""
    ids, x, q = corpus
    idx = new_index(36, hnsw_param(precision="sq8"))
    idx.add(ids[:1500], x[:1500])
    FLAGS.set("hnsw_device_search", True)
    before = idx.search(q, 10, ef=96)
    idx.save(str(tmp_path))
    idx2 = new_index(36, hnsw_param(precision="sq8"))
    idx2.load(str(tmp_path))
    after = idx2.search(q, 10, ef=96)
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a.ids, b.ids)


def test_entry_tombstone_falls_back(corpus):
    """Deleting most of the graph (possibly including the entry node)
    still leaves the device walk serving the remaining rows."""
    ids, x, q = corpus
    idx = new_index(37, hnsw_param())
    idx.add(ids[:300], x[:300])
    idx.delete(ids[:250])
    FLAGS.set("hnsw_device_search", True)
    res = idx.search(q, 5, ef=64)
    for r in res:
        assert len(r.ids) > 0
        assert ((r.ids >= 250) & (r.ids < 300)).all()


def test_device_empty_index(corpus):
    FLAGS.set("hnsw_device_search", True)
    idx = new_index(38, hnsw_param())
    res = idx.search(np.zeros((2, 32), np.float32), 5)
    assert all(len(r.ids) == 0 for r in res)
