"""Aggregation pushdown (reference src/coprocessor/aggregation.h:
AggregationManager with SUM/COUNT/COUNT_WITH_NULL/MAX/MIN aggregators applied
during scans)."""

from __future__ import annotations

import enum
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple


class AggOp(enum.Enum):
    SUM = "sum"
    COUNT = "count"
    COUNT_WITH_NULL = "count_with_null"
    MAX = "max"
    MIN = "min"


class Aggregator:
    def __init__(self, specs: Sequence[Tuple[str, AggOp]]):
        """specs: list of (field, op)."""
        self.specs = list(specs)

    def run(self, rows: Iterable[Dict[str, Any]]) -> List[Optional[Any]]:
        acc: List[Optional[Any]] = [None] * len(self.specs)
        counts = [0] * len(self.specs)
        for row in rows:
            for i, (field, op) in enumerate(self.specs):
                v = row.get(field)
                if op is AggOp.COUNT_WITH_NULL:
                    counts[i] += 1
                    continue
                if v is None:
                    continue
                counts[i] += 1
                if op is AggOp.SUM:
                    acc[i] = v if acc[i] is None else acc[i] + v
                elif op is AggOp.MAX:
                    acc[i] = v if acc[i] is None else max(acc[i], v)
                elif op is AggOp.MIN:
                    acc[i] = v if acc[i] is None else min(acc[i], v)
        out: List[Optional[Any]] = []
        for i, (field, op) in enumerate(self.specs):
            if op in (AggOp.COUNT, AggOp.COUNT_WITH_NULL):
                out.append(counts[i])
            else:
                out.append(acc[i])
        return out
