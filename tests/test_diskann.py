"""DiskANN role: disk-resident PQ-pruned index + proxy index type
(reference src/diskann/ role + VectorIndexDiskANN proxy,
diskann_service_handle.h:29-62, vector_index_diskann.h:24,173)."""

import time

import numpy as np
import pytest

from dingo_tpu.common.config import FLAGS
from dingo_tpu.diskann.core import CoreState, DiskAnnCore, DiskAnnError
from dingo_tpu.diskann.item import DiskAnnItemManager
from dingo_tpu.index.base import IndexParameter, IndexType, NotSupported
from dingo_tpu.index.factory import new_index
from dingo_tpu.server.rpc import DingoServer

DIM = 64


def make_param(**kw):
    return IndexParameter(
        index_type=IndexType.DISKANN, dimension=DIM, ncentroids=16,
        nsubvector=8, default_nprobe=8, **kw,
    )


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(13)
    centers = rng.standard_normal((16, DIM)).astype(np.float32)
    x = centers[rng.integers(0, 16, 5000)] + 0.15 * rng.standard_normal(
        (5000, DIM)
    ).astype(np.float32)
    return np.arange(5000, dtype=np.int64), x


def test_core_lifecycle_and_recall(tmp_path, corpus):
    ids, x = corpus
    core = DiskAnnCore(1, make_param(), str(tmp_path / "d1"))
    assert core.status() is CoreState.UNINIT
    with pytest.raises(DiskAnnError):
        core.build()  # nothing imported
    core.push_data(ids[:3000], x[:3000], has_more=True)
    assert core.status() is CoreState.IMPORTING
    core.push_data(ids[3000:], x[3000:], has_more=False)
    assert core.status() is CoreState.IMPORTED
    with pytest.raises(DiskAnnError):
        core.search(x[:1], 5)  # not loaded
    core.build()
    assert core.status() is CoreState.BUILT
    core.load()
    assert core.status() is CoreState.LOADED

    q = x[:16] + 0.01
    res = core.search(q, 10, nprobe=16)
    d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    gt = np.argsort(d2, axis=1)[:, :10]
    recall = np.mean([
        len(set(r_ids) & set(ids[g])) / 10 for (r_ids, _), g in zip(res, gt)
    ])
    assert recall >= 0.8, recall  # PQ prune + exact disk rerank
    # exact distances from the rerank (not ADC approximations)
    top_ids, top_d = res[0]
    np.testing.assert_allclose(top_d[0], d2[0, top_ids[0]], rtol=1e-2, atol=1e-3)


def test_core_restart_try_load(tmp_path, corpus):
    """A new process can try_load a previously built index from disk."""
    ids, x = corpus
    d = str(tmp_path / "d2")
    core = DiskAnnCore(2, make_param(), d)
    core.push_data(ids[:2000], x[:2000], has_more=False)
    core.build()
    core2 = DiskAnnCore(2, make_param(), d)
    core2.count = 2000
    assert core2.try_load() is True
    res = core2.search(x[:2], 3, nprobe=16)
    assert res[0][0][0] == 0
    core3 = DiskAnnCore(3, make_param(), str(tmp_path / "d3"))
    assert core3.try_load() is False


def test_reset_close_destroy(tmp_path, corpus):
    ids, x = corpus
    core = DiskAnnCore(4, make_param(), str(tmp_path / "d4"))
    core.push_data(ids[:500], x[:500], has_more=False)
    core.build()
    core.load()
    core.close()
    assert core.status() is CoreState.BUILT
    core.load()
    core.reset(delete_data_file=True)
    assert core.status() is CoreState.UNINIT and core.count == 0
    core.destroy()


def test_proxy_index_over_grpc(tmp_path, corpus):
    """Full remote flow through the factory: VECTOR_INDEX_TYPE_DISKANN is
    creatable and serves build/search/status over RPC."""
    ids, x = corpus
    manager = DiskAnnItemManager(str(tmp_path / "server"))
    server = DingoServer()
    server.host_diskann_role(manager)
    port = server.start()
    FLAGS.set("diskann_server_addr", f"127.0.0.1:{port}")
    try:
        idx = new_index(7, make_param())
        idx.upsert(ids[:3000], x[:3000])
        idx.upsert(ids[3000:], x[3000:], has_more=False)
        assert idx.get_count() == 5000
        state = idx.build(sync=False)  # async build via the worker
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = idx.remote_status()
            if st.state == "built":
                break
            assert st.state in ("building", "imported"), st.state
            time.sleep(0.1)
        assert idx.remote_status().state == "built"
        assert idx.load_remote() == "loaded"
        res = idx.search(x[:4] + 0.01, 5)
        assert [r.ids[0] for r in res] == [0, 1, 2, 3]
        with pytest.raises(NotSupported):
            idx.delete(ids[:1])
        idx.close()
    finally:
        FLAGS.set("diskann_server_addr", "")
        manager.stop()
        server.stop()
