"""Search request coalescing: merge concurrent same-shaped searches into
one device batch — grown into the QoS admission/batch-forming layer.

The reference absorbs request-level parallelism with bthread worker sets
(runnable.h:138-291, index_service.cc:362-365) — more threads, same
per-request kernel. On a TPU the economics invert: one [64, d] matmul
costs barely more than one [1, d], so the win is filling the batch
dimension. A coalescer queues requests for the same (region, topk, search
params) key inside a small time window and launches ONE kernel; each
caller gets its slice back.

Latency math on the axon tunnel: the D2H hop is ~60-80 ms, so a ~2 ms
collection window is noise for the requests it helps and a large QPS
multiplier under concurrency.

QoS (``qos.enabled``, obs/pressure.py is the sensor/policy home): the
queue in front of the kernel is the ONLY place admission can act, so the
coalescer is where the loop closes:

- **admission** — a request whose budget is already spent is rejected
  before it queues (its future carries ``DeadlineExceeded``; no kernel is
  ever dispatched for it). Under pressure (estimated wait beyond
  ``qos.max_queue_ms``) low-priority work is shed at admission, and any
  request that could not finish inside its own remaining budget anyway
  is shed as hopeless — serving it late would burn capacity that an
  in-deadline request needs. A per-tenant queued-row cap
  (``qos.tenant_queue_rows``) bounds any one tenant's share of the queue.
- **priority batch forming** — entries dispatch highest-priority-first
  inside a batch, and the full-batch flush threshold sits ON the pow2
  pad ladder (index/flat._pad_batch), so a full batch is exactly a warm
  program shape: batch forming never mints a compile (the PR 5 sentinel
  makes this a tested invariant).
- **expiry before dispatch** — entries whose deadline passed while
  queued (or whose remaining budget cannot cover the estimated run) are
  failed at flush time and their queries EXCLUDED from the stacked
  batch; a batch of only dead entries skips the kernel entirely.
- **accounting** — queue-wait, per-stage budget fractions, demand,
  shed/expired counters all land in the ``qos.*`` family via PRESSURE.

Every QoS decision is budget-driven; with ``qos.enabled = false`` submit
takes the exact pre-QoS path (one flag read).

Tracing: each submit opens a ``coalesce.wait`` span (queue time) as a
child of the caller's current span; the batch run opens ``coalesce.run``
parented to the FIRST sampled waiter and attaches it on the flush thread,
so device-side spans nest into that caller's trace across the handoff.
The batch size and co-batched trace ids ride as span attributes. The
request BUDGET makes the same handoff: captured from the contextvar at
submit, carried on the entry, consulted on the flush thread.

Shutdown contract: ``submit()`` never raises and never hangs — it always
returns a Future, and every returned Future resolves deterministically.
A submit racing ``stop(drain=False)`` gets a ``CoalescerStopped`` future:
the admitted-vs-stopped decision happens atomically under the queue lock
(the pre-QoS code checked the stop flag and appended in one critical
section too, but ANY admission work between the check and the append —
exactly what QoS adds — would have opened a window where a request could
slip into a queue nobody will ever flush; the decision is now made at
append time, where it cannot be stale).

Stall-free pipeline (``pipeline.enabled``, common/pipeline.py): when a
``dispatch_fn`` is provided and the tri-state flag resolves on, the
flush loop splits dispatch from resolve. Each due batch's kernels are
dispatched (priority order, same device_lock discipline — dispatch_fn
returns a resolve thunk without syncing), so region B's kernel overlaps
region A's D2H fetch; the thunks then drain FIFO on a CompletionLane
thread, the only place the pipelined path calls ``jax.device_get``.
Query staging (pad + H2D upload) moves into a per-key StagingRing of
``pipeline.depth`` pow2-ladder host buffers so batch N+1's upload
overlaps batch N's compute. Expiry-before-dispatch runs inside
``_dispatch`` — i.e. at REAL dispatch time even for cap-displaced
batches — and per-stage accounting books the enqueue cost under a
``dispatch`` stage instead of inflating kernel time. The shutdown
contract extends to the lane: stop(drain=True) resolves queued
handoffs, stop(drain=False) abandons them (futures fail fast, but the
fetch still runs so device-side SearchLeases are released).

In-flight dedupe (``cache.enabled``, dingo_tpu/cache/): identical query
rows inside one flush collapse to a single kernel row fanned out to
every waiter's future — entries in a batch already share the (region,
topn, params) key, so row identity is the query bytes (PR 11 row
fingerprints). The plan is built from the POST-expiry, priority-sorted
survivors: an expired member has already failed its own future and
cannot drag duplicate siblings down, first occurrence wins the kernel
slot (the collapsed row dispatches at its highest-priority member's
position), and the hopeless-shed estimate in ``_expire_dead`` prices
the batch at its DEDUPED row count — the kernel cost actually being
bought — so a duplicate-heavy flush is never shed on a phantom row
count (each member's own deadline is still checked individually). The
batch shrinks BEFORE padding/staging, so the pow2 ladder, staging rings
and the one-sync-per-reply contract are untouched.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from dingo_tpu.trace import NOOP_SPAN, TRACER


class CoalescerStopped(RuntimeError):
    """Set on futures whose batch was discarded by stop(drain=False) or
    that arrived after (or concurrently with) stop()."""


#: dispatch-time safety factor on the estimated batch run: an entry whose
#: remaining budget cannot cover ~2x the estimated run would expire
#: mid-flight more often than not — serving it is wasted capacity AND a
#: late reply, the worst of both (2x covers run-time variance on a
#: contended host; the EWMA itself tracks the mean, and under overload
#: shedding a marginal request is strictly cheaper than serving it late)
_EXPIRY_RUN_MARGIN = 2.0


def _prev_pow2(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


class _Entry:
    """One submit: its queries plus everything the flush thread needs."""

    __slots__ = ("queries", "future", "wait_span", "budget", "priority",
                 "tenant", "region_id", "t0", "qos")

    def __init__(self, queries, future, wait_span, budget, region_id,
                 qos=False):
        self.queries = queries
        self.future = future
        self.wait_span = wait_span
        self.budget = budget
        self.priority = budget.priority if budget is not None else 1
        self.tenant = budget.tenant if budget is not None else "default"
        self.region_id = region_id
        self.t0 = time.monotonic()
        #: admitted under QoS accounting: dequeue/row-release must mirror
        #: the admit-side bookkeeping even if the flag flips mid-flight
        self.qos = qos


class _PendingBatch:
    __slots__ = ("entries", "created")

    def __init__(self):
        self.entries: List[_Entry] = []
        self.created = time.monotonic()

    def rows(self) -> int:
        return sum(len(e.queries) for e in self.entries)


class SearchCoalescer:
    """Batches `search(queries) -> per-query results` calls per key.

    run_fn(key, queries[batch, d]) must return a list of per-query result
    rows; callers receive exactly their rows. run_fn may optionally accept
    a ``stage_us`` dict kwarg (the VectorReader stage-timing contract) —
    when it does, the coalescer reads kernel/rerank stage splits out of it
    for the per-stage budget accounting. Flush happens when the window
    expires or the batch hits max_batch. One daemon timer thread serves all
    keys, sleeping until the earliest pending deadline; a caller whose own
    submission fills a batch runs that batch inline (its results are in
    it), while a cap-displaced previous batch is flushed on its own thread
    so the new caller never pays for a search it is not part of and the
    timer thread stays free for other keys' expiries.
    """

    def __init__(self, run_fn: Callable[[Any, np.ndarray], Sequence],
                 window_ms: float = 2.0, max_batch: int = 256,
                 dispatch_fn: Optional[Callable] = None):
        self.run_fn = run_fn
        self.dispatch_fn = dispatch_fn
        self.window_s = window_ms / 1000.0
        self.max_batch = max_batch
        import inspect

        try:
            self._run_takes_stages = "stage_us" in inspect.signature(
                run_fn).parameters
        except (TypeError, ValueError):
            self._run_takes_stages = False
        self._dispatch_params = frozenset()
        if dispatch_fn is not None:
            try:
                self._dispatch_params = frozenset(
                    inspect.signature(dispatch_fn).parameters)
            except (TypeError, ValueError):
                pass
        # pipelined-path state: the lane thread starts lazily on the
        # first handoff; staging rings materialize per key on first use
        from dingo_tpu.common.pipeline import CompletionLane

        self._lane = CompletionLane()
        self._staging = None
        #: cumulative per-stage wall time (ms) across all pipelined
        #: flushes — bench reads dispatch_overhead_fraction from here
        #: without needing QoS budget plumbing
        self.stage_totals_ms: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._pending: Dict[Any, _PendingBatch] = {}
        #: cap-displaced batches awaiting the timer thread (QoS mode):
        #: serialized dispatch keeps the service-rate model honest —
        #: ad-hoc flush threads racing each other would make every run
        #: slower than the EWMA the admission estimates are built on
        self._ready: List = []
        #: queued query rows per tenant (admission cap bookkeeping)
        self._tenant_rows: Dict[str, int] = {}
        #: EWMA of per-row service time / per-batch run time. Zero until
        #: the first measurement — but the admission estimate no longer
        #: reads that zero as "service is free": estimated_wait_ms
        #: prices unmeasured queues at the cost model's conservative
        #: ``cost.prior_row_ms`` prior, so even the FIRST overload burst
        #: sheds. The per-(kernel, shape) surface in obs/cost.py refines
        #: these scalars as real timings land.
        self._ewma_row_ms = 0.0
        self._ewma_run_ms = 0.0
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(
            target=self._flush_loop, name="search-coalescer", daemon=True
        )
        self._thread.start()

    # -- QoS helpers ---------------------------------------------------------
    def _queued_rows(self) -> int:
        # the backlog is BOTH queues: window-pending batches AND cap-
        # displaced batches awaiting the timer thread — under overload
        # most of the real wait sits in _ready, and an estimate that
        # ignored it would under-shed exactly when shedding matters
        return (sum(b.rows() for b in self._pending.values())
                + sum(b.rows() for _, b in self._ready))

    def estimated_wait_ms(self, extra_rows: int = 0,
                          key: Any = None) -> float:
        """Admission estimate: rows ahead priced by the per-shape cost
        model (obs/cost.py) when this key's kernel has been measured,
        the scalar per-row EWMA otherwise, plus one batch run (the one
        possibly in flight). Before ANY sample has landed the estimate
        is the conservative ``cost.prior_row_ms`` prior — never 0 — so
        the first overload burst sheds instead of riding in on a figure
        nobody measured (the old cold-start hole). Cost model off +
        nothing measured keeps the legacy 0.0 answer."""
        with self._lock:
            rows = self._queued_rows()
        total = rows + extra_rows
        try:
            from dingo_tpu.obs import cost as _cost
        except ImportError:  # pragma: no cover — obs always present
            _cost = None
        if _cost is not None and _cost.cost_enabled():
            kid = _cost.kernel_id(key) if key is not None else None
            if _cost.COST.has_model(kid):
                return (_cost.COST.estimate_run_ms(kid, total)
                        + self._ewma_run_ms)
            if self._ewma_row_ms <= 0:
                return total * _cost.prior_row_ms()
        if self._ewma_row_ms <= 0:
            return 0.0
        return total * self._ewma_row_ms + self._ewma_run_ms

    def _est_run_ms(self, rows: int, key: Any = None) -> float:
        """Expected run time for a batch of `rows`: the key's measured
        per-shape surface when the cost model has one; otherwise the
        per-batch EWMA floor (fixed dispatch overhead) scaled up by the
        per-row cost for batches larger than recent history — a 256-row
        batch must not be judged by the run time of the 8-row batches
        that preceded it."""
        if key is not None:
            try:
                from dingo_tpu.obs import cost as _cost

                if _cost.cost_enabled():
                    kid = _cost.kernel_id(key)
                    if _cost.COST.has_model(kid):
                        return _cost.COST.estimate_run_ms(kid, rows)
            except ImportError:  # pragma: no cover
                pass
        if self._ewma_row_ms <= 0:
            return self._ewma_run_ms
        return max(self._ewma_run_ms, rows * self._ewma_row_ms)

    def _note_run(self, rows: int, run_ms: float,
                  key: Any = None) -> None:
        if rows <= 0 or run_ms <= 0:
            return
        row_ms = run_ms / rows
        a = 0.3
        self._ewma_row_ms = (row_ms if self._ewma_row_ms == 0
                             else a * row_ms + (1 - a) * self._ewma_row_ms)
        self._ewma_run_ms = (run_ms if self._ewma_run_ms == 0
                             else a * run_ms + (1 - a) * self._ewma_run_ms)
        if key is not None:
            try:
                from dingo_tpu.obs import cost as _cost

                _cost.COST.note(_cost.kernel_id(key), rows, run_ms,
                                region_id=_cost.kernel_region(key))
            except ImportError:  # pragma: no cover
                pass

    def _admission_reject(self, budget, n_rows: int, region_id: int,
                          key: Any = None):
        """QoS admission decision for one submit. Returns an exception to
        set on the future (after counting it), or None = admit. Called
        OUTSIDE the queue lock — only estimates are read here."""
        from dingo_tpu.obs import pressure as qp

        if budget is not None and budget.expired():
            qp.PRESSURE.on_expired("admission", region_id, budget)
            return qp.DeadlineExceeded(
                f"deadline exceeded at admission "
                f"({-budget.remaining_ms():.1f}ms past)"
            )
        policy_drops = qp.shed_policy() in ("drop", "degrade_drop")
        if not policy_drops:
            return None
        from dingo_tpu.common.config import FLAGS

        tenant_cap = int(FLAGS.get("qos_tenant_queue_rows"))
        if tenant_cap > 0 and budget is not None:
            with self._lock:
                queued = self._tenant_rows.get(budget.tenant, 0)
            if queued + n_rows > tenant_cap:
                qp.PRESSURE.on_shed("tenant_limit", region_id, budget)
                return qp.RequestShed(
                    f"tenant {budget.tenant} over queue cap "
                    f"({queued}+{n_rows} > {tenant_cap} rows)"
                )
        est_ms = self.estimated_wait_ms(extra_rows=n_rows, key=key)
        if budget is not None and budget.deadline_ms > 0 \
                and est_ms > budget.remaining_ms():
            # hopeless: it would expire in queue — serving it late only
            # burns capacity an in-deadline request needs
            qp.PRESSURE.on_shed("hopeless", region_id, budget)
            return qp.RequestShed(
                f"estimated wait {est_ms:.0f}ms exceeds remaining "
                f"budget {budget.remaining_ms():.0f}ms"
            )
        max_queue_ms = float(FLAGS.get("qos_max_queue_ms"))
        if max_queue_ms > 0:
            # pressure shed by priority: batch/background (0) sheds at
            # half the bound, default (1) at the bound, interactive
            # (>= 2) never pressure-sheds (hopeless-shed still applies)
            prio = budget.priority if budget is not None else 1
            allowed = (0.5 * max_queue_ms if prio <= 0
                       else max_queue_ms if prio == 1
                       else float("inf"))
            if est_ms > allowed:
                qp.PRESSURE.on_shed("pressure", region_id, budget)
                return qp.RequestShed(
                    f"queue pressure {est_ms:.0f}ms over bound "
                    f"{allowed:.0f}ms (priority {prio})"
                )
        return None

    # -- submission ----------------------------------------------------------
    def submit(self, key: Any, queries: np.ndarray,
               max_batch: int = 0, region_id: int = 0) -> Future:
        """Queue queries [n, d] under key; resolves to n result rows.
        max_batch (0 = the coalescer default) caps the STACKED row count
        for this key — merging must never build a batch that would trip a
        limit each request individually respects.

        Never raises, never hangs: admission rejections
        (DeadlineExceeded/RequestShed), shutdown (CoalescerStopped), and
        run errors all resolve the returned future deterministically."""
        cap = min(self.max_batch, max_batch or self.max_batch)
        fut: Future = Future()
        wait_span = TRACER.start_span("coalesce.wait")
        qos = False
        budget = None
        try:
            from dingo_tpu.obs import pressure as qp

            qos = qp.qos_enabled()
            if qos:
                budget = qp.current_budget()
        except ImportError:  # pragma: no cover — obs package always present
            pass
        if qos:
            rejection = self._admission_reject(budget, len(queries),
                                               region_id, key=key)
            if rejection is not None:
                wait_span.end()
                fut.set_exception(rejection)
                return fut
            # a full-ladder batch pads to itself: flushing AT a pow2 row
            # count hands the kernel an exactly-warm shape
            cap = _prev_pow2(cap)
        entry = _Entry(np.asarray(queries), fut, wait_span, budget,
                       region_id, qos=qos)
        flush_now = None
        flush_first = None
        with self._lock:
            if self._stop:
                # the submit-vs-stop(drain=False) race resolved: the
                # stopped check and the append are ONE atomic decision, so
                # this future fails deterministically instead of entering
                # a queue whose flush thread is already gone
                wait_span.end()
                fut.set_exception(CoalescerStopped("coalescer stopped"))
                return fut
            batch = self._pending.get(key)
            if batch is not None and batch.rows() + len(queries) > cap:
                # adding would exceed the cap: flush the queued batch
                # elsewhere (running it HERE would charge the previous
                # batch's whole search to this caller's latency) and
                # start fresh for this request. QoS mode hands it to the
                # timer thread's ready queue — one dispatcher, honest
                # service-rate accounting, expiry checked at the moment
                # it actually runs; classic mode spawns a thread so the
                # timer stays free for other keys' window expiries
                displaced = self._pending.pop(key)
                if qos:
                    self._ready.append((key, displaced))
                    displaced = None
                flush_first = displaced
                batch = None
            if batch is None:
                batch = self._pending[key] = _PendingBatch()
            batch.entries.append(entry)
            if qos:
                self._tenant_rows[entry.tenant] = (
                    self._tenant_rows.get(entry.tenant, 0) + len(queries)
                )
                # admit accounting INSIDE the queue lock: a flush can
                # only pop this batch under the same lock, so on_dequeue
                # can never be observed before its on_admit (an
                # admit-after-release race left phantom queue depth)
                from dingo_tpu.obs.pressure import PRESSURE

                PRESSURE.on_admit(region_id, len(queries), budget)
            if batch.rows() >= cap:
                flush_now = self._pending.pop(key)
        if flush_first is not None:
            threading.Thread(
                target=self._run, args=(key, flush_first),
                name="coalescer-flush", daemon=True,
            ).start()
        if flush_now is not None:
            # the caller's own batch is full: run it inline (lowest
            # latency for everyone already in it); wake the timer too —
            # a QoS-displaced batch may be sitting in the ready queue
            self._wake.set()
            self._run(key, flush_now)
        else:
            self._wake.set()
        return fut

    # -- flushing ------------------------------------------------------------
    def _release_rows(self, entries: List[_Entry]) -> None:
        with self._lock:
            for e in entries:
                if not e.qos:
                    continue
                left = self._tenant_rows.get(e.tenant, 0) - len(e.queries)
                if left > 0:
                    self._tenant_rows[e.tenant] = left
                else:
                    self._tenant_rows.pop(e.tenant, None)

    def _expire_dead(self, entries: List[_Entry], region_id: int,
                     now: float, key: Any = None) -> List[_Entry]:
        """Expiry before dispatch: fail entries that died in queue (or
        whose remaining budget cannot cover the estimated run — they
        WOULD die mid-flight) and return the survivors."""
        from dingo_tpu.obs import pressure as qp

        # pure expiry (the deadline contract) always applies; the
        # hopeless-shed arm is a DROP and obeys the same policy gate as
        # admission ('off'/'degrade' must never fail a live request)
        drops = qp._policy_drops()
        rows = sum(len(e.queries) for e in entries)
        if drops:
            try:
                from dingo_tpu.cache import policy as cache_policy

                if cache_policy.dedupe_enabled():
                    # price the batch at the row count dedupe will
                    # actually dispatch: a duplicate-heavy flush must
                    # not be hopeless-shed on phantom rows (the count
                    # here may still include rows about to expire —
                    # over-counting only errs conservative)
                    from dingo_tpu.cache.dedupe import deduped_rows

                    rows = deduped_rows(entries)
            except ImportError:  # pragma: no cover
                pass
        est_run = _EXPIRY_RUN_MARGIN * self._est_run_ms(rows, key=key)
        live: List[_Entry] = []
        for e in entries:
            if e.budget is None or e.budget.deadline_ms <= 0:
                live.append(e)
                continue
            remaining = e.budget.remaining_ms(now)
            if remaining <= 0:
                qp.PRESSURE.on_expired("queue", region_id, e.budget)
                e.future.set_exception(qp.DeadlineExceeded(
                    f"expired in queue ({-remaining:.1f}ms past deadline)"
                ))
            elif drops and est_run > 0 and remaining < est_run:
                qp.PRESSURE.on_shed("hopeless", region_id, e.budget)
                e.future.set_exception(qp.RequestShed(
                    f"remaining {remaining:.0f}ms cannot cover the "
                    f"~{est_run:.0f}ms batch run"
                ))
            else:
                live.append(e)
        return live

    def _begin_flush(self, key: Any, batch: _PendingBatch,
                     flush_t0: float):
        """Shared flush prologue for the serial and pipelined arms:
        end queue-wait spans, mirror QoS dequeue accounting, expire dead
        entries (this runs at REAL dispatch time — cap-displaced batches
        included), priority-sort the survivors, and open the run span
        parented to the first sampled waiter. Returns
        (entries, region_id, run_span, waits_ms, qos); an empty entries
        list means everything expired (the span is already closed and no
        kernel must dispatch)."""
        qos = False
        try:
            from dingo_tpu.obs import pressure as qp

            qos = qp.qos_enabled()
        except ImportError:  # pragma: no cover
            pass
        entries = batch.entries
        region_id = entries[0].region_id if entries else 0
        run_span = NOOP_SPAN
        links = []
        waits_ms: Dict[int, float] = {}
        for e in entries:
            e.wait_span.end()
            waits_ms[id(e)] = (flush_t0 - e.t0) * 1000.0
            if e.wait_span.sampled:
                if run_span is NOOP_SPAN:
                    run_span = TRACER.start_span(
                        "coalesce.run", parent=e.wait_span.context
                    )
                else:
                    links.append(f"{e.wait_span.trace_id:016x}")
        if any(e.qos for e in entries):
            from dingo_tpu.obs.pressure import PRESSURE

            self._release_rows(entries)
            for e in entries:
                if not e.qos:
                    continue
                PRESSURE.on_dequeue(e.region_id, len(e.queries), e.budget)
                PRESSURE.observe_wait(e.region_id, waits_ms[id(e)],
                                      e.budget)
        if qos:
            entries = self._expire_dead(entries, region_id, flush_t0,
                                        key=key)
            if not entries:
                # a batch of only dead requests dispatches NO kernel
                if run_span is not NOOP_SPAN:
                    run_span.set_attr("all_expired", True)
                    run_span.end()
                return [], region_id, NOOP_SPAN, waits_ms, qos
            # priority batch forming: highest priority first (stable), so
            # the result slicing below follows the dispatch order
            entries = sorted(entries, key=lambda e: -e.priority)
        if run_span is not NOOP_SPAN:
            run_span.set_attr("batch_size",
                              sum(len(e.queries) for e in entries))
            run_span.set_attr("requests", len(entries))
            run_span.set_attr(
                "queue_wait_us",
                int((flush_t0 - batch.created) * 1e6),
            )
            if links:
                run_span.set_attr("cobatched_traces", links)
        return entries, region_id, run_span, waits_ms, qos

    def _form_batch(self, entries: List[_Entry], region_id: int):
        """Stack the survivors' queries, collapsing in-flight duplicates
        when dedupe is on. Returns (stacked, plan): plan is None on the
        plain path (contiguous offset slicing) and a DedupePlan when
        rows collapsed — result fan-out then goes through
        ``plan.rows_for``. Runs AFTER expiry and the priority sort, so
        an expired member never holds a kernel slot and a shared row
        dispatches at its most urgent member's position."""
        plan = None
        try:
            from dingo_tpu.cache import policy as cache_policy

            if cache_policy.dedupe_enabled():
                from dingo_tpu.cache.dedupe import build_plan

                plan = build_plan(entries)
        except ImportError:  # pragma: no cover
            pass
        if plan is None:
            return (np.concatenate([e.queries for e in entries], axis=0),
                    None)
        try:
            from dingo_tpu.cache.edge import CACHE

            CACHE.on_dedup(region_id, plan.collapsed)
        except ImportError:  # pragma: no cover
            pass
        return plan.stacked, plan

    @staticmethod
    def _fan_out(entries: List[_Entry], results, plan) -> None:
        """Resolve every entry's future from the batch results — plan
        fan-out when rows collapsed, contiguous slices otherwise."""
        if plan is not None:
            for i, e in enumerate(entries):
                e.future.set_result(plan.rows_for(i, results))
            return
        off = 0
        for e in entries:
            n = len(e.queries)
            e.future.set_result(list(results[off:off + n]))
            off += n

    def _note_stage_totals(self, **stages_ms) -> None:
        with self._lock:
            for name, ms in stages_ms.items():
                if ms > 0:
                    self.stage_totals_ms[name] = (
                        self.stage_totals_ms.get(name, 0.0) + ms)

    def stage_totals(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.stage_totals_ms)

    def _pipelined(self) -> bool:
        if self.dispatch_fn is None:
            return False
        from dingo_tpu.common.config import serving_pipeline_enabled

        return serving_pipeline_enabled()

    def _run(self, key: Any, batch: _PendingBatch) -> None:
        # queue-wait ends here; the run span parents to the first sampled
        # waiter so the device work lands in ITS trace, with the rest of
        # the batch recorded as co-batched trace links
        flush_t0 = time.monotonic()
        entries, region_id, run_span, waits_ms, qos = self._begin_flush(
            key, batch, flush_t0)
        if not entries:
            return
        token = run_span.attach()
        stage_us: Optional[Dict[str, int]] = (
            {} if (qos and self._run_takes_stages) else None
        )
        try:
            stacked, plan = self._form_batch(entries, region_id)
            form_ms = (time.monotonic() - flush_t0) * 1000.0
            run_t0 = time.monotonic()
            if stage_us is not None:
                results = self.run_fn(key, stacked, stage_us=stage_us)
            else:
                results = self.run_fn(key, stacked)
            run_ms = (time.monotonic() - run_t0) * 1000.0
            self._note_run(len(stacked), run_ms, key=key)
            self._fan_out(entries, results, plan)
            if qos:
                self._account_stages(entries, waits_ms, form_ms, run_ms,
                                     stage_us)
        except Exception as exc:  # noqa: BLE001
            run_span.set_error(exc)
            for e in entries:
                if not e.future.done():
                    e.future.set_exception(exc)
        finally:
            run_span.detach(token)
            run_span.end()

    @staticmethod
    def _account_stages(entries, waits_ms, form_ms, run_ms, stage_us,
                        dispatch_ms: Optional[float] = None):
        """Per-stage time-budget accounting: queue / batch_form / kernel /
        rerank as fractions of each entry's deadline. The kernel/rerank
        split comes from the reader's stage_us dict when the run callback
        exposes it (search_us = the device scan+topk, postfilter+backfill
        = the rerank/materialize tail); otherwise the whole run counts as
        kernel time. On the pipelined path ``dispatch_ms`` (the kernel
        enqueue + staging cost, during which the flush thread — not the
        device — was the bottleneck) books under its own ``dispatch``
        stage so overlapped-dispatch wait is not misbooked as kernel
        time."""
        from dingo_tpu.obs.pressure import PRESSURE

        kernel_ms, rerank_ms = run_ms, 0.0
        if stage_us:
            k = stage_us.get("search_us", 0) / 1000.0
            r = (stage_us.get("postfilter_us", 0)
                 + stage_us.get("backfill_us", 0)) / 1000.0
            if k > 0:
                kernel_ms, rerank_ms = k, min(r, run_ms - k)
        for e in entries:
            if e.budget is None:
                continue
            stages = {
                "queue": waits_ms.get(id(e), 0.0),
                "batch_form": form_ms,
                "kernel": kernel_ms,
                "rerank": rerank_ms,
            }
            if dispatch_ms is not None:
                stages["dispatch"] = dispatch_ms
            PRESSURE.observe_stages(e.budget, stages)

    # -- pipelined arm -------------------------------------------------------
    def _dispatch(self, key: Any, batch: _PendingBatch):
        """Dispatch one due batch's kernels WITHOUT resolving: stage the
        stacked queries (reusable pinned ring buffer, upload started
        here so the next batch's H2D overlaps this one's compute), call
        dispatch_fn for the resolve thunk, and return a _Handoff for the
        completion lane. Returns None when the batch fully expired or
        dispatch itself failed (futures are resolved either way). Runs
        on the flush thread; MUST NOT block on device results — the one
        sanctioned ``device_get`` of this path lives in
        _Handoff.resolve() on the lane thread (dingolint: resolve-sync
        enforces this split)."""
        flush_t0 = time.monotonic()
        entries, region_id, run_span, waits_ms, qos = self._begin_flush(
            key, batch, flush_t0)
        if not entries:
            return None
        token = run_span.attach()
        staged = None
        stage_us: Optional[Dict[str, int]] = (
            {} if "stage_us" in self._dispatch_params else None
        )
        try:
            stacked, plan = self._form_batch(entries, region_id)
            if "staged" in self._dispatch_params:
                if self._staging is None:
                    from dingo_tpu.common.config import pipeline_depth
                    from dingo_tpu.common.pipeline import KeyedStaging

                    self._staging = KeyedStaging(pipeline_depth())
                staged = self._staging.ring(key).stage(stacked)
            form_ms = (time.monotonic() - flush_t0) * 1000.0
            dispatch_t0 = time.monotonic()
            kw: Dict[str, Any] = {}
            if staged is not None:
                kw["staged"] = staged
            if stage_us is not None:
                kw["stage_us"] = stage_us
            thunk = self.dispatch_fn(key, stacked, **kw)
            dispatch_ms = (time.monotonic() - dispatch_t0) * 1000.0
            self._note_stage_totals(batch_form=form_ms,
                                    dispatch=dispatch_ms)
            run_span.detach(token)
            return _Handoff(self, key, entries, waits_ms, form_ms,
                            dispatch_ms, run_span, staged, thunk,
                            stage_us, qos, plan, len(stacked))
        except Exception as exc:  # noqa: BLE001
            run_span.set_error(exc)
            run_span.detach(token)
            run_span.end()
            if staged is not None:
                staged.release()
            for e in entries:
                if not e.future.done():
                    e.future.set_exception(exc)
            return None

    def _flush_loop(self) -> None:
        timeout = None   # nothing pending: sleep until a submit wakes us
        while True:
            # wait until the EARLIEST pending batch's deadline (not a
            # fixed half-window poll, which stretched worst-case wait to
            # 1.5x the configured window)
            self._wake.wait(timeout=timeout)
            self._wake.clear()
            if self._stop:
                return
            now = time.monotonic()
            timeout = None
            with self._lock:
                # QoS-displaced batches first: they are strictly older
                # than anything still inside its window
                due = self._ready
                self._ready = []
                for key in list(self._pending):
                    age = now - self._pending[key].created
                    if age >= self.window_s:
                        due.append((key, self._pending.pop(key)))
                    else:
                        remain = self.window_s - age
                        timeout = remain if timeout is None else min(
                            timeout, remain)
            # under pressure several keys come due in one sweep: dispatch
            # the most important batch first (its waiters are the ones a
            # deadline will kill first among equals)
            due.sort(key=lambda kb: -max(
                (e.priority for e in kb[1].entries), default=0
            ))
            if self._pipelined():
                # overlapped dispatch: EVERY due batch's kernels enqueue
                # before ANY resolve runs — batch B's kernel overlaps
                # batch A's D2H fetch; the completion lane drains the
                # thunks FIFO so this thread never blocks on device_get
                handoffs = []
                for key, batch in due:
                    h = self._dispatch(key, batch)
                    if h is not None:
                        handoffs.append(h)
                for h in handoffs:
                    if not self._lane.submit(h):
                        # lane already stopped (stop racing a flush):
                        # resolve inline — the futures must still settle
                        h.resolve()
            else:
                for key, batch in due:
                    self._run(key, batch)

    def stop(self, drain: bool = True) -> None:
        """Shut down. drain=True runs pending batches to completion so
        in-flight callers get results; drain=False fails their futures
        with CoalescerStopped. Either way every pending future resolves
        deterministically — nobody is left hung on a dead timer thread."""
        with self._lock:
            self._stop = True
            # ready-queue batches (QoS cap displacement) resolve under the
            # same contract as window-pending ones
            leftovers = self._ready + list(self._pending.items())
            self._ready = []
            self._pending.clear()
            self._tenant_rows.clear()
        self._wake.set()
        for key, batch in leftovers:
            if drain:
                self._run(key, batch)
            else:
                exc = CoalescerStopped("coalescer stopped before flush")
                for e in batch.entries:
                    e.wait_span.end()
                    if e.qos:
                        # mirror _run's dequeue accounting: a discarded
                        # entry must not leave phantom queue depth in the
                        # pressure plane (heartbeats ship region_stats)
                        from dingo_tpu.obs.pressure import PRESSURE

                        PRESSURE.on_dequeue(e.region_id, len(e.queries),
                                            e.budget)
                    if not e.future.done():
                        e.future.set_exception(exc)
        # the completion lane honors the same contract: drain resolves
        # queued handoffs to real results, no-drain abandons them (their
        # futures fail fast but the fetch still runs so device leases
        # release — see _Handoff.abandon)
        self._lane.stop(drain=drain)
        if self._staging is not None:
            self._staging.close()
        self._thread.join(timeout=2)


class _Handoff:
    """One dispatched-but-unresolved batch riding the completion lane.

    ``resolve()`` is the single sanctioned host-sync point of the
    pipelined path: it runs the dispatch_fn's thunk (one ``device_get``
    inside), slices results to the waiters' futures, and closes the
    accounting the dispatch half opened. ``abandon()`` is the
    stop(drain=False) arm: futures fail fast with CoalescerStopped, but
    the thunk still runs — a dropped fetch must not leak the SlotStore
    SearchLeases the dispatch acquired."""

    __slots__ = ("coalescer", "key", "entries", "waits_ms", "form_ms",
                 "dispatch_ms", "run_span", "staged", "thunk", "stage_us",
                 "qos", "plan", "rows")

    def __init__(self, coalescer, key, entries, waits_ms, form_ms,
                 dispatch_ms, run_span, staged, thunk, stage_us, qos,
                 plan=None, rows=0):
        self.coalescer = coalescer
        self.key = key
        self.entries = entries
        self.waits_ms = waits_ms
        self.form_ms = form_ms
        self.dispatch_ms = dispatch_ms
        self.run_span = run_span
        self.staged = staged
        self.thunk = thunk
        self.stage_us = stage_us
        self.qos = qos
        #: dedupe fan-out plan (None = contiguous slices) and the row
        #: count actually dispatched (deduped) — the EWMA must track the
        #: kernel's true service rate, not the pre-collapse demand
        self.plan = plan
        self.rows = rows

    def resolve(self) -> None:
        c = self.coalescer
        token = self.run_span.attach()
        t0 = time.monotonic()
        try:
            results = self.thunk()
            resolve_ms = (time.monotonic() - t0) * 1000.0
            rows = self.rows or sum(len(e.queries) for e in self.entries)
            c._note_run(rows, self.dispatch_ms + resolve_ms,
                        key=self.key)
            kernel_ms, rerank_ms = resolve_ms, 0.0
            if self.stage_us:
                k = self.stage_us.get("search_us", 0) / 1000.0
                r = (self.stage_us.get("postfilter_us", 0)
                     + self.stage_us.get("backfill_us", 0)) / 1000.0
                if k > 0:
                    kernel_ms = k
                    rerank_ms = min(r, max(0.0, resolve_ms - k))
            c._note_stage_totals(kernel=kernel_ms, rerank=rerank_ms,
                                 resolve=resolve_ms)
            c._fan_out(self.entries, results, self.plan)
            if self.qos:
                c._account_stages(self.entries, self.waits_ms,
                                  self.form_ms, resolve_ms, self.stage_us,
                                  dispatch_ms=self.dispatch_ms)
        except Exception as exc:  # noqa: BLE001
            self.run_span.set_error(exc)
            for e in self.entries:
                if not e.future.done():
                    e.future.set_exception(exc)
        finally:
            self.run_span.detach(token)
            self.run_span.end()
            if self.staged is not None:
                self.staged.release()

    def abandon(self) -> None:
        exc = CoalescerStopped("coalescer stopped before resolve")
        for e in self.entries:
            if not e.future.done():
                e.future.set_exception(exc)
        try:
            # run the fetch anyway: the dispatch half acquired device-
            # side leases (SlotStore begin_search) that only the thunk's
            # finally releases — dropping it would strand limbo slots
            self.thunk()
        except Exception:  # noqa: BLE001
            pass
        finally:
            self.run_span.end()
            if self.staged is not None:
                self.staged.release()
