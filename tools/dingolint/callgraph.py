"""Approximate module-level call graph for inter-procedural checks.

Python's dynamism makes a sound call graph impossible statically; the
checkers need a *useful* one. Resolution is three-tiered, from precise to
fuzzy, and every edge remembers which tier produced it so checkers can
choose their own precision/recall point:

1. **exact** — module-local names (``_pad_batch(...)``), ``self.method``
   calls resolved through the enclosing class (and single inheritance
   within the repo), and names imported via ``from m import f`` /
   ``import m`` followed by ``m.f(...)``.
2. **fuzzy** — a method call on an unknown receiver (``store.put(...)``)
   resolves to every def in the repo whose final name matches, capped at
   ``MAX_FANOUT`` candidates: a name shared by more defs than that (e.g.
   ``get``) carries no signal and would only manufacture reachability.

Nested defs own their body's calls (a call inside the ``resolve()``
closure belongs to ``search_async.resolve``, not ``search_async``) —
that's load-bearing for the host-sync checker, whose whole point is that
the closure IS the designated sync point.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.dingolint.core import Module, Repo

#: a basename matching more defs than this resolves to nothing — it's
#: noise, not an edge (``put``/``search`` stay useful, ``get`` drops out)
MAX_FANOUT = 12

#: method names that collide with builtin container/file/lock methods:
#: an attribute call on an unknown receiver with one of these names is
#: overwhelmingly a list/dict/set/file/Lock operation, and resolving it
#: to a same-named repo def welds unrelated subsystems together (a
#: ``candidates.append(...)`` inside a search once resolved to
#: ``RaftLog.append`` and dragged the whole write path into the "hot"
#: reachability set). Exact (self./imported) resolution is unaffected.
FUZZY_STOPLIST = frozenset({
    "append", "extend", "insert", "remove", "pop", "clear", "copy",
    "count", "index", "sort", "reverse", "add", "discard", "update",
    "get", "keys", "values", "items", "setdefault",
    "read", "write", "close", "flush", "seek", "tell",
    "split", "strip", "join", "encode", "decode", "format",
    "acquire", "release", "wait", "notify", "set", "start", "stop",
})


def dotted_name(expr: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` -> ['a','b','c']; None for non-trivial expressions."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        parts.reverse()
        return parts
    return None


class FuncInfo:
    __slots__ = ("qual", "module", "node", "cls",
                 "exact_calls", "fuzzy_calls")

    def __init__(self, qual: str, module: Module, node: ast.AST,
                 cls: Optional[str]):
        self.qual = qual            #: global qualname (module + local)
        self.module = module
        self.node = node
        self.cls = cls              #: enclosing class local qualname
        self.exact_calls: Set[str] = set()
        self.fuzzy_calls: Set[str] = set()


class CallGraph:
    def __init__(self, repo: Repo):
        self.repo = repo
        #: global qualname -> FuncInfo
        self.funcs: Dict[str, FuncInfo] = {}
        #: basename -> [global qualnames]
        self.by_basename: Dict[str, List[str]] = {}
        #: module name -> {local alias -> imported dotted target}
        self._imports: Dict[str, Dict[str, str]] = {}
        #: (module, class local qual) -> [base class dotted names]
        self._bases: Dict[Tuple[str, str], List[str]] = {}
        #: top-level packages the repo owns — calls rooted at an import
        #: of anything else (jax, numpy, grpc, ...) never fuzzy-resolve:
        #: ``lax.scan`` must not alias a repo method named ``scan``
        self._repo_tops = {m.name.split(".", 1)[0] for m in repo.modules}
        #: (module, class) -> {attr -> (module, class)} from annotated
        #: ctor params: ``def __init__(self, engine: RawEngine)`` +
        #: ``self.engine = engine`` types ``self.engine.X`` calls
        self._attr_types: Dict[Tuple[str, str],
                               Dict[str, Tuple[str, str]]] = {}
        for module in repo.modules:
            self._index_module(module)
        for module in repo.modules:
            self._resolve_module(module)

    # -- indexing ----------------------------------------------------------
    def _index_module(self, module: Module) -> None:
        imports: Dict[str, str] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
            elif isinstance(node, ast.ClassDef):
                bases = []
                for b in node.bases:
                    d = dotted_name(b)
                    if d:
                        bases.append(".".join(d))
                self._bases[(module.name,
                             getattr(node, "_dl_qual", node.name))] = bases
        self._imports[module.name] = imports
        self._index_attr_types(module, imports)
        for local_qual, fnode in module.funcs.items():
            qual = f"{module.name}.{local_qual}"
            cls = None
            cnode = module.enclosing_class(fnode)
            if cnode is not None:
                cls = getattr(cnode, "_dl_qual", cnode.name)
            info = FuncInfo(qual, module, fnode, cls)
            self.funcs[qual] = info
            self.by_basename.setdefault(
                local_qual.rsplit(".", 1)[-1], []
            ).append(qual)

    def _index_attr_types(self, module: Module,
                          imports: Dict[str, str]) -> None:
        """``self.attr = param`` where the param carries a class
        annotation resolvable inside the repo types the attribute."""
        for cnode in ast.walk(module.tree):
            if not isinstance(cnode, ast.ClassDef):
                continue
            ckey = (module.name, getattr(cnode, "_dl_qual", cnode.name))
            for fnode in ast.walk(cnode):
                if not isinstance(fnode, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                    continue
                ann: Dict[str, Tuple[str, str]] = {}
                for a in fnode.args.args:
                    if a.annotation is None:
                        continue
                    d = dotted_name(a.annotation)
                    if not d:
                        continue
                    name = ".".join(d)
                    target = imports.get(d[0])
                    if target and len(d) == 1:
                        full = target
                    elif target:
                        full = f"{target}.{'.'.join(d[1:])}"
                    elif f"{module.name}.{name}" in {
                        f"{module.name}."
                        + getattr(n, "_dl_qual", "")
                        for n in ast.walk(module.tree)
                        if isinstance(n, ast.ClassDef)
                    }:
                        full = f"{module.name}.{name}"
                    else:
                        continue
                    mod, _, c = full.rpartition(".")
                    if mod in self.repo.by_name:
                        ann[a.arg] = (mod, c)
                for node in ast.walk(fnode):
                    if not isinstance(node, ast.Assign):
                        continue
                    if not isinstance(node.value, ast.Name):
                        continue
                    ptype = ann.get(node.value.id)
                    if ptype is None:
                        continue
                    for tgt in node.targets:
                        t = dotted_name(tgt)
                        if t and len(t) == 2 and t[0] == "self":
                            self._attr_types.setdefault(
                                ckey, {})[t[1]] = ptype

    # -- resolution --------------------------------------------------------
    def resolve_call(self, module: Module, call: ast.Call,
                     cls: Optional[str] = None
                     ) -> Tuple[Set[str], Set[str]]:
        """(exact targets, fuzzy targets) for one call site."""
        exact: Set[str] = set()
        fuzzy: Set[str] = set()
        parts = dotted_name(call.func)
        imports = self._imports.get(module.name, {})
        if parts is None:
            return exact, fuzzy
        if len(parts) == 1:
            name = parts[0]
            if f"{module.name}.{name}" in self.funcs:
                exact.add(f"{module.name}.{name}")
            elif name in imports and imports[name] in self.funcs:
                exact.add(imports[name])
            return exact, fuzzy
        base = parts[-1]
        if parts[0] == "self" and cls is not None and len(parts) == 2:
            hit = self._method_on(module, cls, base)
            if hit:
                exact.add(hit)
                return exact, fuzzy
        if parts[0] == "self" and cls is not None and len(parts) == 3:
            # self.attr.method — typed when the ctor annotated the attr
            ptype = self._attr_types.get(
                (module.name, cls), {}).get(parts[1])
            if ptype is not None:
                pmod = self.repo.by_name.get(ptype[0])
                if pmod is not None:
                    hit = self._method_on(pmod, ptype[1], base)
                    if hit:
                        exact.add(hit)
                        return exact, fuzzy
        if parts[0] in imports:
            target = f"{imports[parts[0]]}.{'.'.join(parts[1:])}"
            if target in self.funcs:
                exact.add(target)
                return exact, fuzzy
            if imports[parts[0]].split(".", 1)[0] not in self._repo_tops:
                # rooted at an external module (jax.lax.scan, np.put, ...):
                # a repo def sharing the basename is a coincidence
                return exact, fuzzy
        if base not in FUZZY_STOPLIST:
            candidates = self.by_basename.get(base, [])
            # locality: for a bare-name receiver, a same-module def wins
            # over global basename matches (``kv.put`` next to ``class
            # SortedKv`` is SortedKv's put, not every put in the repo).
            # NOT applied to self.attr receivers — ``self.engine.delete``
            # points at another object, and localizing it once resolved a
            # class's untyped engine call to the class's own method
            if parts[0] != "self":
                local = [c for c in candidates
                         if c.startswith(module.name + ".")]
                if local:
                    candidates = local
            if 0 < len(candidates) <= MAX_FANOUT:
                fuzzy.update(candidates)
        return exact, fuzzy

    def _method_on(self, module: Module, cls: str, name: str
                   ) -> Optional[str]:
        """Resolve ``self.name`` through the class then its repo-local
        bases (single-level walk per base, enough for the index MRO)."""
        seen: Set[Tuple[str, str]] = set()
        stack = [(module.name, cls)]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            mod_name, c = key
            qual = f"{mod_name}.{c}.{name}"
            if qual in self.funcs:
                return qual
            for b in self._bases.get((mod_name, c), []):
                mod = self.repo.by_name.get(mod_name)
                imports = self._imports.get(mod_name, {})
                head = b.split(".")[0]
                if b in (mod.funcs if mod else {}):
                    continue
                # base in the same module
                if mod is not None and any(
                    isinstance(n, ast.ClassDef)
                    and getattr(n, "_dl_qual", None) == b
                    for n in ast.walk(mod.tree)
                ):
                    stack.append((mod_name, b))
                elif head in imports:
                    target = imports[head]
                    tail = b.split(".", 1)[1] if "." in b else ""
                    full = f"{target}.{tail}".rstrip(".")
                    # from m import Base -> target is m.Base already
                    if "." in full:
                        bmod, bcls = full.rsplit(".", 1)
                        if bmod in self.repo.by_name:
                            stack.append((bmod, bcls))
        return None

    def _resolve_module(self, module: Module) -> None:
        for local_qual, fnode in module.funcs.items():
            info = self.funcs[f"{module.name}.{local_qual}"]
            for node in ast.walk(fnode):
                if not isinstance(node, ast.Call):
                    continue
                # a call inside a nested def belongs to that def
                if module.qualname_of(node) != local_qual:
                    continue
                exact, fuzzy = self.resolve_call(module, node, info.cls)
                info.exact_calls |= exact
                info.fuzzy_calls |= fuzzy

    # -- queries -----------------------------------------------------------
    def callees(self, qual: str, fuzzy: bool = False) -> Set[str]:
        info = self.funcs.get(qual)
        if info is None:
            return set()
        out = set(info.exact_calls)
        if fuzzy:
            out |= info.fuzzy_calls
        return out

    def reachable(self, roots: Iterable[str], fuzzy: bool = False,
                  skip=None) -> Set[str]:
        """Transitive closure from `roots`. `skip(qual)` prunes traversal
        INTO a function (it is neither visited nor expanded)."""
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.funcs]
        while stack:
            qual = stack.pop()
            if qual in seen or (skip is not None and skip(qual)):
                continue
            seen.add(qual)
            for callee in self.callees(qual, fuzzy=fuzzy):
                if callee not in seen:
                    stack.append(callee)
        return seen
