"""MonoStoreEngine: single-replica engine (no raft) with the Engine API.

Reference: src/engine/mono_store_engine.{h,cc} — same reader/writer surface
as RaftStoreEngine but writes apply directly through the handlers; used for
MONO_STORE regions and single-node deployments. Keeping the apply path
shared (engine/apply.py) means raft and mono regions behave identically
after commit.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from dingo_tpu.engine.apply import apply_write
from dingo_tpu.engine.apply_results import ApplyResultBuffer
from dingo_tpu.engine.raw_engine import RawEngine
from dingo_tpu.engine.write_data import WriteData
from dingo_tpu.index.vector_reader import ReaderContext, VectorReader
from dingo_tpu.mvcc.codec import MAX_TS
from dingo_tpu.store.region import Region


class MonoStoreEngine:
    def __init__(self, raw_engine: RawEngine):
        self.raw = raw_engine
        self._lock = threading.Lock()
        self._log_ids: Dict[int, int] = {}  # per-region apply log counter
        self._write_locks: Dict[int, "threading.Lock"] = {}
        self._apply_results = ApplyResultBuffer()

    def next_log_id(self, region_id: int) -> int:
        with self._lock:
            n = self._log_ids.get(region_id, 0) + 1
            self._log_ids[region_id] = n
            return n

    # -- Engine::Writer ------------------------------------------------------
    def _region_write_lock(self, region_id: int):
        with self._lock:
            lock = self._write_locks.get(region_id)
            if lock is None:
                lock = self._write_locks[region_id] = threading.Lock()
            return lock

    def write(self, region: Region, data: WriteData) -> int:
        """Synchronous apply; returns the log id (mono engine fakes the raft
        log with a per-region counter so the wrapper's apply-log contract
        stays identical). Applies serialize per region — the raft engine's
        apply loop gives the same guarantee, and result-bearing handlers
        (delete_range count-then-delete) rely on it for atomicity."""
        with self._region_write_lock(region.id):
            log_id = self.next_log_id(region.id)
            # mono IS the proposer, so results are always wanted
            result = apply_write(self.raw, region, data, log_id)
            if result is not None:
                self._apply_results.record(region.id, log_id, result)
            return log_id

    async_write = write  # mono apply is already synchronous

    def take_apply_result(self, region_id: int, log_id: int):
        return self._apply_results.take(region_id, log_id)

    # -- Engine::VectorReader --------------------------------------------------
    def new_vector_reader(self, region: Region, read_ts: int = MAX_TS) -> VectorReader:
        ctx = ReaderContext(
            region_id=region.id,
            partition_id=region.definition.partition_id,
            start_key=region.definition.start_key,
            end_key=region.definition.end_key,
            index_wrapper=region.vector_index_wrapper,
            engine=self.raw,
            read_ts=read_ts,
            parameter=region.definition.index_parameter,
        )
        return VectorReader(ctx)
