"""resolve-sync: exactly one host sync per reply, on the right thread.

The serving pipeline (stall-free serving PR) sharpened the host-sync
contract: ``search_async`` chains the whole epilogue — rerank, prune
stats, top-k — on device and starts ONE async D2H group
(``ops/topk.begin_host_fetch``); the ``resolve()`` thunk then performs
exactly one ``jax.device_get`` over that group. A second sync inside
resolve re-serializes the reply against the device and silently halves
the overlap the pipeline exists to buy: while resolve waits on the
straggler transfer, the completion lane can't drain and the next
batch's staging slot stays leased.

Two rules:

1. **resolve() thunks** (any def named ``resolve`` in the index /
   parallel tiers, plus helpers only they reach):

   - ``block_until_ready`` is always flagged — resolve should *fetch*,
     not barrier; the fetch itself is the wait.
   - the FIRST ``jax.device_get`` is the sanctioned sync; any second
     one on the same execution path is flagged. Two ``device_get``
     calls that diverge at the same ``if`` into different arms are
     branch-exclusive — only one runs per reply — and stay clean
     (the quantized families' rerank/no-rerank arms).
   - reachable helpers (minus the obs/trace/metrics planes and
     ``device_wait_span``) are flagged on ANY explicit sync: resolve
     already fetched, so a helper sync is by construction a second one.

2. **the coalescer flush thread**: methods of ``SearchCoalescer``
   (which run on the flush thread or a caller thread) must never sync
   — they dispatch and hand off. Syncs belong to the completion lane
   (``_Handoff.resolve``, a different class, exempt by scoping) where
   a wait only delays *that* reply, never the next dispatch.

Deliberate exceptions (e.g. a host-side exact rerank whose gather
cannot chain on device) go in the baseline with a rationale, not
inline suppressions — the two-sync shape is an economics judgment, and
the baseline is where judgments are recorded.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.dingolint.callgraph import dotted_name
from tools.dingolint.core import Checker, Finding, Module, Repo

#: where resolve() thunks live (same tiers host-sync roots at)
_ROOT_MODULE_PREFIXES = ("dingo_tpu.index.", "dingo_tpu.parallel.",
                         "dingo_tpu.cache.")

#: admission-path modules: every def runs on a caller or flush thread
#: (cache lookup precedes QoS queuing; the dedupe plan forms batches),
#: so ANY device sync is flagged — there is no sanctioned first fetch
_ADMISSION_MODULE_PREFIXES = ("dingo_tpu.cache.",)

#: traversal never descends into these (their own discipline applies)
_SKIP_MODULE_PREFIXES = ("dingo_tpu.obs.", "dingo_tpu.trace.",
                         "dingo_tpu.metrics.")
_SKIP_BASENAMES = {"device_wait_span"}

#: the flush-thread class; the completion lane's handoff class is
#: intentionally NOT here — its resolve() runs on the lane thread
_FLUSH_CLASSES = {"SearchCoalescer"}


def _is_device_get(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    parts = dotted_name(node.func)
    return bool(parts) and parts[-1] == "device_get" \
        and parts[0] == "jax"


def _is_block_until_ready(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    parts = dotted_name(node.func)
    return bool(parts) and parts[-1] == "block_until_ready"


def _branch_arms(module: Module, node: ast.AST) -> Dict[int, str]:
    """id(If ancestor) -> which arm this node sits in."""
    arms: Dict[int, str] = {}
    child = node
    cur = module.parent(node)
    while cur is not None:
        if isinstance(cur, ast.If):
            if any(child is c for c in cur.body):
                arms[id(cur)] = "body"
            elif any(child is c for c in cur.orelse):
                arms[id(cur)] = "orelse"
            else:
                arms[id(cur)] = "test"
        child = cur
        cur = module.parent(cur)
    return arms


def _branch_exclusive(module: Module, a: ast.AST, b: ast.AST) -> bool:
    """True when a and b diverge at some shared ``if`` into different
    arms — at most one of them runs per call."""
    arms_a = _branch_arms(module, a)
    arms_b = _branch_arms(module, b)
    for if_id, arm in arms_a.items():
        other = arms_b.get(if_id)
        if other is not None and other != arm \
                and {arm, other} == {"body", "orelse"}:
            return True
    return False


class ResolveSyncChecker(Checker):
    name = "resolve-sync"
    description = ("one device_get per resolve(); no syncs on the "
                   "coalescer flush thread")

    def check_repo(self, repo: Repo) -> List[Finding]:
        out: List[Finding] = []
        out.extend(self._check_resolve_thunks(repo))
        out.extend(self._check_flush_thread(repo))
        return out

    # -- rule 1: resolve() thunks -----------------------------------------

    def _check_resolve_thunks(self, repo: Repo) -> List[Finding]:
        cg = repo.callgraph()
        roots = [
            q for q, info in cg.funcs.items()
            if q.rsplit(".", 1)[-1] == "resolve"
            and info.module.name.startswith(_ROOT_MODULE_PREFIXES)
        ]

        def skip(qual: str) -> bool:
            base = qual.rsplit(".", 1)[-1]
            if base in _SKIP_BASENAMES:
                return True
            return qual.startswith(_SKIP_MODULE_PREFIXES)

        hot = cg.reachable(roots, fuzzy=True, skip=skip)
        out: List[Finding] = []
        for gqual in sorted(hot):
            info = cg.funcs[gqual]
            module = info.module
            local = gqual[len(module.name) + 1:]
            if local.rsplit(".", 1)[-1] == "resolve":
                out.extend(self._check_one_resolve(module, info.node,
                                                   local))
            else:
                out.extend(self._check_helper(module, info.node, local))
        return out

    def _check_one_resolve(self, module: Module, fn: ast.AST,
                           local: str) -> List[Finding]:
        out: List[Finding] = []
        gets: List[ast.Call] = []
        for node in ast.walk(fn):
            if module.qualname_of(node) != local:
                continue
            if _is_block_until_ready(node):
                f = module.finding(
                    self.name, node,
                    "block_until_ready inside resolve() — resolve "
                    "performs ONE jax.device_get over the "
                    "begin_host_fetch group; the fetch is the wait",
                )
                if f:
                    out.append(f)
            elif _is_device_get(node):
                gets.append(node)
        gets.sort(key=lambda n: (n.lineno, n.col_offset))
        for i, g in enumerate(gets):
            if any(not _branch_exclusive(module, g, earlier)
                   for earlier in gets[:i]):
                f = module.finding(
                    self.name, g,
                    "second jax.device_get inside resolve() after the "
                    "first fetch — chain the epilogue on device and "
                    "join the reply's single begin_host_fetch group "
                    "(one device_get per reply), or baseline with a "
                    "rationale if the host round-trip is inherent",
                )
                if f:
                    out.append(f)
        return out

    def _check_helper(self, module: Module, fn: ast.AST,
                      local: str) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(fn):
            if module.qualname_of(node) != local:
                continue
            msg: Optional[str] = None
            if _is_device_get(node):
                msg = ("jax.device_get in a helper reachable from "
                       "resolve() — resolve already performed the "
                       "reply's one fetch; return device values and "
                       "let resolve's begin_host_fetch group carry "
                       "them, or baseline with a rationale")
            elif _is_block_until_ready(node):
                msg = ("block_until_ready in a helper reachable from "
                       "resolve() — a barrier under the reply's sync "
                       "point stalls the completion lane; drop it or "
                       "baseline with a rationale")
            if msg is None:
                continue
            f = module.finding(self.name, node, msg)
            if f:
                out.append(f)
        return out

    # -- rule 2: the coalescer flush thread --------------------------------

    def _check_flush_thread(self, repo: Repo) -> List[Finding]:
        out: List[Finding] = []
        for module in repo.modules:
            admission = module.name.startswith(_ADMISSION_MODULE_PREFIXES)
            for local, fn in sorted(module.funcs.items()):
                if admission:
                    # cache/ admission path: no sanctioned sync anywhere
                    for node in ast.walk(fn):
                        if module.qualname_of(node) != local:
                            continue
                        if _is_device_get(node) \
                                or _is_block_until_ready(node):
                            f = module.finding(
                                self.name, node,
                                "device sync in the serving-edge cache — "
                                "the admission-path lookup/fill runs on "
                                "the caller thread before QoS queuing and "
                                "the dedupe plan on the flush thread; "
                                "cache code must stay host-only (keys, "
                                "dicts, numpy over host arrays)",
                            )
                            if f:
                                out.append(f)
                    continue
                cnode = module.enclosing_class(fn)
                if cnode is None or cnode.name not in _FLUSH_CLASSES:
                    continue
                for node in ast.walk(fn):
                    if module.qualname_of(node) != local:
                        continue
                    if _is_device_get(node) \
                            or _is_block_until_ready(node):
                        f = module.finding(
                            self.name, node,
                            "device sync in a SearchCoalescer method — "
                            "the flush thread dispatches and hands off; "
                            "syncs belong on the completion lane "
                            "(_Handoff.resolve), where a wait delays "
                            "one reply instead of every queued batch",
                        )
                        if f:
                            out.append(f)
        return out
