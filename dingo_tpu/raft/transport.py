"""Raft message transport.

The reference replicates over brpc (braft's TCP stack). Here the transport is
pluggable: LocalTransport delivers RPCs in-process with optional fault
injection (drop/partition/delay) — the single-process multi-peer topology the
reference's raft tests use (test_raft_node.cc: 3 braft peers on one
127.0.0.1 server distinguished by peer index). A grpc transport slots in for
multi-process deployments (server/ layer).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, Optional, Set, Tuple


class Transport:
    def send(self, target: str, method: str, msg: dict) -> Optional[dict]:
        """Synchronous RPC; returns response dict or None on network error."""
        raise NotImplementedError

    def register(self, node_id: str, handler: Callable[[str, dict], dict]) -> None:
        raise NotImplementedError


class LocalTransport(Transport):
    """In-process delivery with fault injection for tests."""

    def __init__(self, seed: int = 0):
        self._handlers: Dict[str, Callable[[str, dict], dict]] = {}
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self.drop_rate = 0.0
        self._partitions: Set[Tuple[str, str]] = set()
        self.delay_s = 0.0

    def register(self, node_id: str, handler) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._handlers.pop(node_id, None)

    def partition(self, a: str, b: str) -> None:
        """Cut the link a<->b (both directions)."""
        self._partitions.add((a, b))
        self._partitions.add((b, a))

    def heal(self) -> None:
        self._partitions.clear()

    def send(self, target: str, method: str, msg: dict) -> Optional[dict]:
        src = msg.get("from", "?")
        if (src, target) in self._partitions:
            return None
        if self.drop_rate and self._rng.random() < self.drop_rate:
            return None
        if self.delay_s:
            time.sleep(self.delay_s)
        with self._lock:
            handler = self._handlers.get(target)
        if handler is None:
            return None
        try:
            return handler(method, msg)
        except Exception:
            return None
