"""End-to-end single-node slice: Storage facade -> MonoStoreEngine -> apply
handlers -> raw engine + vector index wrapper -> VectorReader.

Mirrors the reference's §3.1/§3.2 call stacks without RPC/raft: the
dual-write invariant (engine is source of truth, index is an apply-log-
tracked view), filter modes, brute-force fallback, and recovery-by-rebuild.
"""

import numpy as np
import pytest

from dingo_tpu.coprocessor import ScalarFilter
from dingo_tpu.engine.mono_engine import MonoStoreEngine
from dingo_tpu.engine.raw_engine import MemEngine, WalEngine
from dingo_tpu.engine.storage import Storage
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import IndexParameter, IndexType, InvalidParameter
from dingo_tpu.index.vector_reader import VectorFilterMode, VectorFilterType
from dingo_tpu.store.region import (
    Region,
    RegionDefinition,
    RegionType,
    StoreMetaManager,
)

DIM = 16


def make_region(region_id=77, id_lo=0, id_hi=1 << 40, index_type=IndexType.FLAT):
    definition = RegionDefinition(
        region_id=region_id,
        start_key=vcodec.encode_vector_key(1, id_lo),
        end_key=vcodec.encode_vector_key(1, id_hi),
        partition_id=1,
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(index_type=index_type, dimension=DIM,
                                       ncentroids=8, default_nprobe=8),
    )
    region = Region(definition)
    w = region.vector_index_wrapper
    w.build_own()
    w.set_own(w.own_index)
    return region


@pytest.fixture()
def stack():
    raw = MemEngine()
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    region = make_region()
    return raw, engine, storage, region


def rand(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, DIM)).astype(np.float32)


def test_vector_add_search_roundtrip(stack):
    raw, engine, storage, region = stack
    x = rand(100)
    ids = np.arange(100, dtype=np.int64)
    scalars = [{"color": "red" if i % 2 == 0 else "blue", "n": i} for i in range(100)]
    storage.vector_add(region, ids, x, scalars)
    res = storage.vector_batch_search(region, x[:3], 5)
    assert [r[0].id for r in res] == [0, 1, 2]
    assert res[0][0].distance == pytest.approx(0.0, abs=1e-3)
    # engine holds the data (source of truth)
    got = storage.vector_batch_query(region, [5, 99, 12345],
                                     with_scalar_data=True)
    assert got[0].scalar["n"] == 5
    assert np.allclose(got[1].vector, x[99], atol=1e-5)
    assert got[2] is None


def test_vector_delete_hides_everywhere(stack):
    raw, engine, storage, region = stack
    x = rand(50)
    storage.vector_add(region, np.arange(50, dtype=np.int64), x)
    storage.vector_delete(region, [0, 1, 2])
    res = storage.vector_batch_search(region, x[:1], 3)
    assert all(v.id >= 3 for v in res[0])
    assert storage.vector_batch_query(region, [1])[0] is None
    assert storage.vector_count(region) == 47


def test_scalar_post_filter(stack):
    raw, engine, storage, region = stack
    x = rand(200)
    ids = np.arange(200, dtype=np.int64)
    scalars = [{"color": "red" if i % 4 == 0 else "blue"} for i in range(200)]
    storage.vector_add(region, ids, x, scalars)
    res = storage.vector_batch_search(
        region, x[:2], 5,
        filter_mode=VectorFilterMode.SCALAR,
        filter_type=VectorFilterType.QUERY_POST,
        scalar_filter=ScalarFilter.equals({"color": "red"}),
        with_scalar_data=True,
    )
    for row in res:
        assert len(row) == 5
        assert all(v.id % 4 == 0 for v in row)
        assert all(v.scalar == {"color": "red"} for v in row)


def test_scalar_pre_filter(stack):
    raw, engine, storage, region = stack
    x = rand(200)
    ids = np.arange(200, dtype=np.int64)
    scalars = [{"bucket": i % 10} for i in range(200)]
    storage.vector_add(region, ids, x, scalars)
    res = storage.vector_batch_search(
        region, x[:2], 50,
        filter_mode=VectorFilterMode.SCALAR,
        filter_type=VectorFilterType.QUERY_PRE,
        scalar_filter=ScalarFilter.equals({"bucket": 3}),
    )
    for row in res:
        assert len(row) == 20  # only 20 vectors have bucket==3
        assert all(v.id % 10 == 3 for v in row)


def test_vector_id_pre_filter(stack):
    raw, engine, storage, region = stack
    x = rand(100)
    storage.vector_add(region, np.arange(100, dtype=np.int64), x)
    res = storage.vector_batch_search(
        region, x[:1], 10,
        filter_mode=VectorFilterMode.VECTOR_ID,
        vector_ids=[7, 13, 21],
    )
    assert sorted(v.id for v in res[0]) == [7, 13, 21]


def test_bruteforce_fallback_from_untrained_ivf(stack):
    """EVECTOR_NOT_SUPPORT contract (vector_reader.cc:1814-1833): untrained
    IVF search falls back to scanning the engine."""
    raw = MemEngine()
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    region = make_region(index_type=IndexType.IVF_FLAT)
    x = rand(120)
    storage.vector_add(region, np.arange(120, dtype=np.int64), x)
    res = storage.vector_batch_search(region, x[:2], 5)
    assert [r[0].id for r in res] == [0, 1]


def test_bruteforce_type_scans_engine(stack):
    region = make_region(index_type=IndexType.BRUTEFORCE)
    raw = MemEngine()
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    x = rand(30)
    storage.vector_add(region, np.arange(30, dtype=np.int64), x)
    res = storage.vector_batch_search(region, x[:1], 3)
    assert res[0][0].id == 0


def test_rebuild_from_engine_after_restart(tmp_path):
    """Recovery invariant: the index is a materialized view rebuildable from
    the engine (§3.2/§3.4)."""
    path = str(tmp_path / "wal")
    raw = WalEngine(path)
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    region = make_region()
    x = rand(60)
    storage.vector_add(region, np.arange(60, dtype=np.int64), x)
    storage.vector_delete(region, [10, 11])
    raw.close()

    # restart: fresh engine + empty index; rebuild from the data CF
    raw2 = WalEngine(path)
    engine2 = MonoStoreEngine(raw2)
    storage2 = Storage(engine2)
    region2 = make_region()
    reader = engine2.new_vector_reader(region2)
    rows = reader.vector_scan_query(0, limit=10_000, with_vector_data=True)
    assert len(rows) == 58
    w = region2.vector_index_wrapper
    w.add(
        np.asarray([r.id for r in rows], np.int64),
        np.stack([r.vector for r in rows]),
        log_id=1,
    )
    res = storage2.vector_batch_search(region2, x[:1], 3)
    assert res[0][0].id == 0
    assert storage2.vector_count(region2) == 58
    raw2.close()


def test_validation_guards(stack):
    raw, engine, storage, region = stack
    x = rand(10)
    with pytest.raises(InvalidParameter):
        storage.vector_add(region, np.arange(9, dtype=np.int64), x)
    with pytest.raises(InvalidParameter):
        storage.vector_add(
            region, np.arange(5000, dtype=np.int64), rand(5000)
        )
    storage.vector_add(region, np.arange(10, dtype=np.int64), x)
    with pytest.raises(InvalidParameter):
        storage.vector_batch_search(region, x, 100000)


def test_border_ids_and_scan(stack):
    raw, engine, storage, region = stack
    x = rand(20)
    ids = (np.arange(20, dtype=np.int64) + 1) * 5
    storage.vector_add(region, ids, x)
    assert storage.vector_get_border_id(region, get_min=True) == 5
    assert storage.vector_get_border_id(region, get_min=False) == 100
    rows = storage.vector_scan_query(region, start_id=50, limit=3)
    assert [r.id for r in rows] == [50, 55, 60]


def test_kv_surface(stack):
    raw, engine, storage, region = stack
    storage.kv_put(region, [(b"a", b"1"), (b"b", b"2")])
    assert storage.kv_get(region, b"a") == b"1"
    assert storage.kv_put_if_absent(region, [(b"a", b"X"), (b"c", b"3")]) == [
        False,
        True,
    ]
    assert storage.kv_get(region, b"a") == b"1"
    assert storage.kv_compare_and_set(region, b"b", b"2", b"20")
    assert not storage.kv_compare_and_set(region, b"b", b"2", b"30")
    assert storage.kv_get(region, b"b") == b"20"
    storage.kv_batch_delete(region, [b"a"])
    assert storage.kv_get(region, b"a") is None
    got = storage.kv_scan(region, b"a", b"z")
    assert [k for k, _ in got] == [b"b", b"c"]
    assert storage.kv_delete_range(region, [(b"a", b"z")]) == 2  # b, c live
    assert storage.kv_scan(region, b"a", b"z") == []


def test_kv_delete_range_unbounded_end():
    """Empty end key = delete to the end (a region with unbounded end_key):
    the count and the delete must agree — regression for the encoded-b""-
    sorts-below-everything bug."""
    raw = MemEngine()
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    definition = RegionDefinition(
        region_id=88, start_key=b"a", end_key=b"",  # unbounded
        partition_id=1, region_type=RegionType.STORE,
    )
    region = Region(definition)
    storage.kv_put(region, [(b"a", b"1"), (b"m", b"2"), (b"\xffzz", b"3")])
    assert storage.kv_delete_range(region, [(b"b", b"")]) == 2
    assert storage.kv_get(region, b"a") == b"1"
    assert storage.kv_get(region, b"m") is None
    assert storage.kv_get(region, b"\xffzz") is None


def test_meta_manager_recovery(tmp_path):
    raw = WalEngine(str(tmp_path / "meta"))
    mm = StoreMetaManager(raw)
    region = make_region()
    mm.add_region(region)
    raw.close()
    raw2 = WalEngine(str(tmp_path / "meta"))
    mm2 = StoreMetaManager(raw2)
    assert mm2.recover() == 1
    r = mm2.get_region(77)
    assert r is not None and r.definition.partition_id == 1
    raw2.close()


class _CfSpyEngine:
    """RawEngine proxy recording which CFs reads touch."""

    def __init__(self, inner):
        self._inner = inner
        self.read_cfs = []

    def get(self, cf, key):
        self.read_cfs.append(cf)
        return self._inner.get(cf, key)

    def scan(self, cf, start=b"", end=None):
        self.read_cfs.append(cf)
        return self._inner.scan(cf, start, end)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_scalar_speedup_cf_pre_filter():
    """Scalar speed-up CF end-to-end (raft_apply_handler.cc:1115 via
    SplitVectorScalarData + constant.h kVectorScalarKeySpeedUpCF): with
    scalar_speedup_keys flagged, apply writes the flagged subset to the
    narrow CF, a covered pre-filter search reads ONLY the narrow CF, and
    results are identical to the wide-CF path."""
    from dingo_tpu.engine.raw_engine import (
        CF_VECTOR_SCALAR,
        CF_VECTOR_SCALAR_SPEEDUP,
    )
    from dingo_tpu.index.vector_reader import deserialize_scalar

    def build(speedup_keys):
        raw = MemEngine()
        engine = MonoStoreEngine(raw)
        storage = Storage(engine)
        definition = RegionDefinition(
            region_id=88,
            start_key=vcodec.encode_vector_key(1, 0),
            end_key=vcodec.encode_vector_key(1, 1 << 40),
            partition_id=1,
            region_type=RegionType.INDEX,
            index_parameter=IndexParameter(
                index_type=IndexType.FLAT, dimension=DIM,
                scalar_speedup_keys=speedup_keys,
            ),
        )
        region = Region(definition)
        w = region.vector_index_wrapper
        w.build_own()
        w.set_own(w.own_index)
        x = rand(200, seed=7)
        ids = np.arange(200, dtype=np.int64)
        # WIDE scalars: many fields, only "color" is flagged
        scalars = [
            {"color": "red" if i % 5 == 0 else "blue", "size": i,
             "shape": "s" + str(i % 7), "w0": i * 2, "w1": i * 3,
             "w2": "x" * 50}
            for i in range(200)
        ]
        storage.vector_add(region, ids, x, scalars)
        return raw, engine, storage, region, x

    # flagged region: narrow CF holds ONLY the flagged subset
    raw, engine, storage, region, x = build(("color",))
    narrow_rows = list(raw.scan(CF_VECTOR_SCALAR_SPEEDUP, b"", None))
    assert len(narrow_rows) == 200
    from dingo_tpu.mvcc.codec import Codec as _C

    _flag, payload, _ttl = _C.unpackage_value(narrow_rows[0][1])
    assert set(deserialize_scalar(payload)) == {"color"}

    # covered pre-filter search reads only the narrow CF
    spy = _CfSpyEngine(raw)
    reader = engine.new_vector_reader(region)
    reader.ctx = dataclasses_replace_engine(reader.ctx, spy)
    reader._scalar.engine = spy
    reader._speedup.engine = spy
    reader._data.engine = spy
    res_narrow = reader.vector_batch_search(
        x[:8], 10, filter_mode=VectorFilterMode.SCALAR,
        filter_type=VectorFilterType.QUERY_PRE,
        scalar_filter=ScalarFilter.equals({"color": "red"}),
    )
    assert CF_VECTOR_SCALAR_SPEEDUP in spy.read_cfs
    assert CF_VECTOR_SCALAR not in spy.read_cfs, (
        "covered pre-filter touched the wide scalar CF")

    # identical results to a region WITHOUT the speed-up CF
    raw2, engine2, storage2, region2, x2 = build(())
    res_wide = storage2.vector_batch_search(
        region2, x2[:8], 10, filter_mode=VectorFilterMode.SCALAR,
        filter_type=VectorFilterType.QUERY_PRE,
        scalar_filter=ScalarFilter.equals({"color": "red"}),
    )
    for a, b in zip(res_narrow, res_wide):
        assert [v.id for v in a] == [v.id for v in b]

    # an UNCOVERED filter (field not flagged) falls back to the wide CF
    spy.read_cfs.clear()
    reader.vector_batch_search(
        x[:2], 5, filter_mode=VectorFilterMode.SCALAR,
        filter_type=VectorFilterType.QUERY_PRE,
        scalar_filter=ScalarFilter.equals({"size": 5}),
    )
    assert CF_VECTOR_SCALAR in spy.read_cfs

    # deletes tombstone the narrow CF too
    storage.vector_delete(region, [0, 5])
    reader2 = engine.new_vector_reader(region)
    res_after = reader2.vector_batch_search(
        x[:1], 5, filter_mode=VectorFilterMode.SCALAR,
        filter_type=VectorFilterType.QUERY_PRE,
        scalar_filter=ScalarFilter.equals({"color": "red"}),
    )
    got_after = [v.id for v in res_after[0]]
    assert 0 not in got_after and 5 not in got_after


def dataclasses_replace_engine(ctx, engine):
    import dataclasses as _dc

    return _dc.replace(ctx, engine=engine)


def test_table_coprocessor_filter_pre_and_post():
    """VECTOR_FILTER=TABLE (vector_reader.cc:169-232): table rows ride
    VectorAdd into the vector_table CF; search dispatches the coprocessor
    filter over them — pre variant scans the table CF into a candidate id
    set, post variant over-fetches x10 then filters candidates' rows."""
    from dingo_tpu.coprocessor.coprocessor_v2 import (
        CoprocessorDef,
        CoprocessorV2,
        SchemaColumn,
        encode_row,
    )

    raw = MemEngine()
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    region = make_region(region_id=99)
    x = rand(200, seed=3)
    ids = np.arange(200, dtype=np.int64)
    schema = [
        SchemaColumn("dept", "VARCHAR", 0),
        SchemaColumn("salary", "DOUBLE", 1),
    ]
    rows = [
        ["eng" if i % 3 == 0 else "ops", float(50 + i)] for i in range(200)
    ]
    storage.vector_add(region, ids, x,
                       table_values=[encode_row(r) for r in rows])

    cop = CoprocessorV2(CoprocessorDef(
        original_schema=schema,
        filter_expr=["and", ["eq", ["field", "dept"], ["const", "eng"]],
                     ["ge", ["field", "salary"], ["const", 100.0]]],
    ))
    want = {i for i in range(200) if i % 3 == 0 and 50 + i >= 100}

    reader = engine.new_vector_reader(region)
    res_pre = reader.vector_batch_search(
        x[:4], 20, filter_mode=VectorFilterMode.TABLE,
        filter_type=VectorFilterType.QUERY_PRE, coprocessor=cop,
    )
    for qi, row in enumerate(res_pre):
        assert row, "pre-filter returned nothing"
        assert all(v.id in want for v in row), [v.id for v in row]

    res_post = reader.vector_batch_search(
        x[60:62], 5, filter_mode=VectorFilterMode.TABLE,
        filter_type=VectorFilterType.QUERY_POST, coprocessor=cop,
    )
    for row in res_post:
        assert all(v.id in want for v in row)
    # query 60: 60 % 3 == 0 and salary 110 -> its own id must lead
    assert res_post[0][0].id == 60

    # missing coprocessor is a hard error, not a silent no-filter
    with pytest.raises(ValueError):
        reader.vector_batch_search(
            x[:1], 5, filter_mode=VectorFilterMode.TABLE,
            filter_type=VectorFilterType.QUERY_PRE,
        )

    # deletes tombstone the table CF: deleted ids drop out of pre-filter
    storage.vector_delete(region, [60])
    reader2 = engine.new_vector_reader(region)
    res2 = reader2.vector_batch_search(
        x[60:61], 10, filter_mode=VectorFilterMode.TABLE,
        filter_type=VectorFilterType.QUERY_PRE, coprocessor=cop,
    )
    assert all(v.id != 60 for v in res2[0])


def test_speedup_cf_upsert_drops_flagged_field():
    """Regression: an upsert that drops every flagged field must tombstone
    the narrow CF — otherwise the stale narrow row stays visible and a
    covered filter diverges from the wide path."""
    raw = MemEngine()
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    definition = RegionDefinition(
        region_id=91,
        start_key=vcodec.encode_vector_key(1, 0),
        end_key=vcodec.encode_vector_key(1, 1 << 40),
        partition_id=1,
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(
            index_type=IndexType.FLAT, dimension=DIM,
            scalar_speedup_keys=("color",),
        ),
    )
    region = Region(definition)
    w = region.vector_index_wrapper
    w.build_own()
    w.set_own(w.own_index)
    x = rand(4, seed=9)
    ids = np.arange(4, dtype=np.int64)
    storage.vector_add(region, ids, x,
                       [{"color": "red", "n": int(i)} for i in ids])
    # upsert id 0 WITHOUT the flagged field
    storage.vector_add(region, ids[:1], x[:1], [{"n": 100}])
    reader = engine.new_vector_reader(region)
    res = reader.vector_batch_search(
        x[:1], 4, filter_mode=VectorFilterMode.SCALAR,
        filter_type=VectorFilterType.QUERY_PRE,
        scalar_filter=ScalarFilter.equals({"color": "red"}),
    )
    got = [v.id for v in res[0]]
    assert 0 not in got, (
        "stale narrow-CF row survived an upsert that dropped the field")
    assert set(got) == {1, 2, 3}


def test_table_row_clear_with_empty_bytes():
    """Per-entry table semantics: None leaves the row, b'' clears it."""
    from dingo_tpu.coprocessor.coprocessor_v2 import (
        CoprocessorDef,
        CoprocessorV2,
        SchemaColumn,
        encode_row,
    )

    raw = MemEngine()
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    region = make_region(region_id=92)
    x = rand(3, seed=4)
    ids = np.arange(3, dtype=np.int64)
    storage.vector_add(
        region, ids, x,
        table_values=[encode_row(["eng"]) for _ in range(3)])
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=[SchemaColumn("dept", "VARCHAR", 0)],
        filter_expr=["eq", ["field", "dept"], ["const", "eng"]],
    ))
    reader = engine.new_vector_reader(region)
    res = reader.vector_batch_search(
        x[:1], 3, filter_mode=VectorFilterMode.TABLE,
        filter_type=VectorFilterType.QUERY_PRE, coprocessor=cop)
    assert {v.id for v in res[0]} == {0, 1, 2}

    # upsert id 1 clearing its table row, id 2 untouched (None)
    storage.vector_add(region, ids[1:3], x[1:3],
                       table_values=[b"", None])
    reader = engine.new_vector_reader(region)
    res = reader.vector_batch_search(
        x[:1], 3, filter_mode=VectorFilterMode.TABLE,
        filter_type=VectorFilterType.QUERY_PRE, coprocessor=cop)
    assert {v.id for v in res[0]} == {0, 2}
