"""On-device k-means for IVF coarse quantizers and PQ codebooks.

Replaces faiss::Clustering (used by the reference's IVF_FLAT/IVF_PQ training:
vector_index_ivf_flat.cc Train, vector_index_ivf_pq.cc:337-341 where train
size is derived from ClusteringParameters.max_points_per_centroid * nlist).

TPU design: Lloyd's iterations where BOTH phases are matmuls —
  assign:  argmax over the [chunk, k] score matrix (MXU)
  update:  one-hot(assign)^T @ x  accumulated over chunks (MXU again)
Data is processed in fixed-size chunks under lax.scan so arbitrary n compiles
to one program; empty clusters are re-seeded from the globally farthest
points (faiss re-assigns empty clusters similarly).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from dingo_tpu.ops.distance import pairwise_l2sqr, squared_norms
from dingo_tpu.obs.sentinel import sentinel_jit

#: max_points_per_centroid default in faiss ClusteringParameters is 256;
#: the reference derives IVF train sizes from it (vector_index_ivf_pq.cc:337).
MAX_POINTS_PER_CENTROID = 256


def _pad_to_multiple(x: jax.Array, m: int) -> Tuple[jax.Array, jax.Array]:
    n = x.shape[0]
    pad = (-n) % m
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)])
    valid = (jnp.arange(n + pad) < n)
    return x, valid


@sentinel_jit("ops.kmeans.init", static_argnames=("k",))
def farthest_first_init(x: jax.Array, first_idx: jax.Array, k: int) -> jax.Array:
    """Deterministic k-means++-style seeding: greedy farthest-first traversal.

    Replaces faiss's random-subsample init; being deterministic keeps index
    Train() reproducible across raft peers (the reference trains on the leader
    and ships the index via snapshot — we keep training reproducible instead).
    Returns [k] int32 indices into x.
    """
    x = x.astype(jnp.float32)
    n = x.shape[0]
    x_sq = squared_norms(x)

    def body(carry, _):
        min_d, chosen, i = carry
        c = x[chosen[i - 1]]
        d = x_sq - 2.0 * jnp.einsum('nd,d->n', x, c, precision=jax.lax.Precision.HIGHEST) + jnp.dot(c, c, precision=jax.lax.Precision.HIGHEST)
        min_d = jnp.minimum(min_d, d)
        nxt = jnp.argmax(min_d).astype(jnp.int32)
        chosen = chosen.at[i].set(nxt)
        return (min_d, chosen, i + 1), None

    chosen0 = jnp.zeros((k,), jnp.int32).at[0].set(first_idx.astype(jnp.int32))
    (_, chosen, _), _ = jax.lax.scan(
        body, (jnp.full((n,), jnp.inf), chosen0, 1), None, length=k - 1
    )
    return chosen


@sentinel_jit("ops.kmeans.fit", static_argnames=("k", "iters", "chunk"))
def kmeans_fit(
    x: jax.Array,
    seed_idx: jax.Array,
    k: int,
    iters: int = 10,
    chunk: int = 16384,
) -> Tuple[jax.Array, jax.Array]:
    """Fit k centroids to x[n, d] with Lloyd's algorithm.

    seed_idx: [k] int32 initial centroid row indices (host picks a random
    permutation — keeps this function deterministic/jit-pure).
    Returns (centroids[k, d] f32, cluster_sizes[k] f32).
    """
    x = x.astype(jnp.float32)
    n, d = x.shape
    chunk = min(chunk, max(256, n))
    xp, valid = _pad_to_multiple(x, chunk)
    nchunks = xp.shape[0] // chunk
    xc = xp.reshape(nchunks, chunk, d)
    vc = valid.reshape(nchunks, chunk)

    centroids = jnp.take(x, seed_idx, axis=0)

    def lloyd_iter(centroids, _):
        def body(carry, inp):
            sums, counts, far_d, far_pt = carry
            xi, vi = inp
            dist = pairwise_l2sqr(xi, centroids)          # [chunk, k]
            assign = jnp.argmin(dist, axis=1)
            best = jnp.min(dist, axis=1)
            onehot = jax.nn.one_hot(assign, k, dtype=jnp.float32)
            onehot = onehot * vi[:, None]
            sums = sums + jnp.einsum('ck,cd->kd', onehot, xi, precision=jax.lax.Precision.HIGHEST)
            counts = counts + onehot.sum(axis=0)
            # Track the single farthest point for empty-cluster reseeding.
            best = jnp.where(vi, best, -jnp.inf)
            j = jnp.argmax(best)
            better = best[j] > far_d
            far_d = jnp.where(better, best[j], far_d)
            far_pt = jnp.where(better, xi[j], far_pt)
            return (sums, counts, far_d, far_pt), None

        init = (
            jnp.zeros((k, d), jnp.float32),
            jnp.zeros((k,), jnp.float32),
            -jnp.inf,
            jnp.zeros((d,), jnp.float32),
        )
        (sums, counts, _, far_pt), _ = jax.lax.scan(body, init, (xc, vc))
        empty = counts < 0.5
        new_c = sums / jnp.maximum(counts, 1.0)[:, None]
        # Empty clusters: keep old centroid, except the first empty one which
        # jumps to the farthest point (cheap on-device splitting heuristic).
        new_c = jnp.where(empty[:, None], centroids, new_c)
        first_empty = jnp.argmax(empty)
        any_empty = jnp.any(empty)
        new_c = jnp.where(
            (jnp.arange(k) == first_empty)[:, None] & any_empty,
            far_pt[None, :],
            new_c,
        )
        return new_c, counts

    centroids, _ = jax.lax.scan(lloyd_iter, centroids, None, length=iters)

    # Final counts against the RETURNED centroids (the scan's per-iteration
    # counts describe the centroids entering each iteration, which disagrees
    # with the final update; callers use sizes for balance decisions).
    def count_body(counts, inp):
        xi, vi = inp
        dist = pairwise_l2sqr(xi, centroids)
        onehot = jax.nn.one_hot(jnp.argmin(dist, axis=1), k, dtype=jnp.float32)
        return counts + (onehot * vi[:, None]).sum(axis=0), None

    counts, _ = jax.lax.scan(
        count_body, jnp.zeros((k,), jnp.float32), (xc, vc)
    )
    return centroids, counts


def train_kmeans(
    x: jax.Array, k: int, iters: int = 10, seed: int = 0
) -> Tuple[jax.Array, jax.Array]:
    """Host-convenience trainer: farthest-first init + Lloyd iterations.

    Deterministic given (data, seed) — see farthest_first_init docstring."""
    import numpy as _np

    first = _np.random.default_rng(seed).integers(0, x.shape[0])
    seeds = farthest_first_init(x, jnp.int32(first), k)
    return kmeans_fit(x, seeds, k=k, iters=iters)


@sentinel_jit("ops.kmeans.assign", static_argnames=("chunk",))
def kmeans_assign(
    x: jax.Array, centroids: jax.Array, chunk: int = 16384
) -> jax.Array:
    """Nearest-centroid assignment [n] int32, chunked for memory."""
    x = x.astype(jnp.float32)
    n, d = x.shape
    chunk = min(chunk, max(256, n))
    xp, _ = _pad_to_multiple(x, chunk)
    nchunks = xp.shape[0] // chunk
    xc = xp.reshape(nchunks, chunk, d)
    c_sq = squared_norms(centroids)

    def body(_, xi):
        dist = pairwise_l2sqr(xi, centroids, c_sq)
        return None, jnp.argmin(dist, axis=1).astype(jnp.int32)

    _, assign = jax.lax.scan(body, None, xc)
    return assign.reshape(-1)[:n]
