"""Quality observability plane: live recall measured in production.

The observability stack can see latency, recompiles, and HBM — but every
approximate-search knob (nprobe, ef, rerank factor, precision tier) trades
against an axis none of those instruments measure: **result quality**.
This module closes the loop: for a head-sampled fraction of live searches
(``quality.sample_rate``, the trace-sampling discipline — one float
compare and an early return when 0, nothing allocated, nothing
dispatched), the region re-answers the SAME queries **exactly** with a
shadow scan (ops/shadow.py, the FLAT kernel's math over the region's fp32
reference rows) and scores the served result against the ground truth:

- recall@k        — fraction of true top-k ids the served result found;
- rank-biased overlap (RBO, p=0.9) — order-sensitive agreement, so a
  result that found the right ids in the wrong order still reads worse
  than a perfect one;
- score gap       — relative regret of the served k-th best distance vs
  the true k-th best (how much WORSE, not just how different).

Scoring runs on a dedicated async lane (bounded queue + one worker
thread, overflow drops and counts — the served reply never waits), feeds
windowed estimators with Wilson confidence intervals per (region, index
kind, precision tier, parameter bucket), and publishes the curated
``quality.*`` metrics family. Region rollups ride heartbeats to the
coordinator (RegionMetricsSnapshot.quality_*), surface in ``cluster top``
(RECALL column), Prometheus, and flight bundles.

Ground truth sources, per index tier:
- fp32 SlotStore indexes (FLAT / IVF_FLAT / HNSW fp32, IVF_PQ's device
  store) — the index's own rows ARE the fp32 reference: zero extra
  memory, the shadow scan reads them under the store's lease/lock
  discipline.
- quantized tiers (bf16 / sq8) — the oracle keeps a private fp32 mirror
  (a SlotStore fed the ORIGINAL rows at write time via the index hooks),
  so the estimate includes quantization loss — the precision knob must
  never look free to the SLO tuner. A mirror attached mid-life backfills
  from the store's decoded rows (the best reconstruction available) until
  overwritten by fresh writes.
- host-vector stores (IVF_PQ host mode) — numpy scan over the host rows.

Consistency note: a sample scored while writes are in flight is judged
against the FRESHEST reference rows, which may be slightly newer than
the store state the search actually scanned — a served result can be
"wrong" only about rows that changed in the race window, so the skew is
bounded by write rate x scoring latency and washes out in the windowed
estimate (the same eventual-consistency stance the metrics plane takes).

The shadow-path cost model and the estimator math are documented in
ARCHITECTURE.md "Quality observability & SLO tuning"; the closed-loop
controller that acts on these estimates lives in obs/tuner.py.
"""

from __future__ import annotations

import dataclasses
import math
import random
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dingo_tpu.common.log import get_logger
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.ops.distance import Metric, metric_ascending

_log = get_logger("obs.quality")

#: queries scored per sampled batch — a fixed cap so the shadow kernel
#: compiles for ONE batch bucket (pow2-padded) and the estimator's cost
#: per sample is bounded regardless of serving batch size
SHADOW_MAX_QUERIES = 16

#: pending shadow jobs; overflow drops (and counts) — the async lane must
#: never apply backpressure to the serving path
QUEUE_MAX = 64

#: rank-biased overlap persistence (Webber et al.: top-weighted, p=0.9
#: puts ~86% of the weight in the first 10 ranks)
RBO_P = 0.9

#: Wilson interval z for the 95% CI the tuner compares against the SLO
WILSON_Z = 1.96


# ---------------------------------------------------------------------------
# host scoring math (pure, unit-testable)
# ---------------------------------------------------------------------------

def recall_hits(served_ids: np.ndarray, gt_ids: np.ndarray) -> Tuple[int, int]:
    """(hits, trials) for one query: |served ∩ truth| over |truth|
    (-1 padding excluded on both sides). Trials count the TRUE neighbors,
    so a region with fewer than k rows still scores 1.0 when everything
    was found."""
    gt = {int(i) for i in gt_ids if i >= 0}
    if not gt:
        return 0, 0
    served = {int(i) for i in served_ids if i >= 0}
    return len(served & gt), len(gt)


def rank_biased_overlap(served_ids: np.ndarray, gt_ids: np.ndarray,
                        p: float = RBO_P) -> float:
    """Truncated RBO at the list depth: order-sensitive agreement in
    [0, 1], weight p^(d-1) on prefix depth d, normalized over the
    truncated depth so identical lists score exactly 1.0."""
    a = [int(i) for i in served_ids if i >= 0]
    b = [int(i) for i in gt_ids if i >= 0]
    depth = max(len(a), len(b))
    if depth == 0:
        return 1.0
    num = den = 0.0
    sa: set = set()
    sb: set = set()
    for d in range(1, depth + 1):
        if d <= len(a):
            sa.add(a[d - 1])
        if d <= len(b):
            sb.add(b[d - 1])
        w = p ** (d - 1)
        num += w * (len(sa & sb) / d)
        den += w
    return num / den


def score_gap(served_dists: np.ndarray, gt_dists: np.ndarray,
              ascending: bool) -> float:
    """Relative regret of the served k-th best vs the true k-th best wire
    distance (>= 0; 0 = the served tail is as good as the exact tail).
    Distributions of this gap separate 'missed a near-duplicate' from
    'wandered into the wrong cluster' at equal recall."""
    sd = [float(d) for d in served_dists if math.isfinite(d)]
    gd = [float(d) for d in gt_dists if math.isfinite(d)]
    if not sd or not gd:
        return 0.0
    s_kth, g_kth = sd[-1], gd[-1]
    regret = (s_kth - g_kth) if ascending else (g_kth - s_kth)
    return max(0.0, regret / max(abs(g_kth), 1e-9))


def wilson_interval(hits: int, trials: int,
                    z: float = WILSON_Z) -> Tuple[float, float]:
    """Wilson score interval for hits/trials — well-behaved at p near 1
    (where recall SLOs live) and at small n, unlike the normal
    approximation which collapses to a zero-width band at p=1."""
    if trials <= 0:
        return 0.0, 1.0
    n = float(trials)
    phat = hits / n
    z2 = z * z
    denom = 1.0 + z2 / n
    center = (phat + z2 / (2.0 * n)) / denom
    half = (z / denom) * math.sqrt(
        phat * (1.0 - phat) / n + z2 / (4.0 * n * n)
    )
    # at phat in {0, 1} the bound on that side is EXACTLY the endpoint;
    # the float evaluation above lands an ulp inside it
    lo = 0.0 if hits == 0 else max(0.0, center - half)
    hi = 1.0 if hits == trials else min(1.0, center + half)
    return lo, hi


def _shadow_batch_pad(q: np.ndarray) -> np.ndarray:
    """Pow2-pad the shadow batch with the SERVING path's own padding
    (index/flat._pad_batch — one source of truth for the batch ladder,
    lazily imported to keep this module cycle-free from the index
    package)."""
    from dingo_tpu.index.flat import _pad_batch

    return _pad_batch(q)


def _k_bucket(k: int) -> int:
    """Round shadow k up the {1,1.5}x-pow2 ladder (the serving shape
    discipline) so the shadow kernel compiles once per k bucket."""
    from dingo_tpu.index.ivf_layout import shape_bucket

    return shape_bucket(int(k))


# ---------------------------------------------------------------------------
# ground-truth oracle
# ---------------------------------------------------------------------------

class ShadowOracle:
    """Exact top-k answer source for one region. Three arms:

    - ``store``  — the index's own fp32 SlotStore rows (zero extra state);
    - ``mirror`` — a private fp32 SlotStore fed original rows at write
      time (quantized tiers), backfilled from decoded rows on attach;
    - ``host``   — numpy scan over a HostSlotStore's rows.
    """

    def __init__(self, index=None, dim: int = 0, metric=None):
        self.metric = metric if metric is not None else (
            index.metric if index is not None else Metric.L2
        )
        self._index = weakref.ref(index) if index is not None else None
        self._mirror = None
        #: serializes mirror mutations: write hooks run on serving
        #: threads while the deferred backfill runs on the async lane
        self._mu = threading.Lock()
        #: backfill-of-preexisting-rows still owed (mirror arm, see
        #: ensure_backfilled); _fresh = ids touched by hooks SINCE attach
        #: — the backfill must never clobber an original with a decode
        self._pending_backfill = False
        self._fresh: set = set()
        self.mode = "mirror"
        if index is not None:
            store = index.store
            import jax.numpy as jnp
            from dingo_tpu.index.slot_store import HostSlotStore, SqSlotStore

            if isinstance(store, HostSlotStore):
                self.mode = "host"
                return
            if not isinstance(store, SqSlotStore) and (
                jnp.dtype(store.dtype) == jnp.float32
            ):
                self.mode = "store"
                return
            dim = index.dimension
        # quantized tier (or a standalone reference): private fp32 mirror.
        # blocked=False — the mirror is scanned by the plain XLA kernel
        # only; a second dimension-blocked copy would be pure waste.
        # Created EMPTY: rows the store already holds are owed as a
        # DEFERRED backfill (ensure_backfilled, run on the async lane
        # before the first scoring) so attaching mid-life on a large
        # store never stalls the write/serving thread that triggered it.
        from dingo_tpu.index.slot_store import SlotStore

        import jax.numpy as jnp

        self._mirror = SlotStore(dim, jnp.float32, blocked=False)
        self._pending_backfill = index is not None and len(index.store) > 0

    # -- write feed (mirror arm only; others read the live store) ----------
    def observe_write(self, ids: np.ndarray, rows: np.ndarray) -> None:
        if self._mirror is None:
            return
        ids = np.asarray(ids, np.int64)
        with self._mu:
            self._mirror.put(ids, np.asarray(rows, np.float32))
            if self._pending_backfill:
                self._fresh.update(int(i) for i in ids)

    def observe_delete(self, ids: np.ndarray) -> None:
        if self._mirror is None:
            return
        ids = np.asarray(ids, np.int64)
        with self._mu:
            self._mirror.remove_slots(ids)
            if self._pending_backfill:
                self._fresh.update(int(i) for i in ids)

    def ensure_backfilled(self) -> None:
        """Fill the mirror with the store's pre-attach rows (decoded —
        the best reconstruction available) the first time anyone needs to
        SCORE against it. Runs on the async lane; rows the write hooks
        touched since attach keep their original (or deleted) state."""
        with self._mu:
            if not self._pending_backfill:
                return
        idx = self._index() if self._index is not None else None
        if idx is None:
            with self._mu:
                self._pending_backfill = False
                self._fresh.clear()
            return
        snap = idx.store.to_host()          # OUTSIDE _mu: slow download
        with self._mu:
            keep = ~np.isin(snap["ids"],
                            np.fromiter(self._fresh, np.int64,
                                        len(self._fresh)))
            if keep.any():
                self._mirror.reserve(int(keep.sum()))
                self._mirror.put(
                    snap["ids"][keep],
                    np.asarray(snap["vectors"], np.float32)[keep],
                )
            self._pending_backfill = False
            self._fresh.clear()

    # -- exact answers ------------------------------------------------------
    def _ref_store(self):
        if self._mirror is not None:
            return self._mirror
        idx = self._index() if self._index is not None else None
        return idx.store if idx is not None else None

    def exact_topk(self, queries: np.ndarray, k: int, filter_spec=None
                   ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(ids [b, k], wire distances [b, k]) of the exact answer, -1/inf
        padded; None when the reference store is gone (index deleted).

        `filter_spec` restricts the ground truth to the SAME candidate
        set the served search was allowed (compiled id-based against the
        reference store's own slot space, so it works identically for
        store/mirror/host arms) — a filtered search scored against
        unfiltered truth would read as a recall collapse proportional to
        the filter's selectivity."""
        store = self._ref_store()
        if store is None:
            return None
        queries = np.asarray(queries, np.float32)
        b = queries.shape[0]
        filtered = filter_spec is not None and not filter_spec.is_empty()
        if self.mode == "host":
            return self._exact_host(store, queries, k, filter_spec)
        import jax
        import jax.numpy as jnp

        from dingo_tpu.ops.shadow import shadow_exact_topk

        kb = _k_bucket(k)
        qpad = jnp.asarray(_shadow_batch_pad(queries))
        # filter mask compiles in numpy OUTSIDE the device lock (the
        # serving paths' discipline); same [capacity] bool shape as the
        # plain validity mask, so no extra programs
        np_mask = filter_spec.slot_mask(store.ids_by_slot) if filtered \
            else None
        lease = store.begin_search()
        try:
            with store.device_lock:
                mask = jnp.asarray(np_mask) if np_mask is not None \
                    else store.device_mask()
                dists, slots = shadow_exact_topk(
                    store.vecs, store.sqnorm, mask, qpad,
                    k=kb, metric=self.metric,
                )
            dists_h, slots_h = jax.device_get((dists, slots))
            ids = store.ids_of_slots(slots_h[:b, :k])
        finally:
            lease.release()
        return ids, np.asarray(dists_h[:b, :k], np.float32)

    def _exact_host(self, store, queries: np.ndarray, k: int,
                    filter_spec=None):
        vecs = np.asarray(store.vecs, np.float32)
        valid = store.valid_h
        if filter_spec is not None and not filter_spec.is_empty():
            valid = valid & filter_spec.slot_mask(store.ids_by_slot)
        if self.metric is Metric.L2:
            scores = -(
                (queries ** 2).sum(1)[:, None]
                - 2.0 * queries @ vecs.T
                + np.asarray(store.sqnorm)[None, :]
            )
        elif self.metric is Metric.COSINE:
            # rows stored normalized (write-side prep): IP is cosine
            scores = queries @ vecs.T
        else:
            scores = queries @ vecs.T
        scores = np.where(valid[None, :], scores, -np.inf)
        kk = min(k, scores.shape[1])
        part = np.argpartition(-scores, kk - 1, axis=1)[:, :kk]
        vals = np.take_along_axis(scores, part, axis=1)
        order = np.argsort(-vals, axis=1)
        slots = np.take_along_axis(part, order, axis=1)
        vals = np.take_along_axis(vals, order, axis=1)
        ids = store.ids_of_slots(slots)
        ids = np.where(np.isneginf(vals), -1, ids)
        dists = -vals if metric_ascending(self.metric) else vals
        if kk < k:
            ids = np.pad(ids, ((0, 0), (0, k - kk)), constant_values=-1)
            dists = np.pad(dists, ((0, 0), (0, k - kk)),
                           constant_values=np.inf)
        return ids, np.asarray(dists, np.float32)


# ---------------------------------------------------------------------------
# windowed estimator
# ---------------------------------------------------------------------------

class WindowedEstimator:
    """Sliding-window recall/RBO/score-gap aggregate with a Wilson CI.

    Each scored sample contributes (hits, trials) Bernoulli evidence —
    recall@k over n queries is hits/(found slots), so the CI narrows with
    BOTH more sampled queries and larger k. Entries older than
    ``quality.window_s`` age out at read time."""

    def __init__(self):
        self._lock = threading.Lock()
        #: (wall_ts, queries, hits, trials, rbo_sum, gaps tuple)
        self._entries: deque = deque()

    @staticmethod
    def _window_s() -> float:
        from dingo_tpu.common.config import FLAGS

        try:
            return float(FLAGS.get("quality_window_s"))
        except KeyError:
            return 60.0

    def add(self, queries: int, hits: int, trials: int, rbo_sum: float,
            gaps: List[float]) -> None:
        now = time.time()
        window = self._window_s()
        with self._lock:
            self._entries.append(
                (now, queries, hits, trials, rbo_sum, tuple(gaps))
            )
            while self._entries and now - self._entries[0][0] > window:
                self._entries.popleft()

    def reset(self) -> None:
        """Drop the window — the tuner calls this after a knob step so
        pre-step evidence can't vote on the post-step configuration."""
        with self._lock:
            self._entries.clear()

    def stats(self) -> Optional[Dict[str, float]]:
        now = time.time()
        window = self._window_s()
        with self._lock:
            while self._entries and now - self._entries[0][0] > window:
                self._entries.popleft()
            entries = list(self._entries)
        if not entries:
            return None
        queries = sum(e[1] for e in entries)
        hits = sum(e[2] for e in entries)
        trials = sum(e[3] for e in entries)
        rbo_sum = sum(e[4] for e in entries)
        gaps = sorted(g for e in entries for g in e[5])
        lo, hi = wilson_interval(hits, trials)
        pick = (lambda p: gaps[min(len(gaps) - 1,
                                   int(p * len(gaps)))]) if gaps else (
            lambda p: 0.0)
        return {
            "recall": hits / trials if trials else 0.0,
            "ci_low": lo,
            "ci_high": hi,
            "rbo": rbo_sum / queries if queries else 0.0,
            "gap_p50": pick(0.50),
            "gap_p99": pick(0.99),
            "hits": hits,
            "queries": queries,
            "trials": trials,
            "newest_ts": entries[-1][0],
            "oldest_ts": entries[0][0],
        }


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Sample:
    region_id: int
    kind: str
    precision: str
    bucket: str
    metric: Any
    topk: int
    queries: np.ndarray
    served_ids: np.ndarray
    served_dists: Optional[np.ndarray]
    #: the served search's filter (None = unfiltered): ground truth is
    #: computed under the SAME candidate restriction
    filter_spec: Any = None


#: index kinds with quality hooks (binary/diskann/bruteforce have no
#: float shadow-scan semantics here)
_SUPPORTED_KINDS = {"flat", "ivf_flat", "ivf_pq", "hnsw"}


class QualityPlane:
    def __init__(self, registry=METRICS):
        self.registry = registry
        self._lock = threading.Lock()
        #: region_id -> (weakref to index or None, ShadowOracle)
        self._oracles: Dict[int, Tuple[Optional[weakref.ref],
                                       ShadowOracle]] = {}
        #: (region, kind, precision, bucket) -> estimator
        self._estimators: Dict[Tuple, WindowedEstimator] = {}
        self._region_keys: Dict[int, set] = {}
        self._queue: deque = deque()
        self._cond = threading.Condition(self._lock)
        self._worker: Optional[threading.Thread] = None
        self._busy = 0
        self._rng = random.Random(0x51AD0)

    # -- gating -------------------------------------------------------------
    @staticmethod
    def sample_rate() -> float:
        from dingo_tpu.common.config import FLAGS

        try:
            return float(FLAGS.get("quality_sample_rate"))
        except KeyError:   # registry not populated (unit contexts)
            return 0.0

    @staticmethod
    def _supported(index) -> bool:
        try:
            return index.index_type.value in _SUPPORTED_KINDS
        except Exception:  # noqa: BLE001 — duck-typed test fakes
            return False

    def _oracle_for(self, index) -> ShadowOracle:
        oracle = self._attached_oracle(index)
        if oracle is not None:
            return oracle
        # construct OUTSIDE the plane lock: a mid-life attach on a large
        # quantized store backfills its fp32 mirror from a full store
        # download — holding the (shared, also-the-async-lane's) lock
        # across that would stall every other region's hooks and the
        # scoring worker for the duration
        oracle = ShadowOracle(index)
        with self._lock:
            cur = self._oracles.get(index.id)
            if cur is not None and cur[0] is not None and cur[0]() is index:
                return cur[1]      # raced with another creator: keep it
            self._oracles[index.id] = (weakref.ref(index), oracle)
        return oracle

    def _attached_oracle(self, index) -> Optional[ShadowOracle]:
        """The index's oracle ONLY if already attached — never creates."""
        with self._lock:
            cur = self._oracles.get(index.id)
        if cur is not None and cur[0] is not None and cur[0]() is index:
            return cur[1]
        return None

    def _write_oracle(self, index) -> Optional[ShadowOracle]:
        """Oracle the write hooks should feed. An ALREADY-ATTACHED mirror
        keeps syncing even while sampling is momentarily off — toggling
        `quality.sample_rate` 1 -> 0 -> 1 around an incident must not
        leave the ground-truth mirror silently stale (deleted rows
        resurrected, fresh rows missing) and send the tuner chasing a
        phantom recall drop. Only CREATION is gated on the rate."""
        if not self._oracles and self.sample_rate() <= 0.0:
            return None              # common case: plane never engaged
        oracle = self._attached_oracle(index)
        if oracle is None and self.sample_rate() > 0.0:
            oracle = self._oracle_for(index)
        return oracle

    # -- index hooks ----------------------------------------------------------
    def observe_write(self, index, ids: np.ndarray,
                      rows: np.ndarray) -> None:
        """Write-path hook (index upsert, AFTER the store put): keeps the
        quantized tiers' fp32 mirror in sync. No-ops entirely while the
        plane was never engaged for this index."""
        if not self._supported(index):
            return
        try:
            oracle = self._write_oracle(index)
            if oracle is not None:
                oracle.observe_write(ids, rows)
        except Exception:  # noqa: BLE001 — observability must never
            _log.exception("quality observe_write failed")   # break writes

    def observe_delete(self, index, ids: np.ndarray) -> None:
        if not self._supported(index):
            return
        try:
            oracle = self._write_oracle(index)
            if oracle is not None:
                oracle.observe_delete(ids)
        except Exception:  # noqa: BLE001
            _log.exception("quality observe_delete failed")

    def observe_search(self, index, queries: np.ndarray, topk: int,
                       ids: np.ndarray, dists: Optional[np.ndarray],
                       bucket: str = "", filter_spec=None) -> None:
        """Search-resolve hook: head-sample this served batch for shadow
        scoring. The zero-rate path is one float compare; a sampled batch
        pays two small array copies and a queue append — scoring (and,
        on a first-ever sample, oracle attach + mirror backfill) happens
        on the async lane. Filtered searches carry their FilterSpec so
        the ground truth is restricted identically."""
        rate = self.sample_rate()
        if rate <= 0.0 or not self._supported(index):
            return
        if self._rng.random() >= rate:
            return
        try:
            nq = min(int(np.asarray(queries).shape[0]), SHADOW_MAX_QUERIES)
            sample = _Sample(
                region_id=index.id,
                kind=index.index_type.value,
                precision=getattr(index, "_precision", "fp32"),
                bucket=bucket,
                metric=index.metric,
                topk=int(topk),
                queries=np.array(queries[:nq], np.float32, copy=True),
                served_ids=np.array(ids[:nq], np.int64, copy=True),
                served_dists=(np.array(dists[:nq], np.float32, copy=True)
                              if dists is not None else None),
                filter_spec=(filter_spec if filter_spec is not None
                             and not filter_spec.is_empty() else None),
            )
            target = weakref.ref(index)
        except Exception:  # noqa: BLE001
            _log.exception("quality observe_search failed")
            return
        with self._cond:
            if len(self._queue) >= QUEUE_MAX:
                self.registry.counter(
                    "quality.dropped", region_id=index.id).add(1)
                return
            self._queue.append((sample, target))
            self._ensure_worker()
            self._cond.notify()

    # -- direct reference API (bench mesh children, tests) -------------------
    def install_reference(self, region_id: int, ids: np.ndarray,
                          rows: np.ndarray, metric=Metric.L2) -> None:
        """Install a standalone fp32 reference for a region served by an
        index without hooks (mesh-sharded paths): the oracle owns a
        mirror built from the given rows."""
        oracle = ShadowOracle(dim=int(np.asarray(rows).shape[1]),
                              metric=metric)
        oracle._mirror.reserve(len(ids))
        oracle.observe_write(ids, rows)
        with self._lock:
            self._oracles[region_id] = (None, oracle)

    def score_direct(self, region_id: int, queries: np.ndarray,
                     served_ids: np.ndarray, topk: int,
                     served_dists: Optional[np.ndarray] = None,
                     kind: str = "flat", precision: str = "fp32",
                     bucket: str = "") -> Optional[Dict[str, float]]:
        """Synchronous shadow scoring against an installed reference (or
        a hook-registered oracle). Feeds the same estimators/metrics as
        the async lane; returns this call's own scores."""
        with self._lock:
            cur = self._oracles.get(region_id)
        if cur is None:
            return None
        oracle = cur[1]
        oracle.ensure_backfilled()
        sample = _Sample(
            region_id=region_id, kind=kind, precision=precision,
            bucket=bucket, metric=oracle.metric, topk=int(topk),
            queries=np.asarray(queries, np.float32)[:SHADOW_MAX_QUERIES],
            served_ids=np.asarray(served_ids, np.int64)[:SHADOW_MAX_QUERIES],
            served_dists=(np.asarray(served_dists, np.float32)
                          [:SHADOW_MAX_QUERIES]
                          if served_dists is not None else None),
        )
        return self._score(sample, oracle)

    # -- async lane ----------------------------------------------------------
    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._worker = threading.Thread(
            target=self._run, name="quality-shadow", daemon=True
        )
        self._worker.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue:
                    self._cond.wait()
                sample, target = self._queue.popleft()
                self._busy += 1
            try:
                # resolve the oracle HERE: a first-ever sample's oracle
                # attach (potentially a full mirror backfill) runs on
                # this lane, never on the serving thread that sampled
                if isinstance(target, weakref.ref):
                    index = target()
                    oracle = self._oracle_for(index) \
                        if index is not None else None
                else:
                    oracle = target
                if oracle is not None:
                    # mirror arm owes pre-attach rows before it can judge
                    # anyone (no-op bool check on every later sample)
                    oracle.ensure_backfilled()
                    self._score(sample, oracle)
            except Exception:  # noqa: BLE001 — the lane must survive
                _log.exception("shadow scoring failed")
            finally:
                with self._cond:
                    self._busy -= 1
                    self._cond.notify_all()

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every queued shadow job has been scored (tests,
        bench, and the tuner's deterministic drive)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._busy:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._cond.wait(timeout=remain)
        return True

    # -- scoring + publication ------------------------------------------------
    def _score(self, s: _Sample,
               oracle: ShadowOracle) -> Optional[Dict[str, float]]:
        answer = oracle.exact_topk(s.queries, s.topk,
                                   filter_spec=s.filter_spec)
        if answer is None:
            return None
        gt_ids, gt_dists = answer
        self.registry.counter(
            "quality.shadow_scans", region_id=s.region_id).add(1)
        asc = metric_ascending(s.metric)
        hits = trials = 0
        rbo_sum = 0.0
        gaps: List[float] = []
        nq = len(s.queries)
        for qi in range(nq):
            h, t = recall_hits(s.served_ids[qi], gt_ids[qi])
            hits += h
            trials += t
            rbo_sum += rank_biased_overlap(s.served_ids[qi], gt_ids[qi])
            if s.served_dists is not None:
                gaps.append(score_gap(
                    s.served_dists[qi], gt_dists[qi], asc))
        key = (s.region_id, s.kind, s.precision, s.bucket)
        est = self._estimator(key)
        est.add(nq, hits, trials, rbo_sum, gaps)
        self.registry.counter(
            "quality.samples", region_id=s.region_id).add(nq)
        self._publish(key, est.stats())
        self._publish_region(s.region_id)
        lo, hi = wilson_interval(hits, trials)
        return {
            "recall": hits / trials if trials else 0.0,
            "ci_low": lo,
            "ci_high": hi,
            "rbo": rbo_sum / nq if nq else 0.0,
            "queries": nq,
        }

    def _estimator(self, key: Tuple) -> WindowedEstimator:
        with self._lock:
            est = self._estimators.get(key)
            if est is None:
                est = self._estimators[key] = WindowedEstimator()
                self._region_keys.setdefault(key[0], set()).add(key)
            return est

    def _publish(self, key: Tuple, st: Optional[Dict[str, float]]) -> None:
        if st is None:
            return
        region_id, kind, precision, bucket = key
        labels = {"kind": kind, "precision": precision,
                  "bucket": bucket or "-"}
        g = self.registry.gauge
        g("quality.recall", region_id, labels).set(round(st["recall"], 6))
        g("quality.recall_ci_low", region_id, labels).set(
            round(st["ci_low"], 6))
        g("quality.recall_ci_high", region_id, labels).set(
            round(st["ci_high"], 6))
        g("quality.rbo", region_id, labels).set(round(st["rbo"], 6))
        g("quality.score_gap_p50", region_id, labels).set(
            round(st["gap_p50"], 6))
        g("quality.score_gap_p99", region_id, labels).set(
            round(st["gap_p99"], 6))

    def _publish_region(self, region_id: int) -> None:
        st = self.region_estimate(region_id)
        if st is None:
            return
        g = self.registry.gauge
        g("quality.recall", region_id).set(round(st["recall"], 6))
        g("quality.recall_ci_low", region_id).set(round(st["ci_low"], 6))
        g("quality.recall_ci_high", region_id).set(round(st["ci_high"], 6))
        g("quality.rbo", region_id).set(round(st["rbo"], 6))
        g("quality.window_queries", region_id).set(st["queries"])

    # -- read side ------------------------------------------------------------
    def region_estimate(self, region_id: int) -> Optional[Dict[str, float]]:
        """Windowed rollup across the region's (kind, precision, bucket)
        estimators — what the heartbeat, `cluster top`, and the SLO tuner
        read. None when nothing was scored inside the window."""
        with self._lock:
            keys = list(self._region_keys.get(region_id, ()))
            ests = [self._estimators[k] for k in keys]
        parts = [st for st in (e.stats() for e in ests) if st is not None]
        if not parts:
            return None
        hits = sum(p["hits"] for p in parts)
        trials = sum(p["trials"] for p in parts)
        queries = sum(p["queries"] for p in parts)
        lo, hi = wilson_interval(hits, trials)
        return {
            "recall": hits / trials if trials else 0.0,
            "ci_low": lo,
            "ci_high": hi,
            "rbo": (sum(p["rbo"] * p["queries"] for p in parts) / queries
                    if queries else 0.0),
            "gap_p99": max(p["gap_p99"] for p in parts),
            "queries": queries,
            "trials": trials,
            "newest_ts": max(p["newest_ts"] for p in parts),
            "oldest_ts": min(p["oldest_ts"] for p in parts),
        }

    def reset_region(self, region_id: int) -> None:
        """Clear the region's estimator windows (the tuner's post-step
        contract: evidence gathered under the old knob setting must not
        judge the new one)."""
        with self._lock:
            ests = [self._estimators[k]
                    for k in self._region_keys.get(region_id, ())]
        for e in ests:
            e.reset()

    def forget_region(self, region_id: int) -> None:
        """Drop the region's oracle (and, for quantized tiers, its full
        fp32 mirror) + estimator state when the store no longer hosts it
        — the quality-plane leg of the collector's retire loop, next to
        registry.drop_region / HBM.forget_region."""
        with self._lock:
            self._oracles.pop(region_id, None)
            for key in self._region_keys.pop(region_id, ()):
                self._estimators.pop(key, None)

    def clear(self) -> None:
        """Forget every oracle/estimator (tests)."""
        with self._cond:
            self._queue.clear()
        with self._lock:
            self._oracles.clear()
            self._estimators.clear()
            self._region_keys.clear()


QUALITY = QualityPlane()
