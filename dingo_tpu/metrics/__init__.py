"""Store-metrics plane (reference src/metrics/ StoreMetricsManager +
store_bvar_metrics, rebuilt for the TPU store).

Five layers (ARCHITECTURE.md "Metrics"):

- collection: StoreMetricsCollector snapshots every hosted region —
  engine key counts/bytes, vector-index elements + host memory,
  build/snapshot status, WAL replay lag, and live device (HBM) bytes —
  on a crontab, registering everything into MetricsRegistry with a
  region dimension (collector.py, device.py).
- transport: each StoreHeartbeatRequest carries the freshest snapshot
  (store/node.py in-process, server/remote_heartbeat.py over grpc).
- aggregation: CoordinatorControl keeps per-store/per-region snapshots
  with staleness timestamps and cluster rollups; exposed via
  ClusterStatService GetClusterStat / GetStoreMetrics / GetRegionMetrics.
- exposition: MetricsRegistry.render_prometheus() behind
  DebugService.MetricsDump(format="prometheus") and the optional
  plain-HTTP /metrics port (http.py).
- tooling: CLI `cluster top`, tools/metrics_report.py,
  tools/check_metrics_names.py.
"""

from dingo_tpu.metrics.snapshot import (  # noqa: F401
    RegionMetricsSnapshot,
    StoreMetricsSnapshot,
)
from dingo_tpu.metrics.collector import StoreMetricsCollector  # noqa: F401
from dingo_tpu.metrics.device import (  # noqa: F401
    device_memory_stats,
    live_device_bytes,
)
from dingo_tpu.metrics.http import MetricsHttpServer  # noqa: F401
