"""knob-audit: every controller actuation must be evented.

The control-plane flight recorder (dingo_tpu/obs/events.py) only works
if writers cannot bypass it: a live override with no explaining event is
an "orphan knob" in ``cluster explain``, and the only way to guarantee
zero orphans is to make un-evented actuation a lint failure.

An **actuation site** is one of:

- a subscript write or ``pop`` on a ``.tuning`` mapping
  (``index.tuning["nprobe"] = v`` / ``index.tuning.pop("ef")``) — the
  per-region serving-override path every controller shares;
- an attribute assignment to ``.rung`` — the tier ladder's serving rung
  (skipped inside ``__init__``/``reset``/``forget_region``, which
  construct or tear down state rather than actuate);
- a ``.set(...)`` on the ``qos.precision_advisory`` gauge — the shed
  ladder's precision advisory IS a knob, the gauge is its storage.

A site passes when its enclosing function either contains an
``EVENTS.emit(...)`` call itself, or is reachable through EXACT call
edges from a function that does (the shed controller's ``_apply_level``
helper writes tuning on behalf of the emitting ``step_region`` — the
decision and its record live one frame apart, which is fine; an
unreachable writer is not). Fuzzy edges are deliberately excluded: a
basename coincidence must not launder an un-evented write.

Deliberate exceptions carry ``# dingolint: ok[knob-audit] reason``
inline (e.g. a test-only seam), or a baseline entry with a rationale.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from tools.dingolint.core import Checker, Finding, Module, Repo

#: the ledger itself and its test seams may touch knobs while recording
_EXEMPT_MODULES = ("dingo_tpu.obs.events",)

#: constructor/teardown functions where a ``.rung =`` assign is state
#: setup, not an actuation
_RUNG_EXEMPT_FUNCS = {"__init__", "reset", "forget_region"}


def _is_emit_call(node: ast.AST) -> bool:
    """``EVENTS.emit(...)`` (the module-singleton spelling emission sites
    use; a renamed alias would need an inline suppression anyway)."""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "emit"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "EVENTS"
    )


def _is_tuning_sub_write(node: ast.AST) -> bool:
    """``X.tuning[...] = v`` / ``X.tuning[...] += v``."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
    for t in targets:
        if (isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Attribute)
                and t.value.attr == "tuning"):
            return True
    return False


def _is_tuning_pop(node: ast.AST) -> bool:
    """``X.tuning.pop(...)`` — removing an override actuates too."""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "pop"
        and isinstance(node.func.value, ast.Attribute)
        and node.func.value.attr == "tuning"
    )


def _is_rung_assign(node: ast.AST) -> bool:
    """``st.rung = v`` — a tier-ladder serving-rung move."""
    if not isinstance(node, ast.Assign):
        return False
    return any(
        isinstance(t, ast.Attribute) and t.attr == "rung"
        for t in node.targets
    )


def _is_advisory_set(node: ast.AST) -> bool:
    """``<registry>.gauge("qos.precision_advisory", ...).set(v)``."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "set"):
        return False
    inner = node.func.value
    if not isinstance(inner, ast.Call):
        return False
    args = list(inner.args) + [kw.value for kw in inner.keywords]
    return any(
        isinstance(a, ast.Constant) and a.value == "qos.precision_advisory"
        for a in args
    )


class KnobAuditChecker(Checker):
    name = "knob-audit"
    description = (
        "controller actuations (tuning writes, rung moves, precision "
        "advisories) must emit a control-plane event or be called from "
        "a function that does"
    )

    def _sites(self, module: Module) -> List[Tuple[ast.AST, str]]:
        """(node, what) per actuation site in one module."""
        out: List[Tuple[ast.AST, str]] = []
        for node in ast.walk(module.tree):
            if _is_tuning_sub_write(node):
                out.append((node, "tuning override write"))
            elif _is_tuning_pop(node):
                out.append((node, "tuning override removal"))
            elif _is_rung_assign(node):
                fn = module.enclosing_function(node)
                if fn is not None and fn.name in _RUNG_EXEMPT_FUNCS:
                    continue
                out.append((node, "tier rung move"))
            elif _is_advisory_set(node):
                out.append((node, "precision advisory set"))
        return out

    def check_repo(self, repo: Repo) -> List[Finding]:
        # emitting roots: every function whose body contains EVENTS.emit
        roots: Set[str] = set()
        for module in repo.modules:
            for local_qual, fnode in module.funcs.items():
                for node in ast.walk(fnode):
                    if (_is_emit_call(node)
                            and module.qualname_of(node) == local_qual):
                        roots.add(f"{module.name}.{local_qual}")
                        break
        covered = repo.callgraph().reachable(roots, fuzzy=False)
        findings: List[Finding] = []
        for module in repo.modules:
            if module.name in _EXEMPT_MODULES:
                continue
            for node, what in self._sites(module):
                local = module.qualname_of(node)
                qual = f"{module.name}.{local}" if local else ""
                if qual and qual in covered:
                    continue
                f = module.finding(
                    self.name, node,
                    f"{what} without a control-plane event: emit via "
                    "obs.events.EVENTS in this function or an exact "
                    "caller (orphan knobs defeat `cluster explain`)",
                )
                if f is not None:
                    findings.append(f)
        return findings
