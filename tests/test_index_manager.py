"""VectorIndexManager: build / rebuild+catch-up / save+load / scrub
(reference vector_index_manager.cc §3.4 lifecycle)."""


import numpy as np
import pytest

from dingo_tpu.engine import write_data as wd
from dingo_tpu.engine.mono_engine import MonoStoreEngine
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.engine.storage import Storage
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.index.manager import VectorIndexManager
from dingo_tpu.raft.log import RaftLog
from dingo_tpu.store.region import Region, RegionDefinition, RegionType

DIM = 8


def make_stack(index_type=IndexType.FLAT):
    raw = MemEngine()
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    region = Region(RegionDefinition(
        region_id=5,
        start_key=vcodec.encode_vector_key(0, 0),
        end_key=vcodec.encode_vector_key(0, 1 << 40),
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(index_type=index_type, dimension=DIM,
                                       ncentroids=4, default_nprobe=4),
    ))
    w = region.vector_index_wrapper
    w.build_own()
    w.set_own(w.own_index)
    return raw, engine, storage, region


def test_build_from_engine_scan(tmp_path):
    raw, engine, storage, region = make_stack()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((300, DIM)).astype(np.float32)
    storage.vector_add(region, np.arange(300, dtype=np.int64), x)
    mgr = VectorIndexManager(raw, str(tmp_path))
    index = mgr.build_index(region)
    assert index.get_count() == 300
    res = index.search(x[:2], 1)
    assert [r.ids[0] for r in res] == [0, 1]


def test_replay_wal_catchup(tmp_path):
    """ReplayWalToVectorIndex: entries committed after the scan's floor are
    re-applied from the raft log (adds + deletes, idempotent on overlap)."""
    raw, engine, storage, region = make_stack()
    rng = np.random.default_rng(1)
    x = rng.standard_normal((100, DIM)).astype(np.float32)
    storage.vector_add(region, np.arange(50, dtype=np.int64), x[:50])
    mgr = VectorIndexManager(raw, str(tmp_path))
    index = mgr.build_index(region)
    log = RaftLog()
    for i in range(50, 60):
        log.append(1, wd.encode_write(wd.VectorAddData(
            ts=1, ids=np.asarray([i], np.int64), vectors=x[i:i + 1],
        )))
    log.append(1, wd.encode_write(wd.VectorDeleteData(
        ts=2, ids=np.asarray([0, 1], np.int64),
    )))
    # overlap: replaying an add the scan already saw must be harmless
    log.append(1, wd.encode_write(wd.VectorAddData(
        ts=3, ids=np.asarray([10], np.int64), vectors=x[10:11],
    )))
    n = mgr.replay_wal(index, region, log, 1, log.last_index())
    assert n == 12
    assert index.get_count() == 58          # +10 adds, -2 deletes
    assert index.apply_log_id == log.last_index()
    assert index.search(x[55][None, :], 1)[0].ids[0] == 55


def test_rebuild_switches_atomically(tmp_path):
    raw, engine, storage, region = make_stack()
    rng = np.random.default_rng(4)
    x = rng.standard_normal((60, DIM)).astype(np.float32)
    storage.vector_add(region, np.arange(60, dtype=np.int64), x)
    w = region.vector_index_wrapper
    old_index = w.own_index
    log = RaftLog()
    mgr = VectorIndexManager(raw, str(tmp_path))
    mgr.rebuild(region, raft_log=log)
    assert w.own_index is not old_index
    assert w.own_index.get_count() == 60
    assert not w.is_switching


def test_rebuild_trains_ivf(tmp_path):
    raw, engine, storage, region = make_stack(IndexType.IVF_FLAT)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((200, DIM)).astype(np.float32)
    storage.vector_add(region, np.arange(200, dtype=np.int64), x)
    mgr = VectorIndexManager(raw, str(tmp_path))
    mgr.rebuild(region)
    w = region.vector_index_wrapper
    assert w.own_index.is_trained()
    res = w.search(x[:2], 3, nprobe=4)
    assert [r.ids[0] for r in res] == [0, 1]


def test_save_load_snapshot_with_wal_replay(tmp_path):
    raw, engine, storage, region = make_stack()
    rng = np.random.default_rng(3)
    x = rng.standard_normal((80, DIM)).astype(np.float32)
    storage.vector_add(region, np.arange(80, dtype=np.int64), x)
    mgr = VectorIndexManager(raw, str(tmp_path))
    mgr.rebuild(region)
    w = region.vector_index_wrapper
    w.apply_log_id = 7
    w.own_index.apply_log_id = 7
    mgr.save_index(region)
    assert w.snapshot_log_id == 7

    # fresh wrapper (restart): load snapshot + replay the log tail
    log = RaftLog()
    for _ in range(7):
        log.append(1, wd.encode_write(wd.KvPutData(cf="default", ts=1, kvs=[])))
    extra = wd.encode_write(wd.VectorAddData(
        ts=2, ids=np.asarray([999], np.int64),
        vectors=rng.standard_normal((1, DIM)).astype(np.float32),
    ))
    log.append(1, extra)
    region2 = Region(region.definition)
    w2 = region2.vector_index_wrapper
    w2.apply_log_id = 8
    assert mgr.load_index(region2, raft_log=log)
    assert w2.own_index.get_count() == 81
    assert w2.own_index.apply_log_id == 8


def test_load_missing_snapshot_returns_false(tmp_path):
    raw, engine, storage, region = make_stack()
    mgr = VectorIndexManager(raw, str(tmp_path))
    assert not mgr.load_index(region)


def test_scrub_reports_needs(tmp_path):
    raw, engine, storage, region = make_stack()
    mgr = VectorIndexManager(raw, str(tmp_path))
    w = region.vector_index_wrapper
    actions = mgr.scrub(region)
    assert actions == {
        "need_rebuild": False, "need_save": False, "need_compact": False,
    }
    w.write_count = 1_000_000
    assert mgr.scrub(region)["need_save"]


def test_scrub_acts_on_save_and_rebuild(tmp_path):
    """scrub(act=True) performs the work it detects: snapshot save when the
    write-count threshold trips, rebuild when the index asks for it
    (reference scrub crontab launches SaveVectorIndexTask /
    RebuildVectorIndexTask, not just reports)."""
    import numpy as np

    from dingo_tpu.index.manager import VectorIndexManager

    raw, engine, storage, region = make_stack()
    mgr = VectorIndexManager(raw, snapshot_root=str(tmp_path))
    wrapper = region.vector_index_wrapper
    wrapper.ready = True
    rng = np.random.default_rng(0)
    x = rng.standard_normal((50, DIM)).astype(np.float32)
    storage.vector_add(region, np.arange(50, dtype=np.int64), x)
    wrapper.save_write_threshold = 10       # force need_save
    actions = mgr.scrub(region, act=True)
    assert actions.get("saved") is True
    import os

    assert os.path.isdir(mgr.snapshot_path(region.id))
    assert wrapper.write_count == 0         # counter reset by the save
    # second scrub: nothing to do
    actions = mgr.scrub(region, act=True)
    assert "saved" not in actions and "rebuilt" not in actions


def test_scrub_rebuild_branch_and_busy_gate():
    """scrub(act=True) rebuilds when the index asks for it; a concurrent
    rebuild of the same region makes it report skipped_busy instead of
    running a duplicate full scan."""
    import threading

    import numpy as np

    from dingo_tpu.index.manager import VectorIndexManager

    raw, engine, storage, region = make_stack(IndexType.HNSW)
    mgr = VectorIndexManager(raw)
    wrapper = region.vector_index_wrapper
    wrapper.ready = True
    rng = np.random.default_rng(0)
    x = rng.standard_normal((100, DIM)).astype(np.float32)
    storage.vector_add(region, np.arange(100, dtype=np.int64), x)
    storage.vector_delete(region, list(range(60)))
    assert wrapper.need_to_rebuild()    # deleted > live/2 (hnsw trigger)
    actions = mgr.scrub(region, act=True)
    assert actions.get("rebuilt") is True
    assert not wrapper.need_to_rebuild()

    # busy gate: a rebuild marked in flight makes scrub skip
    with mgr._lock:
        mgr._rebuilding.add(region.id)
    try:
        storage.vector_add(region, np.arange(200, 300, dtype=np.int64), x)
        storage.vector_delete(region, list(range(200, 280)))
        assert wrapper.need_to_rebuild()
        actions = mgr.scrub(region, act=True)
        assert actions.get("skipped_busy") is True
    finally:
        with mgr._lock:
            mgr._rebuilding.discard(region.id)


def test_load_index_refuses_compacted_gap(tmp_path):
    """A snapshot older than the raft log's first index must raise
    StaleSnapshot BEFORE replaying (get_data_entries clamps silently)."""
    import numpy as np
    import pytest as _pytest

    from dingo_tpu.index.manager import StaleSnapshot, VectorIndexManager
    from dingo_tpu.raft.log import RaftLog

    raw, engine, storage, region = make_stack()
    mgr = VectorIndexManager(raw, snapshot_root=str(tmp_path))
    wrapper = region.vector_index_wrapper
    wrapper.ready = True
    rng = np.random.default_rng(0)
    x = rng.standard_normal((20, DIM)).astype(np.float32)
    storage.vector_add(region, np.arange(20, dtype=np.int64), x)
    wrapper.apply_log_id = 5
    wrapper.own_index.apply_log_id = 5
    mgr.save_index(region)              # snapshot_log_id = 5

    log = RaftLog()
    for i in range(400):
        log.append(1, b"x")
    log.compact(300)                    # first_index becomes 301
    wrapper.apply_log_id = 400
    with _pytest.raises(StaleSnapshot, match="compacted"):
        mgr.load_index(region, raft_log=log)
