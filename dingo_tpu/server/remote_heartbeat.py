"""Store -> remote coordinator heartbeat over grpc.

The in-process path calls CoordinatorControl directly (StoreNode.heartbeat_
once); multi-process stores use this grpc client instead — same payload,
same command execution on the response (store/heartbeat.cc:61,294 flow).

Replicated-coordinator aware: `coordinator_addr` may be a comma-separated
list of the raft group's endpoints. A follower answers StoreHeartbeat with
errcode 20001 ("not leader"); the client rotates to the next endpoint until
one accepts, the same retry contract the SDK uses for store-side NotLeader.
Executed commands are deduped by cmd_id (coordinator failover re-delivers)
and acked back via done_cmd_ids so the coordinator prunes its queues.
"""

from __future__ import annotations

from typing import List

import grpc

from dingo_tpu.server import convert, pb
from dingo_tpu.server.rpc import ServiceStub

_ERR_NOT_LEADER = 20001


class HeartbeatError(RuntimeError):
    pass


class RemoteHeartbeat:
    def __init__(self, node, coordinator_addr: str):
        self.node = node
        self._addrs: List[str] = [
            a.strip() for a in coordinator_addr.split(",") if a.strip()
        ]
        self._active = 0
        self._channel = None
        self._stub = None
        self._connect(self._active)

    def _connect(self, idx: int) -> None:
        if self._channel is not None:
            self._channel.close()
        self._active = idx % len(self._addrs)
        self._channel = grpc.insecure_channel(self._addrs[self._active])
        self._stub = ServiceStub(self._channel, "CoordinatorService")

    def _call(self, method: str, req):
        """Invoke on the active coordinator; on NotLeader/connect failure
        rotate through the remaining endpoints once before giving up."""
        last = None
        for _attempt in range(len(self._addrs)):
            try:
                resp = getattr(self._stub, method)(req)
            except grpc.RpcError as e:
                last = HeartbeatError(
                    f"{method} via {self._addrs[self._active]}: {e.code()}"
                )
                self._connect(self._active + 1)
                continue
            err = getattr(resp, "error", None)
            if err is not None and err.errcode == _ERR_NOT_LEADER:
                last = HeartbeatError(
                    f"{method}: {self._addrs[self._active]} is not leader "
                    f"({err.errmsg})"
                )
                self._connect(self._active + 1)
                continue
            if err is not None and err.errcode:
                raise HeartbeatError(f"{method}: {err.errmsg}")
            return resp
        raise last or HeartbeatError(f"{method}: no coordinator reachable")

    def beat(self) -> int:
        node = self.node
        regions = node.meta.get_all_regions()
        leader_ids = [
            r.id for r in regions
            if (n := node.engine.get_node(r.id)) is not None
            and n.is_leader()
        ]
        req = pb.StoreHeartbeatRequest()
        req.store_id = node.store_id
        req.region_ids.extend(r.id for r in regions)
        req.leader_region_ids.extend(leader_ids)
        acking = list(node._unacked_done)
        req.done_cmd_ids.extend(acking)
        for r in regions:
            if r.id in leader_ids:
                req.region_definitions.add().CopyFrom(
                    convert.region_def_to_pb(r.definition)
                )
        resp = self._call("StoreHeartbeat", req)
        node._unacked_done.difference_update(acking)
        executed = 0
        for c in resp.commands:
            if c.cmd_id in node._done_cmd_ids:
                node._unacked_done.add(c.cmd_id)   # re-delivered: re-ack
                continue
            cmd = convert.region_cmd_from_pb(c)
            try:
                node.execute_region_cmd(cmd)
                executed += 1
                node._done_cmd_ids[c.cmd_id] = None
                node._unacked_done.add(c.cmd_id)
                while len(node._done_cmd_ids) > 10_000:
                    node._done_cmd_ids.popitem(last=False)
            except Exception as e:  # noqa: BLE001
                from dingo_tpu.raft.core import NotLeader

                if isinstance(e, NotLeader) and e.leader_hint:
                    # hand the command back to the coordinator addressed at
                    # the hinted leader (same flow as the in-process path)
                    rq = pb.RequeueRegionCmdRequest()
                    rq.cmd.CopyFrom(c)
                    rq.target_store_id = e.leader_hint.split("/")[0]
                    rq.from_store_id = node.store_id
                    try:
                        self._call("RequeueRegionCmd", rq)
                    except HeartbeatError:
                        pass
        return executed
