"""grpc service implementations.

Reference service registry (src/server/main.cc:681-1360, per role):
  INDEX/STORE roles — IndexServiceImpl (index_service.h), StoreServiceImpl,
      NodeService, DebugService, UtilService
  COORDINATOR role — CoordinatorServiceImpl, MetaService, VersionService

Handlers are hand-written over the protoc-generated messages (no grpc
codegen plugin in this image); registration uses generic method handlers.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Dict, Optional

import grpc
import numpy as np

from dingo_tpu.common.failpoint import FAILPOINTS
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.coordinator.control import CoordinatorControl, RegionCmd, RegionCmdType
from dingo_tpu.coordinator.kv_control import (
    CompactedError,
    FutureRevError,
    KvControl,
)
from dingo_tpu.coordinator.tso import TsoControl
from dingo_tpu.engine.txn import Mutation, Op, TxnEngine, TxnError
from dingo_tpu.index.base import VectorIndexError
from dingo_tpu.ops.distance import Metric
from dingo_tpu.raft import wire
from dingo_tpu.raft.core import NotLeader
from dingo_tpu.server import convert, pb
from dingo_tpu.store.node import StoreNode
from dingo_tpu.store.region import Region, RegionType


def _err(resp, code: int, msg: str):
    resp.error.errcode = code
    resp.error.errmsg = msg
    return resp


#: server-side ceiling on a single long-poll: a blocked watch holds a
#: semaphore slot AND a grpc pool thread, so the duration must not be
#: client-chosen-unbounded
_MAX_WATCH_TIMEOUT_MS = 30_000


def _long_poll_watch(register_fn, cancel_fn, slots, timeout_ms):
    """Shared one-shot watch harness (VKvWatch + MetaWatch): register a
    callback that may fire immediately (replay), else block up to the
    clamped timeout while holding a bounded slot.

    Returns (event_args tuple | None, "busy" | None). register_fn may
    raise (e.g. CompactedError) — callers map that to their error code."""
    fired = threading.Event()
    holder = {}

    def cb(*args):
        holder["args"] = args
        fired.set()

    register_fn(cb)
    timeout_ms = min(int(timeout_ms or 0), _MAX_WATCH_TIMEOUT_MS)
    if not fired.is_set() and timeout_ms:
        if not slots.acquire(blocking=False):
            cancel_fn(cb)
            return None, "busy"
        try:
            fired.wait(timeout_ms / 1000.0)
        finally:
            slots.release()
    if fired.is_set():
        return holder["args"], None
    cancel_fn(cb)
    return None, None


def _rebuild_region(node: StoreNode, region: Region) -> None:
    """Forced rebuild through the atomic-swap path, WITH the raft log so
    catch-up happens in open rounds and the old index serves throughout
    (blocking-scan rebuild is reserved for regions with no raft node)."""
    raft = node.engine.get_node(region.id)
    node.index_manager.rebuild(region, raft_log=raft.log if raft else None)


def _clamp_range_or_err(region: Region, start: bytes, end: bytes, resp):
    """Validate a KV request range against the region bounds
    (ServiceHelper::ValidateRange analog): a store hosts many regions in
    ONE shared engine, so an unclamped range reads or deletes ANOTHER
    region's keys. Returns (start, end) or None with the error set."""
    if end and start >= end:
        _err(resp, 60003, "illegal range: start >= end")
        return None
    r_start, r_end = region.range
    if start < r_start or (r_end and (not end or end > r_end)):
        _err(resp, 60004,
             f"range outside region {region.id} bounds")
        return None
    return start, end


def _keys_in_region_or_err(region: Region, keys, resp) -> bool:
    for k in keys:
        if not region.contains_key(k):
            _err(resp, 60004,
                 f"key outside region {region.id} bounds")
            return False
    return True


def _region_or_err(node: StoreNode, context_pb, resp) -> Optional[Region]:
    region = node.get_region(context_pb.region_id)
    if region is None:
        _err(resp, 10001, f"region {context_pb.region_id} not found")
        return None
    # epoch check (reference validates region epoch on every request)
    if (
        context_pb.region_epoch.version
        and context_pb.region_epoch.version != region.epoch.version
    ):
        _err(resp, 10002,
             f"epoch mismatch {context_pb.region_epoch.version} != "
             f"{region.epoch.version}")
        return None
    return region


class IndexService:
    """Vector RPCs (index_service.h:92+)."""

    def __init__(self, node: StoreNode):
        self.node = node
        self._coalescer = None
        self._coalescer_lock = threading.Lock()

    def _get_coalescer(self):
        from dingo_tpu.common.coalescer import SearchCoalescer
        from dingo_tpu.common.config import FLAGS

        window = float(FLAGS.get("search_coalescing_window_ms"))
        with self._coalescer_lock:
            # rebuild when the (hot-changeable) window flag moves, so
            # operators tuning it actually change behavior
            if self._coalescer is not None and \
                    self._coalescer.window_s != window / 1000.0:
                self._coalescer.stop()
                self._coalescer = None
            if self._coalescer is None:
                def run(key, stacked, stage_us=None):
                    region_id, topn, kw_items = key
                    region = self.node.get_region(region_id)
                    if region is None:
                        raise VectorIndexError(f"region {region_id} gone")
                    # stage_us (reader stage timings) feeds the QoS
                    # per-stage budget accounting when qos is on; the
                    # coalescer only passes it when it wants the split
                    return self.node.storage.vector_batch_search(
                        region, stacked, topn, stage_us=stage_us,
                        **dict(kw_items)
                    )

                def dispatch(key, stacked, staged=None, stage_us=None):
                    # pipelined arm (pipeline.enabled): enqueue kernels
                    # now, return the resolve thunk — the coalescer's
                    # completion lane performs the one host sync
                    region_id, topn, kw_items = key
                    region = self.node.get_region(region_id)
                    if region is None:
                        raise VectorIndexError(f"region {region_id} gone")
                    return self.node.storage.vector_batch_search_async(
                        region, stacked, topn, staged=staged,
                        stage_us=stage_us, **dict(kw_items)
                    )

                self._coalescer = SearchCoalescer(
                    run, window_ms=window, dispatch_fn=dispatch
                )
            return self._coalescer

    def close(self) -> None:
        with self._coalescer_lock:
            if self._coalescer is not None:
                self._coalescer.stop()
                self._coalescer = None

    def _do_search(self, req, resp, stage_us=None):
        """Shared VectorSearch/VectorSearchDebug body: build kwargs (incl.
        the radius range-search arm), run the reader, fill batch_results
        (binary-aware vector payloads + scalar backfill)."""
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp, None
        # fault-injection point for the search path (flight-recorder tests
        # panic here; a panic propagates to the generic rpc handler which
        # black-boxes it and answers in-band)
        FAILPOINTS.apply("before_vector_search")
        from dingo_tpu.obs import pressure as qos
        from dingo_tpu.trace import current_span

        budget = qos.current_budget() if qos.qos_enabled() else None
        if budget is not None and budget.expired():
            # deadline-aware admission: a request that arrives already
            # dead is rejected before ANY index work — no kernel is
            # dispatched for it (sentinel-verified in tests/test_qos.py)
            qos.PRESSURE.on_expired("admission", region.id, budget)
            return _err(resp, 30002, "deadline exceeded at admission"), None
        ingress = current_span()
        if ingress is not None and ingress.sampled:
            ingress.set_attr("region_id", region.id)
            ingress.set_attr("batch", len(req.vectors))
            ingress.set_attr("topn", req.parameter.top_n or 10)
        lat = METRICS.latency("vector_search", region.id)
        t0 = time.perf_counter_ns()
        try:
            binary = convert.is_binary_parameter(
                region.definition.index_parameter
            )
            queries = convert.queries_from_pb(req.vectors, binary=binary)
            kw = convert.search_kwargs_from_pb(req.parameter)
            if req.parameter.nprobe:
                kw["nprobe"] = req.parameter.nprobe
            if req.parameter.ef_search:
                kw["ef"] = req.parameter.ef_search
            topn = req.parameter.top_n or 10
            if req.parameter.radius > 0:
                # VectorRangeSearch path: over-fetch to the cap, reader cuts
                kw["radius"] = req.parameter.radius
                from dingo_tpu.index.vector_reader import RANGE_SEARCH_CAP

                topn = min(max(topn, 128), RANGE_SEARCH_CAP)
            from dingo_tpu.common.config import FLAGS

            window = FLAGS.get("search_coalescing_window_ms")
            # coalesce only parameter-identical, filter-free searches
            from dingo_tpu.index.vector_reader import VectorFilterMode

            plain = (
                window > 0
                and stage_us is None
                and req.parameter.radius <= 0
                and not kw.get("with_vector_data")
                and not kw.get("with_scalar_data")
                and kw.get("filter_mode") in (None, VectorFilterMode.NONE)
                and not kw.get("vector_ids")
                and kw.get("scalar_filter") is None
            )
            if plain:
                from dingo_tpu.cache import edge as cache_edge
                from dingo_tpu.engine.storage import (
                    MAX_TOPN_BATCH_PRODUCT,
                    VECTOR_MAX_BATCH_COUNT,
                )

                key = (
                    region.id, topn,
                    tuple(sorted(
                        (k, v) for k, v in kw.items()
                        if isinstance(v, (int, float, str, bool, type(None)))
                    )),
                )
                # a merged batch must respect the same guards each request
                # passes alone (4096 rows; topn*rows product)
                cap = min(
                    VECTOR_MAX_BATCH_COUNT,
                    MAX_TOPN_BATCH_PRODUCT // max(1, topn),
                )
                # serving-edge cache consult BEFORE QoS queuing: a hit
                # costs no queue slot, no admission estimate, no kernel;
                # a partial hit dispatches only its miss rows
                looked = None
                if cache_edge.active():
                    w = getattr(region, "vector_index_wrapper", None)
                    looked = cache_edge.lookup(
                        region.id, queries, topn, key[2],
                        cache_edge.region_version(region),
                        index=getattr(w, "own_index", None),
                    )
                if looked is not None and looked.complete:
                    results = looked.rows
                else:
                    submit_q = (queries if looked is None
                                else queries[looked.miss_idx])
                    try:
                        results = self._get_coalescer().submit(
                            key, submit_q, max_batch=cap,
                            region_id=region.id
                        ).result(timeout=30)
                    except qos.QosRejected as e:
                        # an admission/expiry decision is FINAL — falling
                        # back to a direct search would serve exactly the
                        # work the QoS layer decided the store cannot
                        # afford
                        return _err(
                            resp,
                            30002 if isinstance(e, qos.DeadlineExceeded)
                            else 30003,
                            str(e),
                        ), None
                    except (RuntimeError, FuturesTimeoutError):
                        # coalescer stopped mid-flight (flag hot-change) or
                        # the batch stalled: serve this request directly
                        results = self.node.storage.vector_batch_search(
                            region, submit_q, topn, **kw
                        )
                    if looked is not None:
                        # fill only if the store version didn't move while
                        # the kernel ran (edge.fill re-checks), then stitch
                        # cached + fresh rows back into request order
                        cache_edge.fill(
                            region.id, looked, results,
                            cache_edge.region_version(region), queries,
                            tenant=(budget.tenant if budget is not None
                                    else "default"),
                        )
                        results = looked.merge(results)
            else:
                results = self.node.storage.vector_batch_search(
                    region, queries, topn, stage_us=stage_us, **kw
                )
        except (VectorIndexError, ValueError) as e:
            # in-band search failures never reach the generic rpc handler,
            # so they black-box here (device OOMs included)
            from dingo_tpu.obs.flight import black_box_error

            black_box_error("rpc.IndexService.VectorSearch", e, ingress,
                            region_id=region.id)
            return _err(resp, 30001, str(e)), None
        for row in results:
            r = resp.batch_results.add()
            for v in row:
                item = r.results.add()
                item.vector.id = v.id
                item.distance = v.distance
                if v.vector is not None:
                    convert.fill_vector_pb(item.vector, v.vector)
                if v.scalar:
                    convert.scalar_to_pb(item.scalar_data, v.scalar)
        lat.observe_us((time.perf_counter_ns() - t0) / 1000.0)
        if qos.qos_enabled():
            # throughput vs goodput: every reply counts served; only the
            # ones inside their budget count toward goodput (a late reply
            # additionally black-boxes a deadline_exceeded flight bundle)
            qos.PRESSURE.on_served(region.id, budget)
        return resp, region

    def VectorSearch(self, req: pb.VectorSearchRequest) -> pb.VectorSearchResponse:
        resp, _ = self._do_search(req, pb.VectorSearchResponse())
        return resp

    def VectorSearchDebug(self, req: pb.VectorSearchDebugRequest):
        """VectorSearch + per-stage timings (the reference's SearchDebug
        RPC, vector_reader.h:85-88 / index_service.h SearchDebug)."""
        stage_us: Dict[str, int] = {}
        resp, _ = self._do_search(
            req, pb.VectorSearchDebugResponse(), stage_us=stage_us
        )
        for field in ("prefilter_us", "search_us", "postfilter_us",
                      "backfill_us", "total_us"):
            setattr(resp, field, stage_us.get(field, 0))
        return resp

    @staticmethod
    def _vector_batch_from_pb(region, req_vectors):
        """Decode a repeated VectorWithScalar into the storage call shape:
        (ids, vectors, scalars, table_values) — shared by VectorAdd and
        VectorImport so the two RPCs cannot diverge."""
        ids = np.asarray([v.vector.id for v in req_vectors], np.int64)
        if convert.is_binary_parameter(region.definition.index_parameter):
            vectors = np.stack([
                np.frombuffer(v.vector.binary_values, np.uint8)
                for v in req_vectors
            ])
        else:
            vectors = np.asarray(
                [list(v.vector.values) for v in req_vectors], np.float32
            )
        scalars = [convert.scalar_from_pb(v.scalar_data) for v in req_vectors]
        table_values = None
        if any(v.HasField("table_data") for v in req_vectors):
            table_values = [
                v.table_data if v.HasField("table_data") else None
                for v in req_vectors
            ]
        return ids, vectors, scalars, table_values

    def VectorAdd(self, req: pb.VectorAddRequest) -> pb.VectorAddResponse:
        resp = pb.VectorAddResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        try:
            ids, vectors, scalars, table_values = self._vector_batch_from_pb(
                region, req.vectors)
            ts = self.node.storage.vector_add(
                region, ids, vectors, scalars,
                is_update=req.is_update, ttl_ms=req.ttl_ms,
                table_values=table_values,
            )
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        except (VectorIndexError, ValueError) as e:
            return _err(resp, 30001, str(e))
        resp.ts = ts
        resp.key_states.extend([True] * len(req.vectors))
        METRICS.counter("vector_add", region.id).add(len(req.vectors))
        return resp

    def VectorImport(self, req: pb.VectorImportRequest):
        """Bulk import (index_service.h:57 VectorImport): upserts + deletes
        in one call, sharing VectorAdd's validation and write path."""
        resp = pb.VectorImportResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        try:
            ts = 0
            if req.vectors:
                ids, vectors, scalars, table_values = (
                    self._vector_batch_from_pb(region, req.vectors))
                ts = self.node.storage.vector_add(
                    region, ids, vectors, scalars,
                    is_update=True, ttl_ms=req.ttl_ms,
                    table_values=table_values,
                )
                resp.added = len(req.vectors)
            if req.delete_ids:
                ts = self.node.storage.vector_delete(
                    region, list(req.delete_ids))
                resp.deleted = len(req.delete_ids)
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        except (VectorIndexError, ValueError) as e:
            return _err(resp, 30001, str(e))
        resp.ts = ts
        METRICS.counter("vector_import", region.id).add(
            len(req.vectors) + len(req.delete_ids))
        return resp

    def VectorDelete(self, req: pb.VectorDeleteRequest) -> pb.VectorDeleteResponse:
        resp = pb.VectorDeleteResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        try:
            self.node.storage.vector_delete(region, list(req.ids))
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        resp.key_states.extend([True] * len(req.ids))
        return resp

    def VectorBatchQuery(self, req: pb.VectorBatchQueryRequest):
        resp = pb.VectorBatchQueryResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        rows = self.node.storage.vector_batch_query(
            region, list(req.vector_ids),
            with_vector_data=req.with_vector_data,
            with_scalar_data=req.with_scalar_data,
        )
        for row in rows:
            out = resp.vectors.add()
            if row is None:
                out.vector.id = -1
                continue
            out.vector.id = row.id
            if row.vector is not None:
                convert.fill_vector_pb(out.vector, row.vector)
            if row.scalar:
                convert.scalar_to_pb(out.scalar_data, row.scalar)
        return resp

    def VectorGetBorderId(self, req: pb.VectorGetBorderIdRequest):
        resp = pb.VectorGetBorderIdResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        border = self.node.storage.vector_get_border_id(region, req.get_min)
        resp.id = border if border is not None else -1
        return resp

    def VectorScanQuery(self, req: pb.VectorScanQueryRequest):
        resp = pb.VectorScanQueryResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        rows = self.node.storage.vector_scan_query(
            region,
            start_id=req.vector_id_start,
            end_id=req.vector_id_end or None,
            limit=req.max_scan_count or 1000,
            is_reverse=req.is_reverse,
            with_vector_data=req.with_vector_data,
            with_scalar_data=req.with_scalar_data,
        )
        for row in rows:
            out = resp.vectors.add()
            out.vector.id = row.id
            if row.vector is not None:
                convert.fill_vector_pb(out.vector, row.vector)
            if row.scalar:
                convert.scalar_to_pb(out.scalar_data, row.scalar)
        return resp

    def VectorBuild(self, req: pb.VectorBuildRequest):
        """Trigger a full rebuild (LaunchRebuildVectorIndex analog)."""
        resp = pb.VectorBuildResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if region.vector_index_wrapper is None:
            return _err(resp, 70001, "region has no vector index")
        try:
            _rebuild_region(self.node, region)
        except Exception as e:  # noqa: BLE001
            return _err(resp, 70002, f"rebuild failed: {e}")
        return resp

    def VectorLoad(self, req: pb.VectorLoadRequest):
        """Load the index from its snapshot (+ WAL catch-up)."""
        resp = pb.VectorLoadResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if region.vector_index_wrapper is None:
            return _err(resp, 70001, "region has no vector index")
        from dingo_tpu.index.manager import StaleSnapshot

        try:
            raft = self.node.engine.get_node(region.id)
            ok = self.node.index_manager.load_index(
                region, raft_log=raft.log if raft else None,
                path=req.path or None,
            )
        except StaleSnapshot as e:
            return _err(resp, 70004, f"stale snapshot refused: {e}")
        except (OSError, ValueError, VectorIndexError) as e:
            return _err(resp, 70003, f"load failed: {e}")
        if not ok:
            return _err(resp, 70003,
                        "snapshot missing or unreadable (nothing loaded)")
        return resp

    def VectorStatus(self, req: pb.VectorStatusRequest):
        resp = pb.VectorStatusResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        w = region.vector_index_wrapper
        if w is None:
            return _err(resp, 70001, "region has no vector index")
        resp.ready = w.ready
        resp.build_error = w.build_error
        resp.is_switching = w.is_switching
        resp.apply_log_id = w.apply_log_id
        resp.snapshot_log_id = w.snapshot_log_id
        idx = w.own_index
        if idx is not None:
            resp.count = idx.get_count()
            resp.trained = idx.is_trained()
            resp.index_type = idx.index_type.value
        return resp

    def VectorReset(self, req: pb.VectorResetRequest):
        """Drop the in-memory index and rebuild from the engine (the
        engine is the source of truth; the index is a view)."""
        resp = pb.VectorResetResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        w = region.vector_index_wrapper
        if w is None:
            return _err(resp, 70001, "region has no vector index")
        try:
            # rebuild() swaps atomically under the wrapper lock — the old
            # index keeps serving (and absorbing raft applies) until the
            # fresh one is ready; never pre-mark not-ready here
            _rebuild_region(self.node, region)
        except Exception as e:  # noqa: BLE001
            return _err(resp, 70002, f"reset rebuild failed: {e}")
        return resp

    def VectorDump(self, req: pb.VectorDumpRequest):
        resp = pb.VectorDumpResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        w = region.vector_index_wrapper
        if w is None:
            return _err(resp, 70001, "region has no vector index")
        idx = w.own_index
        dump = {
            "region_id": region.id,
            "ready": w.ready,
            "apply_log_id": w.apply_log_id,
            "snapshot_log_id": w.snapshot_log_id,
            "write_count_since_save": getattr(
                idx, "write_count_since_save", 0
            ) if idx else 0,
        }
        if idx is not None:
            dump.update(
                index_type=idx.index_type.value,
                count=idx.get_count(),
                memory_bytes=idx.get_memory_size(),
                trained=idx.is_trained(),
            )
        resp.json = json.dumps(dump)
        return resp

    def VectorCountMemory(self, req: pb.VectorCountMemoryRequest):
        resp = pb.VectorCountMemoryResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        w = region.vector_index_wrapper
        idx = w.own_index if w else None
        if idx is None:
            return _err(resp, 70001, "region has no vector index")
        resp.bytes = idx.get_memory_size()
        return resp

    def VectorGetRegionMetrics(self, req: pb.VectorGetRegionMetricsRequest):
        resp = pb.VectorGetRegionMetricsResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        w = region.vector_index_wrapper
        idx = w.own_index if w else None
        if idx is not None:
            resp.vector_count = idx.get_count()
            resp.memory_bytes = idx.get_memory_size()
        reader = self.node.engine.new_vector_reader(region)
        mn, mx = reader.vector_border_ids()   # one region scan, both ends
        resp.min_id = mn if mn is not None else -1
        resp.max_id = mx if mx is not None else -1
        resp.region_state = region.state.value
        return resp

    def VectorCount(self, req: pb.VectorCountRequest):
        resp = pb.VectorCountResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        resp.count = self.node.storage.vector_count(region)
        return resp


class UtilService:
    """VectorCalcDistance (util service exposure of CalcDistanceEntry,
    vector_index_utils.h:43-160)."""

    def VectorCalcDistance(self, req: pb.VectorCalcDistanceRequest):
        from dingo_tpu.ops.distance import (
            pairwise_cosine,
            pairwise_inner_product,
            pairwise_l2sqr,
        )
        import jax.numpy as jnp

        resp = pb.VectorCalcDistanceResponse()
        left = convert.queries_from_pb(req.op_left_vectors)
        right = convert.queries_from_pb(req.op_right_vectors)
        if left.size == 0 or right.size == 0:
            return _err(resp, 30001, "empty operands")
        metric = {
            pb.METRIC_TYPE_L2: pairwise_l2sqr,
            pb.METRIC_TYPE_INNER_PRODUCT: pairwise_inner_product,
            pb.METRIC_TYPE_COSINE: pairwise_cosine,
        }.get(req.metric_type, pairwise_l2sqr)
        d = np.asarray(metric(jnp.asarray(left), jnp.asarray(right)))
        for row in d:
            resp.distances.add().values.extend(row.tolist())
        return resp


class StoreService:
    """KV + txn RPCs (store_service.h)."""

    def __init__(self, node: StoreNode):
        self.node = node
        # one TxnEngine per region, NOT per request: the engine's
        # ConcurrencyManager (per-key latches) only serializes concurrent
        # check-then-write sections if every request for a region shares it
        # — a per-request manager would let two pessimistic locks for
        # different txns both "win" the same key
        self._txn_engines: Dict[int, TxnEngine] = {}
        self._txn_engines_lock = threading.Lock()

    def _txn(self, region: Region) -> TxnEngine:
        with self._txn_engines_lock:
            eng = self._txn_engines.get(region.id)
            if eng is None or eng.region is not region:
                # new region object (create/epoch change): fresh engine
                eng = TxnEngine(self.node.engine, region)
                self._txn_engines[region.id] = eng
            return eng

    def KvGet(self, req: pb.KvGetRequest) -> pb.KvGetResponse:
        resp = pb.KvGetResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        value = self.node.storage.kv_get(region, req.key)
        resp.found = value is not None
        resp.value = value or b""
        return resp

    def KvBatchPut(self, req: pb.KvBatchPutRequest) -> pb.KvBatchPutResponse:
        resp = pb.KvBatchPutResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if not _keys_in_region_or_err(
            region, [kv.key for kv in req.kvs], resp
        ):
            return resp
        try:
            resp.ts = self.node.storage.kv_put(
                region, [(kv.key, kv.value) for kv in req.kvs],
                ttl_ms=req.ttl_ms,
            )
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        return resp

    def KvBatchGet(self, req: pb.KvBatchGetRequest):
        resp = pb.KvBatchGetResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        if not _keys_in_region_or_err(region, list(req.keys), resp):
            return resp
        values = self.node.storage.kv_batch_get(region, list(req.keys))
        for key, value in zip(req.keys, values):
            kv = resp.kvs.add()
            kv.key = key
            kv.value = value or b""
            resp.found.append(value is not None)
        return resp

    def KvDeleteRange(self, req: pb.KvDeleteRangeRequest):
        resp = pb.KvDeleteRangeResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        clamped = _clamp_range_or_err(
            region, req.range.start_key, req.range.end_key, resp
        )
        if clamped is None:
            return resp
        try:
            # count comes from the applied write itself (exact under
            # concurrent writes; also no follower-side scan before the
            # NotLeader rejection)
            resp.delete_count = self.node.storage.kv_delete_range(
                region, [clamped]
            )
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        return resp

    def KvPutIfAbsent(self, req: pb.KvPutIfAbsentRequest):
        """KvPutIfAbsent / KvBatchPutIfAbsent (store_service.cc KV set)."""
        resp = pb.KvPutIfAbsentResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if not _keys_in_region_or_err(
            region, [kv.key for kv in req.kvs], resp
        ):
            return resp
        try:
            states = self.node.storage.kv_put_if_absent(
                region, [(kv.key, kv.value) for kv in req.kvs],
                is_atomic=req.is_atomic,
            )
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        resp.key_states.extend(states)
        return resp

    def KvCompareAndSet(self, req: pb.KvCompareAndSetRequest):
        """KvCompareAndSet (store_service.cc): expect_value b'' means
        'expect absent' (the reference's empty-value convention)."""
        resp = pb.KvCompareAndSetResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if not _keys_in_region_or_err(region, [req.kv.key], resp):
            return resp
        expect = req.expect_value if req.expect_value else None
        try:
            resp.key_state = self.node.storage.kv_compare_and_set(
                region, req.kv.key, expect, req.kv.value
            )
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        return resp

    def KvBatchDelete(self, req: pb.KvBatchDeleteRequest):
        resp = pb.KvBatchDeleteResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if not _keys_in_region_or_err(region, list(req.keys), resp):
            return resp
        try:
            self.node.storage.kv_batch_delete(region, list(req.keys))
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        return resp

    def KvScan(self, req: pb.KvScanRequest) -> pb.KvScanResponse:
        resp = pb.KvScanResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            cop = convert.coprocessor_from_pb(req.coprocessor)
        except ValueError as e:
            return _err(resp, 60001, f"bad coprocessor: {e}")
        clamped = _clamp_range_or_err(
            region, req.range.start_key, req.range.end_key, resp
        )
        if clamped is None:
            return resp
        pairs = self.node.storage.kv_scan(
            region, clamped[0], clamped[1],
            # coprocessor filtering happens after the scan; a pre-filter
            # limit would truncate the candidate set
            limit=0 if cop is not None else req.limit,
            keys_only=req.keys_only and cop is None,
        )
        if cop is not None:
            try:
                pairs = cop.execute(pairs)
            except ValueError as e:
                return _err(resp, 60002, f"coprocessor execute: {e}")
            if req.limit:
                pairs = pairs[: req.limit]
        for k, v in pairs:
            kv = resp.kvs.add()
            kv.key = k
            kv.value = v
        return resp

    # ---- scan sessions (ScanManager v1/v2 + Stream paging) ----
    def KvScanBegin(self, req: pb.KvScanBeginRequest) -> pb.KvScanBeginResponse:
        resp = pb.KvScanBeginResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        from dingo_tpu.engine.raw_engine import CF_DEFAULT
        from dingo_tpu.mvcc.codec import MAX_TS
        from dingo_tpu.mvcc.reader import Reader as MvccReader

        clamped = _clamp_range_or_err(
            region, req.range.start_key, req.range.end_key, resp)
        if clamped is None:
            return resp
        reader = MvccReader(self.node.raw, CF_DEFAULT)
        # materialize at open: the session must be a stable snapshot —
        # paging a live iterator would skip/repeat keys under concurrent
        # writes (the reference ScanManager pins a snapshot the same way)
        snapshot = tuple(reader.iter_visible(
            clamped[0], clamped[1], req.context.read_ts or MAX_TS,
        ))
        stream = _SCAN_SESSIONS.streams.open(iter(snapshot),
                                             limit=req.page_size or 100)
        items, more = stream.next_page()
        resp.scan_id = stream.id
        resp.has_more = more
        for k, v in items:
            kv = resp.kvs.add()
            kv.key = k
            kv.value = v
        if not more:
            _SCAN_SESSIONS.streams.release(stream.id)
        return resp

    def KvScanContinue(self, req: pb.KvScanContinueRequest):
        resp = pb.KvScanContinueResponse()
        stream = _SCAN_SESSIONS.streams.get(req.scan_id)
        if stream is None:
            return _err(resp, 10010, f"unknown scan {req.scan_id}")
        items, more = stream.next_page(req.page_size or None)
        resp.has_more = more
        for k, v in items:
            kv = resp.kvs.add()
            kv.key = k
            kv.value = v
        if not more:
            _SCAN_SESSIONS.streams.release(req.scan_id)
        return resp

    def KvScanRelease(self, req: pb.KvScanReleaseRequest):
        resp = pb.KvScanReleaseResponse()
        _SCAN_SESSIONS.streams.release(req.scan_id)
        return resp

    # ---- txn ----
    def _leader_region_or_err(self, context_pb, resp):
        """KV and txn RPCs are leader-gated — reads included: a follower
        lagging raft apply would serve state missing already-committed
        writes (the reference serves reads through the raft leader; write
        RPCs would fail at propose anyway, this just fails them earlier
        with the routing hint). Caveat: this is a ROLE check, not a
        read-index/leader-lease pass — a deposed leader that has not yet
        seen the new term can still serve a bounded-stale read during a
        partition (closing that window needs read-index or check-quorum
        in raft/core.py; tracked, matches the coordinator's documented
        stale-read stance in coordinator/raft_meta.py)."""
        region = _region_or_err(self.node, context_pb, resp)
        if region is None:
            return None
        raft = self.node.engine.get_node(region.id)
        if raft is not None and not raft.is_leader():
            hint = getattr(raft, "leader_id", None) or ""
            _err(resp, 20001, f"not leader: {hint}")
            return None
        return region

    def TxnPrewrite(self, req: pb.TxnPrewriteRequest):
        resp = pb.TxnPrewriteResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        muts = [
            Mutation(Op(m.op), m.key, m.value) for m in req.mutations
        ]
        try:
            self._txn(region).prewrite(
                muts, req.primary_lock, req.start_ts,
                lock_ttl_ms=req.lock_ttl_ms or 3000,
                for_update_ts=req.for_update_ts,
            )
        except TxnError as e:
            return _err(resp, 40001, str(e))
        return resp

    def TxnCommit(self, req: pb.TxnCommitRequest):
        resp = pb.TxnCommitResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            self._txn(region).commit(list(req.keys), req.start_ts, req.commit_ts)
        except TxnError as e:
            return _err(resp, 40001, str(e))
        return resp

    def TxnGet(self, req: pb.TxnGetRequest):
        resp = pb.TxnGetResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            value = self._txn(region).get(req.key, req.start_ts)
        except TxnError as e:
            return _err(resp, 40001, str(e))
        resp.found = value is not None
        resp.value = value or b""
        return resp

    def TxnScan(self, req: pb.TxnScanRequest):
        resp = pb.TxnScanResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            cop = convert.coprocessor_from_pb(req.coprocessor)
        except ValueError as e:
            return _err(resp, 60001, f"bad coprocessor: {e}")
        try:
            pairs = self._txn(region).scan(
                req.range.start_key, req.range.end_key, req.start_ts,
                limit=0 if cop is not None else req.limit,
            )
        except TxnError as e:
            return _err(resp, 40001, str(e))
        if cop is not None:
            import struct as _struct

            try:
                pairs = cop.execute(pairs, limit=req.limit)
            except (ValueError, IndexError, _struct.error) as e:
                return _err(resp, 60002, f"coprocessor execute: {e}")
        for k, v in pairs:
            kv = resp.kvs.add()
            kv.key = k
            kv.value = v
        return resp

    def TxnBatchRollback(self, req: pb.TxnBatchRollbackRequest):
        resp = pb.TxnBatchRollbackResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            self._txn(region).batch_rollback(list(req.keys), req.start_ts)
        except TxnError as e:
            return _err(resp, 40001, str(e))
        return resp

    def TxnCheckStatus(self, req: pb.TxnCheckStatusRequest):
        resp = pb.TxnCheckStatusResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        st = self._txn(region).check_txn_status(
            req.primary_key, req.lock_ts, req.caller_start_ts
        )
        resp.action = st["action"]
        resp.commit_ts = st["commit_ts"]
        return resp

    # -- pessimistic / maintenance txn surface (store_service.h exposes 16
    # Txn RPCs; engine semantics live in engine/txn.py) ----------------------
    def TxnPessimisticLock(self, req: pb.TxnPessimisticLockRequest):
        resp = pb.TxnPessimisticLockResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            self._txn(region).pessimistic_lock(
                list(req.keys), req.primary_lock, req.start_ts,
                req.for_update_ts, ttl_ms=req.lock_ttl_ms or 3000,
            )
        except TxnError as e:
            return _err(resp, 40001, str(e))
        return resp

    def TxnPessimisticRollback(self, req: pb.TxnPessimisticRollbackRequest):
        resp = pb.TxnPessimisticRollbackResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            self._txn(region).pessimistic_rollback(
                list(req.keys), req.start_ts)
        except TxnError as e:
            return _err(resp, 40001, str(e))
        return resp

    def TxnResolveLock(self, req: pb.TxnResolveLockRequest):
        resp = pb.TxnResolveLockResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            resp.resolved = self._txn(region).resolve_lock(
                req.start_ts, req.commit_ts,
                keys=list(req.keys) or None,
            )
        except TxnError as e:
            return _err(resp, 40001, str(e))
        return resp

    def TxnHeartBeat(self, req: pb.TxnHeartBeatRequest):
        resp = pb.TxnHeartBeatResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            resp.lock_ttl_ms = self._txn(region).heart_beat(
                req.primary_lock, req.start_ts, req.advise_lock_ttl_ms)
        except TxnError as e:
            return _err(resp, 40001, str(e))
        return resp

    def TxnGc(self, req: pb.TxnGcRequest):
        resp = pb.TxnGcResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            resp.deleted = self._txn(region).gc(req.safe_point_ts)
        except TxnError as e:
            return _err(resp, 40001, str(e))
        return resp

    @staticmethod
    def _lock_to_pb(dst, key: bytes, lock) -> None:
        dst.key = key
        dst.lock_ts = lock.lock_ts
        dst.primary_lock = lock.primary
        dst.op = lock.op.value
        dst.ttl_ms = lock.ttl_ms
        dst.for_update_ts = lock.for_update_ts

    def TxnScanLock(self, req: pb.TxnScanLockRequest):
        resp = pb.TxnScanLockResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        from dingo_tpu.mvcc.codec import MAX_TS as _MAX_TS

        locks = self._txn(region).scan_lock(
            req.range.start_key, req.range.end_key,
            max_ts=req.max_ts or _MAX_TS, limit=req.limit,
        )
        for key, lock in locks:
            self._lock_to_pb(resp.locks.add(), key, lock)
        return resp

    def TxnBatchGet(self, req: pb.TxnBatchGetRequest):
        resp = pb.TxnBatchGetResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            pairs = self._txn(region).batch_get(list(req.keys), req.start_ts)
        except TxnError as e:
            return _err(resp, 40001, str(e))
        for key, value in pairs:
            if value is None:
                continue
            kv = resp.kvs.add()
            kv.key = key
            kv.value = value
        return resp

    def TxnCheckSecondaryLocks(self, req: pb.TxnCheckSecondaryLocksRequest):
        resp = pb.TxnCheckSecondaryLocksResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        st = self._txn(region).check_secondary_locks(
            list(req.keys), req.start_ts)
        for key, lock in st["locks"]:
            self._lock_to_pb(resp.locks.add(), key, lock)
        resp.commit_ts = st["commit_ts"]
        resp.missing_keys.extend(st["missing"])
        return resp

    def TxnDeleteRange(self, req: pb.TxnDeleteRangeRequest):
        resp = pb.TxnDeleteRangeResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        try:
            self._txn(region).delete_range(
                req.range.start_key, req.range.end_key)
        except TxnError as e:
            return _err(resp, 40001, str(e))
        return resp

    def TxnDump(self, req: pb.TxnDumpRequest):
        resp = pb.TxnDumpResponse()
        region = self._leader_region_or_err(req.context, resp)
        if region is None:
            return resp
        d = self._txn(region).dump(
            req.range.start_key, req.range.end_key, limit=req.limit)
        for e in d["locks"]:
            li = resp.locks.add()
            li.key, li.lock_ts, li.primary_lock = (
                e["key"], e["lock_ts"], e["primary"])
            li.op, li.ttl_ms, li.for_update_ts = (
                e["op"], e["ttl_ms"], e["for_update_ts"])
        for e in d["writes"]:
            wi = resp.writes.add()
            wi.key, wi.commit_ts = e["key"], e["commit_ts"]
            wi.start_ts, wi.op = e["start_ts"], e["op"]
        for e in d["datas"]:
            di = resp.datas.add()
            di.key, di.start_ts, di.value = (
                e["key"], e["start_ts"], e["value"])
        return resp


class DocumentService:
    """Full-text RPCs (reference DocumentService, server/main.cc:1176)."""

    def __init__(self, node: StoreNode):
        self.node = node

    def DocumentAdd(self, req: pb.DocumentAddRequest) -> pb.DocumentAddResponse:
        from dingo_tpu.engine import write_data as wd

        resp = pb.DocumentAddResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if region.document_index is None:
            return _err(resp, 80001, "not a DOCUMENT region")
        ids = [d.id for d in req.documents]
        docs = [convert.scalar_from_pb(d.fields) for d in req.documents]
        # typed-schema validation BEFORE the raft propose: a doc that can
        # never apply must not enter the log (apply-time failures would
        # have to fail identically on every replica forever)
        from dingo_tpu.document.index import SchemaError

        try:
            for doc in docs:
                region.document_index.check_doc(doc)
        except SchemaError as e:
            return _err(resp, 80002, str(e))
        try:
            ts = self.node.storage.ts_provider.get_ts()
            self.node.engine.write(region, wd.DocumentAddData(
                ts=ts, ids=ids, documents=docs, is_update=req.is_update,
            ))
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        resp.ts = ts
        return resp

    def DocumentDelete(self, req: pb.DocumentDeleteRequest):
        from dingo_tpu.engine import write_data as wd

        resp = pb.DocumentDeleteResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if region.document_index is None:
            return _err(resp, 80001, "not a DOCUMENT region")
        try:
            ts = self.node.storage.ts_provider.get_ts()
            self.node.engine.write(region, wd.DocumentDeleteData(
                ts=ts, ids=list(req.ids),
            ))
        except NotLeader as e:
            return _err(resp, 20001, f"not leader: {e.leader_hint}")
        return resp

    def DocumentSearch(self, req: pb.DocumentSearchRequest):
        resp = pb.DocumentSearchResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if region.document_index is None:
            return _err(resp, 80001, "not a DOCUMENT region")
        hits = region.document_index.search(
            req.query,
            topk=req.top_n or 10,
            mode=req.mode or "or",
            column_filter=convert.scalar_from_pb(req.column_filter) or None,
        )
        for did, score in hits:
            d = resp.documents.add()
            d.id = did
            d.score = score
            if req.with_fields:
                doc = region.document_index.get(did)
                if doc:
                    convert.scalar_to_pb(d.fields, doc)
        return resp

    def DocumentCount(self, req: pb.DocumentCountRequest):
        resp = pb.DocumentCountResponse()
        region = _region_or_err(self.node, req.context, resp)
        if region is None:
            return resp
        if region.document_index is None:
            return _err(resp, 80001, "not a DOCUMENT region")
        resp.count = region.document_index.count()
        return resp


class _ScanSessions:
    """Shared StreamManager for KvScan sessions (ScanManager v2 role)."""

    def __init__(self):
        from dingo_tpu.common.stream import StreamManager

        self.streams = StreamManager(idle_timeout_s=60.0)


_SCAN_SESSIONS = _ScanSessions()


class PushService:
    """Coordinator -> store push of store operations (push_service.h — the
    inverse of the heartbeat pull)."""

    def __init__(self, node: StoreNode):
        self.node = node

    def PushStoreOperation(self, req: pb.PushStoreOperationRequest):
        resp = pb.PushStoreOperationResponse()
        for c in req.commands:
            # per-command isolation: a malformed or failing command must not
            # abort the batch or lose acks for commands that DID execute
            try:
                cmd = convert.region_cmd_from_pb(c)
                self.node.execute_region_cmd(cmd)
                resp.done_cmd_ids.append(c.cmd_id)
            except NotLeader as e:
                if self.node.coordinator is not None and e.leader_hint:
                    self.node.coordinator.requeue_cmd(
                        cmd, e.leader_hint.split("/")[0],
                        from_store=self.node.store_id,
                    )
            except Exception:  # noqa: BLE001
                pass
        return resp


class NodeService:
    def __init__(self, node: StoreNode):
        self.node = node

    def GetVectorIndexSnapshotMeta(
        self, req: pb.VectorIndexSnapshotMetaRequest
    ) -> pb.VectorIndexSnapshotMetaResponse:
        """Snapshot manifest for peer pull (node_service.h:45-52 flow)."""
        import os

        resp = pb.VectorIndexSnapshotMetaResponse()
        mgr = self.node.index_manager
        if not mgr.snapshot_root:
            return _err(resp, 90001, "store has no snapshot root")
        path = mgr.snapshot_path(req.region_id)
        if not os.path.isdir(path):
            return _err(resp, 90002, f"no snapshot for region {req.region_id}")
        region = self.node.get_region(req.region_id)
        if region is not None and region.vector_index_wrapper is not None:
            resp.snapshot_log_id = region.vector_index_wrapper.snapshot_log_id
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            if os.path.isfile(full):
                f = resp.files.add()
                f.name = name
                f.size = os.path.getsize(full)
        return resp

    def NodeInfo(self, req: pb.NodeInfoRequest) -> pb.NodeInfoResponse:
        resp = pb.NodeInfoResponse()
        resp.store_id = self.node.store_id
        regions = self.node.meta.get_all_regions()
        resp.region_ids.extend(r.id for r in regions)
        resp.leader_region_ids.extend(
            r.id for r in regions
            if (n := self.node.engine.get_node(r.id)) is not None
            and n.is_leader()
        )
        return resp

    def SetLogLevel(self, req: pb.SetLogLevelRequest):
        """Runtime log-level flip (node_service.h log-level RPC)."""
        from dingo_tpu.common import log as dlog

        resp = pb.SetLogLevelResponse()
        try:
            dlog.set_level(req.level, module=req.module or None)
        except ValueError as e:
            return _err(resp, 90003, str(e))
        dlog.get_logger("node").info(
            "log level set to %s (module=%s)", req.level.upper(),
            req.module or "<all>")
        return resp

    def GetLogLevel(self, req: pb.GetLogLevelRequest):
        from dingo_tpu.common import log as dlog

        resp = pb.GetLogLevelResponse()
        for module, level in sorted(dlog.get_levels().items()):
            e = resp.levels.add()
            e.module = module
            e.level = level
        return resp


class FileService:
    """Chunked snapshot file download (reference file_service.{h,cc}: the
    vector-index snapshot transfer's data plane)."""

    CHUNK = 1 << 20

    def __init__(self, node: StoreNode):
        self.node = node

    def ReadFileChunk(self, req: pb.FileChunkRequest) -> pb.FileChunkResponse:
        import os

        resp = pb.FileChunkResponse()
        mgr = self.node.index_manager
        if not mgr.snapshot_root:
            return _err(resp, 90001, "store has no snapshot root")
        base = os.path.realpath(mgr.snapshot_path(req.region_id))
        full = os.path.realpath(os.path.join(base, req.name))
        # no path escape: serve only files inside the region's snapshot dir
        if not full.startswith(base + os.sep):
            return _err(resp, 90003, "invalid file name")
        if not os.path.isfile(full):
            return _err(resp, 90002, f"no such file {req.name}")
        size = min(req.size or self.CHUNK, self.CHUNK)
        with open(full, "rb") as f:
            f.seek(req.offset)
            resp.data = f.read(size)
        resp.eof = req.offset + len(resp.data) >= os.path.getsize(full)
        return resp


class DebugService:
    def MetricsDump(self, req: pb.MetricsDumpRequest) -> pb.MetricsDumpResponse:
        resp = pb.MetricsDumpResponse()
        fmt = req.format or "json"
        if fmt == "prometheus":
            # the payload field stays `json` (wire compatibility); the
            # content is Prometheus text exposition format
            resp.json = METRICS.render_prometheus()
        elif fmt == "json":
            resp.json = json.dumps(METRICS.dump())
        else:
            return _err(resp, 50002, f"unknown metrics format {fmt!r}")
        return resp

    def TraceDump(self, req: pb.MetricsDumpRequest) -> pb.MetricsDumpResponse:
        """Sampled span buffer + slow-query log as JSON (spans grouped by
        trace id) — the RPC face of dingo_tpu/trace."""
        from dingo_tpu.trace import to_json

        resp = pb.MetricsDumpResponse()
        resp.json = json.dumps(to_json())
        return resp

    def TraceChromeDump(self, req: pb.MetricsDumpRequest):
        """Same buffer in Chrome trace_event form: save the payload to a
        file and open it in chrome://tracing / Perfetto, or feed it to
        tools/trace_report.py for a per-stage latency table."""
        from dingo_tpu.trace import to_chrome_trace

        resp = pb.MetricsDumpResponse()
        resp.json = json.dumps(to_chrome_trace())
        return resp

    def FailPoint(self, req: pb.FailPointRequest) -> pb.FailPointResponse:
        resp = pb.FailPointResponse()
        try:
            if req.remove:
                FAILPOINTS.remove(req.name)
            else:
                FAILPOINTS.configure(req.name, req.config)
        except ValueError as e:
            return _err(resp, 50001, str(e))
        return resp

    def FlightDump(self, req: pb.FlightDumpRequest) -> pb.FlightDumpResponse:
        """Flight-recorder export: bundle catalog always; one compressed
        payload (zlib JSON — tools/flight_report.py renders it) when
        include_payload is set (bundle_id empty = newest)."""
        from dingo_tpu.obs.flight import FLIGHT

        resp = pb.FlightDumpResponse()
        metas = FLIGHT.bundles_meta()
        for m in metas:
            out = resp.bundles.add()
            for field in ("id", "reason", "name", "trace_id", "region_id",
                          "created_ms", "payload_bytes"):
                setattr(out, field, m[field])
        if req.include_payload:
            found = FLIGHT.get_with_id(req.bundle_id)
            if found is None:
                return _err(
                    resp, 50003,
                    f"no flight bundle {req.bundle_id!r}" if req.bundle_id
                    else "no flight bundles captured",
                )
            # id + payload resolved atomically: a bundle captured between
            # the catalog read above and here can't mislabel the blob
            resp.payload_bundle_id, resp.payload = found
        return resp

    def EventDump(self, req: pb.EventDumpRequest) -> pb.EventDumpResponse:
        """This process's control-plane decision ring (obs/events.py),
        oldest first — harvested-but-unevicted events included, so the
        local view overlaps the coordinator's merged timeline."""
        from dingo_tpu.obs.events import EVENTS

        resp = pb.EventDumpResponse()
        for ev in EVENTS.recent(
            limit=int(req.limit) or 0,
            region_id=req.region_id or None,
            actor=req.actor,
        ):
            convert.control_event_to_pb(ev, resp.events.add())
        resp.dropped = EVENTS.dropped
        return resp


class CoordinatorService:
    def __init__(self, control: CoordinatorControl, tso: TsoControl):
        self.control = control
        self.tso = tso

    def Hello(self, req: pb.HelloRequest) -> pb.HelloResponse:
        resp = pb.HelloResponse()
        resp.store_count = len(self.control.stores)
        resp.region_count = len(self.control.regions)
        return resp

    def StoreHeartbeat(self, req: pb.StoreHeartbeatRequest):
        resp = pb.StoreHeartbeatResponse()
        cmds = self.control.store_heartbeat(
            req.store_id,
            region_ids=list(req.region_ids),
            leader_region_ids=list(req.leader_region_ids),
            capacity_bytes=req.capacity_bytes,
            used_bytes=req.used_bytes,
            region_defs=[
                convert.region_def_from_pb(d) for d in req.region_definitions
            ],
            done_cmd_ids=list(req.done_cmd_ids),
            failed_cmd_ids=list(req.failed_cmd_ids),
            stalled_cmd_ids=list(req.stalled_cmd_ids),
            metrics=(
                convert.store_metrics_from_pb(req.metrics)
                if req.HasField("metrics") else None
            ),
        )
        for c in cmds:
            out = resp.commands.add()
            out.cmd_id = c.cmd_id
            out.region_id = c.region_id
            out.cmd_type = c.cmd_type.value
            out.split_key = c.split_key
            out.child_region_id = c.child_region_id
            out.target_store_id = c.target_store_id
            if c.definition is not None:
                out.definition.CopyFrom(convert.region_def_to_pb(c.definition))
        return resp

    def CreateRegion(self, req: pb.CreateRegionRequest):
        resp = pb.CreateRegionResponse()
        try:
            d = self.control.create_region(
                start_key=req.range.start_key,
                end_key=req.range.end_key,
                partition_id=req.partition_id,
                region_type=[RegionType.STORE, RegionType.INDEX,
                             RegionType.DOCUMENT][req.region_type],
                index_parameter=convert.index_parameter_from_pb(
                    req.index_parameter
                ),
                replication=req.replication or None,
                document_schema=(
                    {c.name: c.sql_type for c in req.document_schema}
                    if req.document_schema else None
                ),
            )
        except RuntimeError as e:
            return _err(resp, 60001, str(e))
        resp.definition.CopyFrom(convert.region_def_to_pb(d))
        return resp

    def SplitRegion(self, req: pb.SplitRegionRequest):
        resp = pb.SplitRegionResponse()
        try:
            resp.child_region_id = self.control.split_region(
                req.region_id, req.split_key
            )
        except (KeyError, ValueError) as e:
            return _err(resp, 60002, str(e))
        return resp

    def MergeRegion(self, req: pb.MergeRegionRequest):
        """Operator region op (coordinator_service.cc MergeRegion): queue
        MERGE to the target's leader; adjacency/co-location validated."""
        resp = pb.MergeRegionResponse()
        try:
            self.control.merge_region(
                req.target_region_id, req.source_region_id)
        except (KeyError, ValueError) as e:
            return _err(resp, 60002, str(e))
        return resp

    def ChangePeerRegion(self, req: pb.ChangePeerRegionRequest):
        """Operator region op: replace the region's peer set (additions
        get CREATE, survivors CHANGE_PEER, removals DELETE)."""
        resp = pb.ChangePeerRegionResponse()
        if not req.new_peers:
            return _err(resp, 60002, "empty peer set")
        try:
            self.control.change_peer(req.region_id, list(req.new_peers))
        except (KeyError, ValueError) as e:
            return _err(resp, 60002, str(e))
        return resp

    def TransferLeaderRegion(self, req: pb.TransferLeaderRegionRequest):
        """Operator region op: ask the current leader to hand off."""
        resp = pb.TransferLeaderRegionResponse()
        try:
            self.control.transfer_leader(req.region_id, req.target_store)
        except (KeyError, ValueError) as e:
            return _err(resp, 60002, str(e))
        return resp

    def GetRegionMap(self, req: pb.GetRegionMapRequest):
        resp = pb.GetRegionMapResponse()
        for d in self.control.regions.values():
            resp.regions.add().CopyFrom(convert.region_def_to_pb(d))
        return resp

    def RequeueRegionCmd(self, req: pb.RequeueRegionCmdRequest):
        resp = pb.RequeueRegionCmdResponse()
        cmd = convert.region_cmd_from_pb(req.cmd)
        self.control.requeue_cmd(cmd, req.target_store_id,
                                 from_store=req.from_store_id or None)
        return resp

    def GetGCSafePoint(self, req: pb.GetGCSafePointRequest):
        """GC safe point = now - retention (tso-format). Stores poll this
        and run MVCC GC below it (gc_safe_point push/pull flow)."""
        resp = pb.GetGCSafePointResponse()
        resp.safe_ts = self.control.gc_safe_ts(self.tso)
        return resp

    def Tso(self, req: pb.TsoRequest) -> pb.TsoResponse:
        resp = pb.TsoResponse()
        first, count = self.tso.gen_ts(req.count or 1)
        resp.first_ts = first
        resp.count = count
        return resp

    def TsoAdvance(self, req: pb.TsoAdvanceRequest) -> pb.TsoAdvanceResponse:
        """Restore path: future timestamps must stay above the restored
        cluster's watermark or MVCC versions would collide."""
        resp = pb.TsoAdvanceResponse()
        self.tso.advance_to(req.ts)
        return resp


class VersionService:
    """etcd-like KV (version_service.cc analog over KvControl)."""

    def __init__(self, kv: KvControl):
        self.kv = kv
        self._watch_slots = threading.Semaphore(self._MAX_BLOCKED_WATCHES)

    def VKvPut(self, req: pb.VKvPutRequest) -> pb.VKvPutResponse:
        resp = pb.VKvPutResponse()
        try:
            resp.revision = self.kv.kv_put(req.key, req.value, req.lease_id)
        except KeyError as e:
            return _err(resp, 70001, str(e))
        return resp

    @staticmethod
    def _item_to_pb(it, o) -> None:
        o.key = it.key
        o.value = it.value
        o.create_revision = it.create_revision
        o.mod_revision = it.mod_revision
        o.version = it.version

    def VKvRange(self, req: pb.VKvRangeRequest) -> pb.VKvRangeResponse:
        resp = pb.VKvRangeResponse()
        try:
            items, rev = self.kv.kv_range(
                req.start, req.end or None, limit=req.limit,
                revision=req.revision,
            )
        except CompactedError as e:
            return _err(resp, 70002, str(e))
        except FutureRevError as e:
            return _err(resp, 70003, str(e))
        resp.revision = rev
        for it in items:
            self._item_to_pb(it, resp.items.add())
        return resp

    def VKvDeleteRange(self, req: pb.VKvDeleteRangeRequest):
        resp = pb.VKvDeleteRangeResponse()
        resp.deleted = self.kv.kv_delete_range(req.start, req.end or None)
        return resp

    def VKvCompaction(self, req: pb.VKvCompactionRequest):
        """KvCompaction RPC (kv_control.h:287)."""
        resp = pb.VKvCompactionResponse()
        resp.removed_versions = self.kv.kv_compaction(req.revision)
        resp.compact_revision = self.kv._compact_revision
        return resp

    #: cap on concurrently BLOCKED watch polls: the grpc pool is shared
    #: with the puts that would wake the watchers, so unbounded long-polls
    #: could starve the writers and wedge the server
    _MAX_BLOCKED_WATCHES = 8

    def VKvWatch(self, req: pb.VKvWatchRequest) -> pb.VKvWatchResponse:
        """One-time watch with history replay (kv_control.h:47-113):
        events at/after start_revision fire immediately from the revision
        chain; otherwise long-poll up to timeout_ms. Unset start_revision
        means "from now" (etcd watch semantics), NOT from history."""
        resp = pb.VKvWatchResponse()
        start = req.start_revision or (self.kv._revision + 1)
        # pin the window even on timeout: the server clamps long polls
        # (_MAX_WATCH_TIMEOUT_MS), so a client that re-polled "from now"
        # would drop any event landing in the turnaround gap — re-polling
        # from revision + 1 replays it from the revision chain instead
        resp.revision = start - 1
        try:
            args, busy = _long_poll_watch(
                lambda cb: self.kv.watch(req.key, start, cb),
                lambda cb: self.kv.cancel_watch(req.key, cb),
                self._watch_slots, req.timeout_ms,
            )
        except CompactedError as e:
            return _err(resp, 70002, str(e))
        if busy:
            return _err(resp, 70004, "too many blocked watchers")
        if args is not None:
            event, item = args
            resp.fired = True
            resp.event = event
            resp.revision = item.mod_revision
            self._item_to_pb(item, resp.item)
        return resp

    def LeaseGrant(self, req: pb.LeaseGrantRequest) -> pb.LeaseGrantResponse:
        resp = pb.LeaseGrantResponse()
        resp.lease_id = self.kv.lease_grant(req.ttl_s).lease_id
        return resp

    def LeaseRenew(self, req: pb.LeaseRenewRequest):
        resp = pb.LeaseRenewResponse()
        try:
            resp.ttl_s = self.kv.lease_renew(req.lease_id).ttl_s
        except KeyError as e:
            return _err(resp, 70001, str(e))
        return resp

    def LeaseRevoke(self, req: pb.LeaseRevokeRequest):
        resp = pb.LeaseRevokeResponse()
        resp.deleted = self.kv.lease_revoke(req.lease_id)
        return resp


class MetaService:
    """Schema/table meta RPCs (reference src/server/meta_service.cc)."""

    #: same rationale as VersionService: blocked long-polls must not be
    #: able to occupy the whole shared grpc pool
    _MAX_BLOCKED_WATCHES = 8

    def __init__(self, meta):
        from dingo_tpu.coordinator.meta import MetaControl

        self.meta: MetaControl = meta
        self._watch_slots = threading.Semaphore(self._MAX_BLOCKED_WATCHES)

    @staticmethod
    def _table_to_pb(t, out) -> None:
        from dingo_tpu.store.region import RegionType

        out.table_id = t.table_id
        out.schema_name = t.schema_name
        out.name = t.name
        out.table_type = [RegionType.STORE, RegionType.INDEX,
                          RegionType.DOCUMENT].index(t.table_type)
        out.replication = t.replication
        for c in t.columns:
            col = out.columns.add()
            col.name, col.sql_type = c.name, c.sql_type
            col.nullable, col.primary = c.nullable, c.primary
        for p in t.partitions:
            pp = out.partitions.add()
            pp.partition_id = p.partition_id
            pp.id_lo, pp.id_hi = p.id_lo, p.id_hi
            pp.start_key, pp.end_key = p.start_key, p.end_key
            pp.region_id = p.region_id
        if t.index_parameter is not None:
            out.index_parameter.CopyFrom(
                convert.index_parameter_to_pb(t.index_parameter)
            )

    def CreateSchema(self, req: pb.CreateSchemaRequest):
        from dingo_tpu.coordinator.meta import MetaError, MetaExistsError

        resp = pb.CreateSchemaResponse()
        try:
            self.meta.create_schema(req.schema_name)
        except MetaExistsError as e:
            return _err(resp, 40002, str(e))
        except MetaError as e:
            return _err(resp, 40001, str(e))
        return resp

    def DropSchema(self, req: pb.DropSchemaRequest):
        from dingo_tpu.coordinator.meta import MetaError

        resp = pb.DropSchemaResponse()
        try:
            self.meta.drop_schema(req.schema_name)
        except MetaError as e:
            return _err(resp, 40001, str(e))
        return resp

    def GetSchemas(self, req: pb.GetSchemasRequest):
        resp = pb.GetSchemasResponse()
        resp.schema_names.extend(self.meta.get_schemas())
        return resp

    def CreateTable(self, req: pb.CreateTableRequest):
        from dingo_tpu.coordinator.meta import (
            ColumnDefinition,
            MetaError,
            PartitionDefinition,
        )
        from dingo_tpu.store.region import RegionType

        resp = pb.CreateTableResponse()
        d = req.definition
        columns = [
            ColumnDefinition(c.name, c.sql_type or "VARCHAR",
                             c.nullable, c.primary)
            for c in d.columns
        ]
        partitions = [
            PartitionDefinition(
                partition_id=p.partition_id, id_lo=p.id_lo, id_hi=p.id_hi,
                start_key=p.start_key, end_key=p.end_key,
            )
            for p in d.partitions
        ]
        param = (
            convert.index_parameter_from_pb(d.index_parameter)
            if d.HasField("index_parameter") else None
        )
        table_type = [RegionType.STORE, RegionType.INDEX,
                      RegionType.DOCUMENT][d.table_type]
        try:
            t = self.meta.create_table(
                d.schema_name, d.name, partitions,
                columns=columns, index_parameter=param,
                table_type=table_type, replication=d.replication,
            )
        except (MetaError, RuntimeError) as e:
            return _err(resp, 40001, str(e))
        self._table_to_pb(t, resp.definition)
        return resp

    def ImportTable(self, req: pb.ImportTableRequest):
        """Restore-path registration: partitions must already point at
        live regions (no region creation — reference br restore)."""
        from dingo_tpu.coordinator.meta import (
            ColumnDefinition,
            MetaError,
            MetaExistsError,
            PartitionDefinition,
            TableDefinition,
        )
        from dingo_tpu.store.region import RegionType

        resp = pb.ImportTableResponse()
        d = req.definition
        t = TableDefinition(
            table_id=0,
            schema_name=d.schema_name,
            name=d.name,
            table_type=[RegionType.STORE, RegionType.INDEX,
                        RegionType.DOCUMENT][d.table_type],
            columns=[
                ColumnDefinition(c.name, c.sql_type or "VARCHAR",
                                 c.nullable, c.primary)
                for c in d.columns
            ],
            partitions=[
                PartitionDefinition(
                    partition_id=p.partition_id, id_lo=p.id_lo,
                    id_hi=p.id_hi, start_key=p.start_key,
                    end_key=p.end_key, region_id=p.region_id,
                )
                for p in d.partitions
            ],
            index_parameter=(
                convert.index_parameter_from_pb(d.index_parameter)
                if d.HasField("index_parameter") else None
            ),
        )
        try:
            registered = self.meta.import_table(t)
        except MetaExistsError as e:
            return _err(resp, 40002, str(e))
        except (MetaError, RuntimeError) as e:
            return _err(resp, 40001, str(e))
        self._table_to_pb(registered, resp.definition)
        return resp

    def DropTable(self, req: pb.DropTableRequest):
        from dingo_tpu.coordinator.meta import MetaError

        resp = pb.DropTableResponse()
        try:
            self.meta.drop_table(req.schema_name, req.table_name)
        except MetaError as e:
            return _err(resp, 40001, str(e))
        return resp

    def GetTable(self, req: pb.GetTableRequest):
        resp = pb.GetTableResponse()
        t = self.meta.get_table(req.schema_name, req.table_name)
        resp.found = t is not None
        if t is not None:
            self._table_to_pb(t, resp.definition)
        return resp

    def GetTables(self, req: pb.GetTablesRequest):
        resp = pb.GetTablesResponse()
        for t in self.meta.get_tables(req.schema_name):
            self._table_to_pb(t, resp.definitions.add())
        return resp

    def MetaWatch(self, req: pb.MetaWatchRequest) -> pb.MetaWatchResponse:
        """Meta-watch RPC (meta_service.cc analog): one-shot schema/table
        change event with replay, or long-poll up to timeout_ms. Unset
        start_revision = from now. A timed-out response still carries the
        current revision so the next poll can pin its window (events
        between polls must not be lost)."""
        resp = pb.MetaWatchResponse()
        start = req.start_revision or (self.meta.meta_revision + 1)
        args, busy = _long_poll_watch(
            lambda cb: self.meta.watch(start, cb),
            lambda cb: self.meta.cancel_watch(cb),
            self._watch_slots, req.timeout_ms,
        )
        if busy:
            return _err(resp, 70004, "too many blocked watchers")
        if args is not None:
            (ev,) = args
            resp.fired = True
            resp.event = ev["event"]
            resp.schema_name = ev["schema"]
            resp.table_name = ev["table"]
            resp.table_id = ev["table_id"]
            resp.revision = ev["revision"]
        else:
            # not fired: report where the watch window started so the
            # client resumes from revision+1 without a gap
            resp.revision = start - 1
        return resp


class JobService:
    """Job introspection (reference JobService, main.cc registry): lists
    the coordinator's queued/active region commands."""

    def __init__(self, control: CoordinatorControl):
        self.control = control

    def ListJobs(self, req: pb.ListJobsRequest):
        resp = pb.ListJobsResponse()
        with self.control._lock:
            # jobs is the retained history — store_ops queues are pruned
            # once the store acks execution
            for cmd in self.control.jobs:
                if cmd.status == "done" and not req.include_done:
                    continue
                j = resp.jobs.add()
                j.cmd_id = cmd.cmd_id
                j.region_id = cmd.region_id
                j.cmd_type = cmd.cmd_type.value
                j.status = cmd.status
                j.store_id = cmd.store_id
                j.retries = cmd.retries
        return resp


class ClusterStatService:
    """Cluster-level stats (reference ClusterStatService)."""

    def __init__(self, control: CoordinatorControl):
        self.control = control

    def GetClusterStat(self, req: pb.GetClusterStatRequest):
        from dingo_tpu.coordinator.control import StoreState

        resp = pb.GetClusterStatResponse()
        with self.control._lock:
            stores = list(self.control.stores.values())
            resp.store_count = len(stores)
            resp.alive_store_count = sum(
                1 for s in stores if s.state is StoreState.NORMAL
            )
            resp.region_count = len(self.control.regions)
            resp.pending_job_count = sum(
                1 for cmds in self.control.store_ops.values()
                for c in cmds if c.status != "done"
            )
            for s in stores:
                st = resp.stores.add()
                st.store_id = s.store_id
                st.state = s.state.value
                st.region_count = len(s.region_ids)
                st.leader_count = len(s.leader_region_ids)
                st.last_heartbeat_ms = s.last_heartbeat_ms
                summary = self.control.store_metrics_summary(s.store_id)
                st.key_count = summary["key_count"]
                st.vector_count = summary["vector_count"]
                st.memory_bytes = summary["memory_bytes"]
                st.device_memory_bytes = summary["device_memory_bytes"]
                st.metrics_stale = summary["stale"]
                st.leader_qps = summary["leader_qps"]
            rollup = self.control.cluster_metrics_rollup()
        resp.total_key_count = rollup["key_count"]
        resp.total_vector_count = rollup["vector_count"]
        resp.total_memory_bytes = rollup["memory_bytes"]
        resp.total_device_memory_bytes = rollup["device_memory_bytes"]
        return resp

    def GetStoreMetrics(self, req: pb.GetStoreMetricsRequest):
        """Freshest per-store metrics snapshots with staleness flags (the
        query face of the heartbeat metrics plane; `cluster top` renders
        this)."""
        resp = pb.GetStoreMetricsResponse()
        for sid, snap, at_ms, stale in self.control.get_store_metrics(
            req.store_id
        ):
            entry = resp.stores.add()
            entry.store_id = sid
            entry.last_update_ms = at_ms
            entry.stale = stale
            convert.store_metrics_to_pb(snap, entry.metrics)
        resp.diverged_region_ids.extend(self.control.diverged_regions())
        return resp

    def GetRegionMetrics(self, req: pb.GetRegionMetricsRequest):
        """Per-replica rows for one region (or all, region_id=0) across
        stores — leader/follower lag and per-replica HBM side by side."""
        resp = pb.GetRegionMetricsResponse()
        for sid, stale, rm in self.control.get_region_metrics(req.region_id):
            entry = resp.regions.add()
            entry.store_id = sid
            entry.stale = stale
            convert.region_metrics_to_pb(rm, entry.metrics)
        resp.diverged_region_ids.extend(self.control.diverged_regions())
        return resp

    def EventDump(self, req: pb.EventDumpRequest) -> pb.EventDumpResponse:
        """The merged cross-node control-plane timeline (heartbeat-
        harvested store events + the coordinator's own planner/capacity
        decisions), causally ordered — `cluster events` / `cluster
        explain` render this."""
        resp = pb.EventDumpResponse()
        for ev in self.control.cluster_events(
            region_id=int(req.region_id),
            actor=req.actor,
            limit=int(req.limit) or 0,
        ):
            convert.control_event_to_pb(ev, resp.events.add())
        from dingo_tpu.obs.events import EVENTS

        resp.dropped = EVENTS.dropped
        return resp


class RegionControlService:
    """Store-side forced region operations (reference RegionControlService):
    snapshot / index rebuild / detailed state dump, plus the BR transport
    (chunked region export/import — reference src/br/ backup RPCs)."""

    _EXPORT_CHUNK = 1 << 20
    _TRANSFER_TTL_S = 300.0   # abandoned transfer sessions die after this
    #: once the final chunk was served, the (multi-MB) export blob is only
    #: kept long enough for a lost-response re-pull — not the full TTL
    _EOF_GRACE_S = 20.0

    def __init__(self, node: StoreNode):
        self.node = node
        # Transfer sessions, guarded by one lock (the grpc pool is
        # 16-threaded; two br runs against the same region must not
        # corrupt each other's stream):
        #   exports: export_id -> (blob, last_access)   server-assigned id
        #   imports: (region_id, import_id) -> (bytearray, last_access)
        self._transfer_lock = threading.Lock()
        self._exports: Dict[int, list] = {}
        self._imports: Dict[tuple, list] = {}
        self._next_export_id = 1

    def _gc_transfers_locked(self) -> None:
        now = time.monotonic()
        for d in (self._exports, self._imports):
            dead = []
            for k, v in d.items():
                eof_served = len(v) > 2 and v[2]
                ttl = self._EOF_GRACE_S if eof_served else self._TRANSFER_TTL_S
                if now - v[1] > ttl:
                    dead.append(k)
            for k in dead:   # crashed/finished client: drop the buffer
                del d[k]

    def RegionExport(self, req: pb.RegionExportRequest):
        from dingo_tpu.engine.raft_engine import region_snapshot

        resp = pb.RegionExportResponse()
        region = self.node.get_region(req.region_id)
        if region is None:
            return _err(resp, 10001, f"region {req.region_id} not found")
        # leader-gated: a follower can lag raft apply, and a backup that
        # silently exports a stale replica is a data-losing backup. 20001
        # routes the client's retry to the leader (reference br backs up
        # through the leader too).
        raft = self.node.engine.get_node(req.region_id)
        if raft is not None and not raft.is_leader():
            hint = getattr(raft, "leader_id", None) or ""
            return _err(resp, 20001, f"not leader: {hint}")
        if req.export_id == 0 and req.offset != 0:
            return _err(resp, 70004, "offset > 0 requires an export_id")
        blob = None
        if req.export_id == 0:
            # build the (multi-MB) snapshot OUTSIDE the transfer lock: a
            # slow export must not block unrelated concurrent transfers
            try:
                blob = wire.encode(region_snapshot(self.node.raw, region))
            except OSError as e:
                return _err(resp, 70003, f"export snapshot failed: {e}")
        with self._transfer_lock:
            self._gc_transfers_locked()
            if req.export_id == 0:
                export_id = self._next_export_id
                self._next_export_id += 1
                # [blob, last_access, eof_served]
                self._exports[export_id] = [blob, time.monotonic(), False]
            else:
                export_id = int(req.export_id)
                ses = self._exports.get(export_id)
                if ses is None:
                    return _err(resp, 70004,
                                f"unknown/expired export {export_id}")
                ses[1] = time.monotonic()
                blob = ses[0]
            limit = (int(req.max_bytes) if req.max_bytes > 0
                     else self._EXPORT_CHUNK)
            if not 0 <= req.offset <= len(blob):
                return _err(resp, 70004, f"bad export offset {req.offset}")
            resp.data = blob[req.offset:req.offset + limit]
            resp.total_bytes = len(blob)
            resp.export_id = export_id
            resp.eof = req.offset + len(resp.data) >= len(blob)
            if resp.eof:
                # keep the session briefly (eof-grace TTL): if this
                # response is lost in transit the client can re-pull the
                # final chunk, without pinning the blob for the full TTL
                resp.checksum = wire.blob_checksum(blob)
                self._exports[export_id][2] = True
        return resp

    def RegionImport(self, req: pb.RegionImportRequest):
        from dingo_tpu.engine.raft_engine import region_install

        resp = pb.RegionImportResponse()
        region = self.node.get_region(req.region_id)
        if region is None:
            return _err(resp, 10001, f"region {req.region_id} not found")
        # raft-hosted region: reject on the FIRST chunk if this store
        # isn't the leader — the client would otherwise upload the whole
        # multi-MB blob to a peer that can only refuse it at commit time
        raft = self.node.engine.get_node(req.region_id)
        if raft is not None and not raft.is_leader():
            hint = getattr(raft, "leader_id", None) or ""
            return _err(resp, 20001, f"not leader: {hint}")
        key = (int(req.region_id), int(req.import_id))
        with self._transfer_lock:
            self._gc_transfers_locked()
            ses = self._imports.setdefault(key, [bytearray(), 0.0])
            buf = ses[0]
            if req.offset != len(buf):
                if req.offset == 0:
                    buf.clear()   # restarted push: drop the stale prefix
                else:
                    self._imports.pop(key, None)
                    return _err(resp, 70005,
                                f"import offset {req.offset} != {len(buf)}")
            buf.extend(req.data)
            ses[1] = time.monotonic()
            if not req.commit:
                return resp
            blob = bytes(self._imports.pop(key)[0])
        if (req.total_bytes != len(blob)
                or wire.blob_checksum(blob) != req.checksum):
            return _err(resp, 70006,
                        "import blob size/checksum mismatch (torn upload)")
        try:
            state = wire.decode(blob)
        except (ValueError, wire.WireError) as e:
            return _err(resp, 70007, f"install failed: {e}")
        if raft is not None:
            # raft-replicated region: the install MUST ride the log — a
            # direct engine write on one replica would fork it from peers
            # applying concurrent raft traffic (the apply handler also
            # rebuilds derived indexes on every replica)
            from dingo_tpu.engine import write_data as wd

            install = wd.RegionInstallData(
                cfs=[(cf, list(pairs)) for cf, pairs in state.items()])
            try:
                self.node.engine.write(region, install, timeout=60.0)
            except NotLeader as e:
                # election raced the upload: 20001 so the client rotates
                # to the new leader instead of aborting the restore
                return _err(resp, 20001, f"not leader: {e}")
            except (TimeoutError, RuntimeError) as e:
                return _err(resp, 70007, f"install propose failed: {e}")
            return resp
        try:
            region_install(self.node.raw, region, state)
        except (ValueError, OSError) as e:
            return _err(resp, 70007, f"install failed: {e}")
        self.node.after_region_install(region)
        return resp

    def RegionSnapshot(self, req: pb.RegionSnapshotRequest):
        resp = pb.RegionSnapshotResponse()
        region = self.node.get_region(req.region_id)
        if region is None:
            return _err(resp, 10001, f"region {req.region_id} not found")
        if region.vector_index_wrapper is None:
            return _err(resp, 70001, "region has no vector index")
        try:
            resp.path = self.node.index_manager.save_index(region)
        except (AssertionError, OSError) as e:
            return _err(resp, 70002, f"snapshot failed: {e}")
        return resp

    def RegionRebuildIndex(self, req: pb.RegionRebuildIndexRequest):
        resp = pb.RegionRebuildIndexResponse()
        region = self.node.get_region(req.region_id)
        if region is None:
            return _err(resp, 10001, f"region {req.region_id} not found")
        if region.vector_index_wrapper is not None:
            _rebuild_region(self.node, region)
        elif region.document_index is not None:
            self.node.rebuild_document_index(region)
        else:
            return _err(resp, 70001, "region has no index")
        return resp

    def RegionDetail(self, req: pb.RegionDetailRequest):
        resp = pb.RegionDetailResponse()
        region = self.node.get_region(req.region_id)
        if region is None:
            return _err(resp, 10001, f"region {req.region_id} not found")
        resp.definition.CopyFrom(convert.region_def_to_pb(region.definition))
        resp.state = region.state.value
        raft = self.node.engine.get_node(region.id)
        if raft is not None:
            resp.is_leader = raft.is_leader()
            resp.raft_term = raft.current_term
            resp.raft_commit_index = raft.commit_index
            resp.raft_last_applied = raft.last_applied
        wrapper = region.vector_index_wrapper
        if wrapper is not None and wrapper.own_index is not None:
            resp.index_count = wrapper.own_index.get_count()
            resp.index_apply_log_id = wrapper.apply_log_id
        resp.change_log.extend(
            f"{ts:.3f} {msg}" for ts, msg in region.change_log[-20:]
        )
        return resp
