"""CoprocessorV2: typed-schema filter/projection/aggregation pushdown
(reference coprocessor_v2.h + aggregation.h; scan-with-coprocessor suites
under test/unit_test/misc/)."""

import numpy as np
import pytest

from dingo_tpu.coprocessor.coprocessor_v2 import (
    AggOpV2,
    AggregationSpec,
    CoprocessorDef,
    CoprocessorError,
    CoprocessorV2,
    SchemaColumn,
    decode_row,
    encode_row,
)

SCHEMA = [
    SchemaColumn("id", "BIGINT", 0),
    SchemaColumn("dept", "VARCHAR", 1),
    SchemaColumn("salary", "DOUBLE", 2),
    SchemaColumn("active", "BOOL", 3),
]

ROWS = [
    [1, "eng", 100.0, True],
    [2, "eng", 150.0, True],
    [3, "ops", 90.0, False],
    [4, "ops", None, True],
    [5, "hr", 120.0, True],
]


def kvs():
    return [(f"k{r[0]}".encode(), encode_row(r)) for r in ROWS]


def test_row_roundtrip():
    for r in ROWS:
        assert decode_row(encode_row(r), 4) == r


def test_filter_and_projection():
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        selection=[1, 2],
        filter_expr=["and", ["eq", ["field", "active"], ["const", True]],
                     ["ge", ["field", "salary"], ["const", 100.0]]],
    ))
    out = cop.execute(kvs())
    assert [k for k, _ in out] == [b"k1", b"k2", b"k5"]
    assert decode_row(out[0][1], 2) == ["eng", 100.0]


def test_group_by_aggregation():
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        group_by=[1],
        aggregations=[
            AggregationSpec(AggOpV2.COUNT, -1),
            AggregationSpec(AggOpV2.SUM, 2),
            AggregationSpec(AggOpV2.MAX, 2),
            AggregationSpec(AggOpV2.COUNT_WITH_NULL, 2),
        ],
    ))
    out = dict(cop.execute(kvs()))
    eng = decode_row(out[encode_row(["eng"])], 4)
    assert eng == [2, 250.0, 150.0, 2]
    ops = decode_row(out[encode_row(["ops"])], 4)
    # SUM skips the NULL salary; COUNT(*) counts both rows;
    # COUNT_WITH_NULL counts rows regardless of NULL
    assert ops == [2, 90.0, 90.0, 2]


def test_global_aggregation_and_sum0():
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        filter_expr=["eq", ["field", "dept"], ["const", "nope"]],
        aggregations=[AggregationSpec(AggOpV2.SUM0, 2)],
    ))
    out = cop.execute(kvs())
    assert out == []  # no group materialized for an empty result set
    cop2 = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        aggregations=[AggregationSpec(AggOpV2.SUM0, 2),
                      AggregationSpec(AggOpV2.MIN, 2)],
    ))
    out = cop2.execute(kvs())
    assert len(out) == 1 and out[0][0] == b""
    assert decode_row(out[0][1], 2) == [460.0, 90.0]


def test_bad_definitions_rejected():
    with pytest.raises(CoprocessorError):
        CoprocessorV2(CoprocessorDef(original_schema=SCHEMA, selection=[9]))
    with pytest.raises(CoprocessorError):
        CoprocessorV2(CoprocessorDef(
            original_schema=SCHEMA,
            aggregations=[AggregationSpec(AggOpV2.SUM, 7)],
        ))


def test_scan_with_coprocessor_over_grpc():
    """KvScan carrying a Coprocessor: filter+project and aggregate paths
    (reference scan-with-coprocessor, scan_manager v2)."""
    import time

    from dingo_tpu.client import DingoClient
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.coordinator.kv_control import KvControl
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.raft import LocalTransport, wire
    from dingo_tpu.server import pb
    from dingo_tpu.server.rpc import DingoServer
    from dingo_tpu.store.node import StoreNode

    me = MemEngine()
    control = CoordinatorControl(me, replication=1)
    cs = DingoServer()
    cs.host_coordinator_role(control, TsoControl(me), KvControl(me))
    cport = cs.start()
    node = StoreNode("s0", LocalTransport(), control, raft_kw={"seed": 0})
    srv = DingoServer()
    srv.host_store_role(node)
    port = srv.start()
    node.start_heartbeat(0.1)
    client = DingoClient(f"127.0.0.1:{cport}", {"s0": f"127.0.0.1:{port}"})
    try:
        req = pb.CreateRegionRequest()
        req.range.start_key = b"r"
        req.range.end_key = b"s"
        assert client.coordinator.CreateRegion(req).error.errcode == 0
        time.sleep(1.0)
        for k, v in kvs():
            client.kv_put(b"r/" + k, v)

        sreq = pb.KvScanRequest()
        d = client._region_for_key(b"r/")
        sreq.context.region_id = d.region_id
        sreq.range.start_key = b"r"
        sreq.range.end_key = b"s"
        for c in SCHEMA:
            col = sreq.coprocessor.original_schema.add()
            col.name, col.sql_type, col.index = c.name, c.sql_type, c.index
        sreq.coprocessor.selection.extend([0, 2])
        sreq.coprocessor.filter_expr = wire.encode(
            ["gt", ["field", "salary"], ["const", 95.0]]
        )
        resp = client._call_leader(d, "StoreService", "KvScan", sreq)
        assert resp.error.errcode == 0
        got = [decode_row(kv.value, 2) for kv in resp.kvs]
        assert got == [[1, 100.0], [2, 150.0], [5, 120.0]]

        # aggregation arm
        areq = pb.KvScanRequest()
        areq.context.region_id = d.region_id
        areq.range.start_key = b"r"
        areq.range.end_key = b"s"
        for c in SCHEMA:
            col = areq.coprocessor.original_schema.add()
            col.name, col.sql_type, col.index = c.name, c.sql_type, c.index
        areq.coprocessor.group_by.append(1)
        a = areq.coprocessor.aggregations.add()
        a.op, a.column_index = 2, -1  # COUNT(*)
        resp = client._call_leader(d, "StoreService", "KvScan", areq)
        counts = {kv.key: decode_row(kv.value, 1)[0] for kv in resp.kvs}
        assert counts[encode_row(["eng"])] == 2
        assert counts[encode_row(["ops"])] == 2
        assert counts[encode_row(["hr"])] == 1
    finally:
        client.close()
        srv.stop()
        cs.stop()
        node.stop()


# -- expression depth (reference libexpr RelRunner op coverage,
#    coprocessor_v2.cc:209-216) ------------------------------------------------

def test_expr_functions_and_cast():
    from dingo_tpu.coprocessor.expr import Expr

    row = {"a": -3, "b": 2.5, "s": "Ab", "n": None}
    cases = [
        (["abs", ["field", "a"]], 3),
        (["neg", ["field", "a"]], 3),
        (["floor", ["field", "b"]], 2),
        (["ceil", ["field", "b"]], 3),
        (["sqrt", ["const", 9.0]], 3.0),
        (["pow", ["const", 2], ["const", 10]], 1024),
        (["lower", ["field", "s"]], "ab"),
        (["upper", ["field", "s"]], "AB"),
        (["length", ["field", "s"]], 2),
        (["concat", ["field", "s"], ["const", "!"]], "Ab!"),
        (["substr", ["const", "hello"], ["const", 1], ["const", 3]], "ell"),
        (["cast", "BIGINT", ["const", "42"]], 42),
        (["cast", "DOUBLE", ["field", "a"]], -3.0),
        (["cast", "VARCHAR", ["field", "a"]], "-3"),
        (["if", ["gt", ["field", "a"], ["const", 0]],
          ["const", "pos"], ["const", "neg"]], "neg"),
    ]
    for tree, want in cases:
        assert Expr(tree).eval(row) == want, tree


def test_expr_unknown_semantics():
    """Type/domain errors make the predicate unknown (row filtered) and the
    projection NULL — SQL semantics, not a crash."""
    from dingo_tpu.coprocessor.expr import Expr

    row = {"a": 1, "s": "x", "n": None}
    unknowns = [
        ["div", ["field", "a"], ["const", 0]],        # division by zero
        ["sqrt", ["const", -1.0]],                    # math domain
        ["lower", ["field", "a"]],                    # wrong type
        ["add", ["field", "a"], ["field", "s"]],      # int + str
        ["cast", "BIGINT", ["const", "xyz"]],         # bad cast
        ["abs", ["field", "n"]],                      # null operand
        ["exp", ["const", 1e6]],                      # overflow
    ]
    for tree in unknowns:
        e = Expr(tree)
        assert e.matches(row) is False, tree
        assert e.eval_or_null(row) is None, tree


def test_expression_projection():
    """selection entries can be expr trees: computed output columns."""
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        selection=[0, ["mul", ["field", "salary"], ["const", 2.0]],
                   ["upper", ["field", "dept"]]],
        filter_expr=["eq", ["field", "dept"], ["const", "eng"]],
    ))
    out = cop.execute(kvs())
    assert [decode_row(v, 3) for _, v in out] == [
        [1, 200.0, "ENG"], [2, 300.0, "ENG"]]


def test_expression_projection_null_on_error():
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        selection=[0, ["add", ["field", "salary"], ["const", 1.0]]],
    ))
    out = dict(cop.execute(kvs()))
    assert decode_row(out[b"k4"], 2) == [4, None]   # NULL salary -> NULL


def test_aggregation_over_expression():
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        group_by=[1],
        aggregations=[
            AggregationSpec(AggOpV2.SUM,
                            expr=["mul", ["field", "salary"], ["const", 2.0]]),
            AggregationSpec(AggOpV2.MAX,
                            expr=["length", ["field", "dept"]]),
        ],
    ))
    out = {k: decode_row(v, 2) for k, v in cop.execute(kvs())}
    assert out[encode_row(["eng"])] == [500.0, 3]
    assert out[encode_row(["ops"])] == [180.0, 3]   # NULL salary skipped


def test_projection_over_wire_proto():
    """pb.Coprocessor.projections + AggregationSpec.expr reach the engine."""
    from dingo_tpu.raft import wire
    from dingo_tpu.server import convert
    from dingo_tpu.server import dingo_pb2 as pb

    m = pb.Coprocessor()
    for c in SCHEMA:
        col = m.original_schema.add()
        col.name, col.sql_type, col.index = c.name, c.sql_type, c.index
    p = m.projections.add(); p.column_index = 0
    p = m.projections.add()
    p.expr = wire.encode(["add", ["field", "salary"], ["const", 5.0]])
    a = m.aggregations.add()
    a.op = AggOpV2.SUM.value
    a.expr = wire.encode(["mul", ["field", "salary"], ["const", 0.5]])
    cop = convert.coprocessor_from_pb(m)
    # projections path (aggregations ignored when testing project directly)
    assert cop.project([1, "eng", 100.0, True]) == [1, 105.0]
    assert cop._agg_exprs[0] is not None


def _py_source(node):
    """Translate an expr tree to equivalent Python source (the oracle)."""
    op = node[0]
    if op == "const":
        return repr(node[1])
    if op == "field":
        return f"row[{node[1]!r}]"
    # SQL three-valued connectives: the oracle uses Kleene truth-table
    # helpers over lazily-evaluated operands (plain Python and/or/not are
    # two-valued and would diverge on NULL/unknown operands)
    if op == "not":
        return f"_not3(lambda: {_py_source(node[1])})"
    if op == "and":
        return ("_and3(" + ", ".join(
            f"lambda: {_py_source(a)}" for a in node[1:]) + ")")
    if op == "or":
        return ("_or3(" + ", ".join(
            f"lambda: {_py_source(a)}" for a in node[1:]) + ")")
    if op == "is_null":
        return f"_isnull({_py_source(node[1])})"
    args = [_py_source(a) for a in node[1:]]
    pyop = {"eq": "==", "ne": "!=", "lt": "<", "le": "<=", "gt": ">",
            "ge": ">=", "add": "+", "sub": "-", "mul": "*", "div": "/",
            "mod": "%"}
    if op in pyop:
        return f"(_nn({args[0]}) {pyop[op]} _nn({args[1]}))"
    fn = {"abs": "abs", "floor": "math.floor", "ceil": "math.ceil",
          "sqrt": "math.sqrt", "exp": "math.exp", "ln": "math.log"}
    if op in fn:
        # numeric functions: _pynum mirrors the VM's _num (rejects bools
        # and non-numbers) so the oracle is exactly as strict as the VM
        return f"{fn[op]}(_pynum({args[0]}))"
    sfn = {"length": "len", "lower": "str.lower", "upper": "str.upper"}
    if op in sfn:
        return f"{sfn[op]}(_nn({args[0]}))"
    assert op == "neg", op
    return f"_pyneg({args[0]})"


def test_expr_property_vs_python_eval():
    """Random expression trees evaluate identically to plain Python eval
    (or both classify the row as unknown)."""
    import math
    import random

    from dingo_tpu.coprocessor.expr import Expr

    rng = random.Random(7)
    fields = ["a", "b", "c", "s", "t", "n"]

    def gen(depth):
        if depth == 0 or rng.random() < 0.25:
            if rng.random() < 0.5:
                return ["field", rng.choice(fields)]
            return ["const", rng.choice(
                [0, 1, 7, -3, 2.5, -0.5, "x", "Hello", True, None])]
        op = rng.choice(
            ["eq", "ne", "lt", "le", "gt", "ge", "add", "sub", "mul",
             "div", "mod", "and", "or", "not", "is_null", "abs", "neg",
             "floor", "ceil", "sqrt", "exp", "ln", "length", "lower",
             "upper"])
        if op in ("not", "is_null", "abs", "neg", "floor", "ceil",
                  "sqrt", "exp", "ln", "length", "lower", "upper"):
            return [op, gen(depth - 1)]
        if op in ("and", "or"):
            return [op, gen(depth - 1), gen(depth - 1)]
        return [op, gen(depth - 1), gen(depth - 1)]

    def _nn(v):
        if v is None:
            raise TypeError("null operand")
        return v

    def _check_str(v):
        if not isinstance(v, str):
            raise TypeError("not a string")
        return v

    def _tv3(thunk):
        """Three-valued truth of an operand: True/False/None(unknown)."""
        try:
            v = thunk()
        except Exception:
            return None
        return None if v is None else bool(v)

    def _and3(*thunks):
        unknown = False
        for t in thunks:
            v = _tv3(t)
            if v is None:
                unknown = True
            elif not v:
                return False
        if unknown:
            raise TypeError("unknown")
        return True

    def _or3(*thunks):
        unknown = False
        for t in thunks:
            v = _tv3(t)
            if v is None:
                unknown = True
            elif v:
                return True
        if unknown:
            raise TypeError("unknown")
        return False

    def _not3(thunk):
        v = _tv3(thunk)
        if v is None:
            raise TypeError("unknown")
        return not v

    def _pynum(v):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise TypeError("expected number")
        return v

    env = {
        "math": math, "_nn": _nn, "_isnull": lambda v: v is None,
        "_and3": _and3, "_or3": _or3, "_not3": _not3,
        "_pynum": _pynum, "_pyneg": lambda v: -_pynum(v),
    }
    # str.lower/upper only accept str (mirrors the VM's type checks); abs
    # etc. reject bool via the VM but Python allows abs(True) — restrict
    # generated rows so bools never reach numeric ops' edge (rows below
    # have no bare bool fields).
    rows = [
        {"a": 3, "b": -2, "c": 0, "s": "Ab", "t": "zz", "n": None},
        {"a": -1, "b": 2.5, "c": 7, "s": "", "t": "Q", "n": None},
        {"a": 10, "b": 0.5, "c": -4, "s": "mIx", "t": "mix", "n": 5},
    ]
    checked = 0
    for _ in range(4000):
        tree = gen(3)
        try:
            e = Expr(tree)
        except Exception:
            continue
        src = _py_source(tree)
        for row in rows:
            try:
                # row must live in globals: lambda thunks created inside
                # eval resolve names against their __globals__, not the
                # locals mapping
                want = eval(src, {**env, "row": row})
                want_err = False
            except Exception:
                want_err = True
            try:
                got = e.eval(row)
                got_err = False
            except (TypeError, ValueError, ArithmeticError):
                got_err = True
            if want_err or got_err:
                # both sides must agree the value is unknown — the oracle's
                # helpers are built to be exactly as strict as the VM
                assert want_err == got_err, (
                    tree, src, row, got if not got_err else None)
                continue
            assert got == want or (got != got and want != want), (
                tree, src, row, got, want)
            checked += 1
    assert checked > 1000   # the comparison actually exercised real values


def test_filter_row_unknown_does_not_crash_scan():
    """Regression: a div-by-zero inside the filter expression must classify
    the row as unknown (filtered), not raise out of the scan RPC."""
    cop = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        filter_expr=["gt", ["div", ["const", 1.0],
                            ["sub", ["field", "salary"], ["const", 90.0]]],
                     ["const", 0.0]],
    ))
    out = cop.execute(kvs())
    assert [k for k, _ in out] == [b"k1", b"k2", b"k5"]


def test_pow_and_bool_cast_edge_cases():
    """Review regressions: pow never yields complex (SQL POWER is a double,
    domain errors are unknown) and CAST('false' AS BOOL) is false."""
    from dingo_tpu.coprocessor.expr import Expr

    assert Expr(["pow", ["const", 2], ["const", 10]]).eval({}) == 1024.0
    neg_frac = Expr(["pow", ["const", -8.0], ["const", 0.5]])
    assert neg_frac.eval_or_null({}) is None         # not complex
    assert neg_frac.matches({}) is False
    huge = Expr(["pow", ["const", 10], ["const", 10 ** 9]])
    assert huge.eval_or_null({}) is None             # overflow -> unknown

    assert Expr(["cast", "BOOL", ["const", "false"]]).eval({}) is False
    assert Expr(["cast", "BOOL", ["const", "TRUE"]]).eval({}) is True
    assert Expr(["cast", "BOOL", ["const", "0"]]).eval({}) is False
    assert Expr(["cast", "BOOL", ["const", "maybe"]]).eval_or_null({}) is None
    assert Expr(["cast", "BOOL", ["const", 0]]).eval({}) is False


def test_if_null_condition_takes_else():
    """SQL CASE: unknown condition selects the ELSE branch, not NULL."""
    from dingo_tpu.coprocessor.expr import Expr

    e = Expr(["if", ["gt", ["field", "x"], ["const", 0]],
              ["const", "a"], ["const", "b"]])
    assert e.eval({"x": None}) == "b"
    assert e.eval({"x": 5}) == "a"


def test_three_valued_logic():
    """SQL Kleene logic: NOT NULL is unknown; FALSE AND unknown is FALSE;
    TRUE OR unknown is TRUE; TRUE AND unknown is unknown."""
    from dingo_tpu.coprocessor.expr import Expr

    row = {"n": None, "t": 1, "f": 0}
    assert Expr(["not", ["field", "n"]]).eval_or_null(row) is None
    assert Expr(["and", ["field", "f"], ["field", "n"]]).eval(row) is False
    assert Expr(["or", ["field", "t"], ["field", "n"]]).eval(row) is True
    assert Expr(["and", ["field", "t"], ["field", "n"]]).eval_or_null(row) is None
    assert Expr(["or", ["field", "f"], ["field", "n"]]).eval_or_null(row) is None
    # an erroring operand is unknown, absorbed the same way
    err = ["div", ["const", 1], ["const", 0]]
    assert Expr(["and", ["field", "f"], err]).eval(row) is False
    assert Expr(["or", ["field", "t"], err]).eval(row) is True


def test_projection_encode_guard():
    """Computed values the codec can't represent faithfully are a
    CoprocessorError (caught by the scan RPC), never silent corruption."""
    overflow = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        selection=[["mul", ["field", "id"], ["const", 10 ** 19]]],
    ))
    with pytest.raises(CoprocessorError, match="overflows int64"):
        overflow.execute(kvs())
    unencodable = CoprocessorV2(CoprocessorDef(
        original_schema=SCHEMA,
        selection=[["const", [1, 2]]],   # list consts exist for "in"
    ))
    with pytest.raises(CoprocessorError, match="unencodable"):
        unencodable.execute(kvs())


def test_cast_bytes_to_varchar_decodes_utf8():
    from dingo_tpu.coprocessor.expr import Expr

    assert Expr(["cast", "VARCHAR", ["const", b"abc"]]).eval({}) == "abc"
    bad = Expr(["cast", "VARCHAR", ["const", b"\xff\xfe"]])
    assert bad.eval_or_null({}) is None   # not utf-8 -> unknown


def test_malformed_projection_expr_rejected():
    """A Projection.expr decoding to a scalar must be a 60001 bad-coprocessor
    error, not silently treated as a column index."""
    from dingo_tpu.raft import wire
    from dingo_tpu.server import convert
    from dingo_tpu.server import dingo_pb2 as pb

    m = pb.Coprocessor()
    for c in SCHEMA:
        col = m.original_schema.add()
        col.name, col.sql_type, col.index = c.name, c.sql_type, c.index
    p = m.projections.add()
    p.expr = wire.encode(2)   # scalar, not a tree
    with pytest.raises(ValueError, match="not a tree"):
        convert.coprocessor_from_pb(m)
