"""Operator region-op RPCs (MergeRegion / ChangePeerRegion /
TransferLeaderRegion) + VectorImport, driven over gRPC and the CLI on a
live 3-store cluster (reference: src/server/coordinator_service.cc region
ops; index_service.h:57 VectorImport)."""

import json
import time

import numpy as np
import pytest

from dingo_tpu.client.client import DingoClient
from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.coordinator.kv_control import KvControl
from dingo_tpu.coordinator.tso import TsoControl
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.raft import LocalTransport
from dingo_tpu.server import pb
from dingo_tpu.server.rpc import DingoServer
from dingo_tpu.store.node import StoreNode


@pytest.fixture()
def cluster():
    transport = LocalTransport()
    me = MemEngine()
    control = CoordinatorControl(me, replication=3)
    coord_server = DingoServer()
    coord_server.host_coordinator_role(control, TsoControl(me), KvControl(me))
    coord_port = coord_server.start()

    nodes, servers, addrs = {}, [], {}
    for i, sid in enumerate(["s0", "s1", "s2"]):
        node = StoreNode(sid, transport, control, raft_kw={"seed": i})
        server = DingoServer()
        server.host_store_role(node)
        port = server.start()
        node.start_heartbeat(0.1)
        nodes[sid] = node
        servers.append(server)
        addrs[sid] = f"127.0.0.1:{port}"

    client = DingoClient(f"127.0.0.1:{coord_port}", addrs)
    yield client, control, nodes, addrs, coord_port
    client.close()
    for s in servers:
        s.stop()
    coord_server.stop()
    for n in nodes.values():
        n.stop()


def _cli_base(client, addrs, coord_port):
    base = ["--coordinator", f"127.0.0.1:{coord_port}"]
    for sid, addr in addrs.items():
        base += ["--store", f"{sid}={addr}"]
    return base


def _region_leader(nodes, rid, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for sid, n in nodes.items():
            raft = n.engine.get_node(rid)
            if raft is not None and raft.is_leader():
                return sid
        time.sleep(0.05)
    raise AssertionError(f"no leader for region {rid}")


def test_cli_split_merge_roundtrip(cluster, capsys):
    """CLI: split an index region, then merge the child back — data
    survives, the region map returns to one region."""
    from dingo_tpu.client.cli import main

    client, control, nodes, addrs, coord_port = cluster
    base = _cli_base(client, addrs, coord_port)

    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    client.create_index_region(0, 0, 1 << 40, param)
    time.sleep(1.0)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((120, 8)).astype(np.float32)
    client.vector_add(0, list(range(120)), x)

    client.refresh_region_map()
    parent = next(d for d in client._regions
                  if d.index_parameter is not None)
    assert main(base + ["region", "split", "--region",
                        str(parent.region_id), "--at", "60"]) == 0
    child_id = json.loads(capsys.readouterr().out)["child_region_id"]
    time.sleep(1.5)   # split applies + child elects
    assert client.vector_count(0) == 120

    # CLI merge: parent absorbs the child back
    assert main(base + ["region", "merge", "--target",
                        str(parent.region_id), "--source",
                        str(child_id)]) == 0
    capsys.readouterr()
    deadline = time.monotonic() + 8.0
    while time.monotonic() < deadline:
        client.refresh_region_map()
        live = [d for d in client._regions if d.index_parameter is not None]
        if len(live) == 1 and live[0].region_id == parent.region_id:
            break
        time.sleep(0.1)
    client.refresh_region_map()
    live = [d for d in client._regions if d.index_parameter is not None]
    assert len(live) == 1 and live[0].region_id == parent.region_id
    # all 120 vectors searchable through the merged region
    assert client.vector_count(0) == 120
    res = client.vector_search(0, x[[10, 90]], topk=3)
    assert res[0][0][0] == 10
    assert res[1][0][0] == 90


def test_cli_transfer_leader(cluster, capsys):
    """CLI: move a region's raft leadership to a chosen store."""
    from dingo_tpu.client.cli import main

    client, control, nodes, addrs, coord_port = cluster
    base = _cli_base(client, addrs, coord_port)
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    d = client.create_index_region(1, 0, 1 << 40, param)
    time.sleep(1.2)
    rid = d.region_id
    leader = _region_leader(nodes, rid)
    target = next(s for s in nodes if s != leader)

    assert main(base + ["region", "transfer-leader", "--region",
                        str(rid), "--store", target]) == 0
    capsys.readouterr()
    deadline = time.monotonic() + 8.0
    while time.monotonic() < deadline:
        if _region_leader(nodes, rid) == target:
            break
        time.sleep(0.1)
    assert _region_leader(nodes, rid) == target


def test_change_peer_region(cluster):
    """ChangePeerRegion with replication=2: move a replica to the spare
    store; the new peer catches up and serves the data."""
    client, control, nodes, addrs, coord_port = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    d = client.create_index_region(2, 0, 1 << 40, param, replication=2)
    time.sleep(1.2)
    rid = d.region_id
    rng = np.random.default_rng(1)
    x = rng.standard_normal((40, 8)).astype(np.float32)
    client.vector_add(2, list(range(40)), x)

    old_peers = set(d.peers)
    spare = next(s for s in nodes if s not in old_peers)
    victim = sorted(old_peers)[0]
    new_peers = sorted((old_peers - {victim}) | {spare})
    client.change_peer_region(rid, new_peers)

    deadline = time.monotonic() + 10.0
    ok = False
    while time.monotonic() < deadline and not ok:
        n = nodes[spare]
        raft = n.engine.get_node(rid)
        reg = n.engine._regions.get(rid) if raft is not None else None
        if reg is not None:
            from dingo_tpu.engine.storage import Storage

            try:
                if Storage(n.engine).vector_count(reg) == 40:
                    ok = True
                    break
            except Exception:
                pass
        time.sleep(0.2)
    assert ok, f"spare store {spare} never caught up"
    client.refresh_region_map()
    d2 = next(r for r in client._regions if r.region_id == rid)
    assert set(d2.peers) == set(new_peers)


def test_vector_import_bulk(cluster):
    """VectorImport: bulk upserts + deletes in one RPC."""
    client, control, nodes, addrs, coord_port = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    client.create_index_region(3, 0, 1 << 40, param)
    time.sleep(1.0)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((100, 8)).astype(np.float32)
    out = client.vector_import(
        3, ids=list(range(100)), vectors=x,
        scalars=[{"i": i} for i in range(100)])
    assert out == {"added": 100, "deleted": 0}
    assert client.vector_count(3) == 100

    out = client.vector_import(3, delete_ids=[0, 1, 2])
    assert out["deleted"] == 3
    assert client.vector_count(3) == 97

    # import = upsert: re-import id 5 with a new vector
    x5 = rng.standard_normal((1, 8)).astype(np.float32)
    client.vector_import(3, ids=[5], vectors=x5)
    res = client.vector_search(3, x5, topk=1)
    assert res[0][0][0] == 5


def test_region_op_validation(cluster):
    """Operator typos fail loudly: unknown store in change-peers, non-peer
    target in transfer-leader."""
    from dingo_tpu.client.client import ClientError

    client, control, nodes, addrs, coord_port = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
        metric_type=pb.METRIC_TYPE_L2,
    )
    d = client.create_index_region(4, 0, 1 << 40, param, replication=2)
    time.sleep(1.0)
    with pytest.raises(ClientError, match="unknown stores"):
        client.change_peer_region(d.region_id, ["s0", "stroe2"])
    non_peer = next(s for s in nodes if s not in d.peers)
    with pytest.raises(ClientError, match="not a peer"):
        client.transfer_leader_region(d.region_id, non_peer)
    with pytest.raises(ClientError, match="not a peer"):
        client.transfer_leader_region(d.region_id, "ghost")
