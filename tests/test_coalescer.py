"""Search request coalescing: concurrent same-shaped searches share one
device batch (SURVEY §2.6 'batching window to fill the device')."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from dingo_tpu.common.coalescer import CoalescerStopped, SearchCoalescer


def test_coalesces_within_window():
    calls = []

    def run(key, stacked):
        calls.append(len(stacked))
        return [("row", key, float(q.sum())) for q in stacked]

    co = SearchCoalescer(run, window_ms=20.0)
    try:
        with ThreadPoolExecutor(8) as pool:
            futs = [
                pool.submit(
                    lambda i=i: co.submit(
                        "k", np.full((2, 4), i, np.float32)
                    ).result(timeout=5)
                )
                for i in range(8)
            ]
            results = [f.result() for f in futs]
        # all 16 queries ran in very few underlying batches
        assert sum(calls) == 16
        assert len(calls) <= 3, calls
        # each caller got exactly its own rows back
        for i, rows in enumerate(results):
            assert len(rows) == 2
            assert all(r[2] == float(i * 4) for r in rows)
    finally:
        co.stop()


def test_distinct_keys_do_not_mix():
    seen = {}

    def run(key, stacked):
        seen.setdefault(key, 0)
        seen[key] += len(stacked)
        return [key] * len(stacked)

    co = SearchCoalescer(run, window_ms=10.0)
    try:
        f1 = co.submit("a", np.zeros((3, 2), np.float32))
        f2 = co.submit("b", np.zeros((2, 2), np.float32))
        assert f1.result(timeout=5) == ["a"] * 3
        assert f2.result(timeout=5) == ["b"] * 2
        assert seen == {"a": 3, "b": 2}
    finally:
        co.stop()


def test_max_batch_flushes_immediately():
    calls = []

    def run(key, stacked):
        calls.append(len(stacked))
        return list(range(len(stacked)))

    co = SearchCoalescer(run, window_ms=10_000.0, max_batch=4)
    try:
        t0 = time.monotonic()
        f = co.submit("k", np.zeros((4, 2), np.float32))
        f.result(timeout=5)
        assert time.monotonic() - t0 < 1.0  # no window wait at max_batch
        assert calls == [4]
    finally:
        co.stop()


def test_run_errors_propagate_to_all_waiters():
    def run(key, stacked):
        raise ValueError("boom")

    co = SearchCoalescer(run, window_ms=5.0)
    try:
        f1 = co.submit("k", np.zeros((1, 2), np.float32))
        f2 = co.submit("k", np.zeros((1, 2), np.float32))
        for f in (f1, f2):
            with pytest.raises(ValueError, match="boom"):
                f.result(timeout=5)
    finally:
        co.stop()


def test_service_layer_coalescing():
    """Concurrent identical VectorSearch RPCs share one storage search."""
    from dingo_tpu.client import DingoClient
    from dingo_tpu.common.config import FLAGS
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.coordinator.kv_control import KvControl
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.raft import LocalTransport
    from dingo_tpu.server import pb
    from dingo_tpu.server.rpc import DingoServer
    from dingo_tpu.store.node import StoreNode

    me = MemEngine()
    control = CoordinatorControl(me, replication=1)
    cs = DingoServer()
    cs.host_coordinator_role(control, TsoControl(me), KvControl(me))
    cport = cs.start()
    node = StoreNode("s0", LocalTransport(), control, raft_kw={"seed": 0})
    srv = DingoServer()
    srv.host_store_role(node)
    port = srv.start()
    node.start_heartbeat(0.1)
    client = DingoClient(f"127.0.0.1:{cport}", {"s0": f"127.0.0.1:{port}"})
    calls = []
    orig = node.storage.vector_batch_search

    def counting(region, queries, topn, **kw):
        calls.append(len(queries))
        return orig(region, queries, topn, **kw)

    node.storage.vector_batch_search = counting
    FLAGS.set("search_coalescing_window_ms", 25.0)
    try:
        param = pb.VectorIndexParameter(
            index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
            metric_type=pb.METRIC_TYPE_L2,
        )
        client.create_index_region(0, 0, 1 << 30, param)
        time.sleep(1.0)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((100, 8)).astype(np.float32)
        client.vector_add(0, list(range(100)), x)
        calls.clear()

        def one_search(i):
            res = client.vector_search(0, x[[i]], topk=3)
            return res[0][0][0]

        with ThreadPoolExecutor(8) as pool:
            got = list(pool.map(one_search, range(8)))
        assert got == list(range(8))          # each caller got ITS result
        assert sum(calls) == 8
        assert len(calls) < 8, calls          # at least some coalescing
    finally:
        FLAGS.set("search_coalescing_window_ms", 0.0)
        client.close()
        srv.stop()
        cs.stop()
        node.stop()


def test_per_submit_cap_splits_batches():
    """Merged batches must not exceed the per-key cap each request
    respects alone (storage topn*batch guard)."""
    calls = []

    def run(key, stacked):
        calls.append(len(stacked))
        return list(range(len(stacked)))

    co = SearchCoalescer(run, window_ms=50.0, max_batch=1024)
    try:
        f1 = co.submit("k", np.zeros((6, 2), np.float32), max_batch=8)
        f2 = co.submit("k", np.zeros((6, 2), np.float32), max_batch=8)
        assert len(f1.result(timeout=5)) == 6
        assert len(f2.result(timeout=5)) == 6
        assert all(c <= 8 for c in calls), calls
    finally:
        co.stop()


def test_cap_displaced_batch_does_not_block_submitter():
    """A submission that displaces a full previous batch must not run that
    batch's search inline — the displaced batch flushes on the timer
    thread while the new caller's submit returns immediately."""
    import threading
    import time as _time

    release = threading.Event()
    started = threading.Event()

    def run(key, stacked):
        if len(stacked) == 6:          # the displaced batch
            started.set()
            assert release.wait(5)
        return list(range(len(stacked)))

    # window long enough that the 6-row batch CANNOT flush by expiry
    # between the two submits (the displacement path must actually run)
    co = SearchCoalescer(run, window_ms=500.0, max_batch=1024)
    try:
        f1 = co.submit("k", np.zeros((6, 2), np.float32), max_batch=8)
        t0 = _time.monotonic()
        f2 = co.submit("k", np.zeros((4, 2), np.float32), max_batch=8)
        submit_s = _time.monotonic() - t0
        # the displaced batch's (blocked) search runs elsewhere
        assert submit_s < 1.0, submit_s
        assert started.wait(5)
        assert not f1.done()           # still blocked in run_fn
        release.set()
        assert len(f1.result(timeout=5)) == 6
        assert len(f2.result(timeout=5)) == 4
    finally:
        co.stop()


@pytest.mark.parametrize("qos", [False, True])
def test_submit_racing_stop_never_hangs(qos):
    """ISSUE 10 regression: a submit racing stop(drain=False) must get a
    deterministic CoalescerStopped future — never slip into a queue whose
    flush thread is already gone and hang its caller. The admitted-vs-
    stopped decision is made atomically under the queue lock at APPEND
    time, so the QoS admission work a submit now does between "am I
    stopped?" and "append" cannot make the answer stale (the qos=True arm
    exercises exactly that widened window)."""
    from dingo_tpu.common.config import FLAGS

    FLAGS.set("qos_enabled", qos)
    try:
        for trial in range(6):
            def run(key, stacked):
                return list(range(len(stacked)))

            co = SearchCoalescer(run, window_ms=1.0)
            start = threading.Barrier(5)
            futs: list = []
            flock = threading.Lock()

            def submitter():
                start.wait()
                for _ in range(40):
                    f = co.submit("k", np.zeros((1, 2), np.float32))
                    with flock:
                        futs.append(f)

            threads = [threading.Thread(target=submitter)
                       for _ in range(4)]
            for t in threads:
                t.start()
            start.wait()
            # vary the interleaving: stop lands anywhere from "before the
            # first submit ran" to "mid-storm"
            time.sleep(0.0015 * trial)
            co.stop(drain=False)
            for t in threads:
                t.join(timeout=10)
                assert not t.is_alive()
            assert len(futs) == 160
            served = stopped = 0
            for f in futs:
                # every future resolves deterministically within a bound:
                # a result (flushed before the stop) or CoalescerStopped
                try:
                    f.result(timeout=5)
                    served += 1
                except CoalescerStopped:
                    stopped += 1
            assert served + stopped == 160
    finally:
        FLAGS.set("qos_enabled", False)


def test_pipelined_pending_batches_drain_on_stop():
    """stop(drain=True) before the window expires, pipelined mode: the
    never-dispatched pending batches still resolve to real results (the
    leftovers drain through the serial arm; the completion lane honors
    the same contract for anything already dispatched)."""
    from dingo_tpu.common.config import FLAGS

    FLAGS.set("pipeline_enabled", "true")
    try:
        def dispatch(key, stacked, staged=None):
            return lambda: list(range(len(stacked)))

        co = SearchCoalescer(lambda k, q: list(range(len(q))),
                             window_ms=10_000.0, dispatch_fn=dispatch)
        futs = [co.submit("k", np.zeros((2, 4), np.float32))
                for _ in range(3)]
        co.stop(drain=True)
        for f in futs:
            assert len(f.result(timeout=5)) == 2
    finally:
        FLAGS.set("pipeline_enabled", "auto")


def test_pipelined_submit_stop_race_storm():
    """The submit-vs-stop determinism contract holds with the pipelined
    arm on: every future resolves to a result or CoalescerStopped — no
    hangs on the flush thread OR the completion lane."""
    from dingo_tpu.common.config import FLAGS

    FLAGS.set("pipeline_enabled", "true")
    try:
        for trial in range(6):
            def dispatch(key, stacked, staged=None):
                return lambda: list(range(len(stacked)))

            co = SearchCoalescer(lambda k, q: list(range(len(q))),
                                 window_ms=1.0, dispatch_fn=dispatch)
            start = threading.Barrier(4)
            futs: list = []
            flock = threading.Lock()

            def submitter():
                start.wait()
                for _ in range(30):
                    f = co.submit("k", np.zeros((1, 2), np.float32))
                    with flock:
                        futs.append(f)

            threads = [threading.Thread(target=submitter)
                       for _ in range(3)]
            for t in threads:
                t.start()
            start.wait()
            time.sleep(0.0015 * trial)
            co.stop(drain=(trial % 2 == 0))
            for t in threads:
                t.join(timeout=10)
                assert not t.is_alive()
            assert len(futs) == 90
            for f in futs:
                try:
                    f.result(timeout=5)
                except CoalescerStopped:
                    pass
    finally:
        FLAGS.set("pipeline_enabled", "auto")
