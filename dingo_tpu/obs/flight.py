"""Flight recorder: a bounded in-memory black box for bad moments.

When a p99 outlier, a search error, or a device OOM happens, the state an
operator needs — the offending trace's spans, what the metrics were doing
in the seconds before, which kernels were compiling, how HBM was
distributed — is gone by the time anyone looks. The flight recorder
snapshots all of it AT the trigger into a compressed bundle and keeps the
last ``obs.flight_max_bundles`` of them.

Triggers (all rate-limited per reason so a slow-query storm records one
representative bundle, not hundreds):
- slow query crossing ``slow_query_ms`` (hooked from the tracer's slow
  log, sampled or not);
- a search/RPC error (hooked from the server's generic handler and the
  reader's in-band error arm);
- a device allocation failure (hooked from the hbm ledger).

A bundle carries: trigger metadata, the triggering trace's spans (or the
recent slow-log tail when unsampled), metric DELTAS over the last
``obs.flight_buffer_s`` seconds (computed against the periodic tick ring
the metrics collector drives), the recompile sentinel's kernel cache
state, the hbm ledger, and flags/region config. Payload = zlib(JSON) —
shipped by the DebugService ``FlightDump`` RPC, rendered by
``tools/flight_report.py``.

Metric latency series carry EXEMPLARS (trace-id attachments on outlier
samples, see common/metrics.py), so a Prometheus scrape links a bad
bucket -> trace id -> bundle in one hop each.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.log import get_logger
from dingo_tpu.common.metrics import METRICS

_log = get_logger("obs.flight")

#: minimum spacing between bundles of the SAME reason (storm control)
MIN_TRIGGER_INTERVAL_S = 1.0

#: spans attached to a bundle when the trigger has no trace id (unsampled
#: slow query): the recent slow-log tail plus newest ring spans
_UNTRACED_SPAN_LIMIT = 64


def _bundle_id() -> str:
    return f"fb-{int(time.time()):x}-{os.urandom(3).hex()}"


def _flatten_numeric(dump: Dict[str, Any]) -> Dict[str, float]:
    """MetricsRegistry.dump() -> flat numeric view: counters/gauges as-is,
    latency stats keep their count/sum (the delta-able parts)."""
    out: Dict[str, float] = {}
    for key, val in dump.items():
        if isinstance(val, (int, float)):
            out[key] = float(val)
        elif isinstance(val, dict):
            for sub in ("count", "sum_us"):
                if sub in val:
                    out[f"{key}.{sub}"] = float(val[sub])
    return out


class FlightRecorder:
    def __init__(self, registry=METRICS):
        self.registry = registry
        self._lock = threading.Lock()
        #: (meta dict, compressed payload bytes), newest last (a list,
        #: not a deque: eviction is reason-aware, see _trigger)
        self._bundles: List = []
        #: (monotonic, wall_ms, flat numeric metrics) tick ring
        self._ticks: deque = deque()
        self._last_trigger: Dict[str, float] = {}
        #: optional provider of region/index config for bundles — the
        #: server wires node state here; tests inject dicts
        self.config_provider: Optional[Callable[[], Dict[str, Any]]] = None

    # ---- metrics tick ring -------------------------------------------------
    def tick(self, dump: Optional[Dict[str, Any]] = None) -> None:
        """Sample the metrics registry into the delta ring. Driven by the
        store-metrics crontab; call directly in tests/tools."""
        window = float(FLAGS.get("obs_flight_buffer_s"))
        now = time.monotonic()
        flat = _flatten_numeric(dump if dump is not None
                                else self.registry.dump())
        with self._lock:
            self._ticks.append((now, int(time.time() * 1000), flat))
            # keep one tick OLDER than the window so a trigger right after
            # pruning still has a full-window baseline
            while (len(self._ticks) > 2
                   and now - self._ticks[1][0] > window):
                self._ticks.popleft()

    def _metrics_delta(self, now_flat: Optional[Dict[str, float]] = None
                       ) -> Dict[str, Any]:
        if now_flat is None:
            now_flat = _flatten_numeric(self.registry.dump())
        with self._lock:
            base = self._ticks[0] if self._ticks else None
        if base is None:
            return {"window_s": 0.0, "deltas": {}, "note": "no ticks yet"}
        base_t, _base_ms, base_flat = base
        deltas = {}
        for key, val in now_flat.items():
            d = val - base_flat.get(key, 0.0)
            if d:
                deltas[key] = round(d, 3)
        return {
            "window_s": round(time.monotonic() - base_t, 1),
            "deltas": deltas,
        }

    def _family_state(self, now_flat: Dict[str, float],
                      prefix: str) -> Dict[str, Any]:
        """Current values of one curated series family — ABSOLUTE values,
        unlike the delta window: a slow-query bundle must show the shard
        balance / graph-walk health at capture time, not only how it
        moved during the window. Shares the capture's single registry
        dump. Captured families: mesh.* (shard rows, skew, replica
        routing), hnsw.* (hops, visited fraction, beam occupancy,
        adjacency rebuilds), and quality.* (live recall/CI/RBO + tuner
        knob positions — was the store trading recall when the incident
        hit?), qos.* (queue depth/wait, shed/expired counters, degrade
        level — was the store under pressure, and what had admission
        already given up on?), cache.* (hit/miss/dedupe/stale/
        semantic counters, resident bytes — was the serving-edge cache
        absorbing the skewed traffic or churning?), heat.* (traffic
        concentration + working-set bytes — was the incident load skewed
        onto a hot core, and how much of the region did it actually
        touch?), cost.* (learned per-kernel dispatch costs — what did
        the coalescer believe a row cost when it made its admission
        calls?), and capacity.* (coordinator headroom/advisory rollups
        when the bundle fires coordinator-side)."""
        return {k: v for k, v in now_flat.items() if k.startswith(prefix)}

    @staticmethod
    def _integrity_state() -> Dict[str, Any]:
        """Per-region digest vectors + scrub verdicts at capture time
        (obs/integrity.py): a divergence/corruption bundle must carry the
        actual digest vectors of both sides, not only the counters."""
        try:
            from dingo_tpu.obs.integrity import INTEGRITY

            return INTEGRITY.state()
        except Exception:  # noqa: BLE001 — black box must never raise
            return {}

    @staticmethod
    def _events_state() -> list:
        """The newest control-plane decisions at capture time
        (obs/events.py): an anomaly bundle should answer "what did the
        controllers just DO" without a second RPC — the knob walk that
        led into the episode is usually the diagnosis."""
        try:
            import dataclasses as _dc

            from dingo_tpu.obs.events import EVENTS

            return [_dc.asdict(e) for e in EVENTS.last_before(32)]
        except Exception:  # noqa: BLE001 — black box must never raise
            return []

    # ---- triggers ----------------------------------------------------------
    def on_slow_query(self, rec: Dict[str, Any]) -> str:
        """Tracer hook: `rec` is the slow-log record (sampled span or the
        synthesized unsampled one)."""
        extra = {"dur_ms": round(rec.get("dur_us", 0) / 1000.0, 1)}
        prune = self._pruned_fractions()
        if prune:
            # a slow scan with pruning barely engaging (fraction ~0) is
            # a different diagnosis than one pruning hard — carry the
            # per-region gauge right in the trigger meta so the bundle
            # answers it even when no metrics collector tick ring runs
            # (bench, tests)
            extra["pruned_dim_fraction"] = prune
        return self.trigger(
            "slow_query",
            trace_id=rec.get("trace_id", ""),
            name=rec.get("name", ""),
            extra=extra,
        )

    @staticmethod
    def _pruned_fractions() -> Dict[str, float]:
        """Current ivf.pruned_dim_fraction gauge per series (empty when
        the pruned scan never ran)."""
        from dingo_tpu.common.metrics import METRICS

        out = {}
        with METRICS._lock:
            items = list(METRICS._gauges.items())
        for key, g in items:
            if key.startswith("ivf.pruned_dim_fraction"):
                out[key] = round(g.get(), 4)
        return out

    def on_rpc_error(self, span_name: str, exc: BaseException,
                     span=None) -> str:
        from dingo_tpu.obs.hbm import looks_like_oom

        trace_id = ""
        live = None
        if span is not None and getattr(span, "sampled", False):
            trace_id = f"{span.trace_id:016x}"
            # the failing ingress span hasn't ENDED yet (we run inside
            # its except arm), so the buffer snapshot can't contain it —
            # synthesize its in-flight record or the bundle would show a
            # trace with children but no failing root
            live = {
                "name": span.name,
                "trace_id": trace_id,
                "span_id": f"{span.span_id:016x}",
                "parent_id": (f"{span.parent_id:016x}"
                              if span.parent_id else ""),
                "start_us": span.start_ns // 1000,
                "dur_us": int(span.duration_us()),
                "thread": span.thread_id,
                "status": span.status if span.status != "ok"
                else f"error: {type(exc).__name__}",
                "attrs": {**span.attrs, "in_flight": True},
            }
        return self.trigger(
            "device_oom" if looks_like_oom(exc) else "error",
            trace_id=trace_id,
            name=span_name,
            extra={"error": f"{type(exc).__name__}: {exc}"[:2000]},
            live_span=live,
        )

    def trigger(self, reason: str, trace_id: str = "", name: str = "",
                region_id: int = 0,
                extra: Optional[Dict[str, Any]] = None,
                live_span: Optional[Dict[str, Any]] = None) -> str:
        """Capture a bundle; returns its id, or "" when rate-limited or
        disabled (obs.flight_max_bundles = 0). `live_span` is the
        in-flight (not-yet-ended) triggering span's record, appended to
        the trace snapshot. Never raises."""
        try:
            return self._trigger(reason, trace_id, name, region_id, extra,
                                 live_span)
        except Exception:  # noqa: BLE001 — the black box must never be
            _log.exception("flight trigger failed")  # the crash
            return ""

    def _trigger(self, reason, trace_id, name, region_id, extra,
                 live_span=None) -> str:
        max_bundles = int(FLAGS.get("obs_flight_max_bundles"))
        if max_bundles <= 0:
            return ""
        now = time.monotonic()
        with self._lock:
            last = self._last_trigger.get(reason, 0.0)
            if now - last < MIN_TRIGGER_INTERVAL_S:
                self.registry.counter(
                    "flight.suppressed", labels={"reason": reason}
                ).add(1)
                return ""
            self._last_trigger[reason] = now

        from dingo_tpu.obs.sentinel import SENTINEL
        from dingo_tpu.obs.hbm import HBM
        from dingo_tpu.trace import TRACE_BUFFER

        spans_fallback = False
        if trace_id:
            spans = TRACE_BUFFER.snapshot(trace_id=trace_id)
            if live_span is not None and not any(
                    s.get("span_id") == live_span["span_id"] for s in spans):
                spans = spans + [live_span]
            if not spans:
                # nothing of the trace finished and no live record — the
                # recent ring tail is the best available context
                spans = TRACE_BUFFER.snapshot(limit=_UNTRACED_SPAN_LIMIT)
                spans_fallback = True
        else:
            spans = TRACE_BUFFER.snapshot(limit=_UNTRACED_SPAN_LIMIT)
            spans_fallback = True
        config: Dict[str, Any] = {"flags": FLAGS.all()}
        if self.config_provider is not None:
            try:
                config["node"] = self.config_provider()
            except Exception:  # noqa: BLE001
                config["node"] = {"error": "config provider failed"}

        bid = _bundle_id()
        # ONE registry dump per capture, shared by the delta window and
        # the absolute mesh state (capture fires exactly when the store
        # is struggling — don't walk the registry twice)
        now_flat = _flatten_numeric(self.registry.dump())
        payload = {
            "id": bid,
            "reason": reason,
            "name": name,
            "trace_id": trace_id,
            "region_id": region_id,
            "created_ms": int(time.time() * 1000),
            "trigger": extra or {},
            "spans": spans,
            "spans_fallback": spans_fallback,
            "slow_queries": TRACE_BUFFER.slow_queries()[-8:],
            "metrics": self._metrics_delta(now_flat),
            "kernel_cache": SENTINEL.state(),
            "hbm": HBM.state(),
            "mesh": self._family_state(now_flat, "mesh."),
            "hnsw": self._family_state(now_flat, "hnsw."),
            "quality": self._family_state(now_flat, "quality."),
            "qos": self._family_state(now_flat, "qos."),
            "consistency": self._family_state(now_flat, "consistency."),
            "cache": self._family_state(now_flat, "cache."),
            "heat": self._family_state(now_flat, "heat."),
            "cost": self._family_state(now_flat, "cost."),
            "capacity": self._family_state(now_flat, "capacity."),
            "integrity": self._integrity_state(),
            "events": self._events_state(),
            "config": config,
        }
        blob = zlib.compress(
            json.dumps(payload, default=str).encode("utf-8"), 6
        )
        meta = {
            "id": bid,
            "reason": reason,
            "name": name,
            "trace_id": trace_id,
            "region_id": region_id,
            "created_ms": payload["created_ms"],
            "payload_bytes": len(blob),
        }
        with self._lock:
            self._bundles.append((meta, blob))
            while len(self._bundles) > max_bundles:
                # reason-aware eviction: a storm of one reason (generic
                # rpc errors at the rate limit) must not flush the single
                # device_oom/slow_query bundle an operator actually needs
                # — evict the oldest bundle of a reason that still has
                # duplicates; only when every reason is down to one,
                # evict the oldest overall
                counts: Dict[str, int] = {}
                for m, _ in self._bundles:
                    counts[m["reason"]] = counts.get(m["reason"], 0) + 1
                victim = next(
                    (i for i, (m, _) in enumerate(self._bundles)
                     if counts[m["reason"]] > 1),
                    0,
                )
                del self._bundles[victim]
        self.registry.counter("flight.bundles",
                              labels={"reason": reason}).add(1)
        return bid

    # ---- access ------------------------------------------------------------
    def bundles_meta(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(meta) for meta, _ in self._bundles]

    def get(self, bundle_id: str = "") -> Optional[bytes]:
        """Compressed payload by id (newest bundle when id is empty)."""
        found = self.get_with_id(bundle_id)
        return found[1] if found else None

    def get_with_id(self, bundle_id: str = ""):
        """(id, payload) resolved under ONE lock hold, so 'newest' and
        its id can't diverge when a trigger lands concurrently."""
        with self._lock:
            if not self._bundles:
                return None
            if not bundle_id:
                meta, blob = self._bundles[-1]
                return meta["id"], blob
            for meta, blob in self._bundles:
                if meta["id"] == bundle_id:
                    return meta["id"], blob
        return None

    def get_json(self, bundle_id: str = "") -> Optional[Dict[str, Any]]:
        blob = self.get(bundle_id)
        if blob is None:
            return None
        return json.loads(zlib.decompress(blob).decode("utf-8"))

    def clear(self) -> None:
        with self._lock:
            self._bundles.clear()
            self._ticks.clear()
            self._last_trigger.clear()


FLIGHT = FlightRecorder()


def black_box_error(span_name: str, exc: BaseException, span=None,
                    region_id: int = 0) -> str:
    """One-call error black-box for rpc/search failure arms. Encodes the
    ordering contract ONCE: on_rpc_error first (its bundle carries the
    victim's trace id), then the hbm ledger only COUNTS an OOM
    (capture=False — a trace-less device_oom bundle captured first would
    win the per-reason rate limit). Never raises."""
    try:
        from dingo_tpu.obs.hbm import HBM

        bid = FLIGHT.on_rpc_error(span_name, exc, span)
        HBM.on_alloc_failure(exc, context=span_name, region_id=region_id,
                             capture=False)
        return bid
    except Exception:  # noqa: BLE001 — never mask the original error
        return ""
