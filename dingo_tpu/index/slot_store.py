"""Device-resident slot store: the IndexIDMap2 equivalent.

The reference wraps faiss indexes in faiss::IndexIDMap2 (vector_index_flat.h:
57-127) to map external vector ids <-> internal sequential slots. Here the
mapping is split to fit TPU + XLA realities (measured on the axon tunnel:
row-scatter into a [131072,128] array ≈ 385 ms, device->host materialization
≈ 60-80 ms per call, H2D ≈ 230 MB/s):

  host side   — ids_by_slot np.int64[capacity] (-1 = empty) + dict id->slot +
                free-slot list + validity bitmap. 64-bit external ids NEVER
                go on device (JAX x64-off truncates them); kernels work in
                slot space and the host translates slots->ids after top-k.
                The validity bitmap lives host-side and is lazily refreshed
                to device only when dirty (uploading [cap] bools is far
                cheaper than TPU scatter).
  device side — vecs[capacity, d] and sqnorm[capacity] f32 (cached ||x||^2),
                updated by contiguous-run dynamic_update_slice writes with
                donated buffers (TPU scatter is the slow path; appends are
                contiguous because free slots are handed out ascending).

Capacity grows by doubling (static shapes per power-of-two bucket keep the
XLA compile cache bounded — SURVEY.md §7 'capacity-bucketed arrays').
Deletes are tombstones in the host bitmap; compaction happens on
save/rebuild, mirroring the reference's rebuild-on-too-many-deletes policy.
"""

from __future__ import annotations

import threading

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from dingo_tpu.obs.sentinel import sentinel_jit

MIN_CAPACITY = 4096
#: Max rows per dynamic_update_slice program (pads to pow2 buckets up to this).
MAX_WRITE_BUCKET = 4096


@sentinel_jit("index.slot_store.write_run",
              static_argnames=("nrows",), donate_argnums=(0, 1))
def _write_run(vecs, sqnorm, rows, start, lo, hi, nrows):
    """Blend rows[lo:hi] of the padded [nrows] window into vecs/sqnorm at
    window position `start` (i.e. slots start+lo .. start+hi-1).

    Rows outside [lo, hi) keep the old content — the pad can sit at either
    end, which lets the caller shift the window left at the capacity
    boundary instead of letting dynamic_update_slice clamp (a clamped start
    silently lands the write one slot off and corrupts neighbors).
    Donated buffers -> in-place on device.

    sqnorm caches the norms of the STORED rows (post dtype cast): a bf16
    store's scan kernels read bf16-quantized values, so ||bf16(x)||^2 is
    the self-consistent cache — the sq8 tier's decoded-norm convention
    applied to the bf16 tier (norms of the original f32 rows drift ~1e-3
    relative, which breaks the pruned scan's partial-sum bookkeeping and
    mis-ranks near-ties either way)."""
    d = vecs.shape[1]
    stored = rows.astype(vecs.dtype)
    rows32 = stored.astype(jnp.float32)
    old = lax.dynamic_slice(vecs, (start, 0), (nrows, d))
    idx = jnp.arange(nrows)
    keep = (idx >= lo) & (idx < hi)
    blend = jnp.where(keep[:, None], stored, old)
    vecs = lax.dynamic_update_slice(vecs, blend, (start, 0))
    sq = jnp.einsum(
        "ld,ld->l", rows32, rows32, precision=jax.lax.Precision.HIGHEST
    )
    old_sq = lax.dynamic_slice(sqnorm, (start,), (nrows,))
    sqnorm = lax.dynamic_update_slice(
        sqnorm, jnp.where(keep, sq, old_sq), (start,)
    )
    return vecs, sqnorm


@sentinel_jit("index.slot_store.write_run_presq",
              static_argnames=("nrows",), donate_argnums=(0, 1))
def _write_run_presq(vecs, sqnorm, rows, row_sq, start, lo, hi, nrows):
    """`_write_run` variant taking PRECOMPUTED row norms: quantized stores
    write uint8 codes but must cache the norms of the DECODED rows (the
    values the distance kernels actually scan), which the device cannot
    derive from the codes row-dtype-agnostically. Same window/blend/donate
    contract as _write_run."""
    d = vecs.shape[1]
    old = lax.dynamic_slice(vecs, (start, 0), (nrows, d))
    idx = jnp.arange(nrows)
    keep = (idx >= lo) & (idx < hi)
    blend = jnp.where(keep[:, None], rows.astype(vecs.dtype), old)
    vecs = lax.dynamic_update_slice(vecs, blend, (start, 0))
    old_sq = lax.dynamic_slice(sqnorm, (start,), (nrows,))
    sqnorm = lax.dynamic_update_slice(
        sqnorm, jnp.where(keep, row_sq, old_sq), (start,)
    )
    return vecs, sqnorm


@sentinel_jit("index.slot_store.write_run_blk",
              static_argnames=("nrows",), donate_argnums=(0, 1))
def _write_run_blk(vecs_blk, bsq_blk, rows_blk, row_bsq, start, lo, hi, nrows):
    """Blocked-mirror arm of _write_run: blend rows [lo, hi) of the padded
    window into the dimension-blocked arrays ([nblk, capacity, dblk] data +
    [nblk, capacity] per-block norms) at window position `start` along the
    slot axis. Same window/blend/donate contract as _write_run."""
    nblk, _, dblk = vecs_blk.shape
    old = lax.dynamic_slice(vecs_blk, (0, start, 0), (nblk, nrows, dblk))
    idx = jnp.arange(nrows)
    keep = (idx >= lo) & (idx < hi)
    blend = jnp.where(keep[None, :, None], rows_blk.astype(vecs_blk.dtype),
                      old)
    vecs_blk = lax.dynamic_update_slice(vecs_blk, blend, (0, start, 0))
    old_b = lax.dynamic_slice(bsq_blk, (0, start), (nblk, nrows))
    bsq_blk = lax.dynamic_update_slice(
        bsq_blk, jnp.where(keep[None, :], row_bsq, old_b), (0, start)
    )
    return vecs_blk, bsq_blk


class SlotStore:
    def __init__(self, dim: int, dtype=jnp.float32, capacity: int = MIN_CAPACITY,
                 blocked: Optional[bool] = None):
        self.dim = dim
        self.dtype = dtype
        self.capacity = max(MIN_CAPACITY, _next_pow2(capacity))
        # Dimension-blocked scan mirror (PDX vertical layout, ops/blocked.py):
        # [nblk, capacity, dblk] data + [nblk, capacity] per-block norms,
        # read by the pruned FLAT streaming kernel. Decided once at
        # construction (conf vector.blocked_layout; `blocked` forces) —
        # None when off / dtype unsupported / dimension doesn't block.
        self.dim_block: Optional[int] = None
        self.nblk = 0
        self.vecs_blk: Optional[jax.Array] = None
        self.bsq_blk: Optional[jax.Array] = None
        if blocked is None:
            from dingo_tpu.common.config import blocked_layout_enabled

            blocked = blocked_layout_enabled()
        if blocked and self._blocked_dtype_ok():
            from dingo_tpu.ops.blocked import resolve_dim_block

            self.dim_block = resolve_dim_block(dim)
            if self.dim_block:
                self.nblk = dim // self.dim_block
                self.vecs_blk = jnp.zeros(
                    (self.nblk, self.capacity, self.dim_block), self.dtype
                )
                self.bsq_blk = jnp.zeros(
                    (self.nblk, self.capacity), jnp.float32
                )
        # Graph adjacency mirror (device HNSW tier, index/hnsw.py): dense
        # [capacity, deg] int32 slot-space neighbor lists, -1 padded, read
        # by the batched beam kernel (ops/beam.py). Installed/refreshed by
        # set_graph(); grows with capacity like the blocked mirror above.
        self.graph_deg = 0
        self.adj: Optional[jax.Array] = None
        # Monotonic host-mutation counter: bumped by put/remove/growth.
        # Cache keys that depend on the slot<->id mapping (the HNSW
        # filter-mask cache, the device adjacency mirror) key on it the
        # way IVF caches key on view.version.
        self.mutation_version = 0
        self.vecs, self.sqnorm = self._alloc_storage(self.capacity)
        self.ids_by_slot = np.full((self.capacity,), -1, np.int64)
        self.valid_h = np.zeros((self.capacity,), np.bool_)
        self._dmask: Optional[jax.Array] = None   # lazy device copy of valid_h
        self._id_to_slot: dict[int, int] = {}
        self._free: list[int] = list(range(self.capacity - 1, -1, -1))
        # Epoch-based reclamation: slots freed while searches are in flight
        # park in limbo so an async resolve never sees a reassigned slot
        # (it translates them to -1/dropped instead of to the wrong id).
        self._inflight: int = 0
        self._limbo: list[int] = []
        # Guards the _inflight/_limbo/_free transitions: end_search's
        # check-then-drain and remove_slots' limbo-vs-free choice are
        # read-modify-write pairs, and with the serving pipeline's
        # completion lane they run on a thread of their own — unlocked,
        # a release racing a writer could drain a slot to _free while
        # the search that must still translate it is in flight.
        self._lease_lock = threading.Lock()
        # Serializes DONATED device writes against kernel dispatch: the DUS
        # write path donates vecs/sqnorm (invalidating the old Array), so a
        # concurrent search must not dispatch with a stale reference (the
        # reference uses a per-index RWLock, vector_index_flat.h:129).
        # Held only across dispatch, never across device execution.
        self.device_lock = threading.RLock()
        # H2D hook for the write programs' row upload: default is a plain
        # jnp.asarray; the tier ladder's promotion path temporarily swaps
        # in a staging-ring uploader (common/pipeline.StagingRing) so bulk
        # code ingest overlaps the previous chunk's donated write program
        # instead of serializing copy-then-dispatch (index/tiering.py).
        self._upload = jnp.asarray

    # -- storage hooks (HostSlotStore overrides with numpy) ----------------
    def _blocked_dtype_ok(self) -> bool:
        """Tiers whose scan kernels can read a blocked mirror: f32/bf16
        rows (binary ±1 int8 stays on the XLA path; HostSlotStore has no
        device arrays at all). SqSlotStore overrides — its uint8 codes
        decode inside the kernel."""
        return jnp.dtype(self.dtype) in (jnp.float32, jnp.bfloat16)

    def _alloc_storage(self, capacity: int):
        return (
            jnp.zeros((capacity, self.dim), self.dtype),
            jnp.zeros((capacity,), jnp.float32),
        )

    def _grow_storage(self, pad: int):
        return (
            jnp.concatenate(
                [self.vecs, jnp.zeros((pad, self.dim), self.dtype)]
            ),
            jnp.concatenate([self.sqnorm, jnp.zeros((pad,), jnp.float32)]),
        )

    # -- bookkeeping -------------------------------------------------------
    def __len__(self) -> int:
        return len(self._id_to_slot)

    def __contains__(self, vid: int) -> bool:
        return int(vid) in self._id_to_slot

    def slots_of(self, ids: np.ndarray) -> np.ndarray:
        return np.asarray(
            [self._id_to_slot.get(int(i), -1) for i in ids], np.int64
        )

    def ids_of_slots(self, slots: np.ndarray) -> np.ndarray:
        """Translate kernel-space slots (-1 allowed) back to external ids."""
        safe = np.where(slots >= 0, slots, 0)
        out = self.ids_by_slot[safe]
        return np.where(slots >= 0, out, -1)

    def device_mask(self) -> jax.Array:
        """Validity bitmap on device, refreshed only when host state changed."""
        if self._dmask is None:
            self._dmask = jnp.asarray(self.valid_h)
        return self._dmask

    def canonical_rows(self, rows: np.ndarray) -> np.ndarray:
        """Stored-form payload of prepped input rows — the exact bytes the
        device arrays hold after a put() of `rows` (the state-integrity
        ledger digests these so an incremental digest and a device-state
        readback agree bit-for-bit). Float stores cast to the storage
        dtype; SqSlotStore overrides to encode."""
        return np.asarray(rows).astype(np.dtype(self.dtype), copy=False)

    def memory_size(self) -> int:
        itemsize = jnp.zeros((), self.dtype).dtype.itemsize
        size = self.capacity * (self.dim * itemsize + 8 + 4 + 1)
        if self.vecs_blk is not None:
            # blocked scan mirror: one more copy of the rows + block norms
            size += self.capacity * (self.dim * itemsize + self.nblk * 4)
        if self.adj is not None:
            size += self.capacity * self.graph_deg * 4
        return size

    def set_graph(self, adj: np.ndarray, deg: int) -> None:
        """Install the slot-space adjacency mirror: [capacity, deg] int32
        neighbor slots, -1 padded. The owning index (TpuHnsw) builds it
        from the native graph export; a full swap (not a scatter) because
        one node insert can rewire arbitrary neighbors' lists."""
        if adj.shape != (self.capacity, deg):
            raise ValueError(
                f"adjacency shape {adj.shape} != ({self.capacity}, {deg})"
            )
        with self.device_lock:
            self.graph_deg = deg
            self.adj = jnp.asarray(adj, jnp.int32)

    def reserve(self, capacity: int) -> None:
        """Pre-size device arrays (bulk ingest avoids per-growth recompiles
        of the write program — each growth step re-specializes the DUS)."""
        if capacity > self.capacity:
            self._grow(capacity)

    # -- mutation ----------------------------------------------------------
    def put(self, ids: np.ndarray, vectors: np.ndarray) -> np.ndarray:
        """Insert/replace rows; returns assigned slots. Contiguous slot runs
        are written with dynamic_update_slice (fresh appends are one run);
        scattered overwrites degrade to per-run writes."""
        n = len(ids)
        if n == 0:
            return np.empty(0, np.int64)
        slots = np.empty(n, np.int64)
        for i, vid in enumerate(ids):
            vid = int(vid)
            s = self._id_to_slot.get(vid)
            if s is None:
                if not self._free:
                    self._grow(max(self.capacity * 2, _next_pow2(self.capacity + n)))
                s = self._free.pop()
                self._id_to_slot[vid] = s
                self.ids_by_slot[s] = vid
            slots[i] = s
        vectors = np.asarray(vectors)
        # Sort into ascending slot order, then split into contiguous runs.
        order = np.argsort(slots, kind="stable")
        sslots = slots[order]
        svecs = vectors[order]
        run_starts = np.flatnonzero(np.diff(sslots) != 1) + 1
        with self.device_lock:
            for seg_lo, seg_hi in zip(
                np.concatenate([[0], run_starts]),
                np.concatenate([run_starts, [n]]),
            ):
                self._write_segment(int(sslots[seg_lo]), svecs[seg_lo:seg_hi])
        self.valid_h[slots] = True
        self._dmask = None
        self.mutation_version += 1
        return slots

    def _write_segment(self, start: int, rows: np.ndarray) -> None:
        """One contiguous run, chunked into pow2 buckets <= MAX_WRITE_BUCKET.
        Callers arrive via put(), which holds device_lock."""
        off = 0
        total = rows.shape[0]
        while off < total:
            chunk = min(MAX_WRITE_BUCKET, total - off)
            bucket = min(MAX_WRITE_BUCKET, _next_pow2(chunk))
            padded = rows[off:off + chunk]
            if bucket != chunk:
                padded = np.concatenate(
                    [padded, np.zeros((bucket - chunk, self.dim), padded.dtype)]
                )
            win_start = start + off
            lo = 0
            if win_start + bucket > self.capacity:
                # Shift the window left so it stays in bounds; the pad moves
                # to the front (dynamic_update_slice would otherwise clamp
                # the start and shift the whole write — data corruption).
                lo = win_start + bucket - self.capacity
                win_start = self.capacity - bucket
                padded = np.roll(padded, lo, axis=0)
            self._dispatch_write(padded, win_start, lo, chunk, bucket)
            off += chunk

    def _dispatch_write(self, padded, win_start, lo, chunk, bucket) -> None:
        """One donated write program over a padded pow2 window (quantized
        stores override to supply precomputed decoded-row norms)."""
        self.vecs, self.sqnorm = _write_run(
            self.vecs,
            self.sqnorm,
            self._upload(padded),
            jnp.int32(win_start),
            jnp.int32(lo),
            jnp.int32(lo + chunk),
            nrows=bucket,
        )
        self._write_blocked(padded, None, win_start, lo, chunk, bucket)

    def _write_blocked(self, rows, rows_f32, win_start, lo, chunk,
                       bucket) -> None:
        """Mirror the same padded window into the blocked arrays (no-op
        when the mirror is off). `rows` carries what the device stores
        (codes for sq8); `rows_f32` the decoded values the norms must
        describe (None = derive by casting rows through the store dtype,
        matching _write_run's stored-row norm convention). Caller holds
        device_lock (the program donates)."""
        if self.vecs_blk is None:
            return
        from dingo_tpu.ops.blocked import block_sqnorms, to_blocked

        if rows_f32 is None:
            rows_f32 = np.asarray(rows)
            store_dt = jnp.zeros((), self.dtype).dtype
            if store_dt != np.float32:
                rows_f32 = rows_f32.astype(store_dt)
        rows_blk = to_blocked(np.asarray(rows), self.dim_block)
        bsq = block_sqnorms(
            np.asarray(rows_f32, np.float32), self.dim_block
        ).astype(np.float32)
        self.vecs_blk, self.bsq_blk = _write_run_blk(
            self.vecs_blk,
            self.bsq_blk,
            jnp.asarray(rows_blk),
            jnp.asarray(bsq),
            jnp.int32(win_start),
            jnp.int32(lo),
            jnp.int32(lo + chunk),
            nrows=bucket,
        )

    def remove(self, ids: np.ndarray) -> int:
        """Tombstone rows; returns number actually removed."""
        return int((self.remove_slots(ids) >= 0).sum())

    def remove_slots(self, ids: np.ndarray) -> np.ndarray:
        """Tombstone rows; returns the slot each id occupied (-1 for ids
        that were not present). Incremental view maintenance needs the
        freed slots to tombstone the matching bucket rows — returning them
        here avoids a second id->slot resolution pass before removal."""
        slots = np.full(len(ids), -1, np.int64)
        removed = 0
        with self._lease_lock:
            dest = self._limbo if self._inflight > 0 else self._free
            for i, vid in enumerate(ids):
                s = self._id_to_slot.pop(int(vid), None)
                if s is not None:
                    self.ids_by_slot[s] = -1
                    self.valid_h[s] = False
                    dest.append(s)
                    slots[i] = s
                    removed += 1
        if removed:
            self._dmask = None
            self.mutation_version += 1
        return slots

    # -- in-flight search accounting --------------------------------------
    def begin_search(self) -> "SearchLease":
        with self._lease_lock:
            self._inflight += 1
        return SearchLease(self)

    def end_search(self) -> None:
        with self._lease_lock:
            self._inflight -= 1
            if self._inflight == 0 and self._limbo:
                self._free.extend(self._limbo)
                self._limbo.clear()

    def _grow(self, new_capacity: int) -> None:
        new_capacity = _next_pow2(new_capacity)
        pad = new_capacity - self.capacity
        with self.device_lock:
            self.vecs, self.sqnorm = self._grow_storage(pad)
            if self.adj is not None:
                # slots are stable across growth: existing adjacency rows
                # stay correct, fresh capacity starts unlinked
                self.adj = jnp.concatenate(
                    [self.adj,
                     jnp.full((pad, self.graph_deg), -1, jnp.int32)]
                )
            if self.vecs_blk is not None:
                self.vecs_blk = jnp.concatenate(
                    [self.vecs_blk,
                     jnp.zeros((self.nblk, pad, self.dim_block), self.dtype)],
                    axis=1,
                )
                self.bsq_blk = jnp.concatenate(
                    [self.bsq_blk, jnp.zeros((self.nblk, pad), jnp.float32)],
                    axis=1,
                )
        self.ids_by_slot = np.concatenate(
            [self.ids_by_slot, np.full((pad,), -1, np.int64)]
        )
        self.valid_h = np.concatenate(
            [self.valid_h, np.zeros((pad,), np.bool_)]
        )
        self._dmask = None
        self._free.extend(range(new_capacity - 1, self.capacity - 1, -1))
        self.capacity = new_capacity
        # capacity is part of every [capacity]-shaped cached artifact
        # (filter masks, adjacency) — growth invalidates them all
        self.mutation_version += 1

    # -- host round-trips --------------------------------------------------
    def gather(self, ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch vectors by external id (found_mask, vectors)."""
        slots = self.slots_of(ids)
        found = slots >= 0
        safe = np.where(found, slots, 0)
        with self.device_lock:   # vecs reference is donatable
            vecs = np.asarray(
                jnp.take(self.vecs, jnp.asarray(safe, jnp.int32), axis=0)
            )
        return found, vecs

    def rows_device(self, slots: np.ndarray) -> jax.Array:
        """Decoded f32 rows at `slots` as a DEVICE array — the train-path
        gather (ISSUE 18b): samplers pick slot indices host-side (cheap
        ints) and the rows themselves never round-trip; only centroids
        come back. One take per call; quantized stores decode in-device."""
        with self.device_lock:   # vecs reference is donatable
            return jnp.take(
                self.vecs, jnp.asarray(slots, jnp.int32), axis=0
            ).astype(jnp.float32)

    def to_host(self) -> dict:
        """Compacted host snapshot {ids, vectors} of live rows (save path)."""
        live = self.ids_by_slot >= 0
        with self.device_lock:
            vecs_h = np.asarray(self.vecs)
        return {
            "ids": self.ids_by_slot[live],
            "vectors": vecs_h[live],
        }

    @classmethod
    def from_host(cls, dim: int, dtype, ids: np.ndarray, vectors: np.ndarray,
                  capacity: Optional[int] = None) -> "SlotStore":
        store = cls(dim, dtype, capacity or max(MIN_CAPACITY, len(ids)))
        if len(ids):
            store.put(np.asarray(ids, np.int64), vectors)
        return store


class SearchLease:
    """Pairs begin_search with exactly one end_search even when the caller
    drops the resolve thunk or resolve raises: release() is idempotent and
    __del__ backstops it at GC, so limbo can't starve the free list."""

    __slots__ = ("_store", "_done")

    def __init__(self, store: "SlotStore"):
        self._store = store
        self._done = False

    def release(self) -> None:
        if not self._done:
            self._done = True
            self._store.end_search()

    def __del__(self):  # noqa: D105
        self.release()


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


class HostSlotStore(SlotStore):
    """SlotStore variant keeping the vectors in HOST memory.

    For indexes whose SEARCH path never reads full vectors from the device
    (IVF_PQ serves from codes; DiskANN from disk), device-resident vectors
    only cap the index size at HBM: 10M x 768 f32 is ~30 GB, far beyond a
    v5e chip. This store keeps [capacity, d] in numpy; training/encoding
    stream chunks to the device, and the untrained exact fallback scans
    host chunks with a running top-k merge.
    """

    def _blocked_dtype_ok(self) -> bool:
        return False   # rows live in host RAM; no device scan mirror

    def _np_dtype(self):
        return np.dtype(jnp.zeros((), self.dtype).dtype.name)

    def _alloc_storage(self, capacity: int):
        return (
            np.zeros((capacity, self.dim), self._np_dtype()),
            np.zeros((capacity,), np.float32),
        )

    def _grow_storage(self, pad: int):
        return (
            np.concatenate(
                [self.vecs, np.zeros((pad, self.dim), self.vecs.dtype)]
            ),
            np.concatenate([self.sqnorm, np.zeros((pad,), np.float32)]),
        )

    def _write_segment(self, start: int, rows: np.ndarray) -> None:
        n = rows.shape[0]
        stored = rows.astype(self.vecs.dtype)
        rows32 = stored.astype(np.float32)   # stored-row norms (bf16 tier)
        self.vecs[start:start + n] = stored
        self.sqnorm[start:start + n] = (rows32 * rows32).sum(1)

    def gather(self, ids: np.ndarray):
        slots = self.slots_of(ids)
        found = slots >= 0
        safe = np.where(found, slots, 0)
        return found, self.vecs[safe]

    def rows_device(self, slots: np.ndarray) -> jax.Array:
        # rows live in host RAM: the gather itself is the upload
        rows = np.asarray(self.vecs[np.asarray(slots, np.int64)],
                          np.float32)
        return jnp.asarray(rows)

    def memory_size(self) -> int:
        # host bytes; device footprint is the caller's codes/centroids
        return int(self.vecs.nbytes + self.sqnorm.nbytes)


class SqSlotStore(SlotStore):
    """SlotStore whose device rows are SQ8 codes (uint8, 1 byte/dim —
    4x the vectors per chip vs f32; ops/sq.py codec).

    The external contract stays FLOAT: put()/gather()/to_host() speak f32
    rows (encode at the write boundary, decode at the read boundary), so
    index code above — training, reassignment, exact fallbacks — runs
    unchanged. Only the search kernels look at codes directly (via .vecs +
    .sq_vmin_d/.sq_scale_d), and sqnorm caches ||x̂||^2 of the DECODED
    surrogate so L2/cosine scores stay self-consistent with what the
    kernels scan.

    Codec params train lazily on the first write batch (min/max + margin,
    faiss train-once-clip-later convention) unless maybe_train()/
    set_params() installed them earlier (index.train with an explicit
    train set, or a snapshot load)."""

    def __init__(self, dim: int, dtype=jnp.uint8, capacity: int = MIN_CAPACITY,
                 blocked: Optional[bool] = None):
        if jnp.dtype(dtype) != jnp.uint8:
            raise ValueError("SqSlotStore stores uint8 codes")
        super().__init__(dim, jnp.uint8, capacity, blocked=blocked)
        self.sq_params = None            # ops.sq.SqParams (host)
        self._sq_vmin_d = None           # lazy device copies
        self._sq_scale_d = None
        #: (id(float rows), n, codes) of the latest put() — canonical_rows
        #: reuses it so the integrity ledger never re-encodes the batch
        self._canonical_memo = None

    # -- codec lifecycle ---------------------------------------------------
    def set_params(self, params) -> None:
        if self.sq_params is not None and len(self):
            raise RuntimeError(
                "cannot swap SQ params under live codes (re-ingest instead)"
            )
        self.sq_params = params
        self._sq_vmin_d = None
        self._sq_scale_d = None

    def maybe_train(self, rows: np.ndarray) -> None:
        """Install params from `rows` when none exist yet (no-op after)."""
        if self.sq_params is None and len(rows):
            from dingo_tpu.ops.sq import sq_train

            self.set_params(sq_train(np.asarray(rows, np.float32)))

    @property
    def sq_vmin_d(self) -> jax.Array:
        if self._sq_vmin_d is None:
            self._sq_vmin_d = jnp.asarray(self.sq_params.vmin)
        return self._sq_vmin_d

    @property
    def sq_scale_d(self) -> jax.Array:
        if self._sq_scale_d is None:
            self._sq_scale_d = jnp.asarray(self.sq_params.scale)
        return self._sq_scale_d

    def encode(self, rows: np.ndarray) -> np.ndarray:
        from dingo_tpu.ops.sq import sq_encode

        return sq_encode(rows, self.sq_params)

    def decode(self, codes: np.ndarray) -> np.ndarray:
        from dingo_tpu.ops.sq import sq_decode

        return sq_decode(codes, self.sq_params)

    # -- float-facing mutation/read paths ----------------------------------
    def put(self, ids: np.ndarray, vectors: np.ndarray) -> np.ndarray:
        self.maybe_train(vectors)
        codes = self.encode(np.asarray(vectors, np.float32))
        # memo for canonical_rows: the integrity ledger digests the SAME
        # batch right after put() with the SAME float array object —
        # re-encoding it would double the write path's quantization cost
        # for bytes that are identical by construction
        self._canonical_memo = (id(vectors), len(codes), codes)
        return super().put(ids, codes)

    def put_codes(self, ids: np.ndarray, codes: np.ndarray) -> np.ndarray:
        """Raw-code ingest (snapshot load): bypasses encode so a saved
        code array round-trips bit-exactly."""
        assert self.sq_params is not None, "set_params before put_codes"
        return super().put(ids, np.asarray(codes, np.uint8))

    def canonical_rows(self, rows: np.ndarray) -> np.ndarray:
        """Stored payload = the CODES (what the device actually holds and
        the scan kernels decode); the integrity ledger's 'rows' artifact
        for an sq8 store therefore digests codes — a single flipped code
        byte is a rows-artifact mismatch. Reuses the codes the
        immediately-preceding put() of the SAME array object produced
        (memo consumed on use; put() always refreshes it first, so a
        recycled object id can never pair with stale codes)."""
        memo = getattr(self, "_canonical_memo", None)
        if memo is not None and memo[0] == id(rows) \
                and memo[1] == len(rows):
            self._canonical_memo = None
            return memo[2]
        return self.encode(np.asarray(rows, np.float32))

    def _blocked_dtype_ok(self) -> bool:
        # codes mirror blocks fine: the pruned kernel decodes per tile
        return True

    def _dispatch_write(self, padded, win_start, lo, chunk, bucket) -> None:
        # padded rows are CODES here; norms come from the decoded surrogate
        deq = self.decode(padded)
        row_sq = np.einsum("ld,ld->l", deq, deq).astype(np.float32)
        self.vecs, self.sqnorm = _write_run_presq(
            self.vecs,
            self.sqnorm,
            self._upload(padded),
            jnp.asarray(row_sq),
            jnp.int32(win_start),
            jnp.int32(lo),
            jnp.int32(lo + chunk),
            nrows=bucket,
        )
        # blocked mirror scatters the CODES; the per-block norms describe
        # the decoded surrogate the pruned kernel actually accumulates
        self._write_blocked(padded, deq, win_start, lo, chunk, bucket)

    def gather(self, ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        found, codes = super().gather(ids)
        return found, self.decode(np.asarray(codes, np.uint8))

    def rows_device(self, slots: np.ndarray) -> jax.Array:
        from dingo_tpu.ops.sq import sq_decode_device

        with self.device_lock:
            codes = jnp.take(
                self.vecs, jnp.asarray(slots, jnp.int32), axis=0
            )
            if self.sq_params is None:   # no writes yet: nothing to decode
                return codes.astype(jnp.float32)
            return sq_decode_device(
                codes, self.sq_vmin_d, self.sq_scale_d, dtype=jnp.float32
            )

    def to_host(self) -> dict:
        """Decoded float snapshot — the safe default for callers that mean
        'give me the vectors' (train sampling, rebuild). Use
        codes_to_host() for the compact persistence form."""
        snap = super().to_host()
        if self.sq_params is None:
            # untrained codec == no writes ever happened; the live set is
            # empty and there is nothing to decode (an unconditional
            # decode would dereference the missing params)
            snap["vectors"] = np.zeros_like(snap["vectors"], np.float32)
        else:
            snap["vectors"] = self.decode(snap["vectors"])
        return snap

    def codes_to_host(self) -> dict:
        """Compacted {ids, codes} of live rows (save path; 1 byte/dim)."""
        snap = super().to_host()   # base returns raw device rows = codes
        return {"ids": snap["ids"], "codes": snap["vectors"]}


class HostSqSlotStore(SqSlotStore):
    """SqSlotStore variant keeping the uint8 codes in HOST RAM.

    The host rung of the memory-tier ladder (index/tiering.py): a demoted
    region's codes leave HBM entirely, the serving arm becomes a paged
    exact decoded scan on the host, and the device footprint drops to
    zero. Same float-facing contract as SqSlotStore — put() encodes,
    gather() decodes — and canonical_rows() still digests CODES, so the
    state-integrity ledger's 'rows' artifact is byte-comparable across
    the HBM-sq8 / host-sq8 / mmap-sq8 rungs (the digest gate that
    tier transitions verify before swapping)."""

    def _blocked_dtype_ok(self) -> bool:
        return False   # codes live host-side; no device scan mirror

    def _alloc_storage(self, capacity: int):
        return (
            np.zeros((capacity, self.dim), np.uint8),
            np.zeros((capacity,), np.float32),
        )

    def _grow_storage(self, pad: int):
        return (
            np.concatenate(
                [np.asarray(self.vecs),
                 np.zeros((pad, self.dim), np.uint8)]
            ),
            np.concatenate([self.sqnorm, np.zeros((pad,), np.float32)]),
        )

    def _write_segment(self, start: int, rows: np.ndarray) -> None:
        # rows arrive as CODES (SqSlotStore.put encodes before super().put);
        # sqnorm caches the decoded-surrogate norms, same convention as the
        # device store so tier moves never change what a scan accumulates
        n = rows.shape[0]
        codes = np.asarray(rows, np.uint8)
        self.vecs[start:start + n] = codes
        deq = self.decode(codes)
        self.sqnorm[start:start + n] = \
            np.einsum("ld,ld->l", deq, deq).astype(np.float32)

    def gather(self, ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        slots = self.slots_of(ids)
        found = slots >= 0
        safe = np.where(found, slots, 0)
        codes = np.asarray(self.vecs[safe], np.uint8)
        if self.sq_params is None:
            return found, codes.astype(np.float32)
        return found, self.decode(codes)

    def rows_device(self, slots: np.ndarray) -> jax.Array:
        codes = np.asarray(self.vecs[np.asarray(slots, np.int64)], np.uint8)
        if self.sq_params is None:
            return jnp.asarray(codes.astype(np.float32))
        return jnp.asarray(self.decode(codes))

    def memory_size(self) -> int:
        # host bytes; this store holds nothing on device
        return int(np.asarray(self.vecs).nbytes + self.sqnorm.nbytes)


class MmapSqSlotStore(HostSqSlotStore):
    """HostSqSlotStore whose code array is an np.memmap on disk.

    The bottom rung of the tier ladder: codes page in on demand under the
    paged exact scan, so a fully-cold region's steady-state RAM cost is
    the bookkeeping arrays (~13 bytes/slot), not the corpus. The file
    layout is the raw [capacity, dim] uint8 code matrix — identical bytes
    to the host rung's array, which keeps the digest-gated tier copy a
    straight transcription."""

    def __init__(self, dim: int, path: str, dtype=jnp.uint8,
                 capacity: int = MIN_CAPACITY,
                 blocked: Optional[bool] = None):
        # the storage hooks run inside super().__init__ — path first
        self._mmap_path = path
        super().__init__(dim, dtype, capacity, blocked=blocked)

    def _alloc_storage(self, capacity: int):
        import os

        os.makedirs(os.path.dirname(self._mmap_path) or ".", exist_ok=True)
        return (
            np.memmap(self._mmap_path, dtype=np.uint8, mode="w+",
                      shape=(capacity, self.dim)),
            np.zeros((capacity,), np.float32),
        )

    def _grow_storage(self, pad: int):
        new_cap = self.capacity + pad
        self.vecs.flush()
        with open(self._mmap_path, "r+b") as f:
            f.truncate(new_cap * self.dim)
        return (
            np.memmap(self._mmap_path, dtype=np.uint8, mode="r+",
                      shape=(new_cap, self.dim)),
            np.concatenate([self.sqnorm, np.zeros((pad,), np.float32)]),
        )

    def disk_bytes(self) -> int:
        return int(self.capacity) * int(self.dim)

    def memory_size(self) -> int:
        # the codes are disk-resident; RAM cost is the norm cache (+ the
        # base bookkeeping the caller already accounts per slot)
        return int(self.sqnorm.nbytes)

    def close(self, unlink: bool = True) -> None:
        """Release the mapping (promotion/retirement): flush, drop the
        mmap reference, optionally unlink the backing file."""
        import os

        with self.device_lock:
            try:
                self.vecs.flush()
            except (ValueError, OSError):
                pass
            # replace with a zero-row array so a straggling reader fails
            # loudly instead of touching an unmapped page
            self.vecs = np.zeros((0, self.dim), np.uint8)
        if unlink:
            try:
                os.unlink(self._mmap_path)
            except OSError:
                pass
