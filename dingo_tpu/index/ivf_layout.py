"""Skew-proof bucketed IVF layout shared by TpuIvfFlat / TpuIvfPq.

Round-1 layout padded every coarse list to the LARGEST list's pow2 size
([nlist, cap_max, d]); with realistic k-means skew that multiplies HBM by
the skew factor (a 10x-hot list inflates every other list 10x). This layout
fixes the bucket width near the MEAN list size and lets a long list spill
into several fixed-width buckets instead:

  data        [B, cap_list, d]   B = sum_l ceil(count_l / cap_list)  (>= nlist)
  bucket_slot [B, cap_list]      slot per row, -1 pad
  probe_table [nlist, max_spill] bucket ids per coarse list, -1 pad

Memory is bounded by n*d + nlist*cap_list*d regardless of skew, and the
probe expansion (coarse list -> its spill buckets) happens ON DEVICE so no
D2H round-trip enters the search path. Construction is fully vectorized —
the round-1 per-row Python loop was itself a 1M-scale ingest bug.

Reference contract: faiss IndexIVF inverted lists are exact-size per list
(vector_index_ivf_flat.cc:60-62); the fixed-width spill encoding is the
static-shape equivalent XLA needs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dingo_tpu.index.slot_store import _next_pow2

#: bucket width bounds: small enough to bound padding waste (<= nlist*cap*d),
#: large enough to keep per-bucket matmuls MXU-friendly
MIN_CAP = 8
MAX_CAP = 2048


@dataclasses.dataclass
class BucketLayout:
    """Host-side layout description + device probe/slot arrays."""

    cap_list: int
    max_spill: int
    nbuckets: int
    bucket_slot_h: np.ndarray      # [B, cap_list] int32, -1 pad
    bucket_slot: jax.Array         # device copy
    bucket_valid: jax.Array        # [B, cap_list] bool
    probe_table: jax.Array         # [nlist, max_spill] int32, -1 pad
    gather_idx: jax.Array          # [B * cap_list] int32 (slot or 0)
    bucket_coarse: jax.Array       # [B] int32: coarse list of each bucket

    def gather_rows(self, source: jax.Array) -> jax.Array:
        """[B, cap_list, *source.shape[1:]] rows grouped by bucket."""
        out = jnp.take(source, self.gather_idx, axis=0)
        return out.reshape(
            (self.nbuckets, self.cap_list) + source.shape[1:]
        )


def build_layout(
    assign_h: np.ndarray,
    valid_h: np.ndarray,
    nlist: int,
    cap_hint: Optional[int] = None,
) -> BucketLayout:
    """Group live slots by coarse assignment into fixed-width spill buckets.

    assign_h: [capacity] int32 coarse list per slot (-1 unassigned)
    valid_h:  [capacity] bool liveness
    """
    live = np.flatnonzero(valid_h)
    assign = assign_h[live]
    keep = assign >= 0
    live, assign = live[keep], assign[keep]

    counts = np.bincount(assign, minlength=nlist).astype(np.int64)
    mean = max(1, int(np.ceil(len(live) / max(1, nlist))))
    cap_list = cap_hint or min(MAX_CAP, max(MIN_CAP, _next_pow2(mean)))

    # buckets per list (every list gets >= 1 so probe_table[:, 0] is valid)
    nb = np.maximum(1, -(-counts // cap_list))           # ceil div
    max_spill = int(nb.max()) if len(nb) else 1
    offsets = np.zeros(nlist + 1, np.int64)
    np.cumsum(nb, out=offsets[1:])
    nbuckets = int(offsets[-1])

    # stable sort by list; position within list -> (bucket, row) coordinates
    order = np.argsort(assign, kind="stable")
    live_s, assign_s = live[order], assign[order]
    starts = np.zeros(nlist, np.int64)
    np.cumsum(counts, out=starts)
    starts = np.concatenate([[0], starts[:-1]])
    pos = np.arange(len(live_s), dtype=np.int64) - starts[assign_s]
    bucket_id = offsets[assign_s] + pos // cap_list
    row = pos % cap_list

    bucket_slot = np.full((nbuckets, cap_list), -1, np.int32)
    bucket_slot[bucket_id, row] = live_s

    probe = offsets[:nlist, None] + np.arange(max_spill)[None, :]
    probe = np.where(
        np.arange(max_spill)[None, :] < nb[:, None], probe, -1
    ).astype(np.int32)

    safe = np.where(bucket_slot >= 0, bucket_slot, 0)
    coarse = np.repeat(np.arange(nlist, dtype=np.int32), nb)
    return BucketLayout(
        cap_list=cap_list,
        max_spill=max_spill,
        nbuckets=nbuckets,
        bucket_slot_h=bucket_slot,
        bucket_slot=jnp.asarray(bucket_slot),
        bucket_valid=jnp.asarray(bucket_slot >= 0),
        probe_table=jnp.asarray(probe),
        gather_idx=jnp.asarray(safe.reshape(-1), jnp.int32),
        bucket_coarse=jnp.asarray(coarse),
    )


def expand_probes(
    probes: jax.Array, probe_table: jax.Array, nprobe: int, max_spill: int
) -> jax.Array:
    """Coarse probes [b, nprobe] -> virtual bucket probes [b, budget].

    Valid buckets come first in original rank order; when the expansion
    exceeds the budget, the LOWEST-ranked coarse lists' spill buckets are
    dropped (they contribute least to recall). budget == nprobe when there
    is no spill, so the common case is a plain table lookup.
    """
    virt, _ = expand_probes_ranked(probes, probe_table, nprobe, max_spill)
    return virt


def expand_probes_ranked(
    probes: jax.Array, probe_table: jax.Array, nprobe: int, max_spill: int
):
    """expand_probes plus, per virtual probe, the POSITION of its coarse
    list within the query's probe ranking ([b, budget] int32). Lets callers
    that precompute per-(query, coarse-list) state (the IVF-PQ residual
    LUT) share it across a list's spill buckets instead of recomputing."""
    b = probes.shape[0]
    virt = jnp.take(probe_table, probes, axis=0)        # [b, nprobe, spill]
    virt = virt.reshape(b, nprobe * max_spill)
    if max_spill == 1:
        pos = jnp.broadcast_to(
            jnp.arange(nprobe, dtype=jnp.int32)[None, :], (b, nprobe)
        )
        return virt, pos
    width = nprobe * max_spill
    # rank-preserving compaction: valid entries keep their column index as
    # sort key, invalid ones sink to the end
    cols = jnp.arange(width, dtype=jnp.int32)[None, :]
    key = jnp.where(virt >= 0, cols, jnp.int32(width))
    order = jnp.argsort(key, axis=1)
    virt = jnp.take_along_axis(virt, order, axis=1)
    budget = min(width, nprobe + max(8, nprobe // 2) + max_spill - 1)
    pos = (order // max_spill).astype(jnp.int32)
    return virt[:, :budget], pos[:, :budget]
