"""Scalar predicate evaluation over vector scalar data.

Reference: the scalar post-filter in VectorReader compares requested scalar
key/values against each candidate's scalar data (vector_reader.cc:120-215,
CoprocessorScalar schema-typed compare). Scalar data is a map
field -> typed value (pb::common::VectorScalardata).

The reference's SCALAR post-filter mode is equality-on-all-requested-fields;
CoprocessorV2 runs rel-expression bytecode for richer predicates. Here
ScalarFilter supports conjunctions of typed comparisons (EQ/NE/LT/LE/GT/GE/
IN) which covers both the equality mode and the common coprocessor cases;
a full expression VM port is tracked for the coprocessor_v2 milestone.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Sequence


class CmpOp(enum.Enum):
    EQ = "eq"
    NE = "ne"
    LT = "lt"
    LE = "le"
    GT = "gt"
    GE = "ge"
    IN = "in"


@dataclasses.dataclass(frozen=True)
class ScalarPredicate:
    field: str
    op: CmpOp
    value: Any

    def matches(self, scalar: Dict[str, Any]) -> bool:
        if self.field not in scalar:
            return False
        v = scalar[self.field]
        try:
            if self.op is CmpOp.EQ:
                return v == self.value
            if self.op is CmpOp.NE:
                return v != self.value
            if self.op is CmpOp.LT:
                return v < self.value
            if self.op is CmpOp.LE:
                return v <= self.value
            if self.op is CmpOp.GT:
                return v > self.value
            if self.op is CmpOp.GE:
                return v >= self.value
            if self.op is CmpOp.IN:
                return v in self.value
        except TypeError:
            return False
        return False


@dataclasses.dataclass
class ScalarFilter:
    """Conjunction of predicates (the reference's post-filter requires every
    requested scalar entry to match)."""

    predicates: Sequence[ScalarPredicate] = ()

    @classmethod
    def equals(cls, required: Dict[str, Any]) -> "ScalarFilter":
        """Reference SCALAR filter mode: all key/values equal."""
        return cls([ScalarPredicate(k, CmpOp.EQ, v) for k, v in required.items()])

    def matches(self, scalar: Dict[str, Any]) -> bool:
        return all(p.matches(scalar) for p in self.predicates)

    def is_empty(self) -> bool:
        return not self.predicates

    def fields(self) -> set:
        """Scalar fields this filter reads — used to decide whether the
        narrow speed-up CF covers it (vector_index_utils.h split-keys)."""
        return {p.field for p in self.predicates}
