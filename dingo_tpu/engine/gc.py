"""GC safe point management.

Reference: src/engine/gc_safe_point.{h,cc} (gc_safe_point.h:28-92) +
gc_task_tracker — the coordinator computes and pushes a GC safe timestamp
(per tenant); stores run MVCC GC below it (TxnEngineHelper::Gc +
DoGcCoreNonTxn for plain versioned keys).
"""

from __future__ import annotations

import threading
from typing import Dict

from dingo_tpu.engine.raw_engine import (
    CF_DEFAULT,
    CF_VECTOR_SCALAR,
    RawEngine,
    WriteBatch,
)
from dingo_tpu.mvcc.codec import Codec, ValueFlag


class GCSafePointManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._safe_ts: Dict[int, int] = {0: 0}   # tenant -> safe ts
        self.gc_stopped = False

    def update(self, safe_ts: int, tenant: int = 0) -> None:
        """Coordinator push (only moves forward)."""
        with self._lock:
            self._safe_ts[tenant] = max(self._safe_ts.get(tenant, 0), safe_ts)

    def get(self, tenant: int = 0) -> int:
        with self._lock:
            return self._safe_ts.get(tenant, 0)

    def gc_non_txn(self, engine: RawEngine, tenant: int = 0,
                   cfs=(CF_DEFAULT, CF_VECTOR_SCALAR)) -> int:
        """DoGcCoreNonTxn: for each user key keep only the newest version at
        or below the safe point (drop it too if it is a delete tombstone);
        versions above the safe point are untouched."""
        safe_ts = self.get(tenant)
        if safe_ts == 0 or self.gc_stopped:
            return 0
        removed = 0
        for cf in cfs:
            doomed = []
            current = None
            kept_newest = False
            for k, v in engine.scan(cf):
                try:
                    user_key, ts = Codec.decode_key(k)
                except ValueError:
                    continue
                if user_key != current:
                    current = user_key
                    kept_newest = False
                if ts > safe_ts:
                    continue
                flag, _, _ = Codec.unpackage_value(v)
                if not kept_newest:
                    kept_newest = True
                    if flag is ValueFlag.DELETE:
                        doomed.append(k)   # fully dead below the safe point
                    continue
                doomed.append(k)
            if doomed:
                batch = WriteBatch()
                for k in doomed:
                    batch.delete(cf, k)
                engine.write(batch)
                removed += len(doomed)
        return removed
