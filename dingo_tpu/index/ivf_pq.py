"""TpuIvfPq: IVF + product quantization with residual encoding and the
reference's hybrid flat->pq lifecycle.

Reference: VectorIndexIvfPq (src/vector/vector_index_ivf_pq.{h,cc}) is a
**hybrid**: it serves exact search from an internal flat index until trained,
then switches to faiss::IndexIVFPQ (vector_index_ivf_pq.h:113-115,
VectorIndexSubType() vector_index.h:238). Train size derives from
ClusteringParameters.max_points_per_centroid * nlist and
ProductQuantizer(d, m, nbits) (vector_index_ivf_pq.cc:337-341).

TPU-first design:
  codes    — residual PQ (faiss IVFPQ by_residual convention): code(x) =
             pq_encode(x - centroid[assign(x)]). Codes live in a device
             [capacity, m] uint8 array updated incrementally on upsert;
             a bucketed view [nlist, cap_list, m] groups codes by coarse
             list (same scheme as ivf_flat.py).
  search   — per probe rank r: residual LUT [b, m, ksub] for each query's
             rank-r list (m vmapped tiny matmuls), then ADC over the gathered
             code bucket via one take_along_axis ([b, m, cap_list]) + sum.
             Running top-k across ranks.
  fallback — untrained: exact flat-kernel scan over the SlotStore (the
             hybrid contract; NOT an error, unlike IVF_FLAT).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dingo_tpu.common.config import FLAGS
from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    InvalidParameter,
    NotTrained,
    SearchResult,
    VectorIndex,
    strip_invalid,
)
from dingo_tpu.index.flat import (
    _SlotStoreIndex,
    _flat_search_kernel,
    _pad_batch,
    _resolve_train_cap,
    integrity_mutation,
)
from dingo_tpu.index.ivf_flat import IvfViewMaintenance, _probe_lists
from dingo_tpu.index.ivf_layout import MutableIvfView, expand_probes_ranked
from dingo_tpu.index.slot_store import HostSlotStore, SlotStore, _next_pow2
from dingo_tpu.ops.distance import (
    Metric,
    normalize,
    np_normalize,
    pairwise_l2sqr,
    squared_norms,
)
from dingo_tpu.ops.kmeans import (
    MAX_POINTS_PER_CENTROID,
    kmeans_assign,
    train_kmeans,
)
from dingo_tpu.ops.pq import pq_train, split_subvectors
from dingo_tpu.ops.topk import merge_topk
from dingo_tpu.obs.sentinel import sentinel_jit


HOST_SCAN_CHUNK = 65536
#: rows encoded per device round during train-time (re)encode
ENCODE_CHUNK = 131072


def _chunked_host_scan(vecs_h, sqnorm_h, mask_h, qpad, k, metric):
    """Exact scan streaming host chunks through the flat kernel with a
    running top-k merge (the untrained fallback for host-resident stores;
    slot ids stay global)."""
    from dingo_tpu.ops.distance import metric_ascending, scores_to_distances

    b = qpad.shape[0]
    neg_inf = jnp.float32(-jnp.inf)
    best_v = jnp.full((b, k), neg_inf)
    best_s = jnp.full((b, k), -1, jnp.int32)
    n = vecs_h.shape[0]
    asc = metric_ascending(metric)
    for i in range(0, n, HOST_SCAN_CHUNK):
        hi = min(n, i + HOST_SCAN_CHUNK)
        if not mask_h[i:hi].any():
            continue
        pad = HOST_SCAN_CHUNK - (hi - i)
        chunk = np.asarray(vecs_h[i:hi], np.float32)
        sq = np.asarray(sqnorm_h[i:hi], np.float32)
        m = mask_h[i:hi]
        if pad:
            chunk = np.concatenate(
                [chunk, np.zeros((pad, chunk.shape[1]), np.float32)]
            )
            sq = np.concatenate([sq, np.zeros(pad, np.float32)])
            m = np.concatenate([m, np.zeros(pad, bool)])
        d, sl = _flat_search_kernel(
            jnp.asarray(chunk), jnp.asarray(sq), jnp.asarray(m), qpad,
            k=k, metric=metric, nbits=0,
        )
        # kernel returns wire distances; merge in score space
        vals = -d if asc else d
        gsl = jnp.where(sl >= 0, sl + i, -1)
        best_v, best_s = merge_topk(best_v, best_s, vals, gsl, k)
    best_s = jnp.where(jnp.isneginf(best_v), -1, best_s)
    return scores_to_distances(best_v, metric), best_s


def _exact_rerank_host(store, queries, cand_slots, k, metric):
    """Exact rerank of ADC candidates from a host-resident store:
    one host gather + one device einsum (prune+rerank, diskann/core.py
    recipe). Returns (wire distances [b, k], slots [b, k])."""
    from dingo_tpu.ops.distance import scores_to_distances

    b, kprime = cand_slots.shape
    safe = np.where(cand_slots >= 0, cand_slots, 0)
    flat_idx = safe.reshape(-1)
    rows = np.asarray(store.vecs[flat_idx], np.float32).reshape(
        b, kprime, -1
    )
    dc = jnp.asarray(rows)
    qd = jnp.asarray(queries, jnp.float32)
    dots = jnp.einsum(
        "bd,bkd->bk", qd, dc,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    if metric is Metric.L2:
        # candidate norms come from the store's cache, gathered host-side
        # in the same fancy-index as the rows
        c_sq = jnp.asarray(store.sqnorm[flat_idx].reshape(b, kprime))
        scores = -(squared_norms(qd)[:, None] - 2.0 * dots + c_sq)
    else:
        scores = dots
    scores = jnp.where(jnp.asarray(cand_slots) >= 0, scores,
                       jnp.float32(-jnp.inf))
    vals, pos = jax.lax.top_k(scores, min(k, kprime))
    slots_out = jnp.take_along_axis(jnp.asarray(cand_slots), pos, axis=1)
    slots_out = jnp.where(jnp.isneginf(vals), -1, slots_out)
    if min(k, kprime) < k:
        pad = k - min(k, kprime)
        vals = jnp.pad(vals, ((0, 0), (0, pad)),
                       constant_values=float("-inf"))
        slots_out = jnp.pad(slots_out, ((0, 0), (0, pad)),
                            constant_values=-1)
    return scores_to_distances(vals, metric), slots_out


@sentinel_jit("index.ivfpq.encode_residual")
def _encode_residual(vectors, assign, centroids, codebooks):
    """codes[n, m] uint8 for residuals (vectors - their centroid)."""
    resid = vectors - jnp.take(centroids, assign, axis=0)
    m, ksub, dsub = codebooks.shape
    subs = split_subvectors(resid, m)                  # [m, n, dsub]

    def enc_one(sub, cb):
        return jnp.argmin(pairwise_l2sqr(sub, cb), axis=1)

    return jax.vmap(enc_one)(subs, codebooks).T.astype(jnp.uint8)


def _codebook_sqnorms(codebooks):
    """||codeword||^2 per (subspace, codeword): [m, ksub] f32."""
    return jnp.einsum(
        "mkd,mkd->mk", codebooks, codebooks,
        precision=jax.lax.Precision.HIGHEST,
    )


def _residual_lut_tables(resid, codebooks, cb_sq):
    """Residual targets [n, d] -> ADC tables [n, m, ksub]:
    lut[i, j, c] = ||resid_i_subj - codeword_jc||^2. THE one copy of the
    distance-table formula — both the XLA scan kernel and the fused
    Quick-ADC path build tables here, so they cannot drift apart."""
    m = codebooks.shape[0]
    subs = split_subvectors(resid, m)                  # [m, n, dsub]
    dots = jnp.einsum(
        "mbd,mkd->mbk", subs, codebooks,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    q_sq = jnp.einsum(
        "mbd,mbd->mb", subs, subs, precision=jax.lax.Precision.HIGHEST
    )
    lut = q_sq[:, :, None] - 2.0 * dots + cb_sq[:, None, :]  # [m, n, ksub]
    return jnp.transpose(lut, (1, 0, 2))               # [n, m, ksub]


@sentinel_jit("index.ivfpq.adc_lut")
def _ivfpq_adc_lut(queries, centroids, probes_coarse, codebooks):
    """Residual ADC tables [b, nprobe, m, ksub] over the coarse probe
    ranking — the XLA-built input the fused Quick-ADC Pallas kernel
    (ops/pallas_pq.py) keeps resident in VMEM per (query, rank)."""
    b, d = queries.shape
    m, ksub, _ = codebooks.shape
    nprobe = probes_coarse.shape[1]
    resid = (
        queries[:, None, :] - jnp.take(centroids, probes_coarse, axis=0)
    ).reshape(b * nprobe, d)
    lut = _residual_lut_tables(resid, codebooks, _codebook_sqnorms(codebooks))
    return lut.reshape(b, nprobe, m, ksub)


@sentinel_jit("index.ivfpq.scan", static_argnames=("k", "precompute_lut"))
def _ivfpq_scan_kernel(
    code_buckets,      # [B, cap_list, m] uint8 (spill buckets, ivf_layout.py)
    bucket_valid,      # [B, cap_list] bool
    bucket_slot,       # [B, cap_list] int32
    bucket_coarse,     # [B] int32: coarse list of each bucket (for residuals)
    probes_coarse,     # [b, nprobe] int32 coarse probe ranking
    probes,            # [b, budget] int32 virtual bucket ids (-1 pad)
    coarse_pos,        # [b, budget] int32 coarse rank of each virtual probe
    queries,           # [b, d] f32
    centroids,         # [nlist, d] f32
    codebooks,         # [m, ksub, dsub] f32
    k,
    precompute_lut,
):
    """ADC scan over probed lists with per-(query, list) residual LUTs.

    precompute_lut=True builds the [b, nprobe, m, ksub] LUT once over the
    COARSE probe ranking and gathers per rank — a hot list's spill buckets
    then share one LUT instead of recomputing it per bucket. The flag is
    static so callers can fall back when the LUT would not fit HBM."""
    b, d = queries.shape
    m, ksub, dsub = codebooks.shape
    neg_inf = jnp.float32(-jnp.inf)
    cb_sq = _codebook_sqnorms(codebooks)                # [m, ksub]

    def lut_for(resid):
        """residual targets [n, d] -> LUT [n, m, ksub] (shared formula)."""
        return _residual_lut_tables(resid, codebooks, cb_sq)

    if precompute_lut:
        nprobe = probes_coarse.shape[1]
        resid_all = queries[:, None, :] - jnp.take(
            centroids, probes_coarse, axis=0
        )                                               # [b, nprobe, d]
        lut_all = lut_for(resid_all.reshape(b * nprobe, d)).reshape(
            b, nprobe, m, ksub
        )

    def body(carry, r):
        best_vals, best_slots = carry
        vlists = jnp.take(probes, r, axis=1)            # [b] virtual bucket ids
        rank_ok = vlists >= 0
        bkt = jnp.where(rank_ok, vlists, 0)
        if precompute_lut:
            cp = jnp.take(coarse_pos, r, axis=1)        # [b]
            lut = jnp.take_along_axis(
                lut_all, cp[:, None, None, None], axis=1
            )[:, 0]                                     # [b, m, ksub]
        else:
            lists_r = jnp.take(bucket_coarse, bkt)      # coarse list per bucket
            qr = queries - jnp.take(centroids, lists_r, axis=0)
            lut = lut_for(qr)                           # [b, m, ksub]

        codes = jnp.take(code_buckets, bkt, axis=0)      # [b, cap, m]
        val = jnp.take(bucket_valid, bkt, axis=0) & rank_ok[:, None]
        slot = jnp.take(bucket_slot, bkt, axis=0)
        # ADC: dist[b, cap] = sum_m LUT[b, m, codes[b, cap, m]]
        codes_t = jnp.transpose(codes, (0, 2, 1)).astype(jnp.int32)  # [b, m, cap]
        gathered = jnp.take_along_axis(lut, codes_t, axis=2)         # [b, m, cap]
        dist = gathered.sum(axis=1)                                   # [b, cap]
        scores = jnp.where(val, -dist, neg_inf)
        vals_r, idx_r = jax.lax.top_k(scores, min(k, scores.shape[1]))
        slots_r = jnp.take_along_axis(slot, idx_r, axis=1)
        slots_r = jnp.where(jnp.isneginf(vals_r), -1, slots_r)
        return merge_topk(best_vals, best_slots, vals_r, slots_r, k), None

    init = (
        jnp.full((b, k), neg_inf, jnp.float32),
        jnp.full((b, k), -1, jnp.int32),
    )
    (vals, slots), _ = jax.lax.scan(body, init, jnp.arange(probes.shape[1]))
    return -vals, slots    # wire convention: squared-L2-approx ascending


class TpuIvfPq(IvfViewMaintenance, _SlotStoreIndex):
    def __init__(self, index_id: int, parameter: IndexParameter):
        VectorIndex.__init__(self, index_id, parameter)
        p = parameter
        if p.dimension <= 0:
            raise InvalidParameter(f"dimension {p.dimension}")
        if p.dimension % p.nsubvector:
            raise InvalidParameter(
                f"dimension {p.dimension} not divisible by m={p.nsubvector}"
            )
        if p.nbits_per_idx != 8:
            raise InvalidParameter("only nbits=8 supported (uint8 codes)")
        if p.metric is Metric.HAMMING:
            raise InvalidParameter("hamming not valid for IVF_PQ")
        from dingo_tpu.index.base import resolve_precision

        self._precision = resolve_precision(p)
        if self._precision == "sq8":
            raise InvalidParameter(
                "IVF_PQ codes are already quantized; sq8 applies to "
                "FLAT/IVF_FLAT (use bf16 here for a smaller exact store)"
            )
        store_dtype = (
            jnp.bfloat16 if self._precision == "bf16" else jnp.dtype(p.dtype)
        )
        store_cls = HostSlotStore if p.host_vectors else SlotStore
        self.store = store_cls(p.dimension, store_dtype)
        self.nlist = p.ncentroids
        self.m = p.nsubvector
        self.ksub = 1 << p.nbits_per_idx
        self.centroids: Optional[jax.Array] = None
        self._c_sqnorm: Optional[jax.Array] = None
        self.codebooks: Optional[jax.Array] = None       # [m, ksub, dsub]
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)
        self._codes: Optional[jax.Array] = None          # [capacity, m] uint8
        self._code_buckets = None                        # [alloc, cap_list, m]
        self._view: Optional[MutableIvfView] = None
        self._view_dirty = True
        self._filter_cache: dict = {}
        self._kernel_metric = p.metric
        self._kernel_nbits = 0

    # -- prep (shared shape checks + cosine normalize) ----------------------
    def _prep_vectors(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, np.float32)
        if vectors.ndim != 2 or vectors.shape[1] != self.dimension:
            raise InvalidParameter(
                f"vector dim {vectors.shape} != {self.dimension}"
            )
        if self.metric is Metric.COSINE and not getattr(
                self, "_rows_prenormalized", False):
            # load() re-ingests rows the store already normalized once;
            # normalizing again drifts low-order bits (||x|| lands NEAR 1,
            # not exactly) and would break the snapshot's bit-exact
            # restore-digest verification
            vectors = np_normalize(vectors)
        return vectors

    def _prep_queries(self, queries: np.ndarray) -> np.ndarray:
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.shape[1] != self.dimension:
            raise InvalidParameter(
                f"query dim {queries.shape[1]} != {self.dimension}"
            )
        if self.metric is Metric.COSINE:
            queries = np_normalize(queries)
        return queries

    # -- mutation ------------------------------------------------------------
    def _ensure_code_capacity(self) -> None:
        cap = self.store.capacity
        if self._assign_h.shape[0] < cap:
            grown = np.full((cap,), -1, np.int32)
            grown[: self._assign_h.shape[0]] = self._assign_h
            self._assign_h = grown
        if self._codes is not None and self._codes.shape[0] < cap:
            pad = cap - self._codes.shape[0]
            self._codes = jnp.concatenate(
                [self._codes, jnp.zeros((pad, self.m), jnp.uint8)]
            )

    @integrity_mutation
    def upsert(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        vectors = self._prep_vectors(vectors)
        if len(ids) != len(vectors):
            raise InvalidParameter("ids/vectors length mismatch")
        slots = self.store.put(np.asarray(ids, np.int64), vectors)
        self._ensure_code_capacity()
        from dingo_tpu.obs.quality import QUALITY

        # quality plane: the fp32 store/host rows ARE the shadow ground
        # truth for IVF_PQ, so this only syncs mirror-mode oracles
        QUALITY.observe_write(self, np.asarray(ids, np.int64), vectors)
        self._integrity_write(ids, vectors)
        if self.is_trained():
            dv = jnp.asarray(vectors)
            assign = kmeans_assign(dv, self.centroids)
            codes = _encode_residual(dv, assign, self.centroids, self.codebooks)
            assign_h = np.asarray(assign)
            self._assign_h[slots] = assign_h
            self._codes = self._codes.at[jnp.asarray(slots, jnp.int32)].set(codes)
            self._integrity_assign(ids, assign_h)
            self._integrity_codes(ids, codes)
            if self._view is not None and not self._view_dirty:
                # incremental: scatter the fresh codes into the bucketed
                # view instead of invalidating it (rows = device codes)
                self._view_apply_upsert(slots, assign_h, codes)
            else:
                self._invalidate_view()
        else:
            self._view_dirty = True
        self.write_count_since_save += len(ids)

    @integrity_mutation
    def delete(self, ids: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        slots = self.store.remove_slots(ids)
        removed = int((slots >= 0).sum())
        from dingo_tpu.obs.quality import QUALITY

        QUALITY.observe_delete(self, ids)
        self._integrity_delete(ids)
        if removed:
            if self._view is not None and not self._view_dirty:
                self._view_apply_delete(slots[slots >= 0])
            else:
                self._invalidate_view()
        self.write_count_since_save += removed

    # -- training ------------------------------------------------------------
    def need_train(self) -> bool:
        return True

    def is_trained(self) -> bool:
        return self.codebooks is not None

    def _rows_at_slots(self, slots: np.ndarray) -> np.ndarray:
        """Host rows for the given slots (one H2D-free slice for host
        stores; one bounded D2H gather for device stores)."""
        if isinstance(self.store, HostSlotStore):
            return np.asarray(self.store.vecs[slots], np.float32)
        with self.store.device_lock:  # vecs reference is donatable
            return np.asarray(
                jnp.take(self.store.vecs, jnp.asarray(slots, jnp.int32),
                         axis=0),
                np.float32,
            )

    @integrity_mutation
    def train(self, vectors: Optional[np.ndarray] = None) -> None:
        # re-encodes every stored row into _codes chunk by chunk — a
        # scrub overlapping that must classify as raced, not corruption
        # (the decorator's bracket covers the whole method)
        cap = _resolve_train_cap(MAX_POINTS_PER_CENTROID * self.nlist)
        rng = np.random.default_rng(self.id)
        min_train = max(self.nlist, self.ksub)
        if vectors is None:
            # sample SLOTS instead of materializing every live row, and
            # gather them straight to device (ISSUE 18b): device stores
            # never round-trip rows at all, host stores upload only the
            # sample. Conf train.sample_rows=0 lifts the cap entirely —
            # full-corpus training as one chunked device Lloyd.
            live = np.flatnonzero(self.store.ids_by_slot >= 0)
            sel = live if (not cap or len(live) <= cap) else np.sort(
                rng.choice(live, cap, replace=False)
            )
            if len(sel) < min_train:
                raise NotTrained(
                    f"need >= {min_train} train vectors, have {len(sel)}"
                )
            dv = self.store.rows_device(sel)
            if self.metric is Metric.COSINE:
                dv = normalize(dv)
        else:
            vectors = np.asarray(vectors, np.float32)
            if len(vectors) < min_train:
                raise NotTrained(
                    f"need >= {min_train} train vectors, "
                    f"have {len(vectors)}"
                )
            if self.metric is Metric.COSINE:
                vectors = np_normalize(vectors)
            if cap and len(vectors) > cap:
                vectors = vectors[
                    rng.choice(len(vectors), cap, replace=False)
                ]
            dv = jnp.asarray(vectors)
        self.centroids, _ = train_kmeans(dv, k=self.nlist, iters=10, seed=self.id)
        self._c_sqnorm = squared_norms(self.centroids)
        assign = kmeans_assign(dv, self.centroids)
        resid = dv - jnp.take(self.centroids, assign, axis=0)
        self.codebooks = pq_train(resid, m=self.m, ksub=self.ksub, iters=10,
                                  seed=self.id)
        # encode everything stored, CHUNKED — the working set on device is
        # one chunk of rows, never the whole index
        self._codes = jnp.zeros((self.store.capacity, self.m), jnp.uint8)
        self._ensure_code_capacity()
        live = np.flatnonzero(self.store.ids_by_slot >= 0)
        for i in range(0, len(live), ENCODE_CHUNK):
            sl = live[i:i + ENCODE_CHUNK]
            dvv = jnp.asarray(self._rows_at_slots(sl))
            if self.metric is Metric.COSINE:
                dvv = normalize(dvv)
            a = kmeans_assign(dvv, self.centroids)
            codes = _encode_residual(dvv, a, self.centroids, self.codebooks)
            self._assign_h[sl] = np.asarray(a)
            self._codes = self._codes.at[jnp.asarray(sl, jnp.int32)].set(codes)
        # training reassigned + re-encoded every row: rebuild both digests
        self._integrity_reset_assign()
        self._integrity_reset_codes()
        self._invalidate_view()
        # retrain re-encoded every row: results change for identical query
        # bytes, so the serving-edge result cache (keyed on
        # mutation_version) must not serve pre-retrain entries as exact
        self.store.mutation_version += 1

    # -- state-integrity: PQ code artifact -----------------------------------
    def _integrity_codes(self, ids: np.ndarray, codes) -> None:
        """Fold freshly-encoded device codes into the 'pq_codes' digest
        (one bounded D2H of the batch's codes; off the search path and
        gated on integrity.enabled)."""
        from dingo_tpu.obs.integrity import INTEGRITY

        if len(ids) == 0 or not INTEGRITY.tracking(self):
            return
        INTEGRITY.note_write(self, "pq_codes", np.asarray(ids, np.int64),
                             np.asarray(codes, np.uint8))

    def _integrity_reset_codes(self) -> None:
        from dingo_tpu.obs.integrity import INTEGRITY

        if self._codes is None or not INTEGRITY.tracking(self):
            return
        INTEGRITY.reset_artifact(self, "pq_codes")
        live = np.flatnonzero(self.store.ids_by_slot >= 0)
        if len(live):
            codes_h = np.asarray(self._codes)
            self._integrity_codes(self.store.ids_by_slot[live],
                                  codes_h[live])

    # -- bucketed view (IvfViewMaintenance data hooks) -----------------------
    def _materialize_view_data(self, view: MutableIvfView) -> None:
        self._code_buckets = view.gather_rows(self._codes)

    def _scatter_view_data(self, upd, rows) -> None:
        """Scatter freshly-encoded codes ([n, m] uint8, device-resident)
        into the bucketed code view; caller holds device_lock."""
        from dingo_tpu.ops.scatter import pad_buckets, scatter_bucket_update

        if upd.grew_alloc is not None:
            self._code_buckets = pad_buckets(
                self._code_buckets, upd.grew_alloc
            )
        if not upd.appended:
            return
        cap = self._view.cap_list
        pos = np.asarray([p for p, _ in upd.appended], np.int64)
        src = np.asarray([i for _, i in upd.appended], np.int64)
        sel = jnp.take(rows, jnp.asarray(src, jnp.int32), axis=0)
        self._code_buckets = scatter_bucket_update(
            self._code_buckets,
            (pos // cap).astype(np.int32),
            (pos % cap).astype(np.int32),
            sel,
        )

    # -- search --------------------------------------------------------------
    def search(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
        nprobe: Optional[int] = None,
    ) -> List[SearchResult]:
        return self.search_async(queries, topk, filter_spec, nprobe)()

    def search_async(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
        nprobe: Optional[int] = None,
        staged=None,
    ):
        queries = self._prep_queries(queries)
        b = queries.shape[0]
        # staging-ring upload (serving pipeline): claimed only when the
        # identity check proves it was built from THESE queries
        qpad = staged.take(queries) if staged is not None else None
        if qpad is None:
            qpad = jnp.asarray(_pad_batch(queries))
        store = self.store
        # lease BEFORE any kernel dispatch: slots produced by the kernel
        # must stay stable (limbo-parked, not reassigned) until resolve
        # translates and, in rerank mode, gathers host rows for them
        lease = store.begin_search()
        self._count_search()
        try:
            rerank = False
            # quality-estimator bucket: the untrained hybrid arm scans
            # EXACTLY regardless of any requested nprobe — labeling it
            # with the caller's nprobe would pool recall-1.0 evidence
            # into the post-training nprobe window
            quality_bucket = "exact"
            if not self.is_trained():
                # Hybrid contract: exact flat scan until trained
                # (vector_index_ivf_pq.h:113-115).
                filtered = (
                    filter_spec is not None and not filter_spec.is_empty()
                )
                if isinstance(store, HostSlotStore):
                    mask_h = (
                        filter_spec.slot_mask(store.ids_by_slot) if filtered
                        else store.valid_h
                    )
                    dists, slots = _chunked_host_scan(
                        store.vecs, store.sqnorm, mask_h, qpad,
                        k=int(topk), metric=self.metric,
                    )
                else:
                    mask = (
                        jnp.asarray(filter_spec.slot_mask(store.ids_by_slot))
                        if filtered else store.device_mask()
                    )
                    with store.device_lock:
                        dists, slots = _flat_search_kernel(
                            store.vecs, store.sqnorm, mask, qpad,
                            k=int(topk), metric=self.metric, nbits=0,
                        )
            else:
                self._ensure_view()
                # request-pinned nprobe wins; else the SLO tuner's
                # override; else the configured default (obs/tuner.py)
                nprobe = min(
                    nprobe
                    or self.tuned("nprobe", self.parameter.default_nprobe),
                    self.nlist,
                )
                k_eff, nprobe = self._shape_buckets(int(topk), nprobe)
                quality_bucket = f"nprobe={nprobe}"
                probes = _probe_lists(
                    qpad, self.centroids, self._c_sqnorm, nprobe
                )
                fprep = self._prep_filter_mask(filter_spec)
                # share one residual LUT across a list's spill buckets when
                # the [b, nprobe, m, ksub] table fits comfortably in HBM
                lut_bytes = qpad.shape[0] * nprobe * self.m * self.ksub * 4
                factor = self.tuned(
                    "rerank_factor", int(FLAGS.get("ivfpq_rerank_factor"))
                )
                # ADC prune + exact rerank: host-resident rows rerank at
                # resolve time (host gather); DEVICE-resident rows rerank
                # on device right after the scan — no host gather, no
                # pipeline stall (ops/rerank.py)
                rerank = isinstance(store, HostSlotStore) and factor > 1
                rerank_dev = (
                    not isinstance(store, HostSlotStore) and factor > 1
                    and len(store) > 0
                )
                kprime = (
                    min(len(store), int(topk) * factor)
                    if (rerank or rerank_dev) else k_eff
                )
                # view snapshot + dispatch under the device lock:
                # incremental writes donate the bucket arrays to their
                # scatter programs (see ivf_flat.search_async)
                precompute = lut_bytes <= 256 * 1024 * 1024
                from dingo_tpu.common.config import pallas_ivf_enabled

                # Quick-ADC fused kernel: same tri-state crossover as the
                # IVF_FLAT list kernel. Needs the precomputed-LUT regime
                # (tables are the resident VMEM operand) and the 128-lane
                # output block's k ceiling (shared with pallas_ivf).
                use_fused_adc = (
                    pallas_ivf_enabled(self.dimension)
                    and precompute
                    and max(k_eff, kprime) <= 64
                )
                with store.device_lock:
                    view = self._view
                    vprobes, coarse_pos = expand_probes_ranked(
                        probes, view.probe_table, nprobe, view.max_spill
                    )
                    valid = self._bucket_valid_for_filter(filter_spec, fprep)
                    if use_fused_adc:
                        from dingo_tpu.ops.pallas_pq import ivf_pq_adc_search

                        lut_all = _ivfpq_adc_lut(
                            qpad, self.centroids, probes, self.codebooks
                        )
                        vals, slots = ivf_pq_adc_search(
                            vprobes, coarse_pos, lut_all,
                            self._code_buckets, valid, view.bucket_slot,
                            k=max(k_eff, kprime),
                        )
                        dists = -vals    # wire: ADC squared-L2 ascending
                    else:
                        dists, slots = _ivfpq_scan_kernel(
                            self._code_buckets,
                            valid,
                            view.bucket_slot,
                            view.bucket_coarse,
                            probes,
                            vprobes,
                            coarse_pos,
                            qpad,
                            self.centroids,
                            self.codebooks,
                            k=max(k_eff, kprime),
                            precompute_lut=precompute,
                        )
                    if rerank_dev:
                        from dingo_tpu.ops.rerank import exact_rerank_device

                        # store.vecs captured under the SAME lock hold the
                        # scan dispatched in (donated write safety)
                        dists, slots = exact_rerank_device(
                            store.vecs,
                            store.sqnorm,
                            qpad,
                            slots,
                            k=int(topk),
                            metric=self.metric,
                        )
        except Exception:
            lease.release()
            raise
        from dingo_tpu.ops.topk import begin_host_fetch
        from dingo_tpu.obs.heat import HEAT, heat_enabled

        # probed-bucket ids ride the reply's one D2H group (zero extra
        # syncs), same as ivf_flat's heat hook
        heat_on = heat_enabled()
        if heat_on:
            HEAT.register_layout(self.id, "ivf", self._heat_layout)
        fetch = begin_host_fetch(dists, slots,
                                 probes if heat_on else None)

        def resolve() -> List[SearchResult]:
            try:
                fetched = jax.device_get(fetch)
                if heat_on:
                    # fetch tuple is positional over non-None members:
                    # probes joined LAST, so [-1] is safe in both arms
                    HEAT.observe(self.id, "ivf", fetched[-1][:b])
                if rerank:
                    # ADC was a prune; the exact rows sit in host memory
                    # (host_vectors mode), so rerank at RESOLVE time — the
                    # dispatch above stays non-blocking and the device keeps
                    # pipelining (diskann/core.py prune+rerank recipe).
                    # Two syncs are INHERENT to this arm: the candidate
                    # slots must reach the host before the row gather can
                    # even start, and the rerank's output is a second
                    # device round-trip (adjudicated resolve-sync
                    # exception — see dingolint baseline).
                    cand = np.asarray(fetched[1])[:b]
                    d_r, s_r = _exact_rerank_host(
                        store, qpad[:b], cand, int(topk), self.metric
                    )
                    dists_h, slots_h = jax.device_get((d_r, s_r))
                else:
                    dists_h, slots_h = fetched[0], fetched[1]
                # shape bucketing may have run a larger k; slice back
                ids = store.ids_of_slots(slots_h[:b, : int(topk)])
                # head-sampled shadow scoring (async lane; noop at rate 0)
                from dingo_tpu.obs.quality import QUALITY

                QUALITY.observe_search(
                    self, queries, int(topk), ids,
                    dists_h[:b, : int(topk)],
                    bucket=quality_bucket,
                    filter_spec=filter_spec,
                )
                return [
                    strip_invalid(i, d)
                    for i, d in zip(ids, dists_h[:b, : int(topk)])
                ]
            finally:
                lease.release()

        return resolve

    def _heat_layout(self) -> Optional[dict]:
        """Heat-plane layout provider: rows per coarse bucket from the
        host assignment array. A resident PQ row costs its codes (m
        bytes) plus the store rows kept for rerank (heat worker
        thread)."""
        assign = self._assign_h
        if assign is None:
            return None
        from dingo_tpu.obs.heat import TIER_BYTES

        rows = np.bincount(assign[assign >= 0].astype(np.int64),
                           minlength=self.nlist)
        tier = self._precision
        return {
            "unit_rows": rows,
            "row_bytes": self.m + self.dimension * TIER_BYTES.get(
                tier, 4.0),
            "tier": tier,
            "dim": self.dimension,
        }

    # -- lifecycle -----------------------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        snap = self.store.to_host()
        # f32 on disk: numpy savez can't serialize ml_dtypes bfloat16
        snap["vectors"] = np.asarray(snap["vectors"], np.float32)
        extras = {}
        if self.is_trained():
            extras["centroids"] = np.asarray(self.centroids)
            extras["codebooks"] = np.asarray(self.codebooks)
        np.savez(os.path.join(path, "ivf_pq.npz"), **snap, **extras)
        meta = self._save_meta()
        meta.update(nlist=self.nlist, m=self.m, trained=self.is_trained())
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self._check_meta(meta)
        if meta["nlist"] != self.nlist or meta["m"] != self.m:
            raise InvalidParameter("snapshot nlist/m mismatch")
        data = np.load(os.path.join(path, "ivf_pq.npz"))
        store_cls = (
            HostSlotStore if self.parameter.host_vectors else SlotStore
        )
        store_dtype = (
            jnp.bfloat16 if self._precision == "bf16"
            else jnp.dtype(self.parameter.dtype)
        )
        self.store = store_cls(self.dimension, store_dtype,
                               max(len(data["ids"]), 1))
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)
        self._codes = None
        self.centroids = None
        self._c_sqnorm = None
        self.codebooks = None
        if meta.get("trained"):
            self.centroids = jnp.asarray(data["centroids"])
            self._c_sqnorm = squared_norms(self.centroids)
            self.codebooks = jnp.asarray(data["codebooks"])
            self._codes = jnp.zeros((self.store.capacity, self.m), jnp.uint8)
        self._view = None
        self._view_dirty = True
        self._filter_cache.clear()
        if len(data["ids"]):
            # rows on disk are already store-normalized (cosine): skip the
            # re-normalize so the restored bytes match the saved digests
            self._rows_prenormalized = True
            try:
                self.upsert(data["ids"], data["vectors"])
            finally:
                self._rows_prenormalized = False
        self.apply_log_id = meta["apply_log_id"]
        self._view_dirty = True
        self.write_count_since_save = 0
        self._integrity_on_restore(meta)
