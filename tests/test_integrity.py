"""State-integrity plane (ISSUE 11): incremental device-state digests,
the corruption scrub, snapshot restore verification, coordinator replica
divergence detection, and the ReplicaGroup post-fanout monitor."""

import json
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.index.base import (
    IndexParameter,
    IndexType,
    SnapshotCorruption,
)
from dingo_tpu.index.factory import new_index
from dingo_tpu.obs.flight import FLIGHT
from dingo_tpu.obs.integrity import INTEGRITY, diverged_artifacts
from dingo_tpu.ops.digest import SetDigest, row_fingerprints

D = 32
N = 400


@pytest.fixture(autouse=True)
def _integrity_on():
    """Plane on + a clean flight recorder/status per test."""
    was = FLAGS.get("integrity_enabled")
    FLAGS.set("integrity_enabled", True)
    FLIGHT.clear()
    INTEGRITY.clear()
    yield
    FLAGS.set("integrity_enabled", was)
    INTEGRITY.clear()


def _wait_region_leader(node, region_id, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rn = node.engine.get_node(region_id)
        if rn is not None and rn.is_leader():
            return
        node.heartbeat_once()
        time.sleep(0.05)
    raise AssertionError(f"no leader for region {region_id}")


def _corpus(seed=0, n=N, d=D):
    rng = np.random.default_rng(seed)
    return (np.arange(n, dtype=np.int64),
            rng.standard_normal((n, d)).astype(np.float32))


def _param(kind, d=D, **kw):
    defaults = dict(index_type=kind, dimension=d)
    if kind in (IndexType.IVF_FLAT, IndexType.IVF_PQ,
                IndexType.BINARY_IVF_FLAT):
        defaults.update(ncentroids=8, default_nprobe=8)
    if kind is IndexType.IVF_PQ:
        defaults.update(nsubvector=8)
    defaults.update(kw)
    return IndexParameter(**defaults)


# ---------------- digest primitive ----------------

def test_digest_order_invariant_and_homomorphic():
    ids, x = _corpus()
    fps = row_fingerprints("rows", ids, x)
    perm = np.random.default_rng(1).permutation(len(ids))
    assert SetDigest.of(fps) == SetDigest.of(
        row_fingerprints("rows", ids[perm], x[perm])
    )
    d = SetDigest.of(fps)
    d.remove(fps[:50])
    d.add(fps[:50])
    assert d == SetDigest.of(fps)
    assert d.count == len(ids)


def test_digest_detects_flip_swap_and_separates_tags():
    ids, x = _corpus()
    base = SetDigest.of(row_fingerprints("rows", ids, x))
    flipped = x.copy()
    flipped.view(np.uint8)[7, 13] ^= 1            # one byte, one row
    assert SetDigest.of(row_fingerprints("rows", ids, flipped)) != base
    swapped = x.copy()
    swapped[[3, 4]] = swapped[[4, 3]]             # payloads trade owners
    assert SetDigest.of(row_fingerprints("rows", ids, swapped)) != base
    assert SetDigest.of(row_fingerprints("blocked", ids, x)) != base
    assert SetDigest.from_hex(base.hex()) == base


def test_diverged_artifacts_helper():
    a = json.dumps({"rows": "1-a-b", "blocked": "1-c-d"})
    b = json.dumps({"rows": "1-a-b", "blocked": "1-x-y", "extra": "1-e-f"})
    # only artifacts BOTH sides report can diverge
    assert diverged_artifacts(a, b) == ["blocked"]
    assert diverged_artifacts(a, a) == []
    assert diverged_artifacts("", a) == []


# ---------------- incremental ledger vs full-state scrub ----------------

@pytest.mark.parametrize("kind,precision", [
    (IndexType.FLAT, "fp32"),
    (IndexType.FLAT, "bf16"),
    (IndexType.FLAT, "sq8"),
    (IndexType.IVF_FLAT, "fp32"),
    (IndexType.IVF_FLAT, "sq8"),
    (IndexType.HNSW, "fp32"),
    (IndexType.IVF_PQ, "fp32"),
])
def test_incremental_ledger_matches_scrub(kind, precision):
    """Writes + deletes + overwrites maintained incrementally must agree
    with a from-scratch device-state recompute for every index kind and
    precision tier."""
    ids, x = _corpus(seed=3)
    idx = new_index(11, _param(kind, precision=precision))
    idx.upsert(ids, x)
    if idx.need_train():
        idx.train()
        idx.search(x[:4], 5)           # builds the IVF view
    idx.delete(ids[10:40])
    idx.upsert(ids[20:30], x[20:30] + 1.0)   # re-add + fresh values
    idx.upsert(ids[:5], x[:5] * 2.0)          # overwrite in place
    if kind is IndexType.IVF_FLAT:
        idx.search(x[:4], 5)           # re-sync the view post-writes
    res = INTEGRITY.scrub_index(idx)
    assert res, "no artifacts scrubbed"
    for artifact, r in res.items():
        assert r["status"] == "ok", (artifact, r)
    assert "rows" in res
    if kind in (IndexType.IVF_FLAT, IndexType.IVF_PQ):
        assert "ivf_buckets" in res
    if kind is IndexType.IVF_PQ:
        assert "pq_codes" in res


def test_binary_flat_ledger_matches_scrub():
    rng = np.random.default_rng(5)
    packed = rng.integers(0, 256, size=(N, D // 8), dtype=np.uint8)
    ids = np.arange(N, dtype=np.int64)
    idx = new_index(12, _param(IndexType.BINARY_FLAT))
    idx.upsert(ids, packed)
    idx.delete(ids[:17])
    res = INTEGRITY.scrub_index(idx)
    assert res["rows"]["status"] == "ok"


def test_disabled_plane_is_inert():
    FLAGS.set("integrity_enabled", False)
    ids, x = _corpus()
    idx = new_index(13, _param(IndexType.FLAT))
    idx.upsert(ids, x)
    assert INTEGRITY.peek(idx) is None
    applied, digests, mismatch = INTEGRITY.region_report(idx)
    assert digests == "" and not mismatch


# ---------------- fault injection: one flipped byte per artifact --------

def _corrupt_device_array(store, attr, mutate):
    """Simulate silent HBM/restore corruption: read the device array back,
    flip state host-side, re-upload wholesale."""
    arr = np.asarray(getattr(store, attr)).copy()
    mutate(arr)
    with store.device_lock:
        setattr(store, attr, jnp.asarray(arr))


def _assert_detected(idx, artifact, results):
    assert results[artifact]["status"] == "mismatch", results
    mm = METRICS.counter("consistency.scrub_mismatches", region_id=idx.id,
                         labels={"artifact": artifact})
    assert mm.get() >= 1
    metas = FLIGHT.bundles_meta()
    assert any(m["reason"] == "corruption" for m in metas), metas


def test_scrub_detects_flipped_row_byte_and_renders_flight_report():
    ids, x = _corpus()
    idx = new_index(21, _param(IndexType.FLAT))
    idx.upsert(ids, x)
    slot = int(idx.store.slots_of(ids[:1])[0])
    _corrupt_device_array(
        idx.store, "vecs", lambda a: a.view(np.uint8).__setitem__(
            (slot, 3), a.view(np.uint8)[slot, 3] ^ 1)
    )
    res = INTEGRITY.scrub_index(idx)
    _assert_detected(idx, "rows", res)
    # the bundle carries the digest vectors and flight_report renders them
    import tools.flight_report as fr

    bundle = FLIGHT.get_json()
    assert bundle["reason"] == "corruption"
    assert bundle["trigger"]["artifacts"]["rows"]["expected"] != \
        bundle["trigger"]["artifacts"]["rows"]["actual"]
    text = fr.render(bundle)
    assert "state integrity" in text
    assert "MISMATCH" in text or "mismatch" in text


def test_scrub_detects_flipped_sq8_code():
    ids, x = _corpus(seed=7)
    idx = new_index(22, _param(IndexType.FLAT, precision="sq8"))
    idx.upsert(ids, x)
    slot = int(idx.store.slots_of(ids[5:6])[0])
    _corrupt_device_array(
        idx.store, "vecs",
        lambda a: a.__setitem__((slot, 2), a[slot, 2] ^ 1)
    )
    res = INTEGRITY.scrub_index(idx)
    assert res["rows"]["status"] == "mismatch"


def test_scrub_detects_flipped_blocked_mirror_entry():
    was = FLAGS.get("vector_blocked_layout")
    FLAGS.set("vector_blocked_layout", "True")
    try:
        ids, x = _corpus(seed=8, d=256)   # >= 2 x ivf_dim_block blocks
        idx = new_index(23, _param(IndexType.FLAT, d=256))
        assert idx.store.vecs_blk is not None
        idx.upsert(ids, x)
        res = INTEGRITY.scrub_index(idx)
        assert res["blocked"]["status"] == "ok"
        slot = int(idx.store.slots_of(ids[3:4])[0])
        _corrupt_device_array(
            idx.store, "vecs_blk", lambda a: a.view(np.uint8).__setitem__(
                (1, slot, 5), a.view(np.uint8)[1, slot, 5] ^ 1)
        )
        res = INTEGRITY.scrub_index(idx)
        # the rows copy is intact; only the mirror rotted
        assert res["rows"]["status"] == "ok"
        _assert_detected(idx, "blocked", res)
    finally:
        FLAGS.set("vector_blocked_layout", was)


def test_scrub_detects_flipped_adjacency_entry():
    was = FLAGS.get("hnsw_device_search")
    FLAGS.set("hnsw_device_search", "True")
    try:
        ids, x = _corpus(seed=9)
        idx = new_index(24, _param(IndexType.HNSW))
        idx.upsert(ids, x)
        idx.search(x[:2], 5)          # installs the device mirror
        assert idx.adjacency_in_sync()
        res = INTEGRITY.scrub_index(idx)
        assert res["adjacency"]["status"] == "ok"
        # rewire one neighbor entry to a DIFFERENT live slot
        slots = idx.store.slots_of(ids[:2])
        _corrupt_device_array(
            idx.store, "adj",
            lambda a: a.__setitem__((int(slots[0]), 0), int(slots[1]))
        )
        res = INTEGRITY.scrub_index(idx)
        _assert_detected(idx, "adjacency", res)
    finally:
        FLAGS.set("hnsw_device_search", was)


def test_scrub_detects_flipped_ivf_bucket_entry():
    ids, x = _corpus(seed=10)
    idx = new_index(25, _param(IndexType.IVF_FLAT))
    idx.upsert(ids, x)
    idx.train()
    idx.search(x[:2], 5)
    res = INTEGRITY.scrub_index(idx)
    assert res["ivf_buckets"]["status"] == "ok"
    view = idx._view
    bs = np.asarray(view.bucket_slot).copy()
    valid = np.argwhere(bs >= 0)
    b, r = valid[0]
    other = bs[tuple(valid[-1])]
    bs[b, r] = other              # a row claims a slot from another bucket
    with idx.store.device_lock:
        view.bucket_slot = jnp.asarray(bs)
    res = INTEGRITY.scrub_index(idx)
    _assert_detected(idx, "ivf_buckets", res)


def test_scrub_detects_flipped_pq_code():
    ids, x = _corpus(seed=11)
    idx = new_index(26, _param(IndexType.IVF_PQ))
    idx.upsert(ids, x)
    idx.train()
    res = INTEGRITY.scrub_index(idx)
    assert res["pq_codes"]["status"] == "ok"
    slot = int(idx.store.slots_of(ids[:1])[0])
    codes = np.asarray(idx._codes).copy()
    codes[slot, 0] ^= 1
    with idx.store.device_lock:
        idx._codes = jnp.asarray(codes)
    res = INTEGRITY.scrub_index(idx)
    _assert_detected(idx, "pq_codes", res)


def test_scrub_detection_within_one_interval_and_recovery():
    """A flip is caught by the NEXT scrub pass; a rebuilt (healed) state
    clears the region's mismatch flag on the following clean pass."""
    ids, x = _corpus(seed=12)
    idx = new_index(27, _param(IndexType.FLAT))
    idx.upsert(ids, x)
    INTEGRITY.scrub_index(idx)
    _applied, _digests, mismatch = INTEGRITY.region_report(idx)
    assert not mismatch
    slot = int(idx.store.slots_of(ids[:1])[0])
    _corrupt_device_array(
        idx.store, "vecs", lambda a: a.view(np.uint8).__setitem__(
            (slot, 0), a.view(np.uint8)[slot, 0] ^ 1)
    )
    INTEGRITY.scrub_index(idx)
    assert INTEGRITY.region_report(idx)[2] is True
    # heal: re-write the row through the front door
    idx.upsert(ids[:1], x[:1])
    INTEGRITY.scrub_index(idx)
    assert INTEGRITY.region_report(idx)[2] is False


# ---------------- snapshot round-trips ----------------

@pytest.mark.parametrize("kind,precision", [
    (IndexType.FLAT, "fp32"),
    (IndexType.FLAT, "bf16"),
    (IndexType.FLAT, "sq8"),
    (IndexType.IVF_FLAT, "fp32"),
    (IndexType.IVF_FLAT, "bf16"),
    (IndexType.IVF_FLAT, "sq8"),
    (IndexType.HNSW, "fp32"),
    (IndexType.HNSW, "sq8"),
    (IndexType.IVF_PQ, "fp32"),
])
def test_snapshot_digest_round_trip(tmp_path, kind, precision):
    ids, x = _corpus(seed=13)
    idx = new_index(31, _param(kind, precision=precision))
    idx.upsert(ids, x)
    if idx.need_train():
        idx.train()
    path = str(tmp_path / "snap")
    idx.save(path)
    meta = json.load(open(os.path.join(path, "meta.json")))
    assert meta.get("integrity", {}).get("rows"), meta
    fresh = new_index(31, _param(kind, precision=precision))
    fresh.load(path)                        # restore verification passes
    assert fresh.get_count() == len(ids)


@pytest.mark.parametrize("kind,precision,npz,field", [
    (IndexType.FLAT, "fp32", "flat.npz", "vectors"),
    (IndexType.FLAT, "sq8", "flat.npz", "codes"),
    (IndexType.IVF_FLAT, "fp32", "ivf_flat.npz", "vectors"),
    (IndexType.IVF_PQ, "fp32", "ivf_pq.npz", "vectors"),
    (IndexType.HNSW, "fp32", "hnsw_vectors.npz", "vectors"),
])
def test_tampered_snapshot_refused(tmp_path, kind, precision, npz, field):
    ids, x = _corpus(seed=14)
    idx = new_index(32, _param(kind, precision=precision))
    idx.upsert(ids, x)
    if idx.need_train():
        idx.train()
    path = str(tmp_path / "snap")
    idx.save(path)
    data = dict(np.load(os.path.join(path, npz)))
    data[field].view(np.uint8)[1, 0] ^= 1   # one flipped byte at rest
    np.savez(os.path.join(path, npz), **data)
    fresh = new_index(32, _param(kind, precision=precision))
    with pytest.raises(SnapshotCorruption):
        fresh.load(path)
    assert METRICS.counter("consistency.restore_mismatches",
                           region_id=32).get() >= 1


def test_tampered_hnsw_adjacency_snapshot_refused(tmp_path):
    """The PR 8 hnsw_adj.npz arm: the persisted device-graph mirror is
    digest-gated too."""
    was = FLAGS.get("hnsw_device_search")
    FLAGS.set("hnsw_device_search", "True")
    try:
        ids, x = _corpus(seed=15)
        idx = new_index(33, _param(IndexType.HNSW))
        idx.upsert(ids, x)
        idx.search(x[:2], 5)       # installs + syncs the mirror pre-save
        path = str(tmp_path / "snap")
        idx.save(path)
        meta = json.load(open(os.path.join(path, "meta.json")))
        assert "adjacency" in meta["integrity"]
        data = dict(np.load(os.path.join(path, "hnsw_adj.npz")))
        adj = data["adj"]
        r, c = np.argwhere(adj >= 0)[0]
        adj[r, c] = int(data["labels"][-1])   # rewire to another node
        np.savez(os.path.join(path, "hnsw_adj.npz"), **data)
        fresh = new_index(33, _param(IndexType.HNSW))
        with pytest.raises(SnapshotCorruption):
            fresh.load(path)
    finally:
        FLAGS.set("hnsw_device_search", was)


def test_manager_falls_back_to_rebuild_on_corrupt_snapshot(tmp_path):
    """load_index returns False on SnapshotCorruption (any load failure),
    which is the rebuild-from-engine recovery path."""
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.index.manager import VectorIndexManager
    from dingo_tpu.index.wrapper import VectorIndexWrapper
    from dingo_tpu.store.region import (
        Region,
        RegionDefinition,
        RegionType,
    )

    param = _param(IndexType.FLAT)
    ids, x = _corpus(seed=16)
    idx = new_index(34, param)
    idx.upsert(ids, x)
    mgr = VectorIndexManager(MemEngine(), snapshot_root=str(tmp_path))
    path = mgr.snapshot_path(34)
    idx.save(path)
    data = dict(np.load(os.path.join(path, "flat.npz")))
    data["vectors"].view(np.uint8)[0, 0] ^= 1
    np.savez(os.path.join(path, "flat.npz"), **data)
    definition = RegionDefinition(
        region_id=34, start_key=b"", end_key=b"",
        region_type=RegionType.INDEX, index_parameter=param,
    )
    region = Region(definition)
    region.vector_index_wrapper = VectorIndexWrapper(34, param)
    assert mgr.load_index(region) is False


# ---------------- br backup/restore verification ----------------

def test_br_backup_manifest_checksum_and_corrupt_restore(tmp_path):
    from dingo_tpu.br import backup_cluster, restore_cluster
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.index import codec as vcodec
    from dingo_tpu.raft import LocalTransport
    from dingo_tpu.store.node import StoreNode
    from dingo_tpu.store.region import RegionType

    transport = LocalTransport()
    coord = CoordinatorControl(MemEngine(), replication=1)
    node = StoreNode("s0", transport, coord, raft_kw={"seed": 0})
    try:
        d = coord.create_region(
            start_key=vcodec.encode_vector_key(0, 0),
            end_key=vcodec.encode_vector_key(0, 1 << 30),
            region_type=RegionType.INDEX,
            index_parameter=_param(IndexType.FLAT, d=8),
        )
        for _ in range(3):
            node.heartbeat_once()
            time.sleep(0.05)
        _wait_region_leader(node, d.region_id)
        region = node.get_region(d.region_id)
        rng = np.random.default_rng(0)
        node.storage.vector_add(
            region, np.arange(20, dtype=np.int64),
            rng.standard_normal((20, 8)).astype(np.float32),
            [{} for _ in range(20)],
        )
        time.sleep(0.2)
        bak = str(tmp_path / "bak")
        manifest = backup_cluster(coord, {"s0": node}, bak)
        entry = manifest["regions"][0]
        assert len(entry["sha256"]) == 64
        # flip one byte at rest -> restore must refuse the artifact
        fpath = os.path.join(bak, entry["data_file"])
        blob = bytearray(open(fpath, "rb").read())
        blob[len(blob) // 2] ^= 1
        open(fpath, "wb").write(bytes(blob))
        transport2 = LocalTransport()
        coord2 = CoordinatorControl(MemEngine(), replication=1)
        node2 = StoreNode("s0", transport2, coord2, raft_kw={"seed": 0})
        try:
            with pytest.raises(ValueError, match="corrupt"):
                restore_cluster(coord2, {"s0": node2}, bak)
        finally:
            node2.stop()
    finally:
        node.stop()


# ---------------- heartbeat + coordinator divergence ----------------

def _region_snapshot(rid, applied, digests, mismatch=False):
    from dingo_tpu.metrics.snapshot import RegionMetricsSnapshot

    return RegionMetricsSnapshot(
        region_id=rid, is_leader=True,
        integrity_applied_index=applied,
        integrity_digests=digests,
        integrity_mismatch=mismatch,
    )


def _store_snapshot(sid, regions):
    from dingo_tpu.metrics.snapshot import StoreMetricsSnapshot

    return StoreMetricsSnapshot(store_id=sid, regions=regions)


def test_region_metrics_pb_round_trip():
    from dingo_tpu.server import convert, pb

    rm = _region_snapshot(7, 42, json.dumps({"rows": "1-a-b"}), True)
    m = convert.region_metrics_to_pb(rm)
    back = convert.region_metrics_from_pb(
        pb.RegionMetrics.FromString(m.SerializeToString())
    )
    assert back.integrity_applied_index == 42
    assert back.integrity_digests == rm.integrity_digests
    assert back.integrity_mismatch is True


def test_coordinator_divergence_detect_flag_and_clear():
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.engine.raw_engine import MemEngine

    coord = CoordinatorControl(MemEngine(), replication=2)
    coord.register_store("s0")
    coord.register_store("s1")
    good = json.dumps({"rows": "64-aaaa-bbbb", "blocked": "64-cc-dd"})
    bad = json.dumps({"rows": "64-aaaa-bbbb", "blocked": "64-ee-ff"})
    div0 = METRICS.counter("consistency.divergence", region_id=9).get()
    coord.store_heartbeat(
        "s0", metrics=_store_snapshot("s0", [_region_snapshot(9, 5, good)])
    )
    assert coord.diverged_regions() == []     # only one replica reporting
    # equal applied index, differing blocked digest -> DIVERGED
    FLIGHT.clear()
    coord.store_heartbeat(
        "s1", metrics=_store_snapshot("s1", [_region_snapshot(9, 5, bad)])
    )
    assert coord.diverged_regions() == [9]
    assert METRICS.counter(
        "consistency.divergence", region_id=9).get() == div0 + 1
    assert METRICS.gauge("consistency.diverged_regions").get() == 1.0
    metas = FLIGHT.bundles_meta()
    assert any(m["reason"] == "divergence" for m in metas)
    bundle = FLIGHT.get_json()
    assert bundle["trigger"]["peers"][0]["artifacts"] == ["blocked"]
    assert bundle["trigger"]["digests"] == bad
    # a replica merely LAGGING (different applied index) never diverges
    coord.store_heartbeat(
        "s1", metrics=_store_snapshot("s1", [_region_snapshot(9, 6, bad)])
    )
    # healed replica re-converges at the same applied index -> cleared
    coord.store_heartbeat(
        "s1", metrics=_store_snapshot("s1", [_region_snapshot(9, 5, good)])
    )
    coord.store_heartbeat(
        "s0", metrics=_store_snapshot("s0", [_region_snapshot(9, 5, good)])
    )
    assert coord.diverged_regions() == []
    assert METRICS.gauge("consistency.diverged_regions").get() == 0.0


def test_cluster_top_and_consistency_render():
    from dingo_tpu.client.cli import (
        format_cluster_consistency,
        format_cluster_top,
    )
    from dingo_tpu.server import convert, pb

    good = json.dumps({"rows": "64-aaaa-bbbb"})
    bad = json.dumps({"rows": "64-cccc-dddd"})
    top = pb.GetStoreMetricsResponse()
    for sid, digests in (("s0", good), ("s1", bad)):
        entry = top.stores.add()
        entry.store_id = sid
        convert.store_metrics_to_pb(
            _store_snapshot(sid, [_region_snapshot(9, 5, digests)]),
            entry.metrics,
        )
    top.diverged_region_ids.append(9)
    text = format_cluster_top(top)
    assert "DIVERGED" in text

    resp = pb.GetRegionMetricsResponse()
    for sid, digests in (("s0", good), ("s1", bad)):
        entry = resp.regions.add()
        entry.store_id = sid
        convert.region_metrics_to_pb(
            _region_snapshot(9, 5, digests), entry.metrics
        )
    resp.diverged_region_ids.append(9)
    text = format_cluster_consistency(resp)
    assert "DIVERGED" in text and "rows" in text
    # agreeing replicas render ok
    resp2 = pb.GetRegionMetricsResponse()
    for sid in ("s0", "s1"):
        entry = resp2.regions.add()
        entry.store_id = sid
        convert.region_metrics_to_pb(
            _region_snapshot(9, 5, good), entry.metrics
        )
    text = format_cluster_consistency(resp2)
    assert "ok" in text and "DIVERGED" not in text


def test_wrapper_tags_applied_index():
    from dingo_tpu.index.wrapper import VectorIndexWrapper

    param = _param(IndexType.FLAT)
    w = VectorIndexWrapper(41, param)
    w.build_own()
    w.ready = True
    ids, x = _corpus(seed=20, n=32)
    w.add(ids, x, log_id=17)
    led = INTEGRITY.peek(w.own_index)
    assert led is not None and led.applied_index == 17
    w.delete(ids[:4], log_id=18)
    assert led.applied_index == 18
    rep = led.report()
    assert rep["artifacts"]["rows"].startswith(f"{32 - 4:x}-")


def test_collector_fills_integrity_fields():
    """The heartbeat snapshot carries (applied index, digest vector,
    scrub verdict) — via a real StoreNode region."""
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.index import codec as vcodec
    from dingo_tpu.raft import LocalTransport
    from dingo_tpu.store.node import StoreNode
    from dingo_tpu.store.region import RegionType

    transport = LocalTransport()
    coord = CoordinatorControl(MemEngine(), replication=1)
    node = StoreNode("s0", transport, coord, raft_kw={"seed": 0})
    try:
        d = coord.create_region(
            start_key=vcodec.encode_vector_key(0, 0),
            end_key=vcodec.encode_vector_key(0, 1 << 30),
            region_type=RegionType.INDEX,
            index_parameter=_param(IndexType.FLAT, d=8),
        )
        for _ in range(3):
            node.heartbeat_once()
            time.sleep(0.05)
        _wait_region_leader(node, d.region_id)
        region = node.get_region(d.region_id)
        rng = np.random.default_rng(1)
        node.storage.vector_add(
            region, np.arange(10, dtype=np.int64),
            rng.standard_normal((10, 8)).astype(np.float32),
            [{} for _ in range(10)],
        )
        time.sleep(0.2)
        snap = node.metrics.collect()
        rm = snap.region(d.region_id)
        assert rm.integrity_digests, "digest vector missing from heartbeat"
        digests = json.loads(rm.integrity_digests)
        assert digests["rows"].startswith("a-")      # 10 rows
        assert rm.integrity_applied_index > 0
        assert rm.integrity_mismatch is False
    finally:
        node.stop()


# ---------------- ReplicaGroup post-fanout monitor ----------------

def test_replica_group_fanout_divergence_detected():
    from dingo_tpu.parallel.replica_group import ReplicaGroup

    param = _param(IndexType.FLAT, d=16)

    def builder(index_id, parameter, devices):
        return new_index(index_id, parameter)

    group = ReplicaGroup(51, param, replicas=2,
                         devices=list(range(4)), member_builder=builder)
    ids, x = _corpus(seed=21, n=64, d=16)
    group.upsert(ids, x)
    assert group.verify_fanout(force=True) is True
    mm0 = METRICS.counter(
        "consistency.replica_mismatch", region_id=51).get()
    # one member silently loses a row OUTSIDE the next write batch (the
    # failure the bit-identity claim used to just assume away)
    group.members[1].delete(ids[10:11])
    FLIGHT.clear()
    rng = np.random.default_rng(2)
    group.upsert(ids[:4], rng.standard_normal((4, 16)).astype(np.float32))
    assert METRICS.counter(
        "consistency.replica_mismatch", region_id=51).get() == mm0 + 1
    assert any(m["reason"] == "divergence"
               for m in FLIGHT.bundles_meta())
    # healing the member clears the verdict
    group.members[1].upsert(ids[10:11], x[10:11])
    assert group.verify_fanout(force=True) is True


def test_scrub_runner_hot_gates_and_sweeps():
    from dingo_tpu.obs.integrity import IntegrityScrubRunner

    class _Meta:
        def __init__(self, regions):
            self._regions = regions

        def get_all_regions(self):
            return self._regions

    class _Region:
        def __init__(self, rid, idx):
            self.id = rid
            self.vector_index_wrapper = type(
                "W", (), {"own_index": idx})()

    ids, x = _corpus(seed=22, n=64)
    idx = new_index(61, _param(IndexType.FLAT))
    idx.upsert(ids, x)
    node = type("N", (), {"meta": _Meta([_Region(61, idx)])})()
    runner = IntegrityScrubRunner(node)
    runner.tick()
    for _ in range(100):
        t = runner._worker
        if t is None or not t.is_alive():
            break
        time.sleep(0.02)
    assert runner.sweeps == 1
    assert METRICS.gauge("consistency.scrub_ok", region_id=61).get() == 1.0
    # disabled -> no new sweep
    FLAGS.set("integrity_enabled", False)
    runner.tick()
    assert runner.sweeps == 1


# ---------------- review-fix regressions ----------------

def test_scrub_marks_inflight_write_as_raced(monkeypatch):
    """A write that mutated device state but hasn't folded into the
    ledger yet must read as 'raced' (retried next pass), never as a
    phantom 'mismatch' — write paths bump the region mutation counter
    BEFORE touching the device, and the scrub checks it."""
    from dingo_tpu.obs import integrity as integ_mod

    ids, x = _corpus(seed=30)
    idx = new_index(71, _param(IndexType.FLAT))
    idx.upsert(ids, x)
    orig = integ_mod._iter_rows

    def hijacked(index, chunk):
        for ids_, payload in orig(index, chunk):
            # simulate the window: the writer announced its mutation and
            # changed device bytes, but its ledger fold hasn't landed
            INTEGRITY.note_mutation_begin(index)
            bad = payload.copy()
            bad.view(np.uint8)[0, 0] ^= 1
            yield ids_, bad

    monkeypatch.setattr(integ_mod, "_iter_rows", hijacked)
    res = INTEGRITY.scrub_index(idx)
    assert res["rows"]["status"] == "raced", res
    assert INTEGRITY.region_report(idx)[2] is False  # no CORRUPT verdict


def test_ledger_survives_enabled_toggle():
    """integrity.enabled gates ledger CREATION only: an existing ledger
    keeps folding writes made while the flag is momentarily off, so
    re-enabling never yields false corruption verdicts or restore
    vetoes (the PR 9 quality-mirror toggle discipline)."""
    ids, x = _corpus(seed=31)
    idx = new_index(72, _param(IndexType.FLAT))
    idx.upsert(ids[:200], x[:200])
    FLAGS.set("integrity_enabled", False)
    idx.upsert(ids[200:300], x[200:300])       # tracked despite the flag
    idx.delete(ids[:10])
    FLAGS.set("integrity_enabled", True)
    res = INTEGRITY.scrub_index(idx)
    assert res["rows"]["status"] == "ok", res
    # a NEVER-tracked index stays zero-cost while disabled
    FLAGS.set("integrity_enabled", False)
    fresh = new_index(73, _param(IndexType.FLAT))
    fresh.upsert(ids[:50], x[:50])
    assert INTEGRITY.peek(fresh) is None
    FLAGS.set("integrity_enabled", True)


def test_adjacency_excluded_from_heartbeat_vector():
    """The adjacency ledger follows the LAZY mirror re-export (search
    timing), not the raft order — it must not ride the replica-compared
    heartbeat vector, while snapshot meta still carries it."""
    was = FLAGS.get("hnsw_device_search")
    FLAGS.set("hnsw_device_search", "True")
    try:
        ids, x = _corpus(seed=32)
        idx = new_index(74, _param(IndexType.HNSW))
        idx.upsert(ids, x)
        idx.search(x[:2], 5)                 # installs + ledgers the mirror
        led = INTEGRITY.peek(idx)
        assert "adjacency" in led.report()["artifacts"]
        digests = json.loads(led.heartbeat_view()[1])
        assert "adjacency" not in digests
        assert "rows" in digests
        assert "adjacency" in INTEGRITY.snapshot_artifacts(idx)
    finally:
        FLAGS.set("hnsw_device_search", was)


def test_heartbeat_withheld_while_write_in_flight():
    """The (applied, digest) heartbeat pair can be torn between a ledger
    fold and its applied-index tag — while any bracketed write is in
    flight the ledger withholds the digest vector for the beat instead
    of letting the coordinator compare a torn pair."""
    ids, x = _corpus(seed=33, n=64)
    idx = new_index(75, _param(IndexType.FLAT))
    idx.upsert(ids, x)
    led = INTEGRITY.peek(idx)
    applied, digests, _ = INTEGRITY.region_report(idx)
    assert digests != ""
    INTEGRITY.note_mutation_begin(idx)      # a write opened its bracket
    try:
        applied2, digests2, _ = INTEGRITY.region_report(idx)
        assert digests2 == ""               # no evidence this beat
    finally:
        INTEGRITY.note_mutation_end(idx)
    assert INTEGRITY.region_report(idx)[1] == digests
    assert led.pending == 0                  # brackets balanced


def test_scrub_raced_when_write_began_before_pass():
    """A write that opened its bracket BEFORE the scrub pass started and
    folds after it must also read as raced (the pending counter at the
    capture endpoint)."""
    ids, x = _corpus(seed=34)
    idx = new_index(76, _param(IndexType.FLAT))
    idx.upsert(ids, x)
    INTEGRITY.note_mutation_begin(idx)      # in-flight before the pass
    try:
        res = INTEGRITY.scrub_index(idx)
        assert res["rows"]["status"] == "raced", res
    finally:
        INTEGRITY.note_mutation_end(idx)
    assert INTEGRITY.scrub_index(idx)["rows"]["status"] == "ok"


def test_scrub_ok_gauge_holds_through_raced_passes():
    """consistency.scrub_ok only moves on DECISIVE passes: a raced pass
    after a confirmed mismatch must not flip the gauge back to healthy
    while the heartbeat still reports CORRUPT."""
    ids, x = _corpus(seed=35)
    idx = new_index(77, _param(IndexType.FLAT))
    idx.upsert(ids, x)
    slot = int(idx.store.slots_of(ids[:1])[0])
    _corrupt_device_array(
        idx.store, "vecs", lambda a: a.view(np.uint8).__setitem__(
            (slot, 0), a.view(np.uint8)[slot, 0] ^ 1)
    )
    INTEGRITY.scrub_index(idx)
    g = METRICS.gauge("consistency.scrub_ok", region_id=77)
    assert g.get() == 0.0
    INTEGRITY.note_mutation_begin(idx)      # every pass now races
    try:
        res = INTEGRITY.scrub_index(idx)
        assert res["rows"]["status"] == "raced"
        assert g.get() == 0.0               # raced pass: gauge holds
        assert INTEGRITY.region_report(idx)[2] is True
    finally:
        INTEGRITY.note_mutation_end(idx)


def test_sq8_canonical_rows_reuses_put_codes():
    """The integrity hook must not re-quantize the batch the store just
    encoded: canonical_rows reuses put()'s codes for the same array
    object, and still encodes correctly for any other input."""
    from dingo_tpu.index.slot_store import SqSlotStore

    ids, x = _corpus(seed=36, n=64)
    store = SqSlotStore(D)
    store.put(ids, x)
    memo_codes = store._canonical_memo[2]
    got = store.canonical_rows(x)           # same object: memo consumed
    assert got is memo_codes
    assert store._canonical_memo is None
    again = store.canonical_rows(x)         # no memo: fresh encode
    assert np.array_equal(again, memo_codes)
