"""Cluster backup / restore.

Reference: src/br/ — the backup binary exports (1) coordinator meta and
(2) per-region data as SST files written by SstFileWriter
(br/sst_file_writer.h), grouped into sdk/sql meta+data sets; restore
ingests the SSTs back and re-registers meta. An InteractionManager fans the
export RPCs to every store.

Here: backupmeta.json + one data blob per region (the engine's
region-scoped snapshot — the same representation raft snapshot install
uses), restored by replaying the blob into the target store's engine and
re-creating regions through the coordinator.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Dict, List, Optional

from dingo_tpu.engine.raft_engine import region_install, region_snapshot
from dingo_tpu.store.region import RegionDefinition


def backup_cluster(coordinator, nodes: Dict[str, object], path: str) -> dict:
    """Export meta + per-region data. `nodes`: store_id -> StoreNode.
    Returns the backup manifest."""
    os.makedirs(path, exist_ok=True)
    manifest = {
        "created_ms": int(time.time() * 1000),
        "regions": [],
        "stores": sorted(nodes),
    }
    skipped = []
    for region_id, definition in coordinator.regions.items():
        # leader preferred, but fall back to ANY peer that actually holds
        # the region (leadership records can be stale)
        candidates = [coordinator.region_leaders.get(region_id)]
        candidates += [p for p in definition.peers if p not in candidates]
        node = region = None
        for host in candidates:
            cand = nodes.get(host)
            if cand is None:
                continue
            region = cand.get_region(region_id)
            if region is not None:
                node = cand
                break
        if node is None or region is None:
            skipped.append(region_id)
            continue
        blob = pickle.dumps(region_snapshot(node.raw, region), protocol=4)
        fname = f"region_{region_id}.data"
        with open(os.path.join(path, fname), "wb") as f:
            f.write(blob)
        manifest["regions"].append({
            "region_id": region_id,
            "definition": _def_to_json(definition),
            "data_file": fname,
            "bytes": len(blob),
        })
    manifest["skipped_regions"] = skipped
    with open(os.path.join(path, "backupmeta.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    # coordinator meta KV (id counters etc.) travels as a pickle
    with open(os.path.join(path, "coordinator.meta"), "wb") as f:
        f.write(pickle.dumps({
            "next_region_id": coordinator._next_region_id,
        }))
    return manifest


def restore_cluster(coordinator, nodes: Dict[str, object], path: str,
                    wait_s: float = 5.0) -> int:
    """Recreate regions through the coordinator and ingest their data on
    every hosting store. Returns the number of regions restored."""
    with open(os.path.join(path, "backupmeta.json")) as f:
        manifest = json.load(f)
    meta_path = os.path.join(path, "coordinator.meta")
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            saved = pickle.loads(f.read())
        # never reuse ids the backed-up cluster already handed out
        coordinator._next_region_id = max(
            coordinator._next_region_id, saved.get("next_region_id", 0)
        )
        coordinator._persist_ids()
    restored = 0
    for entry in manifest["regions"]:
        definition = _def_from_json(entry["definition"])
        created = coordinator.create_region(
            start_key=definition.start_key,
            end_key=definition.end_key,
            partition_id=definition.partition_id,
            region_type=definition.region_type,
            index_parameter=definition.index_parameter,
        )
        # deliver CREATE commands + wait for region materialization
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            for n in nodes.values():
                n.heartbeat_once()
            if all(
                nodes[sid].get_region(created.region_id) is not None
                for sid in created.peers if sid in nodes
            ):
                break
            time.sleep(0.05)
        with open(os.path.join(path, entry["data_file"]), "rb") as f:
            state = pickle.loads(f.read())
        installed = 0
        for sid in created.peers:
            node = nodes.get(sid)
            if node is None:
                continue
            region = node.get_region(created.region_id)
            if region is None:
                continue
            region_install(node.raw, region, state)
            # indexes rebuild from the ingested engine data
            if region.vector_index_wrapper is not None:
                node.index_manager.rebuild(region)
            if region.document_index is not None:
                node.rebuild_document_index(region)
            installed += 1
        if installed:
            restored += 1
    return restored


def _def_to_json(d: RegionDefinition) -> dict:
    from dingo_tpu.server.convert import region_def_to_pb

    return {"pb_hex": region_def_to_pb(d).SerializeToString().hex()}


def _def_from_json(j: dict) -> RegionDefinition:
    from dingo_tpu.server import pb
    from dingo_tpu.server.convert import region_def_from_pb

    m = pb.RegionDefinition()
    m.ParseFromString(bytes.fromhex(j["pb_hex"]))
    return region_def_from_pb(m)
