"""Chrome-trace -> per-stage latency table.

Companion to tools/report.py (same json+html output convention): feed it
the Chrome ``trace_event`` file produced by
``dingo_tpu.trace.dump_chrome_trace`` or the ``TraceChromeDump`` RPC and
get the Faiss-paper-style per-stage breakdown (count / avg / p50 / p99 /
max / total per span name):

    python tools/trace_report.py /tmp/dingo_trace.json [out_dir]

Prints an aligned table; with out_dir also writes trace_report.json and
trace_report.html (report.py's visual style).
"""

from __future__ import annotations

import html
import json
import os
import sys
from typing import Dict, List


def _percentile(ordered: List[float], p: float) -> float:
    if not ordered:
        return 0.0
    i = min(len(ordered) - 1, int(p / 100.0 * len(ordered)))
    return ordered[i]


def aggregate(events: List[Dict]) -> List[Dict]:
    """Per-name duration stats from trace_event 'X' entries, slowest
    total first (the stage eating the most wall time leads)."""
    by_name: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        by_name.setdefault(ev["name"], []).append(float(ev.get("dur", 0)))
    rows = []
    for name, durs in by_name.items():
        durs.sort()
        total = sum(durs)
        rows.append({
            "stage": name,
            "count": len(durs),
            "avg_us": total / len(durs),
            "p50_us": _percentile(durs, 50),
            "p99_us": _percentile(durs, 99),
            "max_us": durs[-1],
            "total_us": total,
        })
    rows.sort(key=lambda r: r["total_us"], reverse=True)
    return rows


def load_events(path: str) -> List[Dict]:
    with open(path) as f:
        data = json.load(f)
    # both documented forms: {"traceEvents": [...]} or a bare array
    return data["traceEvents"] if isinstance(data, dict) else data


_COLS = ("stage", "count", "avg_us", "p50_us", "p99_us", "max_us", "total_us")


def render_table(rows: List[Dict]) -> str:
    widths = {c: len(c) for c in _COLS}
    lines = []
    for r in rows:
        line = {
            c: (r[c] if isinstance(r[c], str) else
                (str(r[c]) if isinstance(r[c], int) else f"{r[c]:.1f}"))
            for c in _COLS
        }
        for c in _COLS:
            widths[c] = max(widths[c], len(line[c]))
        lines.append(line)
    def fmt(vals):
        return "  ".join(
            vals[c].ljust(widths[c]) if c == "stage"
            else vals[c].rjust(widths[c]) for c in _COLS
        )
    out = [fmt({c: c for c in _COLS})]
    out.append("  ".join("-" * widths[c] for c in _COLS))
    out.extend(fmt(line) for line in lines)
    return "\n".join(out)


_PAGE = """<!doctype html><html><head><meta charset="utf-8">
<title>dingo-tpu trace report</title><style>
body{{font-family:system-ui,sans-serif;margin:2rem;max-width:70rem}}
table{{border-collapse:collapse;width:100%}}
td,th{{padding:.25rem .6rem;border-bottom:1px solid #ddd;text-align:right}}
td:first-child,th:first-child{{text-align:left}}
</style></head><body>
<h1>dingo-tpu per-stage latency</h1>
<p>{n_events} span events &middot; {n_stages} stages</p>
<table><tr>{head}</tr>
{rows}
</table></body></html>"""


def render_html(rows: List[Dict], n_events: int) -> str:
    head = "".join(f"<th>{c}</th>" for c in _COLS)
    body = []
    for r in rows:
        cells = "".join(
            f"<td>{html.escape(str(r[c])) if isinstance(r[c], (str, int)) else f'{r[c]:.1f}'}</td>"
            for c in _COLS
        )
        body.append(f"<tr>{cells}</tr>")
    return _PAGE.format(n_events=n_events, n_stages=len(rows),
                        head=head, rows="\n".join(body))


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) not in (1, 2):
        print("usage: trace_report.py <chrome_trace.json> [out_dir]",
              file=sys.stderr)
        return 2
    events = load_events(argv[0])
    rows = aggregate(events)
    if not rows:
        print("no span events in trace", file=sys.stderr)
        return 1
    print(render_table(rows))
    if len(argv) == 2:
        out_dir = argv[1]
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "trace_report.json"), "w") as f:
            json.dump({"stages": rows, "events": len(events)}, f, indent=1)
        with open(os.path.join(out_dir, "trace_report.html"), "w") as f:
            f.write(render_html(rows, len(events)))
        print(f"{len(rows)} stages -> {out_dir}/trace_report.html")
    return 0


if __name__ == "__main__":
    sys.exit(main())
